"""Content-addressed response cache for the serving edge.

Consumer image traffic is heavy-tailed: a popular image is classified
thousands of times, and every repeat burns a full engine pass for an
answer that is a pure function of (weights, dtypes, payload).  The
cache exploits exactly that purity — the key is

    (model name, active-version params digest, wire dtype,
     infer dtype, blake2b(payload bytes))

so a hit is byte-identical to what the engine would recompute, and
promote / rollback / revert / hot-reload invalidate automatically:
swapping the active version changes ``params_digest`` and every old
key simply stops matching (stale entries age out through the LRU, no
flush coordination with the control plane).

What is deliberately NOT cached:
  * shed (429) and quarantine/error (5xx) responses — transient
    verdicts must be re-evaluated per request;
  * debug-trace responses — the attached span is per-request;
  * models without a ``params_digest`` (raw exported blobs) — no
    version identity means no safe invalidation.

Brownout L2 (serve/brownout.py) relaxes version purity DELIBERATELY:
``get_stale`` answers a miss with the newest cached entry for the same
(route, model, dtypes, payload) under ANY params version — a stale but
well-formed answer beats a 429 when the engine is saturated.  The
stale path is opt-in per lookup (the HTTP layer only consults it at
L2+ and marks the response ``X-DVT-Degraded``), so normal operation
keeps the exact-version contract untouched.

The store is a byte-bounded LRU (``OrderedDict`` under one leaf lock);
lookups and inserts are O(1) and the value is the already-serialized
JSON body, so a hit skips decode, engine, and re-serialization in one
step.  Payload digesting reuses the blake2b shape of
``core/restore.py``'s ``params_digest`` (hex, 8-byte digest) so the
two digest namespaces read the same in traces and stats.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

from deep_vision_tpu.analysis.sanitizer import new_lock

DEFAULT_CACHE_BYTES = 64 * 2**20


def payload_digest(body: bytes) -> str:  # dvtlint: hot
    """blake2b hex digest of the raw request payload bytes — the
    content address.  Same digest family/size as
    ``core.restore.params_digest`` so digests are uniform repo-wide."""
    return hashlib.blake2b(body, digest_size=8).hexdigest()


class ResponseCache:
    """Byte-bounded LRU of serialized 200-responses."""

    def __init__(self, max_bytes: int = DEFAULT_CACHE_BYTES):
        self.max_bytes = max(0, int(max_bytes))
        self._lock = new_lock("serve.cache.ResponseCache._lock")
        # guarded-by: _lock
        self._store: OrderedDict[tuple, bytes] = OrderedDict()
        self._bytes = 0       # guarded-by: _lock
        self.hits = 0         # guarded-by: _lock
        self.misses = 0       # guarded-by: _lock
        self.evictions = 0    # guarded-by: _lock
        self.insertions = 0   # guarded-by: _lock
        # cascade provenance: which tier produced each inserted answer
        # ("front"/"big").  Counters only — the KEY stays tier-agnostic
        # (a hit is a hit no matter which tier computed it), keyed on
        # the cascade's combined digest so either tier's reload still
        # invalidates.  guarded-by: _lock
        self.insertions_by_tier: dict = {}
        # version-agnostic alias → the newest full key inserted for it
        # (the brownout L2 stale path); pruned with its entry on
        # eviction.  guarded-by: _lock
        self._stale: dict[tuple, tuple] = {}
        self.stale_hits = 0   # guarded-by: _lock

    @staticmethod
    def key(route: str, model: str, version_digest: str,
            wire_dtype: str, infer_dtype: str,
            body_digest: str) -> tuple:
        """``route`` keeps /v1/classify and /v1/detect answers for the
        same payload from aliasing each other."""
        return (route, model, version_digest, wire_dtype, infer_dtype,
                body_digest)

    @staticmethod
    def _alias(key: tuple) -> tuple:
        # the full key minus the params digest (index 2)
        return key[:2] + key[3:]

    def get(self, key: tuple) -> bytes | None:  # dvtlint: hot
        with self._lock:
            blob = self._store.get(key)
            if blob is None:
                self.misses += 1
                return None
            self._store.move_to_end(key)
            self.hits += 1
            return blob

    def get_stale(self, key: tuple) -> bytes | None:  # dvtlint: hot
        """Brownout L2 fallback AFTER an exact ``get`` miss: the newest
        entry for the same (route, model, dtypes, payload) under any
        params version — None when no prior version ever answered this
        payload.  The caller owns marking the response degraded."""
        alias = self._alias(key)
        with self._lock:
            full = self._stale.get(alias)
            if full is None or full == key:
                return None
            blob = self._store.get(full)
            if blob is None:
                del self._stale[alias]  # entry aged out of the LRU
                return None
            self._store.move_to_end(full)
            self.stale_hits += 1
            return blob

    def put(self, key: tuple, blob: bytes,
            tier: str | None = None):  # dvtlint: hot
        size = len(blob)
        if size > self.max_bytes:
            return  # larger than the whole budget: not cacheable
        with self._lock:
            old = self._store.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._store[key] = blob
            self._bytes += size
            self.insertions += 1
            self._stale[self._alias(key)] = key
            if tier:
                self.insertions_by_tier[tier] = \
                    self.insertions_by_tier.get(tier, 0) + 1
            while self._bytes > self.max_bytes:
                vkey, victim = self._store.popitem(last=False)
                self._bytes -= len(victim)
                self.evictions += 1
                if self._stale.get(self._alias(vkey)) == vkey:
                    del self._stale[self._alias(vkey)]

    def clear(self):
        with self._lock:
            self._store.clear()
            self._stale.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            lookups = self.hits + self.misses
            return {"entries": len(self._store),
                    "bytes": self._bytes,
                    "max_bytes": self.max_bytes,
                    "hits": self.hits,
                    "stale_hits": self.stale_hits,
                    "misses": self.misses,
                    "hit_rate": self.hits / lookups if lookups else 0.0,
                    "evictions": self.evictions,
                    "insertions": self.insertions,
                    "insertions_by_tier": dict(self.insertions_by_tier)}
