"""Offline batch-inference jobs: manifests in, durable results out.

A *job* is a manifest of N inference items (images, latents, seeds)
POSTed to ``/v1/jobs`` and drained through the existing serving engines
by ``serve/batch_sched.py`` — strictly below every interactive tenant
(docs/BATCH.md).  This module owns the job ledger: the in-memory job
table the scheduler and the HTTP handlers read, and its append-only
JSONL checkpoint on disk, one file per job, in the deploy ledger's
style (deploy/history.py):

  {"kind": "job",   "job": id, "model": ..., "verb": ..., ...}
  {"kind": "shard", "job": id, "index": 3, "results": [...], ...}
  {"kind": "done",  "job": id, ...}

Progress is checkpointed at *shard* granularity — a shard record is the
durability unit.  On restart the store replays every job file, skipping
torn tails (a half-written line from a crash mid-append parses as
garbage and is dropped; every complete line before it survives), and
the scheduler resumes each unfinished job from its first missing shard.
A shard whose record made it to disk is never re-executed and its
results are never produced twice; a shard whose record was torn re-runs
in full, so results land exactly once in the durable log either way.

The ledger is also the result store: in memory each job keeps only a
bounded LRU cache of completed shard payloads (``max_cached_shards``),
and ``GET /v1/jobs/<id>/results`` streams evicted shards back from the
JSONL file by byte offset — a million-image job's results never have
to fit in RAM.

Lock order: ``JobStore._lock`` is a leaf — file appends happen OUTSIDE
it (one slow disk must not stall status polls), and no engine or
scheduler lock is ever taken under it.
"""

from __future__ import annotations

import collections
import json
import math
import os
import time

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.obs.log import event, get_logger

_log = get_logger("dvt.serve.jobs")


class Job:
    """One bulk job: an immutable manifest plus mutable shard progress.

    ``manifest`` is frozen at submit time and never mutated, so the
    scheduler may slice it without the store lock; the mutable fields
    are guarded by the owning store's ``_lock``.

    ``shards_done`` is the authoritative completion state (what the
    scheduler and status views read); ``results`` is only a bounded
    payload CACHE over the durable JSONL ledger — on a durable store
    the store evicts least-recently-read shards past its
    ``max_cached_shards`` cap and the results endpoint re-reads them
    from disk (``JobStore.results_items``)."""

    __slots__ = ("job_id", "model", "verb", "manifest", "shard_size",
                 "n_shards", "shards_done", "results", "pinned",
                 "images_done", "done", "error", "created_ts")

    def __init__(self, job_id: str, model: str, verb: str,
                 manifest: list, shard_size: int,
                 created_ts: float | None = None):
        self.job_id = job_id
        self.model = model
        self.verb = verb
        self.manifest = list(manifest)
        self.shard_size = max(1, int(shard_size))
        self.n_shards = max(1, math.ceil(len(self.manifest)
                                         / self.shard_size))
        self.shards_done: set[int] = set()  # guarded-by: JobStore._lock
        # payload cache, insertion/access-ordered for LRU eviction
        self.results: collections.OrderedDict[int, list] = \
            collections.OrderedDict()  # guarded-by: JobStore._lock
        # shards whose ledger append FAILED: memory is their only copy,
        # so eviction must never touch them
        self.pinned: set[int] = set()  # guarded-by: JobStore._lock
        self.images_done = 0  # guarded-by: JobStore._lock
        self.done = False  # guarded-by: JobStore._lock
        self.error: str | None = None  # guarded-by: JobStore._lock
        self.created_ts = created_ts if created_ts is not None \
            else time.time()

    def shard_range(self, index: int) -> tuple[int, int]:
        """[lo, hi) manifest slice for shard ``index``."""
        lo = index * self.shard_size
        return lo, min(len(self.manifest), lo + self.shard_size)

    def _state(self) -> str:
        if self.error:
            return "failed"
        if self.done:
            return "done"
        return "running" if self.shards_done else "pending"

    def _status_locked(self) -> dict:
        out = {"job_id": self.job_id, "model": self.model,
               "verb": self.verb, "state": self._state(),
               "n_items": len(self.manifest),
               "shard_size": self.shard_size,
               "n_shards": self.n_shards,
               "shards_done": len(self.shards_done),
               "images_done": self.images_done,
               "created_ts": round(self.created_ts, 3)}
        if self.error:
            out["error"] = self.error
        return out


class JobStore:
    """Job table + append-only JSONL checkpoint (one file per job).

    ``root=None`` runs memory-only (tests, servers started without
    ``--jobs-dir``): same API, no durability.  With a root, every job
    submitted, every completed shard, and every terminal transition
    appends one JSON line to ``<root>/<job_id>.jsonl``; construction
    replays existing files so a restarted server picks unfinished jobs
    back up at their first missing shard."""

    def __init__(self, root: str | None = None, *, shard_size: int = 32,
                 max_cached_shards: int = 64):
        self.root = root
        self.default_shard_size = max(1, int(shard_size))
        # per-job in-memory payload cache bound: with a durable root,
        # completed shard payloads past this count spill to the JSONL
        # ledger (LRU) and /v1/jobs/<id>/results streams them back from
        # disk; 0 = unbounded.  Memory-only stores never evict — memory
        # is the only copy
        self.max_cached_shards = max(0, int(max_cached_shards))
        self._lock = new_lock("serve.jobs.JobStore._lock")
        self._jobs: dict[str, Job] = {}  # guarded-by: _lock
        self._order: list[str] = []  # FIFO scheduling order, guarded-by: _lock
        self.submitted = 0  # guarded-by: _lock
        self.resumed = 0  # jobs replayed unfinished, guarded-by: _lock
        self.replayed_shards = 0  # guarded-by: _lock
        self.spilled_shards = 0  # payloads evicted to disk, guarded-by: _lock
        self.write_errors = 0  # guarded-by: _lock
        self.torn_lines = 0  # guarded-by: _lock
        if root:
            os.makedirs(root, exist_ok=True)
            self._load()

    # -- durability ---------------------------------------------------------

    def _path(self, job_id: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in job_id)
        return os.path.join(self.root, f"{safe}.jsonl")

    def _append(self, job_id: str, record: dict) -> bool:
        # called OUTSIDE self._lock — one slow disk must not stall the
        # scheduler or a status poll; memory is already updated, and a
        # lost append only means the shard re-runs after a restart.
        # Returns whether the record is durable (False pins the shard's
        # payload in memory — eviction must not drop the only copy)
        if not self.root:
            return True
        line = json.dumps(record, default=str) + "\n"
        try:
            with open(self._path(job_id), "a", encoding="utf-8") as f:
                f.write(line)
            return True
        except OSError as e:
            with self._lock:
                self.write_errors += 1
            event(_log, "job_write_error", job=job_id, error=str(e))
            return False

    def _load(self) -> None:
        loaded: list[Job] = []
        torn = replayed = 0
        for fname in sorted(os.listdir(self.root)):
            if not fname.endswith(".jsonl"):
                continue
            path = os.path.join(self.root, fname)
            try:
                with open(path, encoding="utf-8") as f:
                    lines = f.readlines()
                if lines and not lines[-1].endswith("\n"):
                    # torn tail repair: terminate the half-written line
                    # now, or the NEXT append would concatenate onto the
                    # garbage and be swallowed with it
                    with open(path, "a", encoding="utf-8") as f:
                        f.write("\n")
            except OSError:
                continue
            job: Job | None = None
            for raw in lines:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    rec = json.loads(raw)
                except ValueError:
                    # torn tail (or mid-file corruption): skip the line,
                    # keep every complete record around it
                    torn += 1
                    continue
                kind = rec.get("kind")
                if kind == "job" and job is None:
                    try:
                        job = Job(rec["job"], rec["model"], rec["verb"],
                                  rec["manifest"], rec["shard_size"],
                                  created_ts=float(rec.get("ts", 0.0)))
                    except (KeyError, TypeError, ValueError):
                        break  # unusable header → skip the file
                elif kind == "shard" and job is not None:
                    idx = rec.get("index")
                    res = rec.get("results")
                    if isinstance(idx, int) and isinstance(res, list) \
                            and 0 <= idx < job.n_shards \
                            and idx not in job.shards_done:
                        # completion state only: the payload already
                        # lives in this very ledger, so replay leaves
                        # the cache cold and results_items streams the
                        # rows back from disk on demand
                        job.shards_done.add(idx)
                        job.images_done += int(rec.get("images",
                                                       len(res)))
                        replayed += 1
                elif kind == "done" and job is not None:
                    job.done = True
                elif kind == "failed" and job is not None:
                    job.error = str(rec.get("reason", "failed"))
                    job.done = True
            if job is not None:
                loaded.append(job)
        loaded.sort(key=lambda j: (j.created_ts, j.job_id))
        resumed: list[Job] = []
        with self._lock:
            self.torn_lines += torn
            self.replayed_shards += replayed
            for job in loaded:
                self._jobs[job.job_id] = job
                self._order.append(job.job_id)
                if not job.done:
                    self.resumed += 1
                    resumed.append(job)
        for job in resumed:
            event(_log, "job_resumed", job=job.job_id,
                  model=job.model, shards_done=len(job.shards_done),
                  n_shards=job.n_shards)

    # -- job API ------------------------------------------------------------

    def submit(self, model: str, verb: str, manifest: list,
               shard_size: int | None = None) -> dict:
        """Register a new job; returns its status view (the HTTP job
        handle).  The job record is durable before this returns."""
        if not manifest:
            raise ValueError("empty manifest")
        job_id = "job-" + os.urandom(8).hex()
        job = Job(job_id, model, verb, manifest,
                  shard_size or self.default_shard_size)
        with self._lock:
            self._jobs[job_id] = job
            self._order.append(job_id)
            self.submitted += 1
            view = job._status_locked()
        self._append(job_id, {"kind": "job", "job": job_id,
                              "model": model, "verb": verb,
                              "shard_size": job.shard_size,
                              "n_items": len(job.manifest),
                              "manifest": job.manifest,
                              "ts": job.created_ts})
        event(_log, "job_submitted", job=job_id, model=model, verb=verb,
              n_items=len(job.manifest), n_shards=job.n_shards)
        return view

    def status(self, job_id: str) -> dict:
        with self._lock:
            return self._jobs[job_id]._status_locked()

    def jobs(self) -> list[dict]:
        with self._lock:
            return [self._jobs[jid]._status_locked()
                    for jid in self._order]

    def get(self, job_id: str) -> Job:
        with self._lock:
            return self._jobs[job_id]

    # -- scheduler API ------------------------------------------------------

    def next_shard(self) -> tuple[Job, int] | None:
        """FIFO: the lowest missing shard of the oldest unfinished job.
        Lowest-first keeps shard completion in index order, which is
        what lets the results endpoint stream the completed prefix."""
        with self._lock:
            for jid in self._order:
                job = self._jobs[jid]
                if job.done:
                    continue
                for i in range(job.n_shards):
                    if i not in job.shards_done:
                        return job, i
        return None

    def record_shard(self, job_id: str, index: int, results: list,
                     images: int) -> bool:
        """Commit one completed shard: memory under the lock, the JSONL
        record outside it.  Returns False (and writes nothing) if the
        shard is already recorded — the exactly-once guard for a
        replayed or double-run shard."""
        with self._lock:
            job = self._jobs[job_id]
            if index in job.shards_done or job.done:
                return False
            job.shards_done.add(index)
            job.results[index] = list(results)
            job.images_done += int(images)
            finished = len(job.shards_done) == job.n_shards
        durable = self._append(job_id, {"kind": "shard", "job": job_id,
                                        "index": index,
                                        "images": int(images),
                                        "results": list(results),
                                        "ts": time.time()})
        with self._lock:
            if not durable:
                job.pinned.add(index)
            self._evict_locked(job)
        if finished:
            with self._lock:
                job.done = True
            self._append(job_id, {"kind": "done", "job": job_id,
                                  "ts": time.time()})
            event(_log, "job_done", job=job_id,
                  images=job.images_done, n_shards=job.n_shards)
        return True

    def fail(self, job_id: str, reason: str) -> None:
        """Terminal failure (unknown model, engine gone): the job stops
        scheduling and reports ``failed`` with the reason."""
        with self._lock:
            job = self._jobs[job_id]
            if job.done:
                return
            job.error = reason
            job.done = True
        self._append(job_id, {"kind": "failed", "job": job_id,
                              "reason": reason, "ts": time.time()})
        event(_log, "job_failed", job=job_id, reason=reason)

    def _evict_locked(self, job: Job) -> None:
        # guarded-by: _lock.  Spill least-recently-read payloads past
        # the cache bound; only shards with a durable ledger record are
        # eligible (memory-only stores and pinned shards keep theirs)
        cap = self.max_cached_shards
        if not self.root or cap <= 0:
            return
        for i in list(job.results):
            if len(job.results) <= cap:
                break
            if i in job.pinned:
                continue
            del job.results[i]
            self.spilled_shards += 1

    def _shard_offsets(self, job_id: str, wanted: set) -> dict:
        """One pass over the job's ledger → byte offset of each wanted
        shard record, so streaming re-reads spilled payloads with one
        seek apiece instead of holding the whole file in memory."""
        offsets: dict[int, int] = {}
        if not self.root or not wanted:
            return offsets
        try:
            # manual tell/readline loop: line iteration disables tell()
            with open(self._path(job_id), encoding="utf-8") as f:
                pos = f.tell()
                line = f.readline()
                while line:
                    if '"shard"' in line:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            rec = None
                        if isinstance(rec, dict) \
                                and rec.get("kind") == "shard":
                            idx = rec.get("index")
                            if idx in wanted and idx not in offsets:
                                offsets[idx] = pos
                    pos = f.tell()
                    line = f.readline()
        except OSError:
            return {}
        return offsets

    def _read_shard(self, job_id: str, offset: int) -> list | None:
        try:
            with open(self._path(job_id), encoding="utf-8") as f:
                f.seek(offset)
                rec = json.loads(f.readline())
            res = rec.get("results")
            return res if isinstance(res, list) else None
        except (OSError, ValueError, AttributeError):
            return None

    def results_items(self, job_id: str):
        """Completed results in manifest order — the contiguous shard
        prefix only, so a partially-drained job streams a stable,
        in-order, never-repeated prefix.  Yields ``(global_index,
        result_dict)``.

        Cached shards stream from memory (refreshing their LRU slot);
        spilled shards stream back from the JSONL ledger via a one-pass
        byte-offset index + per-shard seek, so a bulk job's full result
        set never has to fit in memory at once."""
        with self._lock:
            job = self._jobs[job_id]
            contiguous = 0
            while contiguous in job.shards_done:
                contiguous += 1
            cached: dict[int, list] = {}
            for i in list(job.results):
                if i < contiguous:
                    cached[i] = job.results[i]
                    job.results.move_to_end(i)  # reading = recent use
        missing = set(range(contiguous)) - set(cached)
        offsets = self._shard_offsets(job_id, missing)
        idx = 0
        for i in range(contiguous):
            shard = cached.get(i)
            if shard is None:
                off = offsets.get(i)
                shard = self._read_shard(job_id, off) \
                    if off is not None else None
            if shard is None:
                # spilled payload unreadable (ledger pruned/corrupt):
                # end the stable prefix here rather than renumber the
                # rows after a gap
                event(_log, "job_results_gap", job=job_id, shard=i)
                break
            for item in shard:
                yield idx, item
                idx += 1

    def stats(self) -> dict:
        with self._lock:
            states = {"pending": 0, "running": 0, "done": 0, "failed": 0}
            images = 0
            for job in self._jobs.values():
                states[job._state()] += 1
                images += job.images_done
            return {"jobs_total": len(self._jobs),
                    "submitted": self.submitted,
                    "resumed": self.resumed,
                    "replayed_shards": self.replayed_shards,
                    "spilled_shards": self.spilled_shards,
                    "cached_shards": sum(len(j.results)
                                         for j in self._jobs.values()),
                    "images_done": images,
                    "write_errors": self.write_errors,
                    "torn_lines": self.torn_lines,
                    "states": states,
                    "durable": bool(self.root)}
