"""Confidence-routed model cascade: serve the cheapest model that is
sure.

Classic production-vision economics (ROADMAP): the zoo spans ~50× in
compute for the same task, and most traffic doesn't need the big
model.  The ``CascadeRouter`` layers on the multi-model plane
(serve/models.py) and routes every request addressed to the BIG model
name through an N-TIER CHAIN of cheaper tiers first
(``--cascade t0:t1:...:big``); a request walks the chain front-to-back
and escalates past each tier whose confidence falls below that HOP's
*calibrated* threshold, with the final tier always authoritative.

Addressing contract: clients name the big model — that name is the
quality contract — and the cascade transparently answers from the
cheapest tier that is confident, reporting which tier actually
answered in the ``X-DVT-Tier`` response header ("front", "t1", ...,
"big").  Requests that name a cheap tier directly bypass the cascade
(every tier is still an ordinary routable model), and "always-big" QoS
tenants (serve/admission.py) force every request straight to the big
tier.

Calibration is per-HOP and inverts the PR 9 shadow-sampling machinery:
every ``sample_period``-th request ARRIVING at hop i dual-runs tier i
AND the big tier — the client gets the big answer (authoritative), and
tier-i-vs-big agreement is recorded into hop i's
``AgreementHistogram`` at tier i's confidence bucket.  Each hop's
threshold is then the smallest confidence whose measured at-or-above
agreement clears ``min_agreement``; because every hop calibrates
against the FINAL tier, serving from any hop claims tier-vs-big
quality directly (no transitivity assumption across hops).  What
"confidence" and "agreement" mean is the verb's business: a
``CascadeWorkloadRule`` (serve/workloads.py) resolved from the big
tier's workload supplies both — classify uses fused top-1
probability + top-1 match, detect uses device-decoded valid-count +
max-score with the greedy-IoU mAP-proxy pairing.

With ``per_class=True`` each hop also keeps a per-CLASS histogram
axis: classes with enough of their own sample get their own
thresholds, so a class the cheap tier is systematically wrong about
escalates at confidences where the pooled threshold would have served
it.  A class without a qualifying sample falls back to the pooled
threshold — and escalates (fail-closed) when that is None too.

Fail-closed is the core safety property, applied per hop: an
UNCALIBRATED hop escalates THROUGH — the request skips that tier
entirely (no wasted compute, no guessed answer) and proceeds down the
chain, so a fully-uncalibrated chain serves everything from big.  Any
tier failure (Shed, Quarantined, raise) escalates the same way.  A
version swap of tier i (reload, promote, revert) resets ONLY hop i's
calibration; a swap of the BIG tier resets every hop (big is every
hop's comparison target).

The escalation decision is device-cheap: cheap classify tiers carry
the fused confidence epilogue (workloads.ClassifyWorkload
.make_epilogue), detect tiers their fused decode epilogue, so the
router reads the signal off the bulk D2H row instead of dense outputs.
An escalated image re-enters the NEXT tier's admission queue carrying
its REMAINING deadline — original budget minus everything earlier
tiers burned — and its original trace span, so a twice-escalated
request never exceeds its original SLO budget and each tier's
admission controller judges it by what's actually left.

Brownout hooks (serve/brownout.py, optional — ``router.brownout``
defaults to None and nothing changes): at L1+ the dual-run calibration
sampling PAUSES at every hop (each skipped slot counted in
``samples_paused``) — under overload the duplicate big-tier run is the
first capacity to reclaim.  At L2+ a non-premium request whose
confidence falls BELOW a hop's calibrated threshold is served that
tier's answer anyway, resolved with a ``<tier>-degraded`` token so the
HTTP layer marks it ``X-DVT-Degraded`` — quality traded for the
escalation's slot, visibly, and only where a threshold exists
(uncalibrated hops stay fail-closed escalate-through: no threshold
means no quality claim to degrade from).  Always-big tenants bypass
both hooks — premium degrades last.

Calibration persists across restarts when ``root`` names a ledger
directory (``<workdir>/_cascade`` in production — the deploy-ledger
JSONL idiom, deploy/history.py): every hop's threshold CHANGE appends
that hop's histogram counts plus the combined digest of ALL tiers,
every version-swap reset appends a reset record naming its hop (or all
hops, for a big swap), and boot replays the tail per hop — a hop's
histogram and threshold are adopted only when the persisted digest
matches EVERY live tier (a reload of ANY tier while down rejects the
whole record), and thresholds are RE-derived from the restored counts
so retuned ``min_agreement`` knobs apply immediately.  Any mismatch
stays fail-closed, exactly as if the ledger did not exist.

All chaining is ``Future.add_done_callback`` — the router never blocks
an engine worker thread.  Lock order: ``CascadeRouter._lock`` is a
LEAF lock; no plane or engine call happens under it.
"""

from __future__ import annotations

import json
import os
import time
from concurrent.futures import Future

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.core.metrics import LatencyHistogram
from deep_vision_tpu.obs.log import event, get_logger
from deep_vision_tpu.serve.admission import Shed
from deep_vision_tpu.serve.faults import Quarantined
from deep_vision_tpu.serve.models import AgreementHistogram
from deep_vision_tpu.serve.workloads import ClassifyWorkload

_log = get_logger("dvt.serve.cascade")

FRONT = "front"
BIG = "big"
#: suffix marking a brownout-L2 answer served BELOW the hop's
#: calibrated threshold — serve/http.py strips it for X-DVT-Tier and
#: adds X-DVT-Degraded: 1
DEGRADED_SUFFIX = "-degraded"
# the tier-0 degraded token, kept as a module constant for import
# compatibility (serve/http.py, tests)
DEGRADED = FRONT + DEGRADED_SUFFIX

_DEFAULT_DEADLINE_MS = 30_000.0


def is_degraded(token: str) -> bool:
    """True for any hop's brownout-L2 degraded tier token."""
    return isinstance(token, str) and token.endswith(DEGRADED_SUFFIX)


def base_tier(token: str) -> str:
    """The answering tier token with any degraded suffix stripped."""
    if is_degraded(token):
        return token[: -len(DEGRADED_SUFFIX)]
    return token


class CascadeSpec:
    """Parsed ``--cascade t0:t1:...:big`` plus the calibration knobs —
    one immutable value the CLI hands to the router and the boot
    print.  Two positional names keep the PR 17 front:big form."""

    def __init__(self, *tiers: str,
                 min_agreement: float = 0.98,
                 sample_period: int = 10,
                 min_sample: int = 200,
                 bins: int = 20,
                 topk: int = 5,
                 per_class: bool = False,
                 class_min_sample: int = 50):
        names = [str(t).strip() for t in tiers]
        if len(names) < 2 or any(not n for n in names) \
                or len(set(names)) != len(names):
            raise ValueError(
                f"cascade needs >= 2 distinct model names, got "
                f"{':'.join(names)!r}")
        self.tiers = tuple(names)
        self.front = names[0]
        self.big = names[-1]
        self.min_agreement = float(min_agreement)
        self.sample_period = max(1, int(sample_period))
        self.min_sample = max(1, int(min_sample))
        self.bins = max(1, int(bins))
        self.topk = max(1, int(topk))
        self.per_class = bool(per_class)
        self.class_min_sample = max(1, int(class_min_sample))

    @classmethod
    def parse(cls, spec: str, **kw) -> "CascadeSpec":
        names = [t.strip() for t in str(spec).split(":")]
        if len(names) < 2:
            raise ValueError(
                f"--cascade wants 't0:t1:...:big', got {spec!r}")
        return cls(*names, **kw)

    @property
    def chain(self) -> str:
        return ":".join(self.tiers)

    def tier_token(self, i: int) -> str:
        """The public tier token for chain position ``i``: "front" for
        tier 0, "t<i>" for mid tiers, "big" for the final tier — the
        X-DVT-Tier header values and the ``served`` stats keys (the
        2-tier tokens are unchanged from PR 17)."""
        if i == len(self.tiers) - 1:
            return BIG
        return FRONT if i == 0 else f"t{i}"

    def describe(self) -> dict:
        return {"front": self.front, "big": self.big,
                "tiers": list(self.tiers),
                "min_agreement": self.min_agreement,
                "sample_period": self.sample_period,
                "min_sample": self.min_sample,
                "bins": self.bins, "topk": self.topk,
                "per_class": self.per_class,
                "class_min_sample": self.class_min_sample}


class _Hop:
    """One hop's calibration state: tier i vs the big tier.  Mutable
    fields are guarded by the router's leaf lock (the histogram has its
    own internal lock)."""

    def __init__(self, index: int, tier: str, token: str,
                 bins: int, per_class: bool):
        self.index = index
        self.tier = tier
        self.token = token
        self.hist = AgreementHistogram(bins=bins, per_class=per_class)
        # None = uncalibrated → fail closed (escalate-through)
        self.threshold: float | None = None
        self.class_thresholds: dict = {}
        self.tick = 0
        self.escalations = 0
        self.samples = 0
        self.samples_discarded = 0
        self.restored = False


class CascadeRouter:
    """Route traffic addressed to ``spec.big`` down the tier chain,
    escalating past each hop whose confidence misses its calibrated
    threshold."""

    def __init__(self, plane, spec: CascadeSpec,
                 root: str | None = None):
        self.plane = plane
        self.spec = spec
        self._lock = new_lock("serve.cascade.CascadeRouter._lock")
        self.hops = [
            _Hop(i, name, spec.tier_token(i), spec.bins, spec.per_class)
            for i, name in enumerate(spec.tiers[:-1])
        ]  # hop mutable state guarded-by: _lock
        self._tokens = [h.token for h in self.hops] + [BIG]
        # optional BrownoutController (serve/brownout.py) — the L1
        # sampling pause and L2 degraded hooks; read racily
        self.brownout = None
        self.served = {t: 0 for t in self._tokens}  # guarded-by: _lock
        self.escalations = 0  # guarded-by: _lock
        self.escalated_shed = 0  # no deadline left mid-chain; guarded-by: _lock
        self.escalated_lowconf = 0  # guarded-by: _lock
        self.escalated_error = 0  # tier Shed/Quarantined/raise; guarded-by: _lock
        self.forced_big = 0  # always-big tenants; guarded-by: _lock
        self.samples = 0  # dual-run calibration requests; guarded-by: _lock
        self.samples_discarded = 0  # guarded-by: _lock
        self.samples_paused = 0  # brownout L1 skipped slots; guarded-by: _lock
        self.degraded_served = 0  # brownout L2 below-threshold answers; guarded-by: _lock
        self.calibrations = 0  # threshold (re)computed; guarded-by: _lock
        self.resets = 0  # version-swap calibration drops; guarded-by: _lock
        self._latency = {t: LatencyHistogram()
                         for t in self._tokens}  # guarded-by: _lock
        self._rule = self._resolve_rule()
        # calibration ledger (None = memory-only, the test default)
        self._root = root
        self.restored = False
        self.ledger_write_errors = 0  # guarded-by: _lock
        if root is not None:
            os.makedirs(root, exist_ok=True)
            self._restore()
        plane.add_version_listener(self._on_version_swap)

    def _resolve_rule(self):
        """The verb's CascadeWorkloadRule, from the BIG tier's workload
        (every tier shares the verb — cli.serve validates the chain).
        Falls back to the classify rule when the plane can't resolve
        the tier yet (bare test planes) — the PR 17 behavior."""
        try:
            rule = self.plane.resolve(self.spec.big) \
                .workload.cascade_rule()
            if rule is not None:
                return rule
        except (KeyError, AttributeError):
            pass
        return ClassifyWorkload().cascade_rule()

    # -- routing table ------------------------------------------------------

    def serves(self, name: str) -> bool:
        """True when requests addressed to ``name`` route through the
        cascade (only the big/logical name; cheap tiers stay directly
        addressable)."""
        return name == self.spec.big

    @property
    def hist(self) -> AgreementHistogram:
        """Hop 0's histogram — the 2-tier compatibility alias."""
        return self.hops[0].hist

    @property
    def threshold(self) -> float | None:
        """Hop 0's pooled threshold — the 2-tier compatibility alias."""
        with self._lock:
            return self.hops[0].threshold

    def params_digest(self) -> str | None:
        """Combined version identity of ALL tiers — the response-cache
        digest slot and the calibration-ledger key, so a reload of ANY
        tier stops old cache keys and persisted calibrations from
        matching.  None (uncacheable) unless every tier carries a
        digest, same contract as a single model without one."""
        digests = []
        for name in self.spec.tiers:
            try:
                d = getattr(self.plane.resolve(name),
                            "params_digest", None)
            except KeyError:
                return None
            if not d:
                return None
            digests.append(d)
        return "+".join(digests)

    def canary_active(self) -> bool:
        """Cache inserts pause while ANY tier runs a canary — a
        canary-served answer must not be filed under the steady-state
        combined digest."""
        return any(self.plane.canary_active(name)
                   for name in self.spec.tiers)

    def describe_member(self, name: str) -> dict | None:
        """The ``cascade`` block for ``name``'s /v1/models entry: chain
        membership, hop role, and where that hop's threshold came from
        — None for models outside the chain."""
        if name not in self.spec.tiers:
            return None
        i = self.spec.tiers.index(name)
        out = {"chain": self.spec.chain, "tier": self.spec.tier_token(i)}
        if name == self.spec.big:
            out.update(role="big", hop=None,
                       threshold_source="authoritative")
            return out
        out["role"] = "front" if i == 0 else "mid"
        out["hop"] = i
        hop = self.hops[i]
        with self._lock:
            calibrated = hop.threshold is not None \
                or bool(hop.class_thresholds)
            restored = hop.restored
        out["threshold_source"] = (
            "restored" if restored else
            "calibrated" if calibrated else "uncalibrated")
        return out

    # -- request path -------------------------------------------------------

    def submit(self, image, deadline_ms: float | None = None,
               span=None, force_big: bool = False) -> Future:
        """Route one request.  The future resolves to ``(tier, row)``
        where ``tier`` is the answering tier's token ("front"/"t1"/...
        /"big", the ``X-DVT-Tier`` header; a ``-degraded`` suffix marks
        brownout-L2 answers) and ``row`` is exactly what that tier's
        engine produced — including Shed/Quarantined verdicts, which
        the HTTP layer maps to status codes the same way as for a plain
        model."""
        fut: Future = Future()
        t0 = time.monotonic()
        if deadline_ms is None:
            deadline_ms = _DEFAULT_DEADLINE_MS
        deadline_ms = float(deadline_ms)
        if force_big:
            with self._lock:
                self.forced_big += 1
            if span is not None:
                span.mark("cascade_forced_big")
            self._submit_final(image, deadline_ms, span, fut, t0)
            return fut
        self._enter_hop(0, image, deadline_ms, deadline_ms, span, fut,
                        t0)
        return fut

    def infer(self, image, deadline_ms: float | None = None,
              timeout: float | None = 30.0, span=None,
              force_big: bool = False):
        """Blocking wrapper → ``(tier, row)``."""
        return self.submit(image, deadline_ms, span=span,
                           force_big=force_big).result(timeout)

    def _enter_hop(self, i: int, image, deadline_ms, budget_ms, span,
                   fut: Future, t0):
        """One request arrives at hop ``i`` with ``budget_ms`` of its
        original ``deadline_ms`` left: maybe dual-run a calibration
        sample, escalate-through when the hop is uncalibrated, else run
        the tier and decide on its answer."""
        if i >= len(self.hops):
            self._submit_final(image, budget_ms, span, fut, t0)
            return
        hop = self.hops[i]
        bo = self.brownout
        with self._lock:
            hop.tick += 1
            tick = hop.tick
            calibrated = hop.threshold is not None \
                or bool(hop.class_thresholds)
        if tick % self.spec.sample_period == 0:
            if bo is None or not bo.at_least(1):
                self._submit_sample(hop, image, budget_ms, span, fut,
                                    t0)
                return
            # brownout L1+: the dual-run sample is optional work —
            # skip the slot and route the request like any other
            with self._lock:
                self.samples_paused += 1
        if not calibrated:
            # fail closed: an uncalibrated hop escalates THROUGH — the
            # tier is not run, no compute wasted on an answer nobody
            # would trust
            self._enter_hop(i + 1, image, deadline_ms, budget_ms, span,
                            fut, t0)
            return
        # decided at submit time so one request sees one policy even
        # if the ladder moves while the tier runs
        degrade = bo is not None and bo.at_least(2)
        tfut = self.plane.submit(hop.tier, image, budget_ms, span=span)
        tfut.add_done_callback(
            lambda f: self._hop_done(hop, f, image, deadline_ms, span,
                                     fut, t0, degrade))

    def _submit_final(self, image, budget_ms, span, fut: Future, t0):
        bfut = self.plane.submit(self.spec.big, image, budget_ms,
                                 span=span)
        bfut.add_done_callback(lambda f: self._finish(f, fut, t0, BIG))

    def _threshold_for(self, hop: _Hop, cls) -> float | None:
        """The threshold governing this answer: the class's own entry
        when the per-class axis has a qualifying sample for it — which
        may be ``None`` (a measured-bad class fails closed and always
        escalates) — else the hop's pooled threshold (None → escalate,
        fail-closed)."""
        with self._lock:
            if cls is not None and hop.class_thresholds:
                key = int(cls)
                if key in hop.class_thresholds:
                    return hop.class_thresholds[key]
            return hop.threshold

    def _hop_done(self, hop: _Hop, tfut: Future, image, deadline_ms,
                  span, fut: Future, t0, degrade: bool = False):
        """Tier ``hop.index`` answered (engine worker thread — never
        block): serve it when confident, escalate otherwise."""
        try:
            row = tfut.result()
        except Exception:  # noqa: BLE001 — tier failure must not reach the client; big owns the contract
            self._escalate(hop, image, deadline_ms, span, fut, t0,
                           "error")
            return
        if isinstance(row, (Shed, Quarantined)):
            # tier shed/quarantined: the request still deserves the
            # rest of the chain — the client addressed the big name
            self._escalate(hop, image, deadline_ms, span, fut, t0,
                           "error")
            return
        cls, conf = self._rule.signal(row)
        if conf is None:
            # no signal on the row (a tier missing its epilogue, a
            # foreign shape): never guess — escalate
            self._escalate(hop, image, deadline_ms, span, fut, t0,
                           "error")
            return
        thr = self._threshold_for(hop, cls)
        if thr is not None and conf >= thr:
            if span is not None:
                span.mark(f"cascade_{hop.token}_served")
            self._finish_row(row, fut, t0, hop.token)
            return
        if degrade and thr is not None:
            # brownout L2: trade quality for the escalation's slot —
            # this tier's answer stands, marked degraded
            with self._lock:
                self.degraded_served += 1
            if span is not None:
                span.mark("cascade_degraded")
            self._finish_row(row, fut, t0, hop.token, degraded=True)
            return
        self._escalate(hop, image, deadline_ms, span, fut, t0,
                       "lowconf")

    def _escalate(self, hop: _Hop, image, deadline_ms, span,
                  fut: Future, t0, why: str):
        """Re-enter the next hop with the REMAINING deadline — original
        budget minus everything earlier tiers burned — so a
        twice-escalated request never exceeds its original SLO
        budget."""
        with self._lock:
            self.escalations += 1
            hop.escalations += 1
            if why == "lowconf":
                self.escalated_lowconf += 1
            else:
                self.escalated_error += 1
        remaining_ms = deadline_ms - (time.monotonic() - t0) * 1e3
        if remaining_ms <= 0.0:
            with self._lock:
                self.escalated_shed += 1
            self._finish_row(
                Shed("deadline",
                     f"cascade escalation at hop {hop.index}: earlier "
                     f"tiers consumed the {deadline_ms:.0f}ms budget"),
                fut, t0, BIG)
            return
        if span is not None:
            span.mark("cascade_escalate")
        self._enter_hop(hop.index + 1, image, deadline_ms,
                        remaining_ms, span, fut, t0)

    def _finish(self, inner: Future, fut: Future, t0, tier: str):
        try:
            row = inner.result()
        except Exception as e:  # noqa: BLE001 — propagate the tier's failure as-is
            fut.set_exception(e)
            return
        self._finish_row(row, fut, t0, tier)

    def _finish_row(self, row, fut: Future, t0, tier: str,
                    degraded: bool = False):
        with self._lock:
            self.served[tier] += 1
            self._latency[tier].record(time.monotonic() - t0)
        fut.set_result(
            (tier + DEGRADED_SUFFIX if degraded else tier, row))

    # -- calibration --------------------------------------------------------

    def _submit_sample(self, hop: _Hop, image, budget_ms, span,
                       fut: Future, t0):
        """Dual-run calibration sample at hop ``hop.index``: the tier
        AND the big tier execute, the client gets the big answer
        (authoritative), and tier-vs-big agreement lands in the hop's
        histogram at the tier's confidence bucket.  Same holder-pair
        idiom as the plane's shadow compare."""
        with self._lock:
            self.samples += 1
            hop.samples += 1
        tfut = self.plane.submit(hop.tier, image, budget_ms)
        bfut = self.plane.submit(self.spec.big, image, budget_ms,
                                 span=span)
        holder: dict = {}

        def arrived(which, f):
            with self._lock:
                holder[which] = f
                ready = "f" in holder and "b" in holder \
                    and not holder.get("_done")
                if ready:
                    holder["_done"] = True
            if ready:
                self._record_sample(hop, holder["f"], holder["b"])

        tfut.add_done_callback(lambda f: arrived("f", f))
        bfut.add_done_callback(lambda f: arrived("b", f))
        bfut.add_done_callback(lambda f: self._finish(f, fut, t0, BIG))

    def _record_sample(self, hop: _Hop, tfut: Future, bfut: Future):
        try:
            tr, br = tfut.result(), bfut.result()
        except Exception:  # noqa: BLE001 — either side failed: nothing to compare
            with self._lock:
                self.samples_discarded += 1
                hop.samples_discarded += 1
            return
        cls, conf = self._rule.signal(tr)
        agreed = self._rule.agree(tr, br)
        if conf is None or agreed is None:
            with self._lock:
                self.samples_discarded += 1
                hop.samples_discarded += 1
            return
        hop.hist.record(conf, agreed, cls=cls)
        self._recalibrate(hop)

    def _recalibrate(self, hop: _Hop | None = None):
        """Recompute one hop's thresholds from its histogram (default
        hop 0, the 2-tier compatibility surface) and persist on
        change."""
        if hop is None:
            hop = self.hops[0]
        thr = hop.hist.threshold(self.spec.min_agreement,
                                 self.spec.min_sample)
        cls_thr = {}
        if self.spec.per_class:
            cls_thr = hop.hist.class_thresholds(
                self.spec.min_agreement, self.spec.class_min_sample)
        with self._lock:
            changed = thr != hop.threshold \
                or cls_thr != hop.class_thresholds
            hop.threshold = thr
            hop.class_thresholds = cls_thr
            if changed:
                self.calibrations += 1
        if changed:
            event(_log, "cascade_calibrated",
                  chain=self.spec.chain, hop=hop.index, tier=hop.tier,
                  threshold=thr, classes=len(cls_thr),
                  samples=hop.hist.stats()["samples"])
            h = hop.hist.stats()
            rec = {"event": "calibrated",
                   "hop": hop.index,
                   "tier": hop.tier,
                   "threshold": thr,
                   "digest": self.params_digest(),
                   "bins": h["bins"],
                   "total": h["total"],
                   "agree": h["agree"]}
            if self.spec.per_class:
                rec["class_counts"] = hop.hist.class_counts()
            self._append_ledger(rec)

    def _reset_hop(self, hop: _Hop):
        hop.hist.reset()
        with self._lock:
            had = hop.threshold is not None \
                or bool(hop.class_thresholds)
            hop.threshold = None
            hop.class_thresholds = {}
            hop.restored = False
            self.resets += 1
        return had

    def _on_version_swap(self, name: str):
        """Plane version listener: a reload/promote/revert of tier i
        invalidates ONLY hop i's calibration (its answer distribution
        changed; other hops compare different tiers against big) —
        while a swap of the BIG tier invalidates every hop (big is
        every hop's comparison target).  Fail closed and resample."""
        if name not in self.spec.tiers:
            return
        if name == self.spec.big:
            had = False
            for hop in self.hops:
                had = self._reset_hop(hop) or had
            self._append_ledger({"event": "reset", "model": name})
        else:
            hop = self.hops[self.spec.tiers.index(name)]
            had = self._reset_hop(hop)
            self._append_ledger({"event": "reset", "model": name,
                                 "hop": hop.index})
        if had:
            event(_log, "cascade_recalibrating", model=name,
                  chain=self.spec.chain)

    # -- calibration persistence --------------------------------------------

    def _ledger_path(self) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in "+".join(self.spec.tiers))
        return os.path.join(self._root, f"{safe}.jsonl")

    def _append_ledger(self, record: dict):
        """Append one immutable calibration record (deploy-ledger
        idiom: write failures are counted, never raised — the ledger
        observes, it never gates serving)."""
        if self._root is None:
            return
        record = {"ts": round(time.time(), 3),
                  "front": self.spec.front, "big": self.spec.big,
                  "tiers": list(self.spec.tiers),
                  **record}
        try:
            with open(self._ledger_path(), "a", encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
        except OSError as e:
            with self._lock:
                self.ledger_write_errors += 1
            event(_log, "cascade_ledger_write_failed",
                  error=f"{type(e).__name__}: {e}")

    def _restore(self):
        """Boot-time replay: adopt each hop's newest calibration iff
        its params digest matches EVERY live tier — the ledger key
        covers the whole chain, so ANY tier reloaded while down rejects
        the record.  A trailing reset for the hop, a digest mismatch, a
        torn tail line, or no ledger at all each leave that hop exactly
        where it started — uncalibrated and fail-closed."""
        last: dict = {}  # hop index -> last record affecting it
        try:
            with open(self._ledger_path(), encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail line from a crash
                    ev = rec.get("event")
                    if ev == "calibrated":
                        hop = int(rec.get("hop", 0))
                        if 0 <= hop < len(self.hops):
                            last[hop] = rec
                    elif ev == "reset":
                        hop = rec.get("hop")
                        if hop is None:
                            # a big-tier swap (or a PR 18 2-tier record
                            # without hop info): every hop resets —
                            # unless it named the front tier, which
                            # only ever had hop 0
                            if rec.get("model") == self.spec.front:
                                last[0] = rec
                            else:
                                last = {i: rec
                                        for i in range(len(self.hops))}
                        elif 0 <= int(hop) < len(self.hops):
                            last[int(hop)] = rec
        except OSError:
            return  # no ledger yet — first boot
        digest = self.params_digest()
        restored_any = False
        for i, rec in sorted(last.items()):
            if rec.get("event") != "calibrated":
                continue
            hop = self.hops[i]
            if digest is None or rec.get("digest") != digest:
                event(_log, "cascade_restore_stale",
                      chain=self.spec.chain, hop=i,
                      ledger_digest=rec.get("digest"),
                      live_digest=digest)
                continue
            try:
                hop.hist.restore(rec["total"], rec["agree"],
                                 per_class=rec.get("class_counts"))
            except (KeyError, TypeError, ValueError) as e:
                event(_log, "cascade_restore_invalid", hop=i,
                      error=f"{type(e).__name__}: {e}")
                continue
            # RE-derive thresholds from the restored counts instead of
            # trusting the stored ones: retuned --cascade-min-agreement
            # / min-sample knobs apply to the old sample immediately,
            # and a sample now too thin for the knobs stays fail-closed
            thr = hop.hist.threshold(self.spec.min_agreement,
                                     self.spec.min_sample)
            cls_thr = {}
            if self.spec.per_class:
                cls_thr = hop.hist.class_thresholds(
                    self.spec.min_agreement,
                    self.spec.class_min_sample)
            calibrated = thr is not None or bool(cls_thr)
            with self._lock:
                hop.threshold = thr
                hop.class_thresholds = cls_thr
                hop.restored = calibrated
            restored_any = restored_any or calibrated
            event(_log, "cascade_restored",
                  chain=self.spec.chain, hop=i, tier=hop.tier,
                  threshold=thr, classes=len(cls_thr),
                  samples=hop.hist.stats()["samples"],
                  calibrated=calibrated)
        with self._lock:
            self.restored = restored_any

    # -- observability ------------------------------------------------------

    def _hop_stats(self, hop: _Hop) -> dict:
        """One hop's block for ``stats()["hops"]`` — caller holds no
        locks; this takes the router lock briefly."""
        hstats = hop.hist.stats()
        with self._lock:
            out = {
                "hop": hop.index,
                "tier": hop.tier,
                "token": hop.token,
                "threshold": hop.threshold,
                "calibrated": hop.threshold is not None
                or bool(hop.class_thresholds),
                "class_thresholds": {str(c): v for c, v in
                                     sorted(hop.class_thresholds
                                            .items())},
                "restored": hop.restored,
                "escalations": hop.escalations,
                "samples": hop.samples,
                "samples_discarded": hop.samples_discarded,
            }
        out["agreement"] = hstats["agreement"]
        out["sample_size"] = hstats["samples"]
        return out

    def stats(self) -> dict:
        """The reserved ``cascade`` block in /v1/stats — serve/http.py
        renders the ``dvt_cascade_*`` series from it, and the gateway
        folds it into its fleet view.  Top-level threshold/agreement
        keys mirror hop 0 (the PR 17 2-tier surface); ``hops`` carries
        the full per-hop picture."""
        hop0 = self.hops[0]
        h0stats = hop0.hist.stats()
        hop_blocks = [self._hop_stats(h) for h in self.hops]
        with self._lock:
            served = dict(self.served)
            routed = sum(served[t] for t in served if t != BIG) \
                + self.escalated_lowconf + self.escalated_shed
            out = {
                "front": self.spec.front,
                "big": self.spec.big,
                "tiers": list(self.spec.tiers),
                "per_class": self.spec.per_class,
                "threshold": hop0.threshold,
                "calibrated": hop0.threshold is not None
                or bool(hop0.class_thresholds),
                "min_agreement": self.spec.min_agreement,
                "sample_period": self.spec.sample_period,
                "min_sample": self.spec.min_sample,
                "served": served,
                "escalations": self.escalations,
                "escalated_lowconf": self.escalated_lowconf,
                "escalated_error": self.escalated_error,
                "escalated_shed": self.escalated_shed,
                # of the requests cheap tiers actually judged, how
                # many went upstairs — the live economics gauge
                "escalation_rate": ((self.escalated_lowconf
                                     + self.escalated_shed) / routed)
                if routed else None,
                "forced_big": self.forced_big,
                "samples": self.samples,
                "samples_discarded": self.samples_discarded,
                "samples_paused": self.samples_paused,
                "degraded_served": self.degraded_served,
                "calibrations": self.calibrations,
                "resets": self.resets,
                "restored": self.restored,
                "ledger_root": self._root,
                "ledger_write_errors": self.ledger_write_errors,
                "agreement": h0stats["agreement"],
                "agreement_bins": {"bins": h0stats["bins"],
                                   "samples": h0stats["samples"],
                                   "total": h0stats["total"],
                                   "agree": h0stats["agree"]},
                "latency": {t: h.percentiles()
                            for t, h in self._latency.items()},
                "latency_hist": {t: h.state_dict()
                                 for t, h in self._latency.items()},
            }
        out["hops"] = hop_blocks
        return out
