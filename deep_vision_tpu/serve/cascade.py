"""Confidence-routed model cascade: serve the cheap model when it's
sure.

Classic production-vision economics (ROADMAP): the zoo spans ~50× in
compute for the same task, and most traffic doesn't need the big
model.  The ``CascadeRouter`` layers on the multi-model plane
(serve/models.py) and routes every classify request addressed to the
BIG model name through a cheap FRONT tier first; the request only
escalates to the big tier when the front's top-1 softmax confidence
falls below a *calibrated* threshold.

Addressing contract: clients name the big model — that name is the
quality contract — and the cascade transparently answers from the
front tier when it is confident, reporting which tier actually
answered in the ``X-DVT-Tier`` response header.  Requests that name
the front model directly bypass the cascade (it is still an ordinary
routable model), and "always-big" QoS tenants (serve/admission.py)
force every request straight to the big tier.

Calibration inverts the PR 9 shadow-sampling machinery: every
``sample_period``-th request runs BOTH tiers — the client gets the big
tier's answer (authoritative), and the front-vs-big top-1 agreement is
recorded into an ``AgreementHistogram`` at the front's confidence
bucket.  The threshold is then the smallest confidence whose measured
at-or-above agreement clears ``min_agreement``.  Fail-closed is the
core safety property: with no threshold (sample thinner than
``min_sample``, or no confidence level agrees enough) ALL traffic goes
to the big tier, and a version swap of either tier (reload, promote,
revert) resets calibration through the plane's version listener —
new weights shift the confidence distribution, so the old threshold is
invalid until the sample rebuilds.

The escalation decision is device-cheap: the front tier's bucket
programs carry a fused confidence epilogue
(workloads.ClassifyWorkload.make_epilogue, the PR 15 pose-epilogue
pattern) so the router reads ``(top1_class, top1_prob)`` off the bulk
D2H row instead of the dense logits.  An escalated image re-enters the
big tier's admission queue carrying its REMAINING deadline — original
budget minus the time the front attempt burned — and its original
trace span, so a cascaded request never gets double SLO budget and the
big tier's admission controller judges it by what's actually left.

Brownout hooks (serve/brownout.py, optional — ``router.brownout``
defaults to None and nothing changes): at L1+ the dual-run calibration
sampling PAUSES (each skipped slot counted in ``samples_paused``; the
would-be sample routes like ordinary traffic) — under overload the
duplicate big-tier run is the first capacity to reclaim.  At L2+ a
non-premium request whose front confidence falls BELOW the calibrated
threshold is served the front answer anyway, resolved with the
``DEGRADED`` tier token so the HTTP layer marks it ``X-DVT-Degraded``
— quality traded for the escalation's big-tier slot, visibly, and
only when a threshold exists (uncalibrated traffic stays fail-closed
all-big: no threshold means no quality claim to degrade from).
Always-big tenants bypass both hooks — premium degrades last.

Calibration persists across restarts when ``root`` names a ledger
directory (``<workdir>/_cascade`` in production — the deploy-ledger
JSONL idiom, deploy/history.py): every threshold CHANGE appends the
histogram counts plus the combined params digest, every version-swap
reset appends a reset record, and boot replays the tail — the
histogram and threshold are adopted only when the persisted digest
matches both live tiers (and the threshold is RE-derived from the
restored counts, so retuned ``min_agreement`` knobs apply
immediately).  Any mismatch stays fail-closed, exactly as if the
ledger did not exist.

All chaining is ``Future.add_done_callback`` — the router never blocks
an engine worker thread.  Lock order: ``CascadeRouter._lock`` is a
LEAF lock; no plane or engine call happens under it.
"""

from __future__ import annotations

import json
import os
import time
from concurrent.futures import Future

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.core.metrics import LatencyHistogram
from deep_vision_tpu.obs.log import event, get_logger
from deep_vision_tpu.serve.admission import Shed
from deep_vision_tpu.serve.faults import Quarantined
from deep_vision_tpu.serve.models import AgreementHistogram
from deep_vision_tpu.serve.workloads import ClassifyWorkload

_log = get_logger("dvt.serve.cascade")

FRONT = "front"
BIG = "big"
# tier token for a brownout-L2 front answer served BELOW the
# calibrated threshold — serve/http.py maps it to X-DVT-Tier: front
# plus X-DVT-Degraded: 1
DEGRADED = "front-degraded"

_DEFAULT_DEADLINE_MS = 30_000.0


class CascadeSpec:
    """Parsed ``--cascade front:big`` plus the calibration knobs — one
    immutable value the CLI hands to the router and the boot print."""

    def __init__(self, front: str, big: str, *,
                 min_agreement: float = 0.98,
                 sample_period: int = 10,
                 min_sample: int = 200,
                 bins: int = 20,
                 topk: int = 5):
        if not front or not big or front == big:
            raise ValueError(
                f"cascade needs two distinct model names, got "
                f"{front!r}:{big!r}")
        self.front = front
        self.big = big
        self.min_agreement = float(min_agreement)
        self.sample_period = max(1, int(sample_period))
        self.min_sample = max(1, int(min_sample))
        self.bins = max(1, int(bins))
        self.topk = max(1, int(topk))

    @classmethod
    def parse(cls, spec: str, **kw) -> "CascadeSpec":
        front, sep, big = str(spec).partition(":")
        if not sep:
            raise ValueError(
                f"--cascade wants 'front:big', got {spec!r}")
        return cls(front.strip(), big.strip(), **kw)

    def describe(self) -> dict:
        return {"front": self.front, "big": self.big,
                "min_agreement": self.min_agreement,
                "sample_period": self.sample_period,
                "min_sample": self.min_sample,
                "bins": self.bins, "topk": self.topk}


class CascadeRouter:
    """Route classify traffic addressed to ``spec.big`` through the
    front tier, escalating below the calibrated threshold."""

    def __init__(self, plane, spec: CascadeSpec,
                 root: str | None = None):
        self.plane = plane
        self.spec = spec
        self.hist = AgreementHistogram(bins=spec.bins)
        self._lock = new_lock("serve.cascade.CascadeRouter._lock")
        # None = uncalibrated → fail closed (all-big); guarded-by: _lock
        self._threshold: float | None = None
        self._tick = 0  # guarded-by: _lock
        # optional BrownoutController (serve/brownout.py) — the L1
        # sampling pause and L2 degraded-front hooks; read racily
        self.brownout = None
        self.served = {FRONT: 0, BIG: 0}  # guarded-by: _lock
        self.escalations = 0  # guarded-by: _lock
        self.escalated_shed = 0  # no deadline left post-front; guarded-by: _lock
        self.escalated_lowconf = 0  # guarded-by: _lock
        self.escalated_error = 0  # front Shed/Quarantined/raise; guarded-by: _lock
        self.forced_big = 0  # always-big tenants; guarded-by: _lock
        self.samples = 0  # dual-run calibration requests; guarded-by: _lock
        self.samples_discarded = 0  # guarded-by: _lock
        self.samples_paused = 0  # brownout L1 skipped slots; guarded-by: _lock
        self.degraded_served = 0  # brownout L2 below-threshold fronts; guarded-by: _lock
        self.calibrations = 0  # threshold (re)computed; guarded-by: _lock
        self.resets = 0  # version-swap calibration drops; guarded-by: _lock
        self._latency = {FRONT: LatencyHistogram(),
                         BIG: LatencyHistogram()}  # guarded-by: _lock
        self._top1 = ClassifyWorkload.top1
        # calibration ledger (None = memory-only, the test default)
        self._root = root
        self.restored = False
        self.ledger_write_errors = 0  # guarded-by: _lock
        if root is not None:
            os.makedirs(root, exist_ok=True)
            self._restore()
        plane.add_version_listener(self._on_version_swap)

    # -- routing table ------------------------------------------------------

    def serves(self, name: str) -> bool:
        """True when requests addressed to ``name`` route through the
        cascade (only the big/logical name; the front model stays
        directly addressable)."""
        return name == self.spec.big

    @property
    def threshold(self) -> float | None:
        with self._lock:
            return self._threshold

    def params_digest(self) -> str | None:
        """Combined version identity of BOTH tiers — the response-cache
        digest slot, so a reload of either tier stops old keys from
        matching.  None (uncacheable) unless both tiers carry digests,
        same contract as a single model without one."""
        try:
            f = getattr(self.plane.resolve(self.spec.front),
                        "params_digest", None)
            b = getattr(self.plane.resolve(self.spec.big),
                        "params_digest", None)
        except KeyError:
            return None
        if not f or not b:
            return None
        return f"{f}+{b}"

    def canary_active(self) -> bool:
        """Cache inserts pause while EITHER tier runs a canary — a
        canary-served answer must not be filed under the steady-state
        combined digest."""
        return self.plane.canary_active(self.spec.front) \
            or self.plane.canary_active(self.spec.big)

    # -- request path -------------------------------------------------------

    def submit(self, image, deadline_ms: float | None = None,
               span=None, force_big: bool = False) -> Future:
        """Route one request.  The future resolves to ``(tier, row)``
        where ``tier`` is "front"/"big" (the ``X-DVT-Tier`` header) and
        ``row`` is exactly what the named tier's engine produced —
        including Shed/Quarantined verdicts, which the HTTP layer maps
        to status codes the same way as for a plain model."""
        fut: Future = Future()
        t0 = time.monotonic()
        if deadline_ms is None:
            deadline_ms = _DEFAULT_DEADLINE_MS
        deadline_ms = float(deadline_ms)
        with self._lock:
            self._tick += 1
            tick = self._tick
            thr = self._threshold
            if force_big:
                self.forced_big += 1
        if force_big:
            if span is not None:
                span.mark("cascade_forced_big")
            self._submit_big(image, deadline_ms, span, fut, t0)
            return fut
        bo = self.brownout
        if tick % self.spec.sample_period == 0:
            if bo is None or not bo.at_least(1):
                self._submit_sample(image, deadline_ms, span, fut, t0)
                return fut
            # brownout L1+: the dual-run sample is optional work —
            # skip the slot and route the request like any other
            with self._lock:
                self.samples_paused += 1
        if thr is None:
            # fail closed: uncalibrated traffic belongs to the big tier
            self._submit_big(image, deadline_ms, span, fut, t0)
            return fut
        # decided at submit time so one request sees one policy even
        # if the ladder moves while the front tier runs
        degrade = bo is not None and bo.at_least(2)
        ffut = self.plane.submit(self.spec.front, image, deadline_ms,
                                 span=span)
        ffut.add_done_callback(
            lambda f: self._front_done(f, image, deadline_ms, span,
                                       fut, t0, thr, degrade))
        return fut

    def infer(self, image, deadline_ms: float | None = None,
              timeout: float | None = 30.0, span=None,
              force_big: bool = False):
        """Blocking wrapper → ``(tier, row)``."""
        return self.submit(image, deadline_ms, span=span,
                           force_big=force_big).result(timeout)

    def _submit_big(self, image, deadline_ms, span, fut: Future, t0):
        bfut = self.plane.submit(self.spec.big, image, deadline_ms,
                                 span=span)
        bfut.add_done_callback(lambda f: self._finish(f, fut, t0, BIG))

    def _front_done(self, ffut: Future, image, deadline_ms, span,
                    fut: Future, t0, thr: float,
                    degrade: bool = False):
        """Front answered (engine worker thread — never block): serve
        it when confident, escalate otherwise."""
        try:
            row = ffut.result()
        except Exception:  # noqa: BLE001 — front failure must not reach the client; big owns the contract
            self._escalate(image, deadline_ms, span, fut, t0, "error")
            return
        if isinstance(row, (Shed, Quarantined)):
            # front shed/quarantined: the request still deserves the
            # big tier's attempt — the client addressed the big name
            self._escalate(image, deadline_ms, span, fut, t0, "error")
            return
        _, conf = self._top1(row)
        if conf is None:
            # no confidence on the row (front missing its epilogue and
            # a non-classify shape): never guess — escalate
            self._escalate(image, deadline_ms, span, fut, t0, "error")
            return
        if conf >= thr:
            if span is not None:
                span.mark("cascade_front_served")
            self._finish_row(row, fut, t0, FRONT)
            return
        if degrade:
            # brownout L2: trade quality for the escalation's big-tier
            # slot — the front answer stands, marked degraded
            with self._lock:
                self.degraded_served += 1
            if span is not None:
                span.mark("cascade_degraded_front")
            self._finish_row(row, fut, t0, FRONT, degraded=True)
            return
        self._escalate(image, deadline_ms, span, fut, t0, "lowconf")

    def _escalate(self, image, deadline_ms, span, fut: Future, t0,
                  why: str):
        """Re-admit on the big tier with the REMAINING deadline —
        original budget minus the front attempt — so escalation never
        doubles the SLO budget."""
        with self._lock:
            self.escalations += 1
            if why == "lowconf":
                self.escalated_lowconf += 1
            else:
                self.escalated_error += 1
        remaining_ms = deadline_ms - (time.monotonic() - t0) * 1e3
        if remaining_ms <= 0.0:
            with self._lock:
                self.escalated_shed += 1
            self._finish_row(
                Shed("deadline",
                     f"cascade escalation: front attempt consumed the "
                     f"{deadline_ms:.0f}ms budget"),
                fut, t0, BIG)
            return
        if span is not None:
            span.mark("cascade_escalate")
        bfut = self.plane.submit(self.spec.big, image, remaining_ms,
                                 span=span)
        bfut.add_done_callback(lambda f: self._finish(f, fut, t0, BIG))

    def _finish(self, inner: Future, fut: Future, t0, tier: str):
        try:
            row = inner.result()
        except Exception as e:  # noqa: BLE001 — propagate the tier's failure as-is
            fut.set_exception(e)
            return
        self._finish_row(row, fut, t0, tier)

    def _finish_row(self, row, fut: Future, t0, tier: str,
                    degraded: bool = False):
        with self._lock:
            self.served[tier] += 1
            self._latency[tier].record(time.monotonic() - t0)
        fut.set_result((DEGRADED if degraded else tier, row))

    # -- calibration --------------------------------------------------------

    def _submit_sample(self, image, deadline_ms, span, fut: Future, t0):
        """Dual-run calibration sample: BOTH tiers execute, the client
        gets the big answer (authoritative), and front-vs-big top-1
        agreement lands in the histogram at the front's confidence
        bucket.  Same holder-pair idiom as the plane's shadow compare."""
        with self._lock:
            self.samples += 1
        ffut = self.plane.submit(self.spec.front, image, deadline_ms)
        bfut = self.plane.submit(self.spec.big, image, deadline_ms,
                                 span=span)
        holder: dict = {}

        def arrived(which, f):
            with self._lock:
                holder[which] = f
                ready = "f" in holder and "b" in holder \
                    and not holder.get("_done")
                if ready:
                    holder["_done"] = True
            if ready:
                self._record_sample(holder["f"], holder["b"])

        ffut.add_done_callback(lambda f: arrived("f", f))
        bfut.add_done_callback(lambda f: arrived("b", f))
        bfut.add_done_callback(lambda f: self._finish(f, fut, t0, BIG))

    def _record_sample(self, ffut: Future, bfut: Future):
        try:
            fr, br = ffut.result(), bfut.result()
        except Exception:  # noqa: BLE001 — either side failed: nothing to compare
            with self._lock:
                self.samples_discarded += 1
            return
        fcls, fconf = self._top1(fr)
        bcls, _ = self._top1(br)
        if fcls is None or fconf is None or bcls is None:
            with self._lock:
                self.samples_discarded += 1
            return
        self.hist.record(fconf, fcls == bcls)
        self._recalibrate()

    def _recalibrate(self):
        thr = self.hist.threshold(self.spec.min_agreement,
                                  self.spec.min_sample)
        with self._lock:
            old = self._threshold
            self._threshold = thr
            changed = thr != old
            if changed:
                self.calibrations += 1
        if changed:
            event(_log, "cascade_calibrated",
                  front=self.spec.front, big=self.spec.big,
                  threshold=thr,
                  samples=self.hist.stats()["samples"])
            h = self.hist.stats()
            self._append_ledger({"event": "calibrated",
                                 "threshold": thr,
                                 "digest": self.params_digest(),
                                 "bins": h["bins"],
                                 "total": h["total"],
                                 "agree": h["agree"]})

    def _on_version_swap(self, name: str):
        """Plane version listener: a reload/promote/revert of either
        tier invalidates the calibration — fail closed and resample."""
        if name not in (self.spec.front, self.spec.big):
            return
        self.hist.reset()
        with self._lock:
            had = self._threshold is not None
            self._threshold = None
            self.resets += 1
        if had:
            event(_log, "cascade_recalibrating", model=name,
                  front=self.spec.front, big=self.spec.big)
        self._append_ledger({"event": "reset", "model": name})

    # -- calibration persistence --------------------------------------------

    def _ledger_path(self) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in f"{self.spec.front}+{self.spec.big}")
        return os.path.join(self._root, f"{safe}.jsonl")

    def _append_ledger(self, record: dict):
        """Append one immutable calibration record (deploy-ledger
        idiom: write failures are counted, never raised — the ledger
        observes, it never gates serving)."""
        if self._root is None:
            return
        record = {"ts": round(time.time(), 3),
                  "front": self.spec.front, "big": self.spec.big,
                  **record}
        try:
            with open(self._ledger_path(), "a", encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
        except OSError as e:
            with self._lock:
                self.ledger_write_errors += 1
            event(_log, "cascade_ledger_write_failed",
                  error=f"{type(e).__name__}: {e}")

    def _restore(self):
        """Boot-time replay: adopt the ledger's newest calibration iff
        its params digest matches BOTH live tiers.  A trailing reset, a
        digest mismatch (either tier reloaded while down), a torn tail
        line, or no ledger at all each leave the router exactly where
        it started — uncalibrated and fail-closed."""
        last = None
        try:
            with open(self._ledger_path(), encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        last = json.loads(line)
                    except ValueError:
                        continue  # torn tail line from a crash
        except OSError:
            return  # no ledger yet — first boot
        if not last or last.get("event") != "calibrated":
            return
        digest = self.params_digest()
        if digest is None or last.get("digest") != digest:
            event(_log, "cascade_restore_stale",
                  front=self.spec.front, big=self.spec.big,
                  ledger_digest=last.get("digest"), live_digest=digest)
            return
        try:
            self.hist.restore(last["total"], last["agree"])
        except (KeyError, TypeError, ValueError) as e:
            event(_log, "cascade_restore_invalid",
                  error=f"{type(e).__name__}: {e}")
            return
        # RE-derive the threshold from the restored counts instead of
        # trusting the stored one: retuned --cascade-min-agreement /
        # min-sample knobs apply to the old sample immediately, and a
        # sample now too thin for the knobs stays fail-closed
        thr = self.hist.threshold(self.spec.min_agreement,
                                  self.spec.min_sample)
        with self._lock:
            self._threshold = thr
            self.restored = thr is not None
        event(_log, "cascade_restored",
              front=self.spec.front, big=self.spec.big,
              threshold=thr, samples=self.hist.stats()["samples"],
              calibrated=thr is not None)

    # -- observability ------------------------------------------------------

    def stats(self) -> dict:
        """The reserved ``cascade`` block in /v1/stats — serve/http.py
        renders the ``dvt_cascade_*`` /metrics series from it, and the
        gateway folds it into its fleet view."""
        hstats = self.hist.stats()
        with self._lock:
            served = dict(self.served)
            routed = served[FRONT] + self.escalated_lowconf \
                + self.escalated_shed
            out = {
                "front": self.spec.front,
                "big": self.spec.big,
                "threshold": self._threshold,
                "calibrated": self._threshold is not None,
                "min_agreement": self.spec.min_agreement,
                "sample_period": self.spec.sample_period,
                "min_sample": self.spec.min_sample,
                "served": served,
                "escalations": self.escalations,
                "escalated_lowconf": self.escalated_lowconf,
                "escalated_error": self.escalated_error,
                "escalated_shed": self.escalated_shed,
                # of the requests the front tier actually judged, how
                # many it sent upstairs — the live economics gauge
                "escalation_rate": ((self.escalated_lowconf
                                     + self.escalated_shed) / routed)
                if routed else None,
                "forced_big": self.forced_big,
                "samples": self.samples,
                "samples_discarded": self.samples_discarded,
                "samples_paused": self.samples_paused,
                "degraded_served": self.degraded_served,
                "calibrations": self.calibrations,
                "resets": self.resets,
                "restored": self.restored,
                "ledger_root": self._root,
                "ledger_write_errors": self.ledger_write_errors,
                "agreement": hstats["agreement"],
                "agreement_bins": {"bins": hstats["bins"],
                                   "samples": hstats["samples"],
                                   "total": hstats["total"],
                                   "agree": hstats["agree"]},
                "latency": {t: h.percentiles()
                            for t, h in self._latency.items()},
                "latency_hist": {t: h.state_dict()
                                 for t, h in self._latency.items()},
            }
        return out
