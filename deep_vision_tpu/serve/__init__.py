"""In-process serving subsystem: dynamic micro-batching with deadlines,
load shedding, fault tolerance, and latency metrics over the training
stack's restore path.

    registry.py   checkpoint / StableHLO blob → ServingModel (donated
                  inputs, device-native unblocked outputs)
    engine.py     pipelined background-thread dynamic batcher: bucketed
                  jit cache, reused staging buffers, bounded in-flight
                  window, one bulk D2H per batch; supervised by a
                  watchdog (thread restarts, exec-timeout fast-fail)
                  with bisect-retry poison isolation
    admission.py  deadline-aware load shedding + queue-depth bound
                  (per-bucket exec-time EWMAs, Retry-After hints)
    health.py     heartbeats + the OK → DEGRADED → DEAD state machine
    faults.py     deterministic fault-injection plane (seeded; enabled
                  via --faults / DVT_SERVE_FAULTS; chaos suite:
                  make serve-chaos)
    replicas.py   multi-device serving: N per-device engine replicas
                  behind one queue, least-outstanding-work routing,
                  DEAD-replica evacuation (--serve-devices); the
                  sharded big-batch path pairs registry.for_mesh with
                  engine.sharded_buckets (--shard-batches)
    http.py       stdlib HTTP front-end (/v1/classify, /v1/detect,
                  deep /v1/healthz with 503-on-degraded, ...)

Entry point: ``python -m deep_vision_tpu.cli.serve``; load generator:
``python bench.py --serve``; architecture notes: docs/SERVING.md.
"""

from deep_vision_tpu.serve.admission import AdmissionController, Shed
from deep_vision_tpu.serve.engine import BatchingEngine, StagingPool
from deep_vision_tpu.serve.faults import (
    FaultPlane,
    InjectedFault,
    Quarantined,
)
from deep_vision_tpu.serve.health import EngineHealth
from deep_vision_tpu.serve.registry import ModelRegistry, ServingModel
from deep_vision_tpu.serve.replicas import ReplicatedEngine

__all__ = ["AdmissionController", "BatchingEngine", "EngineHealth",
           "FaultPlane", "InjectedFault", "ModelRegistry", "Quarantined",
           "ReplicatedEngine", "ServingModel", "Shed", "StagingPool"]
