"""In-process serving subsystem: dynamic micro-batching with deadlines,
load shedding, and latency metrics over the training stack's restore path.

    registry.py   checkpoint / StableHLO blob → ServingModel (donated
                  inputs, device-native unblocked outputs)
    engine.py     pipelined background-thread dynamic batcher: bucketed
                  jit cache, reused staging buffers, bounded in-flight
                  window, one bulk D2H per batch
    admission.py  deadline-aware load shedding + queue-depth bound
                  (per-bucket exec-time EWMAs)
    http.py       stdlib HTTP front-end (/v1/classify, /v1/detect, ...)

Entry point: ``python -m deep_vision_tpu.cli.serve``; load generator:
``python bench.py --serve``; architecture notes: docs/SERVING.md.
"""

from deep_vision_tpu.serve.admission import AdmissionController, Shed
from deep_vision_tpu.serve.engine import BatchingEngine, StagingPool
from deep_vision_tpu.serve.registry import ModelRegistry, ServingModel

__all__ = ["AdmissionController", "BatchingEngine", "ModelRegistry",
           "ServingModel", "Shed", "StagingPool"]
