"""In-process serving subsystem: dynamic micro-batching with deadlines,
load shedding, fault tolerance, and latency metrics over the training
stack's restore path.

    registry.py   checkpoint / StableHLO blob → ServingModel (donated
                  inputs, device-native unblocked outputs)
    engine.py     pipelined background-thread dynamic batcher: bucketed
                  jit cache, reused staging buffers, bounded in-flight
                  window, one bulk D2H per batch; supervised by a
                  watchdog (thread restarts, exec-timeout fast-fail)
                  with bisect-retry poison isolation
    admission.py  deadline-aware load shedding + queue-depth bound
                  (per-bucket exec-time EWMAs, Retry-After hints)
    health.py     heartbeats + the OK → DEGRADED → DEAD state machine
    faults.py     deterministic fault-injection plane (seeded; enabled
                  via --faults / DVT_SERVE_FAULTS; chaos suite:
                  make serve-chaos)
    replicas.py   multi-device serving: N per-device engine replicas
                  behind one queue, least-outstanding-work routing,
                  DEAD-replica evacuation (--serve-devices); the
                  sharded big-batch path pairs registry.for_mesh with
                  engine.sharded_buckets (--shard-batches)
    http.py       stdlib HTTP front-end (/v1/classify, /v1/detect,
                  deep /v1/healthz with 503-on-degraded, /v1/drain
                  zero-downtime shutdown, per-connection socket
                  timeouts, Prometheus-text /metrics, /v1/traces,
                  ?debug=1 per-request timing breakdowns)
    gateway.py    cross-host front tier: proxies /v1/classify|detect
                  over a table of backend serve processes with active
                  healthz probing, per-backend circuit breakers,
                  least-outstanding-work routing, bounded retries with
                  failover (a SIGKILL'd backend loses zero admitted
                  requests), and optional tail hedging

Observability (docs/OBSERVABILITY.md) lives in the sibling
``deep_vision_tpu.obs`` package: per-request spans with request-id
propagation (``X-DVT-Request-Id``, gateway → backend), structured
JSON-line logging under the ``dvt.serve.*`` namespaces, and serving-MFU
accounting (analytic per-bucket FLOPs ÷ measured compute time).  Both
HTTP front-ends export ``GET /metrics`` in Prometheus text format.

Entry points: ``python -m deep_vision_tpu.cli.serve`` (one backend),
``python -m deep_vision_tpu.cli.gateway`` (front tier); load generator:
``python bench.py --serve`` / ``--gateway``; architecture notes:
docs/SERVING.md.
"""

from deep_vision_tpu.serve.admission import AdmissionController, Shed
from deep_vision_tpu.serve.engine import BatchingEngine, StagingPool
from deep_vision_tpu.serve.faults import (
    FaultPlane,
    InjectedFault,
    Quarantined,
)
from deep_vision_tpu.serve.gateway import Gateway, GatewayServer
from deep_vision_tpu.serve.health import EngineHealth
from deep_vision_tpu.serve.registry import ModelRegistry, ServingModel
from deep_vision_tpu.serve.replicas import ReplicatedEngine

__all__ = ["AdmissionController", "BatchingEngine", "EngineHealth",
           "FaultPlane", "Gateway", "GatewayServer", "InjectedFault",
           "ModelRegistry", "Quarantined", "ReplicatedEngine",
           "ServingModel", "Shed", "StagingPool"]
