"""Brownout: degrade deliberately under overload instead of failing
randomly.

The plane already sheds — admission bounds the queue, QoS knees shed
by class, breakers isolate dead backends — but every tier sheds
*independently*, and a saturated process keeps spending capacity on
OPTIONAL work (cascade dual-run calibration samples, shadow
duplication, batch cohorts, slow-trace sampling) while paying clients
eat 429s.  Production overload control (DAGOR, Zhou et al. SoCC 2018;
Brownout, Klein et al. ICSE 2014 — PAPERS.md) inverts that: a single
per-process controller reads the pressure signals the stack already
computes and steps a deterministic degradation ladder, cutting the
cheapest work first and the paying work last.

The ladder (each level includes everything above it):

  L0  normal       full service.
  L1  shed-optional pause cascade calibration sampling and shadow
                   duplication, freeze batch-tier cohort admission,
                   suppress slow-trace sampling — capacity spent on
                   nothing a client is waiting for comes back first.
  L2  degrade      cascade serves FRONT-tier answers below the
                   calibrated threshold for non-premium tenants
                   (marked ``X-DVT-Degraded``), and the response cache
                   may serve STALE same-route entries from a retired
                   params version — quality traded for capacity,
                   visibly.
  L3  hard-shed    the QoS pressure knees fire at a floor just below
                   1.0, shedding every class but premium
                   (``shed_at=1.0``) regardless of actual queue
                   depth — premium last, by construction.

Signals (read racily off the live engines each tick — a torn int read
costs one tick of lag, never a lock on the hot path):

  pressure_ms  max over engines of ``queue_depth × bucket-EWMA`` —
               the admission controller's backlog-as-device-time, the
               same number the autoscaler and the batch trough check
               use.  Crossing ``l1/l2/l3_pressure_ms`` picks the
               target level.
  occupancy    max rolling compute duty cycle; ≥ ``occupancy_high``
               engages L1 even with an empty queue (batchy engines
               saturate without backlog).
  shed_rate    sheds / offered over the tick window; ≥
               ``shed_rate_high`` likewise engages L1.

Stability is structural, the autoscaler's hysteresis+cooldown shape
(deploy/autoscale.py) tuned for overload: the ladder ENGAGES fast
(``up_window`` consecutive hot ticks jump straight to the target
level) and RELEASES slowly (one level at a time, each step needing
``down_window`` consecutive ticks below ``down_ratio`` × the engage
thresholds plus a ``cooldown_s`` since the last change) — so a load
spike browns out in ~half a second while recovery cannot flap or
thundering-herd the freshly-unfrozen optional work.

Subsystems consume the controller through two cheap reads — ``level``
(a plain int attribute) and ``at_least(n)`` — via an optional
``brownout`` attribute each of them defaults to None; nothing in the
request path takes a lock or imports this module.  Transitions are
edge-triggered events (one log line per level change, never per
request), and ``stats()`` feeds the reserved ``brownout`` block in
/v1/stats → the ``dvt_brownout_*`` /metrics series (serve/http.py).
The operator override is ``force(level)`` (surfaced as ``POST
/v1/brownout {"force": n}`` and ``--brownout-force``): a forced level
pins the ladder for drills or emergency manual degradation;
``force(None)`` hands control back to the signals.
"""

from __future__ import annotations

import threading
import time

from deep_vision_tpu.obs.log import event, get_logger

_log = get_logger("dvt.serve.brownout")

#: Ladder levels, for docs/stats — index IS the level.
LEVEL_NAMES = ("normal", "shed_optional", "degrade_quality", "hard_shed")
MAX_LEVEL = len(LEVEL_NAMES) - 1

#: The QoS pressure floor L3 applies: just below 1.0, so every class
#: with a shed_at knee under 1.0 sheds while premium (shed_at=1.0)
#: keeps flowing — "premium last" falls out of the existing knees.
HARD_SHED_PRESSURE = 0.999


class BrownoutController:
    """Counters are written only by the tick thread (or a test driving
    ``tick()``) and read racily by ``stats()`` and the per-request
    ``level``/``at_least`` probes — no lock, by design: the ladder
    changes a few times per overload episode while ``at_least`` runs
    on every request, and a one-tick-stale level is harmless."""

    def __init__(self, engines, *, interval_s: float = 0.25,
                 l1_pressure_ms: float = 50.0,
                 l2_pressure_ms: float = 150.0,
                 l3_pressure_ms: float = 400.0,
                 occupancy_high: float = 0.97,
                 shed_rate_high: float = 0.10,
                 up_window: int = 2, down_window: int = 8,
                 cooldown_s: float = 2.0, down_ratio: float = 0.5,
                 forced: int | None = None):
        if not (0.0 < l1_pressure_ms <= l2_pressure_ms
                <= l3_pressure_ms):
            raise ValueError(
                f"pressure thresholds must ascend: "
                f"{l1_pressure_ms}/{l2_pressure_ms}/{l3_pressure_ms}")
        if not 0.0 < down_ratio < 1.0:
            raise ValueError(f"down_ratio {down_ratio}: need (0, 1) — "
                             f"release must undercut engage")
        # engines: a zero-arg callable returning the live engines to
        # sample (the plane wiring passes
        # ``lambda: plane.active_engines().values()`` so reloads swap
        # engines out from under the controller safely), or a static
        # iterable for the single-model path and tests
        self._engines = engines
        self.interval_s = float(interval_s)
        self.l1_pressure_ms = float(l1_pressure_ms)
        self.l2_pressure_ms = float(l2_pressure_ms)
        self.l3_pressure_ms = float(l3_pressure_ms)
        self.occupancy_high = float(occupancy_high)
        self.shed_rate_high = float(shed_rate_high)
        self.up_window = max(1, int(up_window))
        self.down_window = max(1, int(down_window))
        self.cooldown_s = float(cooldown_s)
        self.down_ratio = float(down_ratio)
        self.forced = forced if forced is None \
            else min(MAX_LEVEL, max(0, int(forced)))
        self._level = self.forced or 0
        self._up_ticks = 0
        self._down_ticks = 0
        self._last_change: float | None = None  # monotonic
        self._prev_sheds: int | None = None
        self._prev_offered = 0
        self._last_signals: dict = {}
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self.ticks = 0
        self.transitions_up = 0
        self.transitions_down = 0
        # entries INTO each level > 0 (L0 entries == transitions down
        # to normal, not worth a separate counter)
        self.level_entries = [0] * (MAX_LEVEL + 1)
        self.signal_errors = 0

    # -- the cheap reads every subsystem probes ----------------------------

    @property
    def level(self) -> int:
        return self._level

    def at_least(self, n: int) -> bool:
        """True when the ladder sits at or above level ``n`` — the one
        probe the request-path hooks call."""
        return self._level >= n

    def qos_pressure_floor(self) -> float:
        """Effective queue-pressure floor for the QoS knees: at L3 the
        knees fire as if the queue were full (premium excepted)."""
        return HARD_SHED_PRESSURE if self._level >= 3 else 0.0

    def force(self, level: int | None):
        """Operator override: pin the ladder at ``level``, effective
        immediately (None hands control back to the signals; the
        pinned level then releases through the normal hysteresis path,
        not instantly).  The immediate transition may race the tick
        thread by one counter increment — an operator override is rare
        enough that the simplicity wins."""
        self.forced = level if level is None \
            else min(MAX_LEVEL, max(0, int(level)))
        event(_log, "brownout_forced", forced=self.forced,
              level=self._level)
        if self.forced is not None and self.forced != self._level:
            self._transition(self.forced, dict(self._last_signals),
                             why="forced")

    # -- signals -----------------------------------------------------------

    def signals(self) -> dict:
        """One coherent-enough snapshot across the live engines.
        Counter reads are racy by design (see class docstring)."""
        pressure_ms = 0.0
        occupancy = 0.0
        sheds = admitted = 0
        engines = self._engines() if callable(self._engines) \
            else self._engines
        for eng in engines:
            try:
                adm = eng.admission
                ewma = adm.bucket_ewma_s() or 0.0
                pressure_ms = max(pressure_ms,
                                  eng.queue_depth * ewma * 1e3)
                sheds += adm.shed_queue_full + adm.shed_deadline
                admitted += adm.admitted
                occ_fn = getattr(eng, "occupancy", None)
                if callable(occ_fn):
                    occupancy = max(occupancy, occ_fn() or 0.0)
            except Exception:  # noqa: BLE001 — an engine mid-teardown must not stall the ladder
                self.signal_errors += 1
        offered = sheds + admitted
        d_shed = d_off = 0
        if self._prev_sheds is not None:
            d_shed = max(0, sheds - self._prev_sheds)
            d_off = max(0, offered - self._prev_offered)
        self._prev_sheds, self._prev_offered = sheds, offered
        return {"pressure_ms": round(pressure_ms, 3),
                "occupancy": round(occupancy, 4),
                "shed_rate": round(d_shed / d_off, 4) if d_off else 0.0}

    def _target(self, sig: dict, scale: float = 1.0) -> int:
        """Level the signals call for; ``scale`` < 1 shrinks every
        threshold — the release check asks whether the signals clear
        even the EASIER bar, which is exactly hysteresis."""
        p = sig["pressure_ms"]
        if p >= self.l3_pressure_ms * scale:
            t = 3
        elif p >= self.l2_pressure_ms * scale:
            t = 2
        elif p >= self.l1_pressure_ms * scale:
            t = 1
        else:
            t = 0
        if t == 0 and (sig["occupancy"] >= self.occupancy_high * scale
                       or sig["shed_rate"] >=
                       self.shed_rate_high * scale):
            t = 1
        return t

    # -- the ladder --------------------------------------------------------

    def tick(self) -> int:
        """One ladder decision; returns the (possibly new) level.
        Public: tests and the smoke drive it synchronously, production
        runs it on the Event-paced daemon thread."""
        self.ticks += 1
        sig = self.signals()
        self._last_signals = sig
        if self.forced is not None:
            if self.forced != self._level:
                self._transition(self.forced, sig, why="forced")
            return self._level
        lvl = self._level
        engage = self._target(sig)
        release = self._target(sig, self.down_ratio)
        if engage > lvl:
            self._up_ticks += 1
            self._down_ticks = 0
            if self._up_ticks >= self.up_window:
                self._transition(engage, sig, why="pressure")
        elif release < lvl:
            self._down_ticks += 1
            self._up_ticks = 0
            now = time.monotonic()
            cooled = self._last_change is None \
                or now - self._last_change >= self.cooldown_s
            if self._down_ticks >= self.down_window and cooled:
                # release ONE level per cooldown: recovery re-admits
                # the optional work gradually, never as a herd
                self._transition(lvl - 1, sig, why="recovered")
        else:
            self._up_ticks = 0
            self._down_ticks = 0
        return self._level

    def _transition(self, new: int, sig: dict, why: str):
        old = self._level
        self._level = new
        self._up_ticks = 0
        self._down_ticks = 0
        self._last_change = time.monotonic()
        if new > old:
            self.transitions_up += 1
        else:
            self.transitions_down += 1
        for lvl in range(min(old, new) + 1, max(old, new) + 1):
            if new > old:
                self.level_entries[lvl] += 1
        # edge-triggered: one line per level CHANGE, never per request
        # (`level`/`name` are event()'s own params — field keys differ)
        event(_log,
              "brownout_level_up" if new > old else "brownout_level_down",
              to_level=new, prev=old, level_name=LEVEL_NAMES[new], why=why,
              **sig)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "BrownoutController":
        if self._thread is None or not self._thread.is_alive():
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop, name="brownout", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    def _loop(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the ladder thread never dies
                pass

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The reserved ``brownout`` block in /v1/stats — serve/http.py
        renders the ``dvt_brownout_*`` /metrics series from it."""
        lvl = self._level
        return {"level": lvl,
                "level_name": LEVEL_NAMES[lvl],
                "forced": self.forced,
                "interval_s": self.interval_s,
                "thresholds": {"l1_pressure_ms": self.l1_pressure_ms,
                               "l2_pressure_ms": self.l2_pressure_ms,
                               "l3_pressure_ms": self.l3_pressure_ms,
                               "occupancy_high": self.occupancy_high,
                               "shed_rate_high": self.shed_rate_high,
                               "down_ratio": self.down_ratio},
                "up_window": self.up_window,
                "down_window": self.down_window,
                "cooldown_s": self.cooldown_s,
                "ticks": self.ticks,
                "transitions_up": self.transitions_up,
                "transitions_down": self.transitions_down,
                "level_entries": {f"L{i}": n for i, n
                                  in enumerate(self.level_entries)
                                  if i > 0},
                "signal_errors": self.signal_errors,
                "signals": dict(self._last_signals)}
