"""deep_vision_tpu — a TPU-native (JAX/Flax/pjit) computer-vision framework.

Re-designed from scratch with the capabilities of the `deep-vision` reference
model zoo (classification / detection / pose / GANs), built TPU-first:

- NHWC layouts, bfloat16 matmul/conv policy, static shapes everywhere.
- One unified :class:`~deep_vision_tpu.core.trainer.Trainer` replacing the
  reference's three trainer generations (PyTorch imperative, TF1-Keras,
  TF2 MirroredStrategy custom loops).
- Parallelism via ``jax.sharding.Mesh`` + ``jit`` (GSPMD): data parallelism is
  input sharding over the ``data`` mesh axis with XLA-inserted collectives over
  ICI, not NCCL wrappers.
- Host-side numpy input pipelines with double-buffered ``device_put`` prefetch
  replacing torch DataLoader / tf.data.
"""

__version__ = "0.1.0"
