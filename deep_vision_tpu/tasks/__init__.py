from deep_vision_tpu.tasks.classification import ClassificationTask

__all__ = ["ClassificationTask"]
