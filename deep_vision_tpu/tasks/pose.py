"""Pose task: Gaussian heatmap targets, weighted-MSE intermediate
supervision, PCKh eval.

Parity map (all in /root/reference/Hourglass/tensorflow/):
- heatmap target: ``generate_2d_guassian`` preprocess.py:91-155 (σ=1 px,
  ×12 scale, 7×7 support, zeros when invisible/out-of-bounds) +
  ``make_heatmaps`` :158-173 — here vectorized over all keypoints at once
  instead of the reference's per-pixel TensorArray scatter loop;
- loss: ``compute_loss`` train.py:65-76 — MSE with foreground weight
  (label>0)·81 + 1, summed over the stack's intermediate predictions;
- eval: PCKh@0.5 (standard MPII metric; the reference publishes none).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def make_heatmaps(keypoints: np.ndarray, height: int = 64, width: int = 64,
                  sigma: int = 1, scale: float = 12.0) -> np.ndarray:
    """(K, 3) [x, y, visibility] in heatmap pixel coords → (H, W, K) f32.

    Vectorized: one broadcasted Gaussian over the full grid per keypoint,
    truncated to the reference's (6σ+1)² support window; invisible or fully
    out-of-bounds keypoints give all-zero channels (preprocess.py:108-110).
    """
    kp = np.asarray(keypoints, np.float32)
    K = kp.shape[0]
    x0 = np.round(kp[:, 0]).astype(np.int64)
    y0 = np.round(kp[:, 1]).astype(np.int64)
    vis = kp[:, 2]
    ys, xs = np.mgrid[0:height, 0:width]
    dx = xs[None] - x0[:, None, None]
    dy = ys[None] - y0[:, None, None]
    g = np.exp(-(dx**2 + dy**2) / (2.0 * sigma**2)) * scale
    # truncate to the 7×7 patch support (|d| ≤ 3σ), like the reference
    g = np.where((np.abs(dx) <= 3 * sigma) & (np.abs(dy) <= 3 * sigma), g, 0.0)
    inb = (x0 - 3 * sigma < width) & (y0 - 3 * sigma < height) & \
        (x0 + 3 * sigma >= 0) & (y0 + 3 * sigma >= 0)
    valid = (vis > 0) & inb
    g = g * valid[:, None, None]
    return np.transpose(g, (1, 2, 0)).astype(np.float32)


def heatmap_argmax(heatmaps: np.ndarray) -> np.ndarray:
    """(H, W, K) → (K, 2) [x, y] peak coordinates."""
    h, w, k = heatmaps.shape
    flat = heatmaps.reshape(-1, k)
    idx = flat.argmax(0)
    return np.stack([idx % w, idx // w], axis=1).astype(np.float32)


def decode_heatmaps(heatmaps, refine: bool = True):  # dvtlint: traced
    """Traced batched heatmap decode: (B, H, W, K) → {"keypoints":
    (B, K, 2) [x, y] float32, "scores": (B, K) float32}.

    The serving epilogue behind ``/v1/pose`` (serve/workloads.py):
    fused into the compiled bucket programs so the bulk D2H moves K
    coordinate pairs per image instead of an H×W×K heatmap stack.
    ``refine`` adds the standard quarter-pixel offset toward the larger
    neighbor on each axis (MPII/hourglass post-processing); off, the
    integer peak matches the host-side ``heatmap_argmax`` exactly
    (tests/test_workloads.py holds the parity to 1e-6).  Peaks on the
    heatmap border skip the refinement on that axis — a clipped
    neighbor gather would compare the peak against itself and shift
    toward nothing."""
    b, h, w, k = heatmaps.shape
    flat = heatmaps.reshape(b, h * w, k)
    idx = jnp.argmax(flat, axis=1)                      # (B, K)
    scores = jnp.max(flat, axis=1)
    xi, yi = idx % w, idx // w
    x = xi.astype(jnp.float32)
    y = yi.astype(jnp.float32)
    if refine:
        def neighbor(dy, dx):
            yy = jnp.clip(yi + dy, 0, h - 1)
            xx = jnp.clip(xi + dx, 0, w - 1)
            return jnp.take_along_axis(
                flat, (yy * w + xx)[:, None, :], axis=1)[:, 0, :]

        dx = jnp.sign(neighbor(0, 1) - neighbor(0, -1))
        dy = jnp.sign(neighbor(1, 0) - neighbor(-1, 0))
        x = x + 0.25 * dx * ((xi > 0) & (xi < w - 1))
        y = y + 0.25 * dy * ((yi > 0) & (yi < h - 1))
    return {"keypoints": jnp.stack([x, y], axis=-1),
            "scores": scores}


def pckh(pred_xy: np.ndarray, true_xy: np.ndarray, visible: np.ndarray,
         head_size: float, alpha: float = 0.5) -> tuple[float, int]:
    """PCKh: fraction of visible keypoints within α·head_size of truth.
    Returns (num correct, num visible)."""
    d = np.linalg.norm(pred_xy - true_xy, axis=-1)
    ok = (d <= alpha * head_size) & (visible > 0)
    return float(ok.sum()), int((visible > 0).sum())


class PoseTask:
    """Trainer bundle: multi-stack weighted MSE + per-batch eval sums."""

    monitor = "neg_loss"

    def __init__(self, foreground_weight: float = 81.0):
        self.fg = foreground_weight

    def _stack_loss_per_image(self, outputs, labels):
        """(B,) summed over the stack — per-image so eval can mask
        weight-0 padding rows."""
        loss = 0.0
        for out in outputs:
            w = (labels > 0).astype(jnp.float32) * self.fg + 1.0
            loss = loss + (jnp.square(labels - out) * w).mean((1, 2, 3))
        return loss

    def _stack_loss(self, outputs, labels):
        return self._stack_loss_per_image(outputs, labels).mean()

    def loss(self, outputs, batch):
        if not isinstance(outputs, (tuple, list)):
            outputs = (outputs,)
        loss = self._stack_loss(outputs, batch["heatmaps"])
        return loss, {"mse_stacks": loss}

    def eval_metrics(self, outputs, batch):
        if not isinstance(outputs, (tuple, list)):
            outputs = (outputs,)
        per = self._stack_loss_per_image(outputs, batch["heatmaps"])
        w = batch.get("weight")
        if w is None:
            w = jnp.ones_like(per)
        return {"loss": (per * w).sum(), "neg_loss": -(per * w).sum(),
                "count": w.sum()}
