"""mAP evaluation — the reference admits this is unfinished
("Evaluation ... working in progress", YOLO/tensorflow/README.md; SURVEY §7
step 8 says finish it).  Host-side numpy, VOC-style AP with both the
VOC2007 11-point and the continuous (area-under-PR) interpolation.
"""

from __future__ import annotations

import numpy as np


def _iou_matrix(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(N,4)×(M,4) corner boxes → (N,M) IoU."""
    lo = np.maximum(a[:, None, :2], b[None, :, :2])
    hi = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(hi - lo, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(a[:, 3] - a[:, 1], 0)
    area_b = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-9)


def average_precision(recall: np.ndarray, precision: np.ndarray,
                      use_07_metric: bool = False) -> float:
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.01, 0.1):
            p = precision[recall >= t].max() if (recall >= t).any() else 0.0
            ap += p / 11.0
        return float(ap)
    # continuous: envelope + area under PR
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    for i in range(len(mpre) - 2, -1, -1):
        mpre[i] = max(mpre[i], mpre[i + 1])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]).sum())


class MeanAPEvaluator:
    """Accumulate per-image detections + ground truth, then compute mAP.

    ``add(dets, gts)`` per image:
      dets: (boxes (K,4), scores (K,), classes (K,)) — corner coords
      gts:  (boxes (M,4), classes (M,))
    """

    def __init__(self, num_classes: int, iou_threshold: float = 0.5,
                 use_07_metric: bool = False):
        self.num_classes = num_classes
        self.iou_threshold = iou_threshold
        self.use_07 = use_07_metric
        self._dets: list[list] = [[] for _ in range(num_classes)]
        self._n_gt = np.zeros(num_classes, np.int64)
        self._img = 0

    def add(self, det_boxes, det_scores, det_classes, gt_boxes, gt_classes):
        img = self._img
        self._img += 1
        gt_boxes = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
        gt_classes = np.asarray(gt_classes, np.int64).reshape(-1)
        for c in np.unique(gt_classes):
            self._n_gt[c] += int((gt_classes == c).sum())
        for b, s, c in zip(np.asarray(det_boxes).reshape(-1, 4),
                           np.asarray(det_scores).reshape(-1),
                           np.asarray(det_classes, np.int64).reshape(-1)):
            self._dets[c].append(
                (float(s), b, img,
                 gt_boxes[gt_classes == c]))

    def compute(self) -> dict:
        aps = {}
        for c in range(self.num_classes):
            if self._n_gt[c] == 0:
                continue
            dets = sorted(self._dets[c], key=lambda d: -d[0])
            if not dets:
                aps[c] = 0.0
                continue
            matched: dict[int, set] = {}
            tp = np.zeros(len(dets))
            fp = np.zeros(len(dets))
            for i, (score, box, img, gts) in enumerate(dets):
                if len(gts) == 0:
                    fp[i] = 1
                    continue
                ious = _iou_matrix(box[None], gts)[0]
                j = int(np.argmax(ious))
                if ious[j] >= self.iou_threshold and \
                        j not in matched.setdefault(img, set()):
                    tp[i] = 1
                    matched[img].add(j)
                else:
                    fp[i] = 1
            ctp, cfp = np.cumsum(tp), np.cumsum(fp)
            recall = ctp / self._n_gt[c]
            precision = ctp / np.maximum(ctp + cfp, 1e-9)
            aps[c] = average_precision(recall, precision, self.use_07)
        mean_ap = float(np.mean(list(aps.values()))) if aps else 0.0
        return {"mAP": mean_ap, "per_class": aps}


class DetectionMAPAccumulator:
    """Trainer host-evaluator: consumes ``task.eval_outputs`` batches
    (device-side decode+NMS results + padded gt lists) and reduces to
    scalar metrics merged into the validation dict."""

    def __init__(self, num_classes: int, iou_threshold: float = 0.5,
                 use_07_metric: bool = False):
        self.ev = MeanAPEvaluator(num_classes, iou_threshold, use_07_metric)

    def add_batch(self, outs: dict):
        det_boxes = np.asarray(outs["det_boxes"])
        det_scores = np.asarray(outs["det_scores"])
        det_classes = np.asarray(outs["det_classes"])
        det_valid = np.asarray(outs["det_valid"])
        gt_boxes = np.asarray(outs["gt_boxes"])
        gt_mask = np.asarray(outs["gt_mask"])
        gt_classes = np.asarray(outs["gt_classes"])
        # weight-0 rows are eval padding (pad_last batches): skip whole image
        img_w = np.asarray(outs.get("weight", np.ones(len(det_boxes))))
        for i in range(len(det_boxes)):
            if img_w[i] <= 0:
                continue
            v = det_valid[i] > 0
            m = gt_mask[i] > 0
            self.ev.add(det_boxes[i][v], det_scores[i][v], det_classes[i][v],
                        gt_boxes[i][m], gt_classes[i][m])

    def compute(self) -> dict:
        return {"mAP": self.ev.compute()["mAP"]}
