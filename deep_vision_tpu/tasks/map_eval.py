"""mAP evaluation — the reference admits this is unfinished
("Evaluation ... working in progress", YOLO/tensorflow/README.md; SURVEY §7
step 8 says finish it).  Host-side numpy, VOC-style AP with both the
VOC2007 11-point and the continuous (area-under-PR) interpolation, plus the
COCO-standard mAP@[.5:.95] (AP averaged over IoU 0.50:0.95:0.05) so both
detection stacks report the modern headline metric alongside mAP@0.5.
"""

from __future__ import annotations

import numpy as np


def _iou_matrix(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(N,4)×(M,4) corner boxes → (N,M) IoU."""
    lo = np.maximum(a[:, None, :2], b[None, :, :2])
    hi = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(hi - lo, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(a[:, 3] - a[:, 1], 0)
    area_b = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-9)


def average_precision(recall: np.ndarray, precision: np.ndarray,
                      use_07_metric: bool = False) -> float:
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.01, 0.1):
            p = precision[recall >= t].max() if (recall >= t).any() else 0.0
            ap += p / 11.0
        return float(ap)
    # continuous: envelope + area under PR
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    for i in range(len(mpre) - 2, -1, -1):
        mpre[i] = max(mpre[i], mpre[i + 1])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]).sum())


class MeanAPEvaluator:
    """Accumulate per-image detections + ground truth, then compute mAP.

    ``add(dets, gts)`` per image:
      dets: (boxes (K,4), scores (K,), classes (K,)) — corner coords
      gts:  (boxes (M,4), classes (M,))
    """

    def __init__(self, num_classes: int, iou_threshold: float = 0.5,
                 use_07_metric: bool = False):
        self.num_classes = num_classes
        self.iou_threshold = iou_threshold
        self.use_07 = use_07_metric
        self._dets: list[list] = [[] for _ in range(num_classes)]
        self._n_gt = np.zeros(num_classes, np.int64)
        self._img = 0

    def add(self, det_boxes, det_scores, det_classes, gt_boxes, gt_classes):
        img = self._img
        self._img += 1
        gt_boxes = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
        gt_classes = np.asarray(gt_classes, np.int64).reshape(-1)
        for c in np.unique(gt_classes):
            self._n_gt[c] += int((gt_classes == c).sum())
        for b, s, c in zip(np.asarray(det_boxes).reshape(-1, 4),
                           np.asarray(det_scores).reshape(-1),
                           np.asarray(det_classes, np.int64).reshape(-1)):
            self._dets[c].append(
                (float(s), b, img,
                 gt_boxes[gt_classes == c]))

    # IoU grid for the COCO-standard average: 0.50, 0.55, ..., 0.95.
    # Invariant: a detection whose IoU lands EXACTLY on a grid value
    # (e.g. 80/100 overlap vs threshold 0.80) must count as matched at
    # that threshold.  ``np.arange(...).round(2)`` happens to produce
    # the same nearest-doubles as the IoU arithmetic today, but that is
    # representation luck, not a guarantee — so ``_class_ap`` compares
    # against ``threshold - IOU_EPS`` to make boundary inclusion
    # explicit and robust to any future grid construction.
    COCO_IOUS = tuple(np.arange(0.50, 0.96, 0.05).round(2))
    IOU_EPS = 1e-9

    def _class_entries(self, c: int) -> list:
        """Score-sorted detections with their per-gt IoU vectors AND the
        IoU-descending gt order computed ONCE — scores, IoUs, and sort
        order are threshold-independent, so the per-threshold passes
        below only redo the (cheap) matching/cumsum."""
        dets = sorted(self._dets[c], key=lambda d: -d[0])
        out = []
        for (_s, box, img, gts) in dets:
            if len(gts):
                ious = _iou_matrix(box[None], gts)[0]
                out.append((img, ious, np.argsort(-ious)))
            else:
                out.append((img, None, None))
        return out

    def _class_ap(self, entries: list, n_gt: int, iou_threshold: float,
                  coco_matching: bool) -> float:
        """AP for one class at one IoU threshold.

        Matching rule differs by metric family (and it matters on crowded
        scenes): the VOC devkit assigns each detection (score-descending)
        to its ARGMAX-IoU gt and counts FP if that gt is already matched;
        COCO lets the detection fall through to the highest-IoU UNMATCHED
        gt above threshold."""
        if not entries:
            return 0.0
        # boundary-exact IoUs count as matched (see IOU_EPS invariant)
        thr = iou_threshold - self.IOU_EPS
        matched: dict[int, set] = {}
        tp = np.zeros(len(entries))
        fp = np.zeros(len(entries))
        for i, (img, ious, order) in enumerate(entries):
            if ious is None:
                fp[i] = 1
                continue
            taken = matched.setdefault(img, set())
            j = -1
            if coco_matching:
                for cand in order:
                    if ious[cand] < thr:
                        break
                    if int(cand) not in taken:
                        j = int(cand)
                        break
            else:
                jmax = int(np.argmax(ious))
                if ious[jmax] >= thr and jmax not in taken:
                    j = jmax
            if j >= 0:
                tp[i] = 1
                taken.add(j)
            else:
                fp[i] = 1
        ctp, cfp = np.cumsum(tp), np.cumsum(fp)
        recall = ctp / n_gt
        precision = ctp / np.maximum(ctp + cfp, 1e-9)
        # the 11-point interpolation is a VOC2007 compatibility mode; the
        # COCO grid always uses continuous AP regardless of use_07
        use_07 = self.use_07 and not coco_matching
        return average_precision(recall, precision, use_07)

    def compute(self) -> dict:
        """``mAP`` at the primary threshold (default 0.5) with the VOC-
        devkit matching rule — comparable to published VOC numbers;
        ``mAP50_95`` averaged over the COCO IoU grid with COCO matching
        (continuous-AP interpolation, within ~1e-2 of COCO's 101-point)."""
        aps = {}
        coco = {}
        for c in range(self.num_classes):
            if self._n_gt[c] == 0:
                continue
            entries = self._class_entries(c)
            n = int(self._n_gt[c])
            aps[c] = self._class_ap(entries, n, self.iou_threshold,
                                    coco_matching=False)
            coco[c] = float(np.mean(
                [self._class_ap(entries, n, t, coco_matching=True)
                 for t in self.COCO_IOUS]))
        mean_ap = float(np.mean(list(aps.values()))) if aps else 0.0
        map50_95 = float(np.mean(list(coco.values()))) if coco else 0.0
        return {"mAP": mean_ap, "mAP50_95": map50_95, "per_class": aps}


class DetectionMAPAccumulator:
    """Trainer host-evaluator: consumes ``task.eval_outputs`` batches
    (device-side decode+NMS results + padded gt lists) and reduces to
    scalar metrics merged into the validation dict."""

    def __init__(self, num_classes: int, iou_threshold: float = 0.5,
                 use_07_metric: bool = False):
        self.ev = MeanAPEvaluator(num_classes, iou_threshold, use_07_metric)

    def add_batch(self, outs: dict):
        det_boxes = np.asarray(outs["det_boxes"])
        det_scores = np.asarray(outs["det_scores"])
        det_classes = np.asarray(outs["det_classes"])
        det_valid = np.asarray(outs["det_valid"])
        gt_boxes = np.asarray(outs["gt_boxes"])
        gt_mask = np.asarray(outs["gt_mask"])
        gt_classes = np.asarray(outs["gt_classes"])
        # weight-0 rows are eval padding (pad_last batches): skip whole image
        img_w = np.asarray(outs.get("weight", np.ones(len(det_boxes))))
        for i in range(len(det_boxes)):
            if img_w[i] <= 0:
                continue
            v = det_valid[i] > 0
            m = gt_mask[i] > 0
            self.ev.add(det_boxes[i][v], det_scores[i][v], det_classes[i][v],
                        gt_boxes[i][m], gt_classes[i][m])

    def compute(self) -> dict:
        res = self.ev.compute()
        return {"mAP": res["mAP"], "mAP50_95": res["mAP50_95"]}
