"""Detection task: YOLOv3 box codecs, loss, label encoding, postprocess.

Parity map (all in /root/reference/YOLO/tensorflow/):
- decode/encode: ``get_absolute_yolo_box`` yolov3.py:238-326,
  ``get_relative_yolo_box`` :329-349
- loss: ``YoloLoss`` :352-552 (xy/wh L2 in t-space ×(2-w·h)×λ_coord=5,
  obj/noobj BCE with ignore-mask IoU>0.5, λ_noobj=0.5, per-anchor class BCE)
- label encoding: preprocess.py:137-269 — reimplemented as one vectorized
  scatter over boxes instead of the reference's per-box Python loop
- postprocess: postprocess.py:12-96 → ops.boxes.batched_nms

TPU notes: the ignore mask compares pred boxes against a FIXED-SIZE padded
list of ground-truth boxes per image (batch["boxes"], mask in
batch["boxes_mask"]) — the reference's ``tf.boolean_mask`` is dynamic-shaped
(and mixes images across the batch); this formulation is static, per-image
correct, and vmap-free.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deep_vision_tpu.models.yolo import ANCHOR_MASKS, YOLO_ANCHORS
from deep_vision_tpu.ops.boxes import batched_nms, broadcast_iou, xywh_to_corners

MAX_BOXES = 100  # static per-image ground-truth capacity


def decode_boxes(raw, anchors_wh):
    """t-space raw head output → (normalized xywh boxes, obj, classes).

    raw: (B, G, G, A, 5+C).  bx = (σ(tx)+Cx)/G;  bwh = anchor·e^t  —
    yolov3.py:238-326.
    """
    grid = raw.shape[1]
    t_xy, t_wh, obj, cls = jnp.split(raw, (2, 4, 5), axis=-1)
    cy, cx = jnp.meshgrid(jnp.arange(grid, dtype=jnp.float32),
                          jnp.arange(grid, dtype=jnp.float32), indexing="ij")
    c_xy = jnp.stack([cx, cy], axis=-1)[None, :, :, None, :]  # (1,G,G,1,2)
    b_xy = (jax.nn.sigmoid(t_xy) + c_xy) / grid
    b_wh = jnp.exp(jnp.clip(t_wh, -9.0, 9.0)) * anchors_wh
    return (jnp.concatenate([b_xy, b_wh], -1),
            jax.nn.sigmoid(obj), jax.nn.sigmoid(cls))


def encode_boxes(xywh, anchors_wh, eps: float = 1e-9):
    """normalized xywh → t-space targets (inverse of decode; :329-349)."""
    grid = xywh.shape[1]
    xy, wh = xywh[..., :2], xywh[..., 2:4]
    t_xy = xy * grid - jnp.floor(xy * grid)  # σ(tx) value, cell offset
    t_wh = jnp.log(jnp.maximum(wh, eps) / anchors_wh)
    t_wh = jnp.where(wh <= eps, 0.0, t_wh)  # empty cells → 0 target
    return t_xy, t_wh


def _bce(logit_or_prob, target, from_probs: bool, eps: float = 1e-7):
    if from_probs:
        p = jnp.clip(logit_or_prob, eps, 1 - eps)
        return -(target * jnp.log(p) + (1 - target) * jnp.log(1 - p))
    return jnp.maximum(logit_or_prob, 0) - logit_or_prob * target + \
        jnp.log1p(jnp.exp(-jnp.abs(logit_or_prob)))


def yolo_scale_loss(raw, y_true, gt_boxes, gt_mask, anchors_wh,
                    ignore_thresh: float = 0.5, lambda_coord: float = 5.0,
                    lambda_noobj: float = 0.5, use_pallas: bool = False,
                    mesh=None):
    """Loss for ONE scale.

    raw: (B,G,G,A,5+C) head output; y_true: same shape, absolute xywh +
    obj + one-hot; gt_boxes: (B,MAX_BOXES,4) corner boxes; gt_mask: (B,M).
    Returns (total (B,), components dict).
    """
    num_classes = raw.shape[-1] - 5
    pred_xy_rel = jax.nn.sigmoid(raw[..., 0:2])
    pred_wh_rel = raw[..., 2:4]
    pred_box_abs, pred_obj, _ = decode_boxes(raw, anchors_wh)
    pred_corners = xywh_to_corners(pred_box_abs)

    true_xy_abs = y_true[..., 0:2]
    true_wh_abs = y_true[..., 2:4]
    true_obj = y_true[..., 4:5]
    true_class = y_true[..., 5:]
    true_xy_rel, true_wh_rel = encode_boxes(y_true[..., 0:4], anchors_wh)

    # small-box upweighting (2 - w·h), darknet yolo_layer.c:190 via :405-407
    weight = 2.0 - true_wh_abs[..., 0] * true_wh_abs[..., 1]
    obj = true_obj[..., 0]

    xy_loss = jnp.square(true_xy_rel - pred_xy_rel).sum(-1)
    xy_loss = (obj * weight * xy_loss).sum((1, 2, 3)) * lambda_coord
    wh_loss = jnp.square(true_wh_rel - pred_wh_rel).sum(-1)
    wh_loss = (obj * weight * wh_loss).sum((1, 2, 3)) * lambda_coord

    # ignore mask: preds overlapping ANY same-image gt > thresh are not
    # penalized as background (yolov3.py:438-459, static-shape version).
    # stop_gradient: the mask is a hard threshold (zero gradient anyway) and
    # pallas_call has no autodiff rule — without this the Pallas path fails
    # to linearize under value_and_grad.
    B, G = raw.shape[0], raw.shape[1]
    flat_pred = jax.lax.stop_gradient(pred_corners.reshape(B, -1, 4))
    if use_pallas:
        # fused tiled kernel (ops/pallas_ops.py) — avoids the (B,N,M) HBM
        # intermediate.  pallas_call has no GSPMD partitioning rule, so a
        # sharded mesh routes through a shard_map over the data axis (the
        # reduction is per-image independent); single-device calls the
        # kernel directly.
        from deep_vision_tpu.ops.pallas_ops import (
            best_iou_max_auto,
            best_iou_max_sharded,
        )

        if mesh is not None and mesh.devices.size > 1:
            best_iou = best_iou_max_sharded(
                flat_pred, gt_boxes, gt_mask, mesh).reshape(obj.shape)
        else:
            best_iou = best_iou_max_auto(flat_pred, gt_boxes,
                                         gt_mask).reshape(obj.shape)
    else:
        iou = broadcast_iou(flat_pred, gt_boxes)           # (B, N, M)
        iou = jnp.where(gt_mask[:, None, :] > 0, iou, 0.0)
        best_iou = iou.max(-1).reshape(obj.shape)
    ignore = (best_iou < ignore_thresh).astype(jnp.float32)

    obj_entropy = _bce(raw[..., 4:5], true_obj, from_probs=False)[..., 0]
    obj_loss = (obj * obj_entropy).sum((1, 2, 3))
    noobj_loss = ((1 - obj) * obj_entropy * ignore).sum((1, 2, 3)) * lambda_noobj

    class_entropy = _bce(raw[..., 5:], true_class, from_probs=False)
    class_loss = (true_obj * class_entropy).sum((1, 2, 3, 4))

    total = xy_loss + wh_loss + obj_loss + noobj_loss + class_loss
    return total, {"xy": xy_loss, "wh": wh_loss,
                   "obj": obj_loss + noobj_loss, "class": class_loss}


class YoloTask:
    """Task bundle for the Trainer: multi-scale loss + eval.

    Validation computes mAP@0.5 (decode + NMS on device via
    ``eval_outputs``, VOC-style AP accumulated on host) — the evaluation
    the reference's README admits is "WIP" and never shipped.
    """

    monitor = "mAP"

    def __init__(self, num_classes: int,
                 anchors: np.ndarray = YOLO_ANCHORS,
                 masks: np.ndarray = ANCHOR_MASKS,
                 use_pallas: bool = False,
                 eval_score_threshold: float = 0.05,
                 mesh=None):
        self.num_classes = num_classes
        self.anchors = jnp.asarray(anchors)
        self.masks = masks
        self.use_pallas = use_pallas
        self.eval_score_threshold = eval_score_threshold
        # mesh routes the Pallas kernel through a data-axis shard_map
        # under multi-device meshes (best_iou_max_sharded); None or a
        # 1-device mesh calls the kernel directly
        self.mesh = mesh

    def _scale_anchors(self, scale: int):
        return self.anchors[self.masks[scale]]

    def loss(self, outputs, batch):
        totals, comps = 0.0, {}
        for s, raw in enumerate(outputs):
            t, c = yolo_scale_loss(
                raw, batch[f"y_true_{s}"], batch["boxes"],
                batch["boxes_mask"], self._scale_anchors(s),
                use_pallas=self.use_pallas, mesh=self.mesh)
            totals = totals + t.mean()
            for k, v in c.items():
                comps[f"{k}_{s}"] = v.mean()
        return totals, comps

    def eval_metrics(self, outputs, batch):
        # per-image loss, masked by the eval-padding weight so weight-0
        # filler rows don't pollute the metric
        w = batch.get("weight")
        if w is None:
            w = jnp.ones((batch["boxes"].shape[0],), jnp.float32)
        per_image = 0.0
        for s, raw in enumerate(outputs):
            t, _ = yolo_scale_loss(
                raw, batch[f"y_true_{s}"], batch["boxes"],
                batch["boxes_mask"], self._scale_anchors(s),
                use_pallas=self.use_pallas, mesh=self.mesh)
            per_image = per_image + t
        loss_sum = (per_image * w).sum()
        return {"loss": loss_sum, "neg_loss": -loss_sum, "count": w.sum()}

    def eval_outputs(self, outputs, batch):
        """Device-side decode + static-shape NMS for the host mAP
        accumulator (Trainer host-evaluator protocol)."""
        boxes, scores, classes, valid = postprocess(
            outputs, self.num_classes, anchors=np.asarray(self.anchors),
            masks=self.masks, score_threshold=self.eval_score_threshold)
        return {"det_boxes": boxes, "det_scores": scores,
                "det_classes": classes, "det_valid": valid,
                "gt_boxes": batch["boxes"], "gt_mask": batch["boxes_mask"],
                "gt_classes": batch["gt_classes"]}

    def make_host_evaluator(self):
        from deep_vision_tpu.tasks.map_eval import DetectionMAPAccumulator

        return DetectionMAPAccumulator(self.num_classes)


# ---------------------------------------------------------------------------
# Label encoding (host-side, numpy): preprocess.py:137-269 vectorized
# ---------------------------------------------------------------------------


def find_best_anchor(wh: np.ndarray, anchors: np.ndarray = YOLO_ANCHORS
                     ) -> np.ndarray:
    """Best of the 9 anchors by centered IoU (preprocess.py:226-269).

    wh: (N, 2) normalized → (N,) anchor index.
    """
    inter = np.minimum(wh[:, None, 0], anchors[None, :, 0]) * \
        np.minimum(wh[:, None, 1], anchors[None, :, 1])
    union = wh[:, None, 0] * wh[:, None, 1] + \
        anchors[None, :, 0] * anchors[None, :, 1] - inter
    return np.argmax(inter / np.maximum(union, 1e-9), axis=1)


def encode_labels(boxes_xywh: np.ndarray, classes: np.ndarray,
                  num_classes: int, grids: Sequence[int] = (52, 26, 13),
                  anchors: np.ndarray = YOLO_ANCHORS,
                  masks: np.ndarray = ANCHOR_MASKS):
    """One image's gt boxes → the 3 y_true grids + padded box list.

    boxes_xywh: (N, 4) normalized centroids; classes: (N,) int.
    Returns dict {y_true_0..2: (G,G,3,5+C), boxes: (MAX_BOXES,4) corners,
    boxes_mask: (MAX_BOXES,)}.
    Vectorized scatter (no per-box Python loop over grid ops): one
    best-anchor lookup, one np index-assign per scale.
    """
    n = len(boxes_xywh)
    out = {f"y_true_{s}": np.zeros((g, g, 3, 5 + num_classes), np.float32)
           for s, g in enumerate(grids)}
    boxes_list = np.zeros((MAX_BOXES, 4), np.float32)
    boxes_mask = np.zeros((MAX_BOXES,), np.float32)
    classes_list = np.zeros((MAX_BOXES,), np.int32)
    if n:
        # truncate EVERYTHING to MAX_BOXES so the y_true positives stay
        # consistent with the ignore-mask box list — otherwise overflow
        # boxes would be positives penalized as background
        m = min(n, MAX_BOXES)
        boxes_xywh = boxes_xywh[:m]
        classes = classes[:m]
        corners = np.concatenate([boxes_xywh[:, :2] - boxes_xywh[:, 2:4] / 2,
                                  boxes_xywh[:, :2] + boxes_xywh[:, 2:4] / 2], 1)
        boxes_list[:m] = corners
        boxes_mask[:m] = 1.0
        classes_list[:m] = classes
        best = find_best_anchor(boxes_xywh[:, 2:4], anchors)
        for s, g in enumerate(grids):
            sel = np.isin(best, masks[s])
            if not sel.any():
                continue
            b = boxes_xywh[sel]
            cls = classes[sel]
            a_idx = np.searchsorted(masks[s], best[sel])
            gx = np.clip((b[:, 0] * g).astype(int), 0, g - 1)
            gy = np.clip((b[:, 1] * g).astype(int), 0, g - 1)
            y = out[f"y_true_{s}"]
            y[gy, gx, a_idx, 0:4] = b[:, 0:4]
            y[gy, gx, a_idx, 4] = 1.0
            y[gy, gx, a_idx, 5 + cls] = 1.0
    return {**out, "boxes": boxes_list, "boxes_mask": boxes_mask,
            "gt_classes": classes_list}


# ---------------------------------------------------------------------------
# Postprocess: decode all scales → NMS (postprocess.py:12-96, batched)
# ---------------------------------------------------------------------------


def postprocess(outputs, num_classes: int, max_outputs: int = 100,
                iou_threshold: float = 0.5, score_threshold: float = 0.1,
                anchors: np.ndarray = YOLO_ANCHORS,
                masks: np.ndarray = ANCHOR_MASKS,
                pre_nms_top_k: int = 512,
                class_aware: bool = False,
                soft_nms: str = "off", soft_sigma: float = 0.5,
                max_per_class: int = 0):
    """raw 3-scale outputs → (boxes (B,K,4) corners, scores (B,K),
    classes (B,K), valid (B,K)).

    Only the ``pre_nms_top_k`` highest-scoring candidates per image enter
    NMS: the greedy N×N IoU matrix over all 10,647 anchors at 416² costs
    ~20 GB HBM at batch 16 (an OOM), while top-512 costs ~1 MB.  A box
    outside the top-k can never outrank one inside it, so results differ
    from exhaustive NMS only if >top_k−max_outputs of the leading boxes
    get suppressed — pick top_k ≫ max_outputs (default 512 ≫ 100).

    ``class_aware=True`` makes suppression CLASS-WISE (a box only
    suppresses same-class neighbours, via ops/boxes' class-offset
    trick) — what the serving epilogue uses; the default keeps the
    reference's class-agnostic eval behavior.  Fully jittable either
    way: this whole function traces into the AOT bucket programs
    (serve/workloads.DetectWorkload.make_epilogue).

    ``soft_nms``/``soft_sigma`` switch suppression to Soft-NMS decay
    and ``max_per_class`` caps each class's kept boxes — the
    ``--detect-*`` serving knobs, threaded to ops/boxes.nms_single
    (per-class K needs ``class_aware=True``; it is ignored in
    class-agnostic mode where per-box labels do not partition the
    kept set).
    """
    all_boxes, all_scores, all_cls = [], [], []
    anchors = jnp.asarray(anchors)
    for s, raw in enumerate(outputs):
        box, obj, cls = decode_boxes(raw, anchors[masks[s]])
        B = raw.shape[0]
        scores = obj * cls  # per-class confidence
        best_cls = jnp.argmax(scores, -1)
        best_score = jnp.max(scores, -1)
        all_boxes.append(xywh_to_corners(box).reshape(B, -1, 4))
        all_scores.append(best_score.reshape(B, -1))
        all_cls.append(best_cls.reshape(B, -1))
    boxes = jnp.concatenate(all_boxes, 1)
    scores = jnp.concatenate(all_scores, 1)
    classes = jnp.concatenate(all_cls, 1)
    k = min(pre_nms_top_k, scores.shape[1])
    scores, top_idx = jax.lax.top_k(scores, k)
    boxes = jnp.take_along_axis(boxes, top_idx[..., None], axis=1)
    classes = jnp.take_along_axis(classes, top_idx, axis=1)
    idx, sel_scores, valid = batched_nms(
        boxes, scores, max_outputs, iou_threshold, score_threshold,
        classes=classes if class_aware else None,
        soft=soft_nms, soft_sigma=soft_sigma,
        max_per_class=max_per_class if class_aware else 0)
    sel_boxes = jnp.take_along_axis(boxes, idx[..., None], axis=1)
    sel_classes = jnp.take_along_axis(classes, idx, axis=1)
    return sel_boxes, sel_scores, sel_classes, valid
