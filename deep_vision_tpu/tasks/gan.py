"""GAN tasks for the AdversarialTrainer.

- DCGANTask — twin simultaneous G/D step with BCE-from-logits
  (DCGAN/tensorflow/main.py:42-71).
- CycleGANTask — 4-network step: one gradient over BOTH generators
  (LSGAN/MSE gan loss + L1 cycle λ=10 + L1 identity λ=5,
  CycleGAN/tensorflow/train.py:150-205), then one gradient over both
  discriminators fed POOLED fakes (:207-255); the 50-image ImagePool replay
  buffer (utils.py:32-61) is host-side state applied between jitted steps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deep_vision_tpu.core.optim import OptimizerConfig, build_optimizer
from deep_vision_tpu.core.state import TrainState


def _bce_logits(logits, target_ones: bool):
    t = jnp.ones_like(logits) if target_ones else jnp.zeros_like(logits)
    return optax.sigmoid_binary_cross_entropy(logits, t).mean()


def _mse(pred, target_ones: bool):
    t = jnp.ones_like(pred) if target_ones else jnp.zeros_like(pred)
    return jnp.square(pred - t).mean()


def _apply(state: TrainState, params, x, train, rng=None):
    variables = {"params": params}
    has_bn = bool(state.batch_stats)
    if has_bn:
        variables["batch_stats"] = state.batch_stats
    kwargs = dict(rngs={"dropout": rng}) if rng is not None else {}
    out = state.apply_fn(variables, x, train=train,
                         mutable=["batch_stats"] if (has_bn and train) else False,
                         **kwargs)
    if has_bn and train:
        out, new_vars = out
        return out, new_vars["batch_stats"]
    return out, state.batch_stats


class ImagePool:
    """50-image replay buffer (CycleGAN/tensorflow/utils.py:32-61): each
    fake is stored; with p=0.5 an older stored fake is returned instead.
    Host-side numpy — exactly as the reference keeps it eager-only."""

    def __init__(self, pool_size: int = 50, seed: int = 0):
        self.pool_size = pool_size
        self.pool: list[np.ndarray] = []
        self.rng = np.random.default_rng(seed)

    def query(self, images: np.ndarray) -> np.ndarray:
        if self.pool_size == 0:
            return images
        out = []
        for img in np.asarray(images):
            if len(self.pool) < self.pool_size:
                self.pool.append(img)
                out.append(img)
            elif self.rng.random() > 0.5:
                i = int(self.rng.integers(0, self.pool_size))
                out.append(self.pool[i])
                self.pool[i] = img
            else:
                out.append(img)
        return np.stack(out)


class DCGANTask:
    """models: generator (noise→image), discriminator (image→logit)."""

    # no host state between steps → the AdversarialTrainer may scan K
    # steps per dispatch (core/adversarial.py train_multi)
    scan_safe = True
    # host_prepare is stateless (identity) → batches may be staged ahead
    # by the DevicePrefetcher (core/adversarial.py _epoch_steps)
    prefetch_safe = True

    def __init__(self, generator, discriminator, latent_dim: int = 100,
                 opt: OptimizerConfig | None = None):
        self.generator = generator
        self.discriminator = discriminator
        self.latent_dim = latent_dim
        # reference: Adam(1e-4) for both (DCGAN/tensorflow/main.py:31-32)
        self.opt = opt or OptimizerConfig(name="adam", learning_rate=1e-4)

    def init_states(self, rng, sample_batch) -> dict:
        g_rng, d_rng = jax.random.split(rng)
        z = jnp.zeros((1, self.latent_dim))
        img = jnp.asarray(sample_batch["image"][:1])
        g_vars = self.generator.init({"params": g_rng}, z, train=False)
        d_vars = self.discriminator.init({"params": d_rng}, img, train=False)
        tx_g, tx_d = build_optimizer(self.opt), build_optimizer(self.opt)
        return {
            "generator": TrainState.create(
                apply_fn=self.generator.apply, params=g_vars["params"],
                tx=tx_g, batch_stats=g_vars.get("batch_stats", {}), rng=g_rng),
            "discriminator": TrainState.create(
                apply_fn=self.discriminator.apply, params=d_vars["params"],
                tx=tx_d, batch_stats=d_vars.get("batch_stats", {}), rng=d_rng),
        }

    def host_prepare(self, batch):
        return batch

    def host_update(self, outputs):
        pass

    def train_step(self, states, batch, rng):
        """Twin-tape simultaneous update (main.py:55-71): both grads are
        computed against the CURRENT params, then both applied."""
        g, d = states["generator"], states["discriminator"]
        # independent dropout masks per discriminator application — the
        # reference's eager TF calls each draw fresh masks
        z_rng, drop_g, drop_real, drop_fake = jax.random.split(rng, 4)
        real = batch["image"]
        z = jax.random.normal(z_rng, (real.shape[0], self.latent_dim))

        def g_loss_fn(g_params):
            fake, g_bs = _apply(g, g_params, z, train=True)
            fake_logit, _ = _apply(d, d.params, fake, train=True,
                                   rng=drop_g)
            return _bce_logits(fake_logit, True), (g_bs, fake)

        def d_loss_fn(d_params, fake):
            real_logit, _ = _apply(d, d_params, real, train=True,
                                   rng=drop_real)
            fake_logit, _ = _apply(d, d_params, fake, train=True,
                                   rng=drop_fake)
            return _bce_logits(real_logit, True) + _bce_logits(fake_logit,
                                                               False)

        (g_loss, (g_bs, fake)), g_grads = jax.value_and_grad(
            g_loss_fn, has_aux=True)(g.params)
        d_loss, d_grads = jax.value_and_grad(d_loss_fn)(
            d.params, jax.lax.stop_gradient(fake))
        new_states = {
            "generator": g.apply_gradients(g_grads, batch_stats=g_bs),
            "discriminator": d.apply_gradients(d_grads),
        }
        return new_states, {}, {"g_loss": g_loss, "d_loss": d_loss}

    def sample(self, states, n: int, rng) -> np.ndarray:
        """Inference path (DCGAN/tensorflow/inference.py:7-32)."""
        g = states["generator"]
        z = jax.random.normal(rng, (n, self.latent_dim))
        img, _ = _apply(g, g.params, z, train=False)
        return np.asarray(jax.device_get(img))


class CycleGANTask:
    """models: gen_a2b, gen_b2a, disc_a, disc_b."""

    # the per-step host ImagePool exchange (host_prepare/host_update)
    # is semantic — scanning would replay stale pools, so: per-step
    scan_safe = False
    # same hazard for the staged DevicePrefetcher: host_prepare draws
    # from the pool, so batches staged ahead would see it stale
    prefetch_safe = False

    LAMBDA_CYCLE = 10.0  # train.py:16
    LAMBDA_ID = 5.0      # train.py:17

    def __init__(self, make_generator, make_discriminator,
                 opt: OptimizerConfig | None = None, pool_size: int = 50):
        self.make_generator = make_generator
        self.make_discriminator = make_discriminator
        # reference: Adam(2e-4, β1=0.5) ×2 (train.py:126-131)
        self.opt = opt or OptimizerConfig(name="adam", learning_rate=2e-4,
                                          b1=0.5)
        self.pool_a2b = ImagePool(pool_size)
        self.pool_b2a = ImagePool(pool_size, seed=1)
        self._pending_fakes = None

    def init_states(self, rng, sample_batch) -> dict:
        img = jnp.asarray(sample_batch["image_a"][:1])
        states = {}
        models = {"gen_a2b": self.make_generator(),
                  "gen_b2a": self.make_generator(),
                  "disc_a": self.make_discriminator(),
                  "disc_b": self.make_discriminator()}
        for i, (name, model) in enumerate(models.items()):
            variables = model.init(
                {"params": jax.random.fold_in(rng, i)}, img, train=False)
            states[name] = TrainState.create(
                apply_fn=model.apply, params=variables["params"],
                tx=build_optimizer(self.opt),
                batch_stats=variables.get("batch_stats", {}),
                rng=jax.random.fold_in(rng, 100 + i))
        return states

    def host_prepare(self, batch):
        """Inject pooled fakes from the PREVIOUS step (host-side replay)."""
        batch = dict(batch)
        if self._pending_fakes is not None:
            fake_a2b, fake_b2a = self._pending_fakes
            batch["pool_a2b"] = self.pool_a2b.query(fake_a2b)
            batch["pool_b2a"] = self.pool_b2a.query(fake_b2a)
            batch["pool_valid"] = np.ones((), np.float32)
        else:
            batch["pool_a2b"] = np.zeros_like(batch["image_b"])
            batch["pool_b2a"] = np.zeros_like(batch["image_a"])
            batch["pool_valid"] = np.zeros((), np.float32)
        return batch

    def host_update(self, outputs):
        self._pending_fakes = (
            np.asarray(jax.device_get(outputs["fake_a2b"])),
            np.asarray(jax.device_get(outputs["fake_b2a"])))

    def train_step(self, states, batch, rng):
        real_a, real_b = batch["image_a"], batch["image_b"]
        g_ab, g_ba = states["gen_a2b"], states["gen_b2a"]
        d_a, d_b = states["disc_a"], states["disc_b"]

        # ---- generator step: ONE grad over both generators (:183-185)
        def gen_loss_fn(gen_params):
            p_ab, p_ba = gen_params
            fake_a2b, bs_ab = _apply(g_ab, p_ab, real_a, train=True)
            recon_a, bs_ba = _apply(g_ba, p_ba, fake_a2b, train=True)
            fake_b2a, bs_ba2 = _apply(g_ba, p_ba, real_b, train=True)
            recon_b, bs_ab2 = _apply(g_ab, p_ab, fake_b2a, train=True)
            ident_b, _ = _apply(g_ab, p_ab, real_b, train=True)
            ident_a, _ = _apply(g_ba, p_ba, real_a, train=True)
            logit_fake_b, _ = _apply(d_b, d_b.params, fake_a2b, train=True)
            logit_fake_a, _ = _apply(d_a, d_a.params, fake_b2a, train=True)
            gan = _mse(logit_fake_b, True) + _mse(logit_fake_a, True)
            cycle = jnp.abs(recon_a - real_a).mean() + \
                jnp.abs(recon_b - real_b).mean()
            ident = jnp.abs(ident_b - real_b).mean() + \
                jnp.abs(ident_a - real_a).mean()
            loss = gan + self.LAMBDA_CYCLE * cycle + self.LAMBDA_ID * ident
            return loss, (bs_ab2, bs_ba2, fake_a2b, fake_b2a,
                          {"gen_gan": gan, "cycle": cycle, "ident": ident})

        (g_loss, (bs_ab, bs_ba, fake_a2b, fake_b2a, g_metrics)), g_grads = \
            jax.value_and_grad(gen_loss_fn, has_aux=True)(
                (g_ab.params, g_ba.params))

        # ---- discriminator step with pooled fakes (:207-246); on the very
        # first step (empty pool) fall back to this step's fakes
        use_pool = batch["pool_valid"] > 0
        pool_a2b = jnp.where(use_pool, batch["pool_a2b"],
                             jax.lax.stop_gradient(fake_a2b))
        pool_b2a = jnp.where(use_pool, batch["pool_b2a"],
                             jax.lax.stop_gradient(fake_b2a))

        def disc_loss_fn(disc_params):
            p_a, p_b = disc_params
            logit_real_a, bs_a = _apply(d_a, p_a, real_a, train=True)
            logit_fake_a, _ = _apply(d_a, p_a, pool_b2a, train=True)
            logit_real_b, bs_b = _apply(d_b, p_b, real_b, train=True)
            logit_fake_b, _ = _apply(d_b, p_b, pool_a2b, train=True)
            loss_a = (_mse(logit_real_a, True) + _mse(logit_fake_a, False)) / 2
            loss_b = (_mse(logit_real_b, True) + _mse(logit_fake_b, False)) / 2
            return loss_a + loss_b, (bs_a, bs_b,
                                     {"disc_a": loss_a, "disc_b": loss_b})

        (d_loss, (bs_a, bs_b, d_metrics)), d_grads = jax.value_and_grad(
            disc_loss_fn, has_aux=True)((d_a.params, d_b.params))

        new_states = {
            "gen_a2b": g_ab.apply_gradients(g_grads[0], batch_stats=bs_ab),
            "gen_b2a": g_ba.apply_gradients(g_grads[1], batch_stats=bs_ba),
            "disc_a": d_a.apply_gradients(d_grads[0], batch_stats=bs_a),
            "disc_b": d_b.apply_gradients(d_grads[1], batch_stats=bs_b),
        }
        outputs = {"fake_a2b": jax.lax.stop_gradient(fake_a2b),
                   "fake_b2a": jax.lax.stop_gradient(fake_b2a)}
        metrics = {"g_loss": g_loss, "d_loss": d_loss,
                   **g_metrics, **d_metrics}
        return new_states, outputs, metrics

    def translate(self, states, images, direction: str = "a2b") -> np.ndarray:
        """Inference path (CycleGAN/tensorflow/inference.py:11-77)."""
        g = states["gen_a2b" if direction == "a2b" else "gen_b2a"]
        out, _ = _apply(g, g.params, jnp.asarray(images), train=False)
        return np.asarray(jax.device_get(out))
