"""Classification task: softmax cross-entropy + top-k accuracy.

Mirrors the reference's ``nn.CrossEntropyLoss`` + ``accuracy(topk=(1,5))``
(ResNet/pytorch/train.py:358, :524-538) and the Inception multi-head loss
(aux classifiers weighted 0.3 — Inception/pytorch/train.py discounts per the
GoogLeNet paper; model emits (logits, aux1, aux2) in training mode,
Inception/pytorch/models/inception_v1.py:92-113).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


@jax.custom_jvp
def _barrier(x):
    return jax.lax.optimization_barrier(x)


@_barrier.defjvp
def _barrier_jvp(primals, tangents):
    # The barrier is an identity: pass the tangent straight through.
    # jax.lax.optimization_barrier has no differentiation rule of its own
    # on some JAX versions, which would otherwise make the training loss
    # non-differentiable.
    (x,), (t,) = primals, tangents
    return jax.lax.optimization_barrier(x), t


def _materialize(logits):
    """f32 logits behind an optimization barrier.

    Without the barrier, XLA on TPU may fuse/rematerialize the (bf16)
    classifier matmul separately into the cross-entropy's max-reduce and
    exp-sum-reduce; the two recomputations can disagree in the last bf16
    bits, so the computed log-normalizer falls BELOW the true-class logit
    and the "cross-entropy" goes negative (observed: −0.04/sample on a
    converged eval whose true loss was 1e-6 — a ~0.04 absolute error
    hiding inside every fused eval loss).  The barrier forces the logits
    to materialize once, making both reductions read the same values.
    """
    return _barrier(logits.astype(jnp.float32))


class ClassificationTask:
    monitor = "top1"

    def __init__(self, num_classes: int, label_smoothing: float = 0.0,
                 aux_weight: float = 0.3):
        self.num_classes = num_classes
        self.label_smoothing = label_smoothing
        self.aux_weight = aux_weight

    def _xent(self, logits, labels):
        logits = _materialize(logits)
        if self.label_smoothing > 0:
            onehot = optax.smooth_labels(
                jnp.eye(self.num_classes)[labels], self.label_smoothing)
            return optax.softmax_cross_entropy(logits, onehot).mean()
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    def loss(self, outputs, batch):
        labels = batch["label"]
        if isinstance(outputs, (tuple, list)):  # main + aux heads (Inception)
            main, *aux = outputs
            loss = self._xent(main, labels)
            for a in aux:
                loss = loss + self.aux_weight * self._xent(a, labels)
            logits = main
        else:
            loss = self._xent(outputs, labels)
            logits = outputs
        top1 = (jnp.argmax(logits, -1) == labels).mean()
        return loss, {"top1": top1}

    def eval_metrics(self, outputs, batch):
        logits = outputs[0] if isinstance(outputs, (tuple, list)) else outputs
        logits = _materialize(logits)
        labels = batch["label"]
        # weight=0 marks padded filler rows from pad_last loaders
        w = batch.get("weight", jnp.ones(labels.shape[0], jnp.float32))
        xent = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        top1 = ((jnp.argmax(logits, -1) == labels) * w).sum()
        k = min(5, logits.shape[-1])
        topk_idx = jnp.argsort(logits, -1)[:, -k:]
        top5 = ((topk_idx == labels[:, None]).any(-1) * w).sum()
        return {"loss": (xent * w).sum(), "top1": top1,
                "top5": top5, "count": w.sum()}
