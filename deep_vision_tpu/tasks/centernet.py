"""CenterNet task: the loss/labels/decode the reference left empty
(ObjectsAsPoints/tensorflow/train.py:35 ``loss_objects = []``, trainer
commented out :248; preprocess computes labels then throws them away
:22-27).  Implemented per the "Objects as Points" paper:

- penalty-reduced pixelwise focal loss on the class heatmap (α=2, β=4)
- L1 on wh (weight 0.1) and center offset (weight 1), at positives only
- size-adaptive Gaussian radius label splat (vectorized)
- decode: 3×3 max-pool peak NMS + top-K, no box-NMS needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

MAX_OBJECTS = 100


def gaussian_radius(h: np.ndarray, w: np.ndarray, min_iou: float = 0.7
                    ) -> np.ndarray:
    """CenterNet's size-adaptive radius: smallest r such that a corner
    shifted by r still gives IoU ≥ min_iou (the CornerNet derivation)."""
    a1, b1 = 1.0, h + w
    c1 = w * h * (1 - min_iou) / (1 + min_iou)
    r1 = (b1 - np.sqrt(np.maximum(b1**2 - 4 * a1 * c1, 0))) / 2
    a2, b2 = 4.0, 2 * (h + w)
    c2 = (1 - min_iou) * w * h
    r2 = (b2 - np.sqrt(np.maximum(b2**2 - 4 * a2 * c2, 0))) / 2
    a3, b3 = 4 * min_iou, -2 * min_iou * (h + w)
    c3 = (min_iou - 1) * w * h
    r3 = (b3 + np.sqrt(np.maximum(b3**2 - 4 * a3 * c3, 0))) / (2 * a3)
    return np.maximum(np.minimum(np.minimum(r1, r2), r3), 0.0)


def encode_centernet_labels(boxes_xywh: np.ndarray, classes: np.ndarray,
                            num_classes: int, grid: int = 64) -> dict:
    """One image's gt (normalized centroid xywh) → training targets.

    Returns {"heatmap": (G,G,C), "wh": (M,2), "offset": (M,2),
    "indices": (M,) flat grid index, "obj_mask": (M,),
    "boxes": (M,4) normalized corner gt list, "gt_classes": (M,)} —
    the gt list feeds the host mAP accumulator.
    """
    heat = np.zeros((grid, grid, num_classes), np.float32)
    wh = np.zeros((MAX_OBJECTS, 2), np.float32)
    offset = np.zeros((MAX_OBJECTS, 2), np.float32)
    indices = np.zeros((MAX_OBJECTS,), np.int64)
    mask = np.zeros((MAX_OBJECTS,), np.float32)
    boxes_list = np.zeros((MAX_OBJECTS, 4), np.float32)
    classes_list = np.zeros((MAX_OBJECTS,), np.int32)
    n = min(len(boxes_xywh), MAX_OBJECTS)
    if n:
        b = np.asarray(boxes_xywh[:n], np.float32)
        cls = np.asarray(classes[:n], np.int64)
        cx, cy = b[:, 0] * grid, b[:, 1] * grid
        gw, gh = b[:, 2] * grid, b[:, 3] * grid
        xi = np.clip(cx.astype(np.int64), 0, grid - 1)
        yi = np.clip(cy.astype(np.int64), 0, grid - 1)
        radius = np.maximum(gaussian_radius(gh, gw).astype(np.int64), 0)
        ys, xs = np.mgrid[0:grid, 0:grid]
        for k in range(n):
            sigma = max((2 * radius[k] + 1) / 6.0, 1e-3)
            g = np.exp(-((xs - xi[k]) ** 2 + (ys - yi[k]) ** 2)
                       / (2 * sigma**2))
            g = np.where((np.abs(xs - xi[k]) <= radius[k]) &
                         (np.abs(ys - yi[k]) <= radius[k]), g, 0.0)
            c = cls[k]
            heat[:, :, c] = np.maximum(heat[:, :, c], g)
            heat[yi[k], xi[k], c] = 1.0
        wh[:n] = np.stack([gw, gh], 1)
        offset[:n] = np.stack([cx - xi, cy - yi], 1)
        indices[:n] = yi * grid + xi
        mask[:n] = 1.0
        boxes_list[:n] = np.concatenate(
            [b[:, :2] - b[:, 2:4] / 2, b[:, :2] + b[:, 2:4] / 2], 1)
        classes_list[:n] = cls
    return {"heatmap": heat, "wh": wh, "offset": offset,
            "indices": indices, "obj_mask": mask,
            "boxes": boxes_list, "gt_classes": classes_list}


def focal_loss(pred_logits, gt_heatmap, alpha: float = 2.0, beta: float = 4.0,
               eps: float = 1e-6):
    """Penalty-reduced pixelwise focal loss, normalized by num positives."""
    p = jax.nn.sigmoid(pred_logits)
    pos = (gt_heatmap >= 1.0).astype(jnp.float32)
    neg_weight = jnp.power(1.0 - gt_heatmap, beta)
    pos_loss = -jnp.log(jnp.clip(p, eps)) * jnp.power(1 - p, alpha) * pos
    neg_loss = -jnp.log(jnp.clip(1 - p, eps)) * jnp.power(p, alpha) * \
        neg_weight * (1 - pos)
    num_pos = jnp.maximum(pos.sum(axis=(1, 2, 3)), 1.0)
    return (pos_loss.sum(axis=(1, 2, 3)) +
            neg_loss.sum(axis=(1, 2, 3))) / num_pos


def _gather_at(features, indices):
    """features (B,G,G,C), indices (B,M) flat → (B,M,C)."""
    B, G = features.shape[0], features.shape[1]
    flat = features.reshape(B, G * G, -1)
    return jnp.take_along_axis(flat, indices[..., None], axis=1)


class CenterNetTask:
    monitor = "mAP"

    def __init__(self, num_classes: int, wh_weight: float = 0.1,
                 offset_weight: float = 1.0,
                 eval_score_threshold: float = 0.05):
        self.num_classes = num_classes
        self.wh_weight = wh_weight
        self.offset_weight = offset_weight
        self.eval_score_threshold = eval_score_threshold

    def _stack_loss(self, heat, wh, offset, batch):
        l_heat = focal_loss(heat, batch["heatmap"]).mean()
        mask = batch["obj_mask"][..., None]
        n = jnp.maximum(batch["obj_mask"].sum(), 1.0)
        pred_wh = _gather_at(wh, batch["indices"])
        pred_off = _gather_at(offset, batch["indices"])
        l_wh = (jnp.abs(pred_wh - batch["wh"]) * mask).sum() / n
        l_off = (jnp.abs(pred_off - batch["offset"]) * mask).sum() / n
        return l_heat, l_wh, l_off

    def loss(self, outputs, batch):
        total = 0.0
        comps = {}
        for s, (heat, wh, offset) in enumerate(outputs):
            l_heat, l_wh, l_off = self._stack_loss(heat, wh, offset, batch)
            total = total + l_heat + self.wh_weight * l_wh + \
                self.offset_weight * l_off
            comps.update({f"heat_{s}": l_heat, f"wh_{s}": l_wh,
                          f"off_{s}": l_off})
        return total, comps

    def eval_metrics(self, outputs, batch):
        # per-image loss (objects normalized per image rather than per
        # batch), masked by the eval-padding weight
        w = batch.get("weight")
        if w is None:
            w = jnp.ones((batch["heatmap"].shape[0],), jnp.float32)
        mask = batch["obj_mask"][..., None]
        n_img = jnp.maximum(batch["obj_mask"].sum(-1), 1.0)
        per_image = 0.0
        for heat, wh, offset in outputs:
            l_heat = focal_loss(heat, batch["heatmap"])            # (B,)
            pred_wh = _gather_at(wh, batch["indices"])
            pred_off = _gather_at(offset, batch["indices"])
            l_wh = (jnp.abs(pred_wh - batch["wh"]) * mask).sum((1, 2)) / n_img
            l_off = (jnp.abs(pred_off - batch["offset"]) * mask
                     ).sum((1, 2)) / n_img
            per_image = per_image + l_heat + self.wh_weight * l_wh + \
                self.offset_weight * l_off
        loss_sum = (per_image * w).sum()
        return {"loss": loss_sum, "neg_loss": -loss_sum, "count": w.sum()}

    def eval_outputs(self, outputs, batch):
        """Decode the FINAL stack's peaks for the host mAP accumulator;
        boxes normalized to [0,1] to match the encoded gt list."""
        heat, wh, offset = outputs[-1]
        G = heat.shape[1]
        boxes, scores, cls = decode_detections(heat, wh, offset)
        valid = (scores > self.eval_score_threshold).astype(jnp.float32)
        return {"det_boxes": boxes / G, "det_scores": scores,
                "det_classes": cls, "det_valid": valid,
                "gt_boxes": batch["boxes"], "gt_mask": batch["obj_mask"],
                "gt_classes": batch["gt_classes"]}

    def make_host_evaluator(self):
        from deep_vision_tpu.tasks.map_eval import DetectionMAPAccumulator

        return DetectionMAPAccumulator(self.num_classes)


def decode_detections(heat_logits, wh, offset, k: int = 100):
    """Peak-NMS (3×3 max-pool) + top-K → (boxes xyxy grid coords, scores,
    classes) — the paper's NMS-free decode."""
    B, G = heat_logits.shape[0], heat_logits.shape[1]
    heat = jax.nn.sigmoid(heat_logits)
    peak = jax.lax.reduce_window(
        heat, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 1, 1, 1), "SAME")
    heat = jnp.where(heat == peak, heat, 0.0)
    flat = heat.reshape(B, -1)                         # (B, G·G·C)
    scores, idx = jax.lax.top_k(flat, k)
    C = heat_logits.shape[-1]
    cls = idx % C
    cell = idx // C
    ys, xs = cell // G, cell % G
    cell_idx = ys * G + xs
    pwh = _gather_at(wh, cell_idx)
    poff = _gather_at(offset, cell_idx)
    cx = xs + poff[..., 0]
    cy = ys + poff[..., 1]
    boxes = jnp.stack([cx - pwh[..., 0] / 2, cy - pwh[..., 1] / 2,
                       cx + pwh[..., 0] / 2, cy + pwh[..., 1] / 2], -1)
    return boxes, scores, cls
