"""The unified Trainer.

One trainer replacing the reference's three generations (SURVEY §1): the
PyTorch imperative loop (``run_epochs``/``train``/``validate``,
ResNet/pytorch/train.py:310-520), TF1-Keras ``model.fit``
(ResNet/tensorflow/train.py:221-297), and TF2 MirroredStrategy custom loops
(YOLO/tensorflow/train.py:122-250).

TPU mapping:
- the whole train step (forward, loss, backward, optimizer) is ONE jitted
  function with donated state — XLA fuses elementwise ops into the conv/matmul
  MXU kernels and inserts the data-parallel gradient all-reduce from the
  batch's ``data``-axis sharding (GSPMD), the psum the reference got from NCCL
  inside DataParallel/MirroredStrategy;
- metrics come back as device scalars, fetched asynchronously so the host
  epoch loop (LR plateau logic, best-val checkpointing — the reference's
  host-side callbacks) never stalls the device pipeline;
- eval accumulates metric *sums* on device and normalizes on host, like the
  reference's running ``total_correct/total`` counters
  (ResNet/pytorch/train.py:488-520).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Iterable

import jax
import jax.numpy as jnp

from deep_vision_tpu.core import checkpoint as ckpt_lib
from deep_vision_tpu.core.config import TrainConfig
from deep_vision_tpu.core.metrics import MetricLogger, ThroughputMeter
from deep_vision_tpu.core.optim import (
    build_optimizer,
    build_scheduler,
    set_learning_rate,
)
from deep_vision_tpu.core.state import DivergenceGuard, TrainState
from deep_vision_tpu.parallel import make_mesh, replicate, shard_batch


def install_sigterm_flag(on_sigterm):
    """Install a SIGTERM → callback handler; returns a restore function.
    Safe when not on the main thread (no-op) and when the previous handler
    was installed outside Python (restores SIG_DFL, not None)."""
    import signal

    try:
        prev = signal.signal(signal.SIGTERM, lambda *_: on_sigterm())
    except ValueError:  # not the main thread: no handler, no-op restore
        return lambda: None
    restore_to = prev if prev is not None else signal.SIG_DFL
    return lambda: signal.signal(signal.SIGTERM, restore_to)


class Trainer:
    """Single-model/single-optimizer trainer (classification, detection,
    pose).  Adversarial multi-model training lives in
    :class:`deep_vision_tpu.core.adversarial.AdversarialTrainer`."""

    def __init__(self, config: TrainConfig, model, task, mesh=None,
                 workdir: str | None = None, preprocess_fn=None,
                 upload: str | None = None):
        self.config = config
        ema = float(getattr(config, "ema_decay", 0.0))
        if not 0.0 <= ema < 1.0:
            raise ValueError(
                f"ema_decay={ema} must be in [0, 1): 1.0 would freeze the "
                f"EMA at its init forever, >1 diverges")
        self.model = model
        self.task = task
        # optional device-side input preprocessing run INSIDE the jitted
        # steps (e.g. uint8→jitter→normalize, ops/preprocess.py) — XLA
        # fuses it into the first conv; signature (batch, rng, train)
        self.preprocess_fn = preprocess_fn
        self.mesh = mesh if mesh is not None else make_mesh()
        self.workdir = workdir or os.path.join("runs", config.name)
        self.logger = MetricLogger(self.workdir)
        self.tx = build_optimizer(config.optimizer)
        self.scheduler = build_scheduler(
            config.scheduler.name, config.optimizer.learning_rate,
            **config.scheduler.kwargs)
        # optional off-host artifact sync after each checkpoint (the
        # Hourglass GCS-upload role, Hourglass/tensorflow/main.py:21-65)
        self.uploader = None
        if upload:
            from deep_vision_tpu.core.upload import ArtifactUploader

            self.uploader = ArtifactUploader(upload)
            # preemption recovery: a fresh host (empty workdir) with a
            # populated mirror pulls the checkpoints back down before the
            # Checkpointer (whose Orbax manager scans at construction) and
            # maybe_resume() look for them — without this, the first
            # post-checkpoint sync of the fresh run would instead wipe
            # the mirror's preempt-saved copies (the only ones left)
            ckpt_dir = os.path.join(self.workdir, "checkpoints")
            if not os.path.isdir(ckpt_dir) or not os.listdir(ckpt_dir):
                self.uploader.restore(ckpt_dir, "checkpoints")
                self.uploader.restore(
                    os.path.join(self.workdir, "checkpoints_best"),
                    "checkpoints_best")
        self.checkpointer = ckpt_lib.Checkpointer(
            os.path.join(self.workdir, "checkpoints"),
            max_to_keep=config.keep_checkpoints)
        self.best_checkpointer = ckpt_lib.Checkpointer(
            os.path.join(self.workdir, "checkpoints_best"), max_to_keep=1)
        self._has_bn: bool | None = None
        self._jit_train_step = None
        self._jit_train_multi = None
        self._jit_eval_step = None
        self.start_epoch = 1
        self.guard = DivergenceGuard(config.max_bad_steps)
        # preemption safety: TPU VMs get SIGTERM before eviction; fit()
        # installs a handler that requests a step-boundary checkpoint +
        # clean return so a preempted run loses at most one step, not an
        # epoch (the reference could only resume from its last epoch save)
        self._preempted = False
        # profiling: trace steps [start, stop) of epoch 1 to
        # workdir/profile (the reference had only throughput prints —
        # SURVEY §5 tracing; TPU-native answer is a jax.profiler trace)
        self.profile_steps: tuple[int, int] | None = None
        # staged input pipeline (data/pipeline.DevicePrefetcher): built
        # lazily on the first train epoch, persists across epochs so the
        # host staging pool reuses its buffers, closed by fit()'s finally
        # path so abandoned epochs leak neither thread nor device batches
        self.prefetch_depth = max(1, int(getattr(config,
                                                 "prefetch_depth", 2)))
        self._prefetcher = None

    # ------------------------------------------------------------------ init

    def init_state(self, sample_batch: dict) -> TrainState:
        rng = jax.random.PRNGKey(self.config.seed)
        init_rng, state_rng = jax.random.split(rng)
        image = jnp.asarray(sample_batch["image"][:1])
        if self.preprocess_fn is not None:
            image = self.preprocess_fn({"image": image}, init_rng,
                                       train=False)["image"]
        variables = jax.jit(
            functools.partial(self.model.init, train=False)
        )({"params": init_rng, "dropout": init_rng}, image)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        self._has_bn = "batch_stats" in variables
        state = TrainState.create(
            apply_fn=self.model.apply, params=params, tx=self.tx,
            batch_stats=batch_stats, rng=state_rng,
            ema=getattr(self.config, "ema_decay", 0.0) > 0)
        return self._place_state(state)

    @staticmethod
    @functools.lru_cache(maxsize=None)
    def _resharder(sharding):
        """One jitted identity per DISTINCT target sharding (its own jit
        cache then keys on leaf shape/dtype), so a reshard-restore
        compiles O(distinct shardings), not O(leaves) — a fresh
        ``jax.jit`` per leaf never hits the compile cache."""
        return jax.jit(lambda a: a, out_shardings=sharding)

    def _place_state(self, state: TrainState) -> TrainState:
        """Place state on the mesh.  Models that partition their own state
        (e.g. pipeline stages over ``pipe`` —
        ``parallel.pipelined.PipelinedModel.state_partition_rule``) expose
        a per-leaf rule: (path string, leaf) → PartitionSpec; params, EMA
        copy, and optimizer moments all flow through it (the moments
        mirror the param tree, so path matching covers them).  Without a
        rule, everything is replicated (the dp/tp default)."""
        rule = getattr(self.model, "state_partition_rule", None)
        if rule is None:
            return replicate(state, self.mesh)
        from jax.sharding import NamedSharding

        multiproc = jax.process_count() > 1

        def place(path, leaf):
            spec = rule(jax.tree_util.keystr(path), leaf)
            sharding = NamedSharding(self.mesh, spec)
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                # already a GLOBAL array — e.g. Orbax restored it into the
                # placed template's shardings on a multi-process mesh; its
                # remote shards can't be read host-side, and don't need to
                # be: keep it, or reshard device-side if the target differs
                if leaf.sharding.is_equivalent_to(sharding, leaf.ndim):
                    return leaf
                return self._resharder(sharding)(leaf)
            if multiproc:
                # device_put can't build a multi-host global array from a
                # host-local value; assemble it the way replicate() does.
                # global_shape=leaf.shape: every host holds the FULL leaf
                # (init/restore are replicated), so local data IS the global
                # array — without it, a rule axis spanning processes would
                # be inferred as a per-host chunk and double-counted
                return jax.make_array_from_process_local_data(
                    sharding, leaf, global_shape=leaf.shape)
            return jax.device_put(leaf, sharding)

        return jax.tree_util.tree_map_with_path(place, state)

    def maybe_resume(self, state: TrainState) -> TrainState:
        """Resume from the latest checkpoint if one exists (the reference's
        ``-c`` flag, ResNet/pytorch/train.py:381-388)."""
        if self.checkpointer.latest_step() is None:
            return state
        # reconcile EMA with what the checkpoint actually stores: enabling
        # --ema-decay on a checkpoint trained without it must seed the EMA
        # from the RESTORED params (not the fresh random init the template
        # carries, and not crash on a {} / missing stored subtree)
        ema_on = float(getattr(self.config, "ema_decay", 0.0)) > 0
        if ema_on and not self.checkpointer.has_state_key("ema_params"):
            state, extras = self.checkpointer.restore(
                state.replace(ema_params={}))
            state = state.replace(ema_params=jax.tree_util.tree_map(
                jnp.array, state.params))
            print("[resume] checkpoint has no EMA — seeded from restored "
                  "params")
        else:
            state, extras = self.checkpointer.restore(state)
        self.start_epoch = int(extras.get("epoch", 0)) + 1
        if "scheduler" in extras:
            self.scheduler.load_state_dict(extras["scheduler"])
        if "history" in extras:
            self.logger.load_state_dict(extras["history"])
        # old skips must not count against the resumed run's budget
        self.guard.set_baseline(int(jax.device_get(state.bad_steps)))
        print(f"[resume] restored step={int(state.step)} "
              f"start_epoch={self.start_epoch}")
        return self._place_state(state)

    # ------------------------------------------------------------- jit steps

    def _build_steps(self):
        task, has_bn = self.task, self._has_bn
        preprocess_fn = self.preprocess_fn

        accum = max(1, getattr(self.config, "grad_accum_steps", 1))
        ema_decay = float(getattr(self.config, "ema_decay", 0.0))

        def grad_one(apply_fn, params, batch_stats, dropout_rng, batch):
            """loss/grads/BN-update for ONE (micro)batch."""

            def loss_fn(params):
                variables = {"params": params}
                if has_bn:
                    variables["batch_stats"] = batch_stats
                out = apply_fn(
                    variables, batch["image"], train=True,
                    rngs={"dropout": dropout_rng},
                    mutable=["batch_stats"] if has_bn else False)
                if has_bn:
                    out, new_vars = out
                    new_bs = new_vars["batch_stats"]
                else:
                    new_bs = batch_stats
                loss, aux = task.loss(out, batch)
                return loss, (new_bs, aux)

            (loss, (new_bs, aux)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return loss, new_bs, aux, grads

        def train_step(state: TrainState, batch: dict):
            step_rng = jax.random.fold_in(state.rng, state.step)
            if preprocess_fn is not None:
                batch = preprocess_fn(
                    batch, jax.random.fold_in(step_rng, 1), train=True)

            if accum == 1:
                loss, new_bs, aux, grads = grad_one(
                    state.apply_fn, state.params, state.batch_stats,
                    step_rng, batch)
            else:
                # gradient accumulation: A sequential microbatches, one
                # optimizer update.  Interleaved split (microbatch a =
                # batch[a::A]) keeps every microbatch evenly spread over
                # the data-sharded batch dim, so each micro-step is the
                # same all-devices data-parallel step — GSPMD sees a
                # local reshape, no resharding.  Mean-reduced losses make
                # the averaged grads EXACTLY the full-batch grads for
                # BN-free models (tests/test_grad_accum.py); with BN,
                # stats thread through microbatches sequentially.
                b = jax.tree_util.tree_leaves(batch)[0].shape[0]
                if b % accum:
                    raise ValueError(
                        f"global batch {b} not divisible by "
                        f"grad_accum_steps={accum}")

                def split(x):
                    return jnp.swapaxes(
                        x.reshape(x.shape[0] // accum, accum,
                                  *x.shape[1:]), 0, 1)

                micro = jax.tree_util.tree_map(split, batch)
                gzero = jax.tree_util.tree_map(jnp.zeros_like, state.params)

                def body(carry, xs):
                    bs, gsum = carry
                    mb, i = xs
                    l, bs, a, g = grad_one(
                        state.apply_fn, state.params, bs,
                        jax.random.fold_in(step_rng, 2 + i), mb)
                    gsum = jax.tree_util.tree_map(jnp.add, gsum, g)
                    return (bs, gsum), (l, a)

                (new_bs, gsum), (losses, auxes) = jax.lax.scan(
                    body, (state.batch_stats, gzero),
                    (micro, jnp.arange(accum)))
                grads = jax.tree_util.tree_map(
                    lambda g: g / accum, gsum)
                loss = jnp.mean(losses)
                aux = jax.tree_util.tree_map(
                    lambda a: jnp.mean(a, axis=0), auxes)

            # divergence guard: a non-finite loss/grad step is skipped (not
            # applied) and counted; the epoch loop halts past
            # config.max_bad_steps (reference context: the NaN val losses
            # Hourglass/tensorflow/train.py:126-130 only TODO'd about)
            new_state = state.apply_gradients_if_finite(
                loss, grads, batch_stats=new_bs)
            if ema_decay:
                # guard-aware: a skipped step reverted params, so the EMA
                # merely re-averages toward the unchanged weights.
                # Warmup (tf.train.ExponentialMovingAverage num_updates /
                # timm ModelEmaV2 semantics): the effective decay ramps as
                # min(d, (1+t)/(10+t)) so short or freshly-seeded runs
                # aren't dominated by the seed point at high decays.
                t = new_state.step.astype(jnp.float32)
                d = jnp.minimum(ema_decay, (1.0 + t) / (10.0 + t))
                new_state = new_state.replace(
                    ema_params=jax.tree_util.tree_map(
                        lambda e, p: d * e + (1 - d) * p,
                        new_state.ema_params, new_state.params))
            metrics = {"loss": loss, "bad_steps": new_state.bad_steps, **aux}
            return new_state, metrics

        # host-evaluator protocol (e.g. detection mAP): the task decodes
        # postprocessed outputs ON DEVICE (static shapes — decode+NMS stay
        # XLA-compiled) in the SAME forward pass as the loss metrics; the
        # host accumulates AP across the val set
        has_outputs = hasattr(task, "eval_outputs")

        def eval_step(state: TrainState, batch: dict):
            if preprocess_fn is not None:
                batch = preprocess_fn(batch, jax.random.PRNGKey(0),
                                      train=False)
            # modern-recipe semantics: with EMA on, validation/serving
            # scores the averaged copy (what gets deployed), not the raw
            # last-step weights.  Emptiness is pytree structure — static
            # at trace time — so a state without an EMA copy (old
            # checkpoint, external caller) falls back to raw params
            # instead of crashing.
            use_ema = ema_decay and bool(
                jax.tree_util.tree_leaves(state.ema_params))
            variables = {"params": state.ema_params if use_ema
                         else state.params}
            if has_bn:
                variables["batch_stats"] = state.batch_stats
            out = state.apply_fn(variables, batch["image"], train=False)
            sums = task.eval_metrics(out, batch)
            extra = None
            if has_outputs:
                extra = task.eval_outputs(out, batch)
                if "weight" in batch:
                    extra["weight"] = batch["weight"]
            return sums, extra

        # donate the BATCH too (argnum 1): the prefetcher's device batches
        # are consumed exactly once, so XLA may overwrite their HBM in
        # place — input buffers stop double-counting against HBM headroom.
        # Host numpy batches (tests, direct callers) are unaffected:
        # donation only claims committed jax.Arrays.
        self._jit_train_step = jax.jit(train_step, donate_argnums=(0, 1))
        self._jit_eval_step = jax.jit(eval_step)

        # multi-step dispatch (config.scan_steps > 1): K steps per device
        # program via lax.scan over stacked batches — per-dispatch host
        # overhead (~2ms/step through a tunneled chip) amortizes K×.
        # Metrics come back per step ((K,)-leaved tree) so the guard still
        # sees every step.
        if getattr(self.config, "scan_steps", 1) > 1:
            def multi_train_step(state: TrainState, batches: dict):
                def body(s, b):
                    return train_step(s, b)

                # unroll=2: halves loop-trip overhead and lets XLA overlap
                # step i's update with step i+1's first convs (bench.py:
                # 99.6 ms/step vs 101.1 at unroll=1 on the v5e)
                return jax.lax.scan(body, state, batches, unroll=2)

            self._jit_train_multi = jax.jit(multi_train_step,
                                            donate_argnums=(0, 1))

    def train_step(self, state, batch):
        if self._jit_train_step is None:
            self._build_steps()
        return self._jit_train_step(state, shard_batch(batch, self.mesh))

    def eval_step(self, state, batch):
        """Metric sums for one batch (decoded-output extras, if the task
        produces them, are consumed by :meth:`evaluate`)."""
        if self._jit_eval_step is None:
            self._build_steps()
        sums, _ = self._jit_eval_step(state, shard_batch(batch, self.mesh))
        return sums

    # ------------------------------------------------------------------ loops

    def evaluate(self, state: TrainState, val_data: Iterable) -> dict:
        if self._has_bn is None:
            # evaluating a restored state without going through init_state
            # (e.g. cli.infer eval): derive BN presence from the state
            self._has_bn = bool(state.batch_stats)
        if self._jit_eval_step is None:
            self._build_steps()
        make_ev = getattr(self.task, "make_host_evaluator", None)
        evaluator = make_ev() if make_ev is not None else None
        totals: dict[str, float] = {}
        for batch in val_data:
            batch = shard_batch(batch, self.mesh)
            sums, extra = self._jit_eval_step(state, batch)
            sums = jax.device_get(sums)
            for k, v in sums.items():
                totals[k] = totals.get(k, 0.0) + float(v)
            if evaluator is not None and extra is not None:
                if jax.process_count() > 1:
                    # extras are batch-sharded over `data`, which spans
                    # processes — gather every host's shard (the gather
                    # is collective: every rank must call it) but feed
                    # the host-side accumulator on process 0 ONLY; the
                    # other ranks get the scalar metrics broadcast below
                    # instead of redoing the whole mAP sweep per rank
                    from jax.experimental import multihost_utils
                    extra = multihost_utils.process_allgather(extra,
                                                              tiled=True)
                    if jax.process_index() != 0:
                        continue
                else:
                    extra = jax.device_get(extra)
                evaluator.add_batch(extra)
        count = max(totals.pop("count", 1.0), 1.0)
        out = {k: v / count for k, v in totals.items()}
        if evaluator is not None:
            ev = evaluator.compute()
            if jax.process_count() > 1:
                # non-zero ranks hold an EMPTY accumulator: compute()
                # still yields the metric KEYS (zero-valued), which is
                # all they need to receive rank 0's values in a fixed
                # key order — every rank reports identical metrics while
                # only one ran the host-side mAP sweep
                import numpy as np
                from jax.experimental import multihost_utils
                keys = sorted(k for k, v in ev.items()
                              if isinstance(v, (int, float)))
                vals = multihost_utils.broadcast_one_to_all(
                    np.asarray([float(ev[k]) for k in keys], np.float32))
                ev = {k: float(v) for k, v in zip(keys, np.asarray(vals))}
            out.update(ev)
        return out

    def _get_prefetcher(self):
        if self._prefetcher is None:
            from deep_vision_tpu.data.pipeline import DevicePrefetcher

            self._prefetcher = DevicePrefetcher(self.mesh,
                                                depth=self.prefetch_depth)
        return self._prefetcher

    def _log_input_stats(self, step: int, stats: dict, epoch: int):
        """The input-goodput block: epoch-level stall fraction + per-step
        H2D traffic from the prefetcher's stage timers, logged to the
        MetricLogger series and echoed as one epoch line."""
        if not stats or not stats.get("batches"):
            return
        self.logger.log_input_block(step, stats)
        prod = stats.get("producer_ms", {})
        n = max(1, stats["batches"])
        print(f"[input] epoch {epoch} stall {stats['input_stall_frac']:.1%} "
              f"h2d {stats['h2d_bytes_per_step'] / 1e6:.2f} MB/step "
              f"prep {prod.get('prep_wait', 0.0) / n:.1f} "
              f"assemble {prod.get('assemble', 0.0) / n:.1f} "
              f"h2d {prod.get('h2d', 0.0) / n:.1f} ms/batch "
              f"(pool alloc {stats['pool']['allocated']} "
              f"reuse {stats['pool']['reused']})", flush=True)

    def train_epoch(self, state: TrainState, train_data: Iterable,
                    epoch: int) -> TrainState:
        cfg = self.config
        if getattr(cfg, "scan_steps", 1) > 1:
            return self._train_epoch_scan(state, train_data, epoch)
        meter = ThroughputMeter()
        pending = None  # async metric fetch: log step N-1 while N runs
        profiling = self.profile_steps if epoch == self.start_epoch else None
        trace_active = False
        # staged input pipeline: batch N+1 assembles/stages/transfers on
        # the producer thread while step N computes; the stream yields
        # already-placed device batches (shard_batch in train_step is a
        # no-op on them) that the jitted step consumes via donation
        stream = self._get_prefetcher().iterate(train_data)
        for i, batch in enumerate(stream):
            if profiling is not None:
                if i == profiling[0]:
                    jax.profiler.start_trace(
                        os.path.join(self.workdir, "profile"))
                    trace_active = True
                elif i == profiling[1]:
                    jax.profiler.stop_trace()
                    trace_active = False
                    print(f"[profile] trace written to "
                          f"{self.workdir}/profile", flush=True)
                    profiling = None
            bs = len(jax.tree_util.tree_leaves(batch)[0])
            state, metrics = self.train_step(state, batch)
            meter.update(bs)
            if pending is not None and (i % cfg.log_every_steps == 0):
                m = {k: float(v) for k, v in jax.device_get(pending).items()}
                self.guard.check(m)
                self.logger.log_dict(int(state.step) - 1,
                                     {f"train_{k}": v for k, v in m.items()})
                print(f"Epoch {epoch} Batch {i} loss {m['loss']:.4f} "
                      f"lr {self.scheduler.lr:.2e} "
                      f"{meter.images_per_sec:.1f} img/s", flush=True)
            pending = metrics
            if self._preempted:
                print("[preempt] SIGTERM — stopping at step boundary",
                      flush=True)
                break
        if trace_active:
            # epoch ended inside the trace window: flush what we have
            jax.profiler.stop_trace()
            print(f"[profile] short-epoch trace written to "
                  f"{self.workdir}/profile", flush=True)
        if pending is not None:
            m = {k: float(v) for k, v in jax.device_get(pending).items()}
            self.guard.check(m)
            self.logger.log_dict(int(state.step),
                                 {f"train_{k}": v for k, v in m.items()})
        self.logger.log("images_per_sec", int(state.step), meter.images_per_sec)
        self._log_input_stats(int(state.step), stream.stats(), epoch)
        return state

    def _train_epoch_scan(self, state: TrainState, train_data: Iterable,
                          epoch: int) -> TrainState:
        """K-step-per-dispatch epoch (``config.scan_steps``): host batches
        are stacked K at a time and one jitted ``lax.scan`` program applies
        all K optimizer updates.  Logging, the divergence guard, and
        preemption run at K-step granularity; a trailing ragged group falls
        back to the single-step path.  ``--profile`` tracing is per-step
        and is not supported in this mode."""
        import numpy as np

        from deep_vision_tpu.parallel import shard_batch_stacked

        cfg = self.config
        K = cfg.scan_steps
        if self._jit_train_multi is None:
            self._build_steps()
        meter = ThroughputMeter()
        pending = None  # async per-step metric fetch from the PREVIOUS group
        group = 0
        buf: list[dict] = []

        def dispatch(state, buf):
            stacked = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *buf)
            return self._jit_train_multi(
                state, shard_batch_stacked(stacked, self.mesh))

        def log_pending(ms, last_step):
            # ms: (K,)-leaved metric tree — guard sees EVERY step
            ms = {k: np.asarray(v) for k, v in jax.device_get(ms).items()}
            for j in range(next(iter(ms.values())).shape[0]):
                self.guard.check({k: float(v[j]) for k, v in ms.items()})
            self.logger.log_dict(
                last_step,
                {f"train_{k}": float(v[-1]) for k, v in ms.items()})
            print(f"Epoch {epoch} Group {group} loss {ms['loss'][-1]:.4f} "
                  f"lr {self.scheduler.lr:.2e} "
                  f"{meter.images_per_sec:.1f} img/s", flush=True)

        for batch in train_data:
            buf.append(batch)
            if len(buf) < K:
                continue
            n_imgs = sum(len(jax.tree_util.tree_leaves(b)[0]) for b in buf)
            state, metrics = dispatch(state, buf)
            buf = []
            meter.update(n_imgs)
            if pending is not None:
                log_pending(pending, int(state.step) - K)
            pending = metrics
            group += 1
            if self._preempted:
                print("[preempt] SIGTERM — stopping at group boundary",
                      flush=True)
                break
        if pending is not None:
            log_pending(pending, int(state.step))
        # ragged tail (< K batches): single-step dispatches
        for batch in buf:
            if self._preempted:
                break
            state, metrics = self.train_step(state, batch)
            m = {k: float(v) for k, v in jax.device_get(metrics).items()}
            self.guard.check(m)
            self.logger.log_dict(int(state.step),
                                 {f"train_{k}": v for k, v in m.items()})
        self.logger.log("images_per_sec", int(state.step),
                        meter.images_per_sec)
        return state

    def fit(self, train_data, val_data=None, state: TrainState | None = None,
            resume: bool = False, monitor: str | None = None) -> TrainState:
        """The reference's ``run_epochs`` (ResNet/pytorch/train.py:310-428):
        epoch loop of train → validate → scheduler.step(metric) → checkpoint."""
        cfg = self.config
        if state is None:
            sample = next(iter(train_data))
            state = self.init_state(sample)
        if resume:
            state = self.maybe_resume(state)
        monitor = monitor or getattr(self.task, "monitor", None)
        best = None
        restore_handler = self._install_preempt_handler()
        try:
            return self._fit_epochs(train_data, val_data, state, monitor,
                                    best)
        finally:
            restore_handler()
            # abandoned epochs (preemption, divergence abort, exception)
            # must not leave a producer thread parked on the queue or
            # device batches pinned in it
            if self._prefetcher is not None:
                self._prefetcher.close()
            # the last epoch's async saves must commit before the process
            # exits — interpreter shutdown kills orbax's background
            # executor mid-finalize, leaving a *.orbax-checkpoint-tmp-*
            # directory that restore() cannot see
            for ckpt in (self.checkpointer, self.best_checkpointer):
                try:
                    ckpt.wait_until_finished()
                except Exception:  # noqa: BLE001 — a failed async save already logged itself; don't mask the fit() result
                    pass

    def _install_preempt_handler(self):
        self._preempted = False  # stale flag must not abort a fresh fit()
        return install_sigterm_flag(
            lambda: setattr(self, "_preempted", True))

    def _fit_epochs(self, train_data, val_data, state, monitor, best):
        cfg = self.config
        for epoch in range(self.start_epoch, cfg.total_epochs + 1):
            # LR for THIS epoch (so warmup covers epoch 1); plateau-style
            # metric schedules adjust in scheduler.step() after validation.
            lr = self.scheduler.epoch_begin(epoch)
            state = state.replace(
                opt_state=set_learning_rate(state.opt_state, lr))
            if hasattr(train_data, "set_epoch"):
                train_data.set_epoch(epoch)
            t0 = time.monotonic()
            state = self.train_epoch(state, train_data, epoch)
            if self._preempted:
                # mid-epoch save as epoch-1: resume re-runs this epoch
                # from its start but keeps every applied step/param update
                self.save(state, epoch - 1)
                # the VM disappears seconds after SIGTERM: block until
                # the (possibly async) save is durable before reporting
                self.checkpointer.wait_until_finished()
                print(f"[preempt] checkpoint saved at step "
                      f"{int(jax.device_get(state.step))}; rerun with "
                      f"--resume to continue", flush=True)
                return state
            metric_val = None
            if val_data is not None:
                val_metrics = self.evaluate(state, val_data)
                self.logger.log_dict(
                    int(state.step),
                    {f"val_{k}": v for k, v in val_metrics.items()})
                if monitor is not None:
                    metric_val = val_metrics.get(monitor)
                print(f"Epoch {epoch} val "
                      + " ".join(f"{k}={v:.4f}" for k, v in val_metrics.items())
                      + f" ({time.monotonic() - t0:.1f}s)", flush=True)
            if self._preempted:
                # SIGTERM during validation: save NOW — the preemption
                # grace period is too short for best-ckpt/scheduler work
                self.save(state, epoch)
                self.checkpointer.wait_until_finished()  # durable first
                print(f"[preempt] checkpoint saved at step "
                      f"{int(jax.device_get(state.step))}; rerun with "
                      f"--resume to continue", flush=True)
                return state
            self.scheduler.step(epoch, metric_val)
            if epoch % cfg.checkpoint_every_epochs == 0:
                self.save(state, epoch)
            if metric_val is not None and (best is None or metric_val > best):
                # best-val checkpoint, kept separately from the rolling window
                # (the reference's save-best-by-val, YOLO/tensorflow/train.py:243-247)
                best = metric_val
                self.best_checkpointer.save(
                    int(jax.device_get(state.step)), state,
                    extras={"epoch": epoch, "metric": float(metric_val),
                            "monitor": monitor or ""})
                if self.uploader is not None:
                    # the async save must be on disk before the mirror
                    # copies the directory (else it uploads a partial)
                    self.best_checkpointer.wait_until_finished()
                    self.uploader.sync(self.best_checkpointer.directory,
                                       "checkpoints_best")
        return state

    def save(self, state: TrainState, epoch: int):
        self.checkpointer.save(
            int(jax.device_get(state.step)), state,
            extras={"epoch": epoch,
                    "scheduler": self.scheduler.state_dict(),
                    "history": self.logger.state_dict()})
        if self.uploader is not None:
            # durability barrier before the mirror walks the directory
            self.checkpointer.wait_until_finished()
            self.uploader.sync(self.checkpointer.directory, "checkpoints")
