"""Optimizers and LR scheduling.

The reference drives LR two ways: torch/Keras ``ReduceLROnPlateau``
(ResNet/pytorch/train.py:358-372, ResNet/tensorflow/train.py:271-272), and
hand-rolled epoch-table decay (YOLO/tensorflow/train.py:56-68,
Hourglass/tensorflow/train.py:46-58) plus CycleGAN's constant-then-linear
``LinearDecay`` (CycleGAN/tensorflow/utils.py:5-28).

Here the optimizer is built with ``optax.inject_hyperparams`` so the learning
rate lives inside ``opt_state`` as a traced scalar: host-side scheduler objects
(plateau logic needs val metrics, so it *must* run on host) rewrite it between
steps without retracing the jitted train step.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax.numpy as jnp
import optax


@dataclasses.dataclass
class OptimizerConfig:
    name: str = "sgd"  # sgd | adam | rmsprop
    learning_rate: float = 0.1
    momentum: float = 0.9
    nesterov: bool = False
    weight_decay: float = 0.0  # decoupled, applied to all non-BN params
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    rms_decay: float = 0.9  # torch RMSprop 'alpha' (MobileNet config uses 0.9)
    grad_clip_norm: float | None = None
    # SGD momentum accumulator storage dtype (None = param dtype, f32).
    # "bfloat16" halves the optimizer-state HBM traffic in the elementwise
    # band of the step — a measured experiment, see docs/PERF.md; changes
    # update numerics (~1e-3 relative), so NOT part of the parity recipe.
    momentum_dtype: str | None = None


def _weight_decay_mask(params):
    """Decay kernels only — skip biases and BN scale/bias, matching the
    effective behavior of torch SGD weight_decay on conv/fc layers dominating
    the norm (ResNet/pytorch/train.py:166-184 uses blanket 1e-4; we use the
    modern no-BN-decay recipe required to reach 76% top-1)."""
    import jax

    def keep(path, x):
        leaf = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        return leaf not in ("bias", "scale")

    return jax.tree_util.tree_map_with_path(keep, params)


def build_optimizer(cfg: OptimizerConfig) -> optax.GradientTransformation:
    if cfg.momentum_dtype not in (None, "bfloat16"):
        raise ValueError(f"momentum_dtype must be None or 'bfloat16', "
                         f"got {cfg.momentum_dtype!r}")
    if cfg.momentum_dtype is not None and cfg.name != "sgd":
        raise ValueError(
            f"momentum_dtype applies to the sgd momentum accumulator "
            f"only; optimizer is {cfg.name!r}")

    def make(learning_rate):
        txs = []
        if cfg.grad_clip_norm:
            txs.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
        if cfg.name == "sgd":
            if cfg.weight_decay:
                txs.append(
                    optax.add_decayed_weights(cfg.weight_decay, mask=_weight_decay_mask)
                )
            acc_dtype = (jnp.bfloat16 if cfg.momentum_dtype == "bfloat16"
                         else None)
            txs.append(optax.sgd(learning_rate, momentum=cfg.momentum,
                                 nesterov=cfg.nesterov,
                                 accumulator_dtype=acc_dtype))
        elif cfg.name == "adam":
            if cfg.weight_decay:
                txs.append(optax.adamw(learning_rate, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
                                       weight_decay=cfg.weight_decay,
                                       mask=_weight_decay_mask))
            else:
                txs.append(optax.adam(learning_rate, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps))
        elif cfg.name == "rmsprop":
            txs.append(optax.rmsprop(learning_rate, decay=cfg.rms_decay,
                                     momentum=cfg.momentum, eps=cfg.eps))
        else:
            raise ValueError(f"unknown optimizer {cfg.name}")
        return optax.chain(*txs)

    return optax.inject_hyperparams(make)(learning_rate=cfg.learning_rate)


def get_learning_rate(opt_state) -> float:
    return float(opt_state.hyperparams["learning_rate"])


def set_learning_rate(opt_state, lr: float):
    """Functionally rewrite the injected LR (no retrace: same pytree shape)."""
    hp = dict(opt_state.hyperparams)
    hp["learning_rate"] = jnp.asarray(lr, jnp.asarray(hp["learning_rate"]).dtype)
    return opt_state._replace(hyperparams=hp)


# ---------------------------------------------------------------------------
# Host-side schedulers (stateful, epoch-granularity like the reference's)
# ---------------------------------------------------------------------------


class Scheduler:
    """Base contract: ``epoch_begin(epoch)`` fixes the LR used *during*
    ``epoch`` (1-indexed) — so warmup applies to the very first epoch;
    ``step(epoch, metric)`` runs after validation for metric-driven
    schedules (plateau).  Read ``.lr``."""

    def __init__(self, base_lr: float):
        self.base_lr = base_lr
        self.lr = base_lr

    def epoch_begin(self, epoch: int) -> float:
        return self.lr

    def step(self, epoch: int, metric: float | None = None) -> float:
        return self.lr

    def state_dict(self) -> dict:
        return dict(self.__dict__)

    def load_state_dict(self, d: dict):
        self.__dict__.update(d)


class ConstantSchedule(Scheduler):
    pass


class ReduceLROnPlateau(Scheduler):
    """Mirror of torch's, as configured by the reference
    (mode='max' on val top-1, factor=0.1, patience=10 —
    ResNet/pytorch/train.py:186-195)."""

    def __init__(self, base_lr, mode="max", factor=0.1, patience=10,
                 threshold=1e-4, min_lr=0.0):
        super().__init__(base_lr)
        assert mode in ("min", "max")
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.min_lr = threshold, min_lr
        self.best: float | None = None
        self.bad_epochs = 0

    def _improved(self, metric: float) -> bool:
        if self.best is None:
            return True
        if self.mode == "max":
            return metric > self.best * (1 + self.threshold)
        return metric < self.best * (1 - self.threshold)

    def step(self, epoch, metric=None):
        if metric is None:
            return self.lr
        if self._improved(metric):
            self.best = metric
            self.bad_epochs = 0
        else:
            self.bad_epochs += 1
            if self.bad_epochs > self.patience:
                self.lr = max(self.lr * self.factor, self.min_lr)
                self.bad_epochs = 0
        return self.lr


class EpochTableSchedule(Scheduler):
    """Piecewise-constant by epoch boundaries — the YOLO/Hourglass pattern
    (YOLO/tensorflow/train.py:56-68: {0:1e-3, 40:1e-4, ...})."""

    def __init__(self, table: dict[int, float]):
        self.table = {int(k): v for k, v in sorted(table.items())}
        super().__init__(next(iter(self.table.values())))

    def epoch_begin(self, epoch):
        for boundary, lr in sorted(self.table.items()):
            if epoch >= boundary:
                self.lr = lr
        return self.lr

    def load_state_dict(self, d: dict):
        # JSON round-trips stringify int keys; restore them
        d = dict(d)
        d["table"] = {int(k): v for k, v in d["table"].items()}
        self.__dict__.update(d)


class LinearDecay(Scheduler):
    """Constant for ``decay_start`` epochs then linear to 0 at ``total`` —
    CycleGAN/tensorflow/utils.py:5-28."""

    def __init__(self, base_lr, total_epochs: int, decay_start: int):
        super().__init__(base_lr)
        self.total_epochs, self.decay_start = total_epochs, decay_start

    def epoch_begin(self, epoch):
        if epoch <= self.decay_start:
            self.lr = self.base_lr
        else:
            frac = (epoch - 1 - self.decay_start) / max(
                1, self.total_epochs - self.decay_start
            )
            self.lr = self.base_lr * max(0.0, 1.0 - frac)
        return self.lr


class WarmupCosine(Scheduler):
    """Linear warmup + cosine decay (per-epoch granularity): the modern
    large-batch recipe needed for the 76% ResNet-50 target (parity-plus;
    the reference itself only used plateau decay)."""

    def __init__(self, base_lr, total_epochs: int, warmup_epochs: int = 5,
                 final_lr: float = 0.0):
        super().__init__(base_lr)
        self.total_epochs, self.warmup_epochs = total_epochs, warmup_epochs
        self.final_lr = final_lr

    def epoch_begin(self, epoch):
        import math

        if epoch <= self.warmup_epochs:
            # ramp base·(1/w) … base·(w/w) over the first w epochs
            self.lr = self.base_lr * epoch / self.warmup_epochs
        else:
            t = (epoch - 1 - self.warmup_epochs) / max(
                1, self.total_epochs - self.warmup_epochs
            )
            self.lr = self.final_lr + 0.5 * (self.base_lr - self.final_lr) * (
                1 + math.cos(math.pi * min(t, 1.0))
            )
        return self.lr


class StepDecay(Scheduler):
    """torch ``StepLR``: lr = base·gamma^(epoch//step_size) — the reference's
    VGG (step 10, γ=0.5) and MobileNet (step 2, γ=0.94, the Inception-V3
    policy) configs (VGG/pytorch/train.py scheduler_params)."""

    def __init__(self, base_lr, step_size: int, gamma: float):
        super().__init__(base_lr)
        self.step_size, self.gamma = step_size, gamma

    def epoch_begin(self, epoch):
        self.lr = self.base_lr * self.gamma ** ((epoch - 1) // self.step_size)
        return self.lr


class SqrtPolyDecay(Scheduler):
    """The reference's Inception V1 LambdaLR policy
    (Inception/pytorch/train.py scheduler_params): base·(1-e/horizon)^0.5
    until ``horizon``, then fixed small multipliers."""

    def __init__(self, base_lr, horizon: int = 60):
        super().__init__(base_lr)
        self.horizon = horizon

    def epoch_begin(self, epoch):
        e = epoch - 1
        if e < self.horizon:
            mult = (1 - e / self.horizon) ** 0.5
        elif e < self.horizon + 15:
            mult = 0.01
        else:
            mult = 0.001
        self.lr = self.base_lr * mult
        return self.lr


SCHEDULERS = {
    "constant": ConstantSchedule,
    "plateau": ReduceLROnPlateau,
    "epoch_table": EpochTableSchedule,
    "linear_decay": LinearDecay,
    "warmup_cosine": WarmupCosine,
    "step": StepDecay,
    "sqrt_poly": SqrtPolyDecay,
}


def build_scheduler(name: str, base_lr: float, **kwargs) -> Scheduler:
    cls = SCHEDULERS[name]
    if cls is EpochTableSchedule:
        return cls(kwargs["table"])
    return cls(base_lr, **kwargs)
