"""Training state: the single checkpointable unit.

Unifies the reference's four checkpoint payloads —
``{epoch, model, optimizer, scheduler, loggers}`` torch dict
(ResNet/pytorch/train.py:422-428), Keras HDF5 full-model
(ResNet/tensorflow/train.py:65-78), ``save_weights``
(YOLO/tensorflow/train.py:252-257) and object-graph ``tf.train.Checkpoint``
(CycleGAN/tensorflow/train.py:133-148) — into one pytree.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import core, struct


class DivergenceGuard:
    """Host-side policy over the cumulative ``bad_steps`` counter: warn on
    newly-skipped non-finite steps, halt once THIS RUN skipped more than
    ``limit``.  ``baseline`` is the counter value restored from a
    checkpoint so old skips never count against the current run."""

    def __init__(self, limit: int):
        self.limit = limit
        self.baseline = 0
        self._seen = 0

    def set_baseline(self, bad_steps: int):
        self.baseline = self._seen = int(bad_steps)

    def check(self, metrics: dict):
        bad = int(metrics.get("bad_steps", 0))
        if bad > self._seen:
            print(f"[warn] skipped {bad - self._seen} non-finite step(s) — "
                  f"{bad - self.baseline} total this run", flush=True)
            self._seen = bad
        if bad - self.baseline > self.limit:
            raise RuntimeError(
                f"training diverged: {bad - self.baseline} non-finite steps "
                f"skipped (> max_bad_steps={self.limit}); lower the "
                f"learning rate or inspect the input data")


def all_finite(tree) -> jax.Array:
    """Scalar bool: every element of every array leaf is finite."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.bool_(True)
    checks = [jnp.all(jnp.isfinite(leaf)) for leaf in leaves]
    return jnp.stack(checks).all()


class TrainState(struct.PyTreeNode):
    """Immutable train state; ``apply_fn``/``tx`` are static (not saved)."""

    step: jax.Array
    params: core.FrozenDict[str, Any] | dict
    opt_state: optax.OptState
    batch_stats: core.FrozenDict[str, Any] | dict  # {} for BN-free models
    rng: jax.Array
    # cumulative count of skipped non-finite steps (divergence guard — the
    # reference merely TODO'd its NaN val losses, Hourglass/tensorflow/
    # train.py:126-130; we skip the bad update, count it, and let the host
    # loop halt past config.max_bad_steps)
    bad_steps: jax.Array
    # exponential moving average of params ({} when disabled): the eval/
    # serving copy of modern recipes.  Updated by the Trainer each applied
    # step: ema = d·ema + (1−d)·params
    ema_params: core.FrozenDict[str, Any] | dict
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads, **changes) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            **changes,
        )

    def keep_if(self, ok, old: "TrainState") -> "TrainState":
        """Branch-free guard merge: where ``ok`` is False, revert
        params/opt_state/batch_stats to ``old`` and count one bad step;
        the step counter keeps its advanced value either way (so per-step
        rng folding never repeats a stream).  No host sync, jit/GSPMD-safe."""

        def sel(new, prev):
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(ok, a, b), new, prev)

        return self.replace(
            params=sel(self.params, old.params),
            opt_state=sel(self.opt_state, old.opt_state),
            batch_stats=sel(self.batch_stats, old.batch_stats),
            ema_params=sel(self.ema_params, old.ema_params),
            bad_steps=old.bad_steps + (~ok).astype(jnp.int32),
        )

    def apply_gradients_if_finite(self, loss, grads, **changes) -> "TrainState":
        """``apply_gradients`` guarded on loss/grad finiteness: a non-finite
        step keeps params/opt_state/batch_stats unchanged and increments
        ``bad_steps`` (see :meth:`keep_if`)."""
        ok = jnp.isfinite(loss) & all_finite(grads)
        return self.apply_gradients(grads, **changes).keep_if(ok, self)

    @classmethod
    def create(cls, *, apply_fn, params, tx, batch_stats=None, rng=None,
               ema: bool = False) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            batch_stats=batch_stats if batch_stats is not None else {},
            rng=rng if rng is not None else jax.random.PRNGKey(0),
            bad_steps=jnp.zeros((), jnp.int32),
            ema_params=(jax.tree_util.tree_map(jnp.array, params)
                        if ema else {}),
            apply_fn=apply_fn,
            tx=tx,
        )

    # --- checkpoint payload (pure arrays, no callables) -------------------
    def save_dict(self) -> dict:
        return {
            "step": self.step,
            "params": self.params,
            "opt_state": self.opt_state,
            "batch_stats": self.batch_stats,
            "rng": self.rng,
            "bad_steps": self.bad_steps,
            "ema_params": self.ema_params,
        }

    def load_dict(self, payload: dict) -> "TrainState":
        return self.replace(**payload)
