"""Training state: the single checkpointable unit.

Unifies the reference's four checkpoint payloads —
``{epoch, model, optimizer, scheduler, loggers}`` torch dict
(ResNet/pytorch/train.py:422-428), Keras HDF5 full-model
(ResNet/tensorflow/train.py:65-78), ``save_weights``
(YOLO/tensorflow/train.py:252-257) and object-graph ``tf.train.Checkpoint``
(CycleGAN/tensorflow/train.py:133-148) — into one pytree.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import core, struct


class TrainState(struct.PyTreeNode):
    """Immutable train state; ``apply_fn``/``tx`` are static (not saved)."""

    step: jax.Array
    params: core.FrozenDict[str, Any] | dict
    opt_state: optax.OptState
    batch_stats: core.FrozenDict[str, Any] | dict  # {} for BN-free models
    rng: jax.Array
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads, **changes) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            **changes,
        )

    @classmethod
    def create(cls, *, apply_fn, params, tx, batch_stats=None, rng=None) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            batch_stats=batch_stats if batch_stats is not None else {},
            rng=rng if rng is not None else jax.random.PRNGKey(0),
            apply_fn=apply_fn,
            tx=tx,
        )

    # --- checkpoint payload (pure arrays, no callables) -------------------
    def save_dict(self) -> dict:
        return {
            "step": self.step,
            "params": self.params,
            "opt_state": self.opt_state,
            "batch_stats": self.batch_stats,
            "rng": self.rng,
        }

    def load_dict(self, payload: dict) -> "TrainState":
        return self.replace(**payload)
