"""Unified experiment configuration.

Replaces the reference's scattered config surfaces — per-file
``training_config`` dicts (ResNet/pytorch/train.py:26-215,
ResNet/tensorflow/train.py:21-62), module constants
(YOLO/tensorflow/train.py:13-17), click CLIs (Hourglass/tensorflow/main.py:21-40)
and ``tf.app.flags`` (build_imagenet_tfrecord.py:104-160) — with one dataclass
registry keyed by experiment name.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from deep_vision_tpu.core.optim import OptimizerConfig


@dataclasses.dataclass
class SchedulerConfig:
    name: str = "constant"  # see core.optim.SCHEDULERS
    kwargs: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class TrainConfig:
    name: str
    model: Callable[[], Any]  # zero-arg ctor, like the reference's config dicts
    task: str = "classification"
    batch_size: int = 128  # GLOBAL batch (split over the data mesh axis)
    eval_batch_size: int | None = None
    total_epochs: int = 90
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    scheduler: SchedulerConfig = dataclasses.field(default_factory=SchedulerConfig)
    label_smoothing: float = 0.0
    half_precision: bool = True  # bf16 activations/compute on TPU
    image_size: int = 224
    channels: int = 3
    num_classes: int = 1000
    checkpoint_every_epochs: int = 1
    keep_checkpoints: int = 3
    log_every_steps: int = 10  # reference printed every 10 batches
    # divergence guard: non-finite steps are skipped + counted; the run
    # halts with a clear error once more than this many were skipped
    max_bad_steps: int = 100
    # multi-step dispatch: run this many train steps per device program
    # (one lax.scan) — amortizes per-dispatch host overhead (~2ms/step on
    # a tunneled v5e, worth ~4% throughput at K=40); logging/guard/
    # preemption work at K-step granularity. 1 = step-per-dispatch.
    scan_steps: int = 1
    # gradient accumulation: split each global batch into this many
    # sequential microbatches inside the jitted step, averaging grads
    # before the single optimizer update — the full recipe batch on a
    # fraction of the HBM.  (The reference's answer to OOM was shrinking
    # the batch mid-run: ResNet/pytorch/train.py:141-148, VGG README's
    # "batch 128→64".)  1 = off.
    grad_accum_steps: int = 1
    # exponential moving average of params: eval/serving uses the EMA
    # copy (the modern-recipe trick for a ~0.2-0.5 top-1 bump at zero
    # training cost).  0 = off.  PARAMS ONLY: BN running stats are served
    # raw (tf.train.ExponentialMovingAverage semantics; timm's ModelEmaV2
    # averages buffers too — both are defensible, this one keeps the
    # stats a single source of truth).  The effective decay warms up as
    # min(decay, (1+step)/(10+step)) so short/seeded runs aren't
    # dominated by the init point.
    ema_decay: float = 0.0
    seed: int = 42
    extra: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.eval_batch_size is None:
            self.eval_batch_size = self.batch_size


_REGISTRY: dict[str, Callable[[], TrainConfig]] = {}


def register_config(name: str):
    def deco(fn: Callable[[], TrainConfig]):
        _REGISTRY[name] = fn
        return fn
    return deco


def get_config(name: str) -> TrainConfig:
    # Import for side effects: each zoo module registers its configs.
    import deep_vision_tpu.zoo  # noqa: F401

    if name not in _REGISTRY:
        raise KeyError(f"unknown config '{name}'; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]()


def list_configs() -> list[str]:
    import deep_vision_tpu.zoo  # noqa: F401

    return sorted(_REGISTRY)
