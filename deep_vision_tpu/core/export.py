"""Model export — the reference's deployment path is TFLite conversion
(CycleGAN/tensorflow/convert.py:7-16: SavedModel → TFLiteConverter →
OPTIMIZE_FOR_SIZE).  The JAX-native equivalent is ``jax.export``: serialize
the jitted forward to portable StableHLO bytes, reloadable on any XLA
backend (CPU/GPU/TPU) without Python model code.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp


def export_forward(model, variables, input_shape, path: str,
                   train: bool = False) -> int:
    """Serialize model.apply(variables, x) to StableHLO at ``path``.

    Returns the serialized byte count.  ``input_shape`` includes batch.
    """
    from jax import export as jexport

    def forward(variables, x):
        return model.apply(variables, x, train=train)

    x_spec = jax.ShapeDtypeStruct(tuple(input_shape), jnp.float32)
    v_spec = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), variables)
    exported = jexport.export(jax.jit(forward))(v_spec, x_spec)
    blob = exported.serialize()
    # The loader hands ``(variables, x)`` straight to the deserialized
    # callable, so the artifact is only servable if the variables pytree
    # (collection/key ordering included) survives serialization exactly.
    # Verify on the bytes being shipped, not the in-memory object.
    reloaded = jexport.deserialize(blob)
    if (reloaded.in_tree != exported.in_tree
            or list(reloaded.in_avals) != list(exported.in_avals)):
        raise ValueError(
            "exported variables pytree does not round-trip through "
            "serialize/deserialize — the blob would reorder or retype "
            f"inputs at load time (exported {exported.in_tree}, "
            f"reloaded {reloaded.in_tree})")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(blob)
    return len(blob)


def load_exported(path: str):
    """Deserialize; returns a callable (variables, x) -> outputs.

    The callable carries ``in_tree``/``in_avals`` (the exported input
    pytree structure and shapes) so callers — e.g. ``serve/registry.py``
    — can validate variables and read the traced batch size without
    re-parsing the blob.
    """
    from jax import export as jexport

    with open(path, "rb") as f:
        exported = jexport.deserialize(f.read())
    def call(*args, **kwargs):
        return exported.call(*args, **kwargs)

    call.in_tree = exported.in_tree
    call.in_avals = exported.in_avals
    return call
