from deep_vision_tpu.core.state import TrainState
from deep_vision_tpu.core.trainer import Trainer

__all__ = ["TrainState", "Trainer"]
