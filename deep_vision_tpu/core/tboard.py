"""Dependency-free TensorBoard scalar logging (tfevents writer).

The reference logs scalars to TensorBoard everywhere (per-batch
``tf.summary.scalar`` — YOLO/tensorflow/train.py:159-179, Keras callback —
ResNet/tensorflow/train.py:268-269, per-loss GAN metrics —
CycleGAN/tensorflow/train.py:271-304).  This writer produces the same
``events.out.tfevents.*`` files WITHOUT TensorFlow or the tensorboard
package: the Event protobuf schema needed for scalars is tiny (wall_time,
step, summary.value{tag, simple_value}), so it is hand-serialized, and the
TFRecord framing (u64 length + masked crc32c, payload + masked crc32c) is
~20 lines.  Verified against TensorBoard's own EventFileLoader in
tests/test_tboard.py.
"""

from __future__ import annotations

import os
import socket
import struct
import time

# ---------------------------------------------------------------------------
# crc32c (Castagnoli, poly 0x82F63B78) + TFRecord masking
# ---------------------------------------------------------------------------

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# minimal protobuf wire encoding for Event{wall_time, step, summary|file_version}
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    if n < 0:  # protobuf int64: negatives are two's-complement 10-byters
        n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _event(wall_time: float, step: int | None = None,
           file_version: str | None = None,
           scalars: list[tuple[str, float]] | None = None) -> bytes:
    ev = bytearray()
    ev += _varint((1 << 3) | 1) + struct.pack("<d", wall_time)  # wall_time
    if step is not None:
        ev += _varint(2 << 3) + _varint(step)                   # step
    if file_version is not None:
        ev += _field_bytes(3, file_version.encode())
    if scalars:
        summary = bytearray()
        for tag, value in scalars:
            val = _field_bytes(1, tag.encode()) \
                + _varint((2 << 3) | 5) + struct.pack("<f", value)
            summary += _field_bytes(1, val)                     # Summary.value
        ev += _field_bytes(5, bytes(summary))                   # Event.summary
    return bytes(ev)


class TFEventWriter:
    """Append-only scalar event file a stock TensorBoard can plot."""

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        name = (f"events.out.tfevents.{int(time.time())}."
                f"{socket.gethostname()}")
        self._f = open(os.path.join(logdir, name), "ab")
        self._write(_event(time.time(), file_version="brain.Event:2"))

    def _write(self, payload: bytes):
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def scalar(self, tag: str, value: float, step: int):
        self._write(_event(time.time(), step=int(step),
                           scalars=[(tag, float(value))]))

    def scalars(self, metrics: dict, step: int):
        self._write(_event(time.time(), step=int(step),
                           scalars=[(k, float(v)) for k, v in
                                    metrics.items()]))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()
