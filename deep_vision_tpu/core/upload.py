"""Artifact upload: sync run artifacts (checkpoints) off-host.

The reference's cloud path is Hourglass-only: ``main.py:21-65`` trains,
then pushes the saved model to a GCS bucket with ``google.cloud.storage``.
Generalized here as a destination-URI sync usable from every trainer via
``--upload <uri>``:

- ``/path`` or ``file:///path`` — local/NFS mirror (works everywhere,
  including air-gapped CI);
- ``gs://bucket/prefix`` — Google Cloud Storage, via the
  ``google.cloud.storage`` client if installed, else the ``gsutil`` CLI
  (both gated: this repo adds no cloud dependencies).

Sync is one-way and incremental by (size, mtime), rsync-style, so calling
it after every checkpoint is cheap.
"""

from __future__ import annotations

import os
import shutil
import subprocess


def _iter_files(src_dir: str):
    for root, _, files in os.walk(src_dir):
        for f in files:
            full = os.path.join(root, f)
            yield full, os.path.relpath(full, src_dir)


def _sync_local(src_dir: str, dest_dir: str) -> int:
    n = 0
    keep = set()
    for full, rel in _iter_files(src_dir):
        keep.add(rel)
        dest = os.path.join(dest_dir, rel)
        st = os.stat(full)
        if os.path.exists(dest):
            dst = os.stat(dest)
            if dst.st_size == st.st_size and dst.st_mtime >= st.st_mtime:
                continue
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.copy2(full, dest)
        n += 1
    # true mirror: drop files pruned locally (max_to_keep rotation),
    # so the destination doesn't accumulate every checkpoint ever written.
    # Guard: an empty src means a fresh run that has written nothing yet —
    # never let it wipe a backup it hasn't superseded.
    if keep:
        for full, rel in list(_iter_files(dest_dir)):
            if rel not in keep:
                os.remove(full)
        for root, dirs, files in os.walk(dest_dir, topdown=False):
            if not dirs and not files and root != dest_dir:
                os.rmdir(root)
    return n


def _sync_gcs(src_dir: str, uri: str) -> int:
    try:
        from google.cloud import storage  # type: ignore
    except ImportError:
        # fall back to the gsutil CLI if present (-d: true mirror,
        # deletes remotely what max_to_keep pruned locally)
        if shutil.which("gsutil"):
            subprocess.run(["gsutil", "-m", "rsync", "-r", "-d",
                            src_dir, uri], check=True)
            return -1  # count unknown
        raise RuntimeError(
            "gs:// upload needs google-cloud-storage or gsutil; neither "
            "is available — use a file:// destination or install one")
    bucket_name, _, prefix = uri[len("gs://"):].partition("/")
    bucket = storage.Client().bucket(bucket_name)
    # incremental: list what's already there once, skip same-size blobs
    # (checkpoint files are content-addressed-ish — same size ⇒ same file
    # for orbax array payloads; a rare same-size edit re-uploads next run).
    # prefix listed with a trailing '/': bare "run/checkpoints" would also
    # match the SIBLING "run/checkpoints_best/..." blobs and the mirror
    # loop below would delete them
    existing = {b.name: b.size
                for b in bucket.list_blobs(
                    prefix=prefix + "/" if prefix else None)}
    n = 0
    keep = set()
    for full, rel in _iter_files(src_dir):
        name = os.path.join(prefix, rel) if prefix else rel
        keep.add(name)
        if existing.get(name) == os.path.getsize(full):
            continue
        bucket.blob(name).upload_from_filename(full)
        n += 1
    if keep:  # mirror semantics + fresh-run guard (see _sync_local)
        for name in existing:
            if name not in keep:
                bucket.blob(name).delete()
    return n


def _restore_gcs(uri: str, local_dir: str) -> int:
    try:
        from google.cloud import storage  # type: ignore
    except ImportError:
        if shutil.which("gsutil"):
            os.makedirs(local_dir, exist_ok=True)  # rsync needs the target
            subprocess.run(["gsutil", "-m", "rsync", "-r", uri, local_dir],
                           check=True)
            return -1
        raise RuntimeError(
            "gs:// restore needs google-cloud-storage or gsutil; neither "
            "is available")
    bucket_name, _, prefix = uri[len("gs://"):].partition("/")
    bucket = storage.Client().bucket(bucket_name)
    n = 0
    # trailing '/' so "run/checkpoints" doesn't also pull the sibling
    # "run/checkpoints_best/..." blobs into this tree (see _sync_gcs)
    for blob in bucket.list_blobs(prefix=prefix + "/" if prefix else None):
        rel = blob.name[len(prefix):].lstrip("/") if prefix else blob.name
        if not rel:
            continue
        dest = os.path.join(local_dir, rel)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        blob.download_to_filename(dest)
        n += 1
    return n


def restore_dir(dest_uri: str, local_dir: str) -> int:
    """Pull a previously mirrored tree back into ``local_dir`` (the inverse
    of :func:`sync_dir`) — the preemption-recovery path: a fresh VM with an
    empty workdir re-hydrates its checkpoints from the upload URI before
    resuming.  Returns files copied (-1 if unknown); 0 if the mirror is
    empty or absent."""
    if dest_uri.startswith("gs://"):
        return _restore_gcs(dest_uri, local_dir)
    src = dest_uri[len("file://"):] if dest_uri.startswith("file://") \
        else dest_uri
    if not os.path.isdir(src):
        return 0
    os.makedirs(local_dir, exist_ok=True)
    return _sync_local(src, local_dir)


def sync_dir(src_dir: str, dest_uri: str) -> int:
    """Mirror ``src_dir`` under ``dest_uri``; returns files copied
    (-1 if the backend doesn't report)."""
    if dest_uri.startswith("gs://"):
        return _sync_gcs(src_dir, dest_uri)
    dest = dest_uri[len("file://"):] if dest_uri.startswith("file://") \
        else dest_uri
    os.makedirs(dest, exist_ok=True)
    return _sync_local(src_dir, dest)


class ArtifactUploader:
    """Post-checkpoint hook: mirrors the workdir's checkpoint dirs to a
    destination URI.  Failures are reported but never kill training —
    losing an upload must not lose the run."""

    def __init__(self, dest_uri: str):
        self.dest_uri = dest_uri.rstrip("/")

    def sync(self, src_dir: str, tag: str):
        try:
            n = sync_dir(src_dir, f"{self.dest_uri}/{tag}")
            print(f"[upload] {tag}: {n if n >= 0 else '?'} file(s) → "
                  f"{self.dest_uri}/{tag}", flush=True)
        except Exception as e:  # noqa: BLE001 — deliberately broad
            print(f"[upload] FAILED for {tag}: {e}", flush=True)

    def restore(self, local_dir: str, tag: str) -> int:
        """Re-hydrate ``local_dir`` from the mirror (preemption recovery:
        the VM died, the local disk is gone, the mirror is the only copy).
        Failures are reported, not fatal — a missing mirror just means a
        genuinely fresh run."""
        try:
            n = restore_dir(f"{self.dest_uri}/{tag}", local_dir)
            if n:
                print(f"[upload] restored {n if n >= 0 else '?'} file(s) "
                      f"← {self.dest_uri}/{tag}", flush=True)
            return n
        except Exception as e:  # noqa: BLE001 — deliberately broad
            print(f"[upload] restore FAILED for {tag}: {e}", flush=True)
            return 0
