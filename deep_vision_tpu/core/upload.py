"""Artifact upload: sync run artifacts (checkpoints) off-host.

The reference's cloud path is Hourglass-only: ``main.py:21-65`` trains,
then pushes the saved model to a GCS bucket with ``google.cloud.storage``.
Generalized here as a destination-URI sync usable from every trainer via
``--upload <uri>``:

- ``/path`` or ``file:///path`` — local/NFS mirror (works everywhere,
  including air-gapped CI);
- ``gs://bucket/prefix`` — Google Cloud Storage, via the
  ``google.cloud.storage`` client if installed, else the ``gsutil`` CLI
  (both gated: this repo adds no cloud dependencies).

Sync is one-way and incremental by (size, mtime), rsync-style, so calling
it after every checkpoint is cheap.
"""

from __future__ import annotations

import os
import shutil
import subprocess


def _iter_files(src_dir: str):
    for root, _, files in os.walk(src_dir):
        for f in files:
            full = os.path.join(root, f)
            yield full, os.path.relpath(full, src_dir)


def _sync_local(src_dir: str, dest_dir: str) -> int:
    n = 0
    keep = set()
    for full, rel in _iter_files(src_dir):
        keep.add(rel)
        dest = os.path.join(dest_dir, rel)
        st = os.stat(full)
        if os.path.exists(dest):
            dst = os.stat(dest)
            if dst.st_size == st.st_size and dst.st_mtime >= st.st_mtime:
                continue
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.copy2(full, dest)
        n += 1
    # true mirror: drop files pruned locally (max_to_keep rotation),
    # so the destination doesn't accumulate every checkpoint ever written
    for full, rel in list(_iter_files(dest_dir)):
        if rel not in keep:
            os.remove(full)
    for root, dirs, files in os.walk(dest_dir, topdown=False):
        if not dirs and not files and root != dest_dir:
            os.rmdir(root)
    return n


def _sync_gcs(src_dir: str, uri: str) -> int:
    try:
        from google.cloud import storage  # type: ignore
    except ImportError:
        # fall back to the gsutil CLI if present (-d: true mirror,
        # deletes remotely what max_to_keep pruned locally)
        if shutil.which("gsutil"):
            subprocess.run(["gsutil", "-m", "rsync", "-r", "-d",
                            src_dir, uri], check=True)
            return -1  # count unknown
        raise RuntimeError(
            "gs:// upload needs google-cloud-storage or gsutil; neither "
            "is available — use a file:// destination or install one")
    bucket_name, _, prefix = uri[len("gs://"):].partition("/")
    bucket = storage.Client().bucket(bucket_name)
    # incremental: list what's already there once, skip same-size blobs
    # (checkpoint files are content-addressed-ish — same size ⇒ same file
    # for orbax array payloads; a rare same-size edit re-uploads next run)
    existing = {b.name: b.size
                for b in bucket.list_blobs(prefix=prefix or None)}
    n = 0
    keep = set()
    for full, rel in _iter_files(src_dir):
        name = os.path.join(prefix, rel) if prefix else rel
        keep.add(name)
        if existing.get(name) == os.path.getsize(full):
            continue
        bucket.blob(name).upload_from_filename(full)
        n += 1
    for name in existing:  # mirror semantics (see _sync_local)
        if name not in keep:
            bucket.blob(name).delete()
    return n


def sync_dir(src_dir: str, dest_uri: str) -> int:
    """Mirror ``src_dir`` under ``dest_uri``; returns files copied
    (-1 if the backend doesn't report)."""
    if dest_uri.startswith("gs://"):
        return _sync_gcs(src_dir, dest_uri)
    dest = dest_uri[len("file://"):] if dest_uri.startswith("file://") \
        else dest_uri
    os.makedirs(dest, exist_ok=True)
    return _sync_local(src_dir, dest)


class ArtifactUploader:
    """Post-checkpoint hook: mirrors the workdir's checkpoint dirs to a
    destination URI.  Failures are reported but never kill training —
    losing an upload must not lose the run."""

    def __init__(self, dest_uri: str):
        self.dest_uri = dest_uri.rstrip("/")

    def sync(self, src_dir: str, tag: str):
        try:
            n = sync_dir(src_dir, f"{self.dest_uri}/{tag}")
            print(f"[upload] {tag}: {n if n >= 0 else '?'} file(s) → "
                  f"{self.dest_uri}/{tag}", flush=True)
        except Exception as e:  # noqa: BLE001 — deliberately broad
            print(f"[upload] FAILED for {tag}: {e}", flush=True)
