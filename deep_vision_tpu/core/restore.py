"""Checkpoint → serving-state restore, shared by every inference surface.

Extracted from ``cli/infer.py`` (where it was private to the demo CLI) so
the serving engine (``serve/registry.py``), the CLI, and any future
deployment path all build serving states through one function: workdir
checkpoint discovery (``checkpoints_best`` preferred), pipeline-layout →
monolithic conversion for runs trained with ``--mesh ...,pipe=p``, and the
EMA-params preference (serve the averaged copy — the weights eval scored).

A corrupt or partially-written latest checkpoint (killed mid-save, torn
copy) does NOT take the serving path down: restore walks the retained
steps newest-first and falls back to the previous step, logging which
step was actually restored.  Callers that need the answer
programmatically pass ``info={}`` and read ``info["step"]`` /
``info["fallback"]`` back (serve/registry.py surfaces it per model).

The model control plane (serve/models.py) additionally needs to answer
"did the trainer publish a new step?" WITHOUT paying a full restore:
``checkpoint_fingerprint(workdir)`` walks the same directories and
returns (newest step, source dir, dir mtime) from filesystem metadata
alone, and ``load_state`` stamps ``info["mtime"]`` (checkpoint dir
mtime) plus ``info["digest"]`` (a cheap tree-reduced byte hash of the
restored params) so a version's identity survives into ``describe()``.
"""

from __future__ import annotations

import functools
import os


def params_digest(params) -> str:
    """Cheap tree-reduced byte hash of a params pytree: leaf shapes +
    raw bytes folded through one blake2b.  Deterministic for a given
    tree (leaf order is the pytree flatten order), collision-safe
    enough to answer "are these the same weights?" for reload
    detection — NOT a cryptographic artifact signature."""
    import hashlib

    import jax
    import numpy as np

    h = hashlib.blake2b(digest_size=8)
    for leaf in jax.tree_util.tree_leaves(params):
        a = np.asarray(leaf)
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def serving_input_shape(cfg, model=None) -> tuple:
    """Per-example input shape inference traces need for ``cfg``.

    Almost every zoo config takes images — (H, W, C) from the config —
    but the latent-in generative models invert that: ``DCGANGenerator``
    maps a latent vector to an image, and its Dense kernel shapes derive
    from the *latent* width, so initializing with an image-shaped zeros
    batch (what ``load_state`` did before the generate workload existed)
    would build params the trainer's checkpoints can't restore into
    (tasks/gan.py inits with ``(1, latent_dim)``).  Pass ``model`` when
    one is already built to avoid a second ``cfg.model()``."""
    if getattr(cfg, "task", "") == "gan_dcgan":
        if model is None:
            model = cfg.model()
        return (int(getattr(model, "latent_dim", 100)),)
    return (cfg.image_size, cfg.image_size, cfg.channels)


#: substring Orbax stamps on its atomic-rename staging artifacts
#: (``<step>.orbax-checkpoint-tmp-<ts>`` dirs, and item-level tmp dirs
#: inside a step while an async save is materializing it)
_ORBAX_TMP_MARKER = "orbax-checkpoint-tmp"


def _complete_step_dir(path: str) -> bool:
    """A step dir counts as durable only once it has content and none
    of that content is an Orbax in-progress staging artifact — a step
    mid-async-save (empty, or holding ``*.orbax-checkpoint-tmp-*``
    items) must not fingerprint as deployable."""
    try:
        with os.scandir(path) as it:
            children = [e.name for e in it]
    except OSError:
        return False
    if not children:
        return False
    return not any(_ORBAX_TMP_MARKER in name for name in children)


def checkpoint_fingerprint(workdir: str) -> dict:
    """Filesystem-only "new step published?" probe: the newest retained
    step under ``checkpoints_best``/``checkpoints`` (same preference
    order as ``load_state``), its source dir, and the STEP dir's mtime
    — no checkpoint bytes are read and no Orbax manager is built, so
    the control plane and the deploy watcher can poll this on a tight
    interval without touching the restore path (or blocking on an
    in-flight async save).

    Orbax in-progress artifacts are invisible here: ``*.orbax-
    checkpoint-tmp-*`` staging dirs, non-numeric names, and incomplete
    step dirs (empty, or still holding item-level tmp dirs) are all
    skipped, and the mtime is taken from the newest durable step dir
    itself rather than the parent — so an async save materializing next
    door never changes the fingerprint of what is already deployable.
    Returns ``{"step": None, "dir": None, "mtime": None}`` for a
    workdir with no durable checkpoints (the random-init fixture
    path)."""
    for sub in ("checkpoints_best", "checkpoints"):
        d = os.path.join(workdir, sub)
        if not os.path.isdir(d):
            continue
        newest = None  # (step, mtime)
        try:
            with os.scandir(d) as it:
                entries = list(it)
        except OSError:
            continue
        for ent in entries:
            name = ent.name
            if _ORBAX_TMP_MARKER in name or not name.isdigit():
                continue
            try:
                if not ent.is_dir(follow_symlinks=False):
                    continue
                if not _complete_step_dir(ent.path):
                    continue
                mtime = ent.stat(follow_symlinks=False).st_mtime
            except OSError:
                continue  # torn down mid-scan: not durable
            step = int(name)
            if newest is None or step > newest[0]:
                newest = (step, mtime)
        if newest is not None:
            return {"step": newest[0], "dir": d, "mtime": newest[1]}
    return {"step": None, "dir": None, "mtime": None}


def load_state(cfg, workdir, *, log=print, tag: str = "restore",
               info: dict | None = None):
    """Restore (model, TrainState) ready to serve from ``workdir``.

    Prefers ``checkpoints_best`` over ``checkpoints``; converts
    pipeline-trained layouts to monolithic; serves EMA params when the run
    trained with them.  Falls back step-by-step when the newest retained
    checkpoint fails to restore, and to a fresh random init (with a
    warning) when no restorable checkpoint exists — the synthetic /
    smoke-test path.  ``info`` (optional dict) receives ``step`` (the
    step actually restored, None for random init), ``dir``, ``fallback``
    (True when an earlier step than the newest was used), ``mtime``
    (the checkpoint dir's mtime, None for random init), and ``digest``
    (``params_digest`` of the restored weights).
    """
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.core import checkpoint as ckpt_lib
    from deep_vision_tpu.core.optim import build_optimizer
    from deep_vision_tpu.core.state import TrainState

    if info is None:
        info = {}
    model = cfg.model()
    x = jnp.zeros((1, *serving_input_shape(cfg, model)))

    def fresh_state():
        variables = jax.jit(functools.partial(model.init, train=False))(
            {"params": jax.random.PRNGKey(0)}, x)
        return TrainState.create(
            apply_fn=model.apply, params=variables["params"],
            tx=build_optimizer(cfg.optimizer),
            batch_stats=variables.get("batch_stats", {}))

    def restore_step(ckpt, step):
        if ckpt.state_subtree_keys("params", step) == {"stem", "stages"}:
            # pipeline-trained run (cli.train --mesh ...,pipe=p):
            # restore the pipelined layout, convert to monolithic
            # (no monolithic init needed — the merged variables
            # build the serving state directly)
            return restore_pipelined(cfg, model, ckpt, x, step=step), \
                "pipeline layout → monolithic"
        state = fresh_state()
        if ckpt.has_state_key("ema_params", step):
            # serve the averaged copy — the weights eval scored
            # and the deployment artifact (README: params EMA)
            state = state.replace(
                ema_params=jax.tree_util.tree_map(
                    jnp.array, state.params))
            state, _ = ckpt.restore(state, step=step)
            return state.replace(params=state.ema_params), "EMA weights"
        state, _ = ckpt.restore(state, step=step)
        return state, ""

    for sub in ("checkpoints_best", "checkpoints"):
        d = os.path.join(workdir, sub)
        if not os.path.isdir(d):
            continue
        ckpt = ckpt_lib.Checkpointer(d)
        steps = sorted(ckpt.all_steps(), reverse=True)
        for step in steps:
            try:
                state, how = restore_step(ckpt, step)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # noqa: BLE001 — corrupt/partial step
                log(f"[{tag}] WARNING: checkpoint step {step} under {d} "
                    f"failed to restore ({type(e).__name__}: {e}); "
                    f"falling back to the previous retained step")
                continue
            fallback = step != steps[0]
            info.update({"step": step, "dir": d, "fallback": fallback,
                         "mtime": os.path.getmtime(d),
                         "digest": params_digest(state.params)})
            log(f"[{tag}] restored from {d} step {step}"
                + (f" ({how})" if how else "")
                + (" [FALLBACK: newer step was corrupt]" if fallback
                   else ""))
            return model, state
        if steps:
            log(f"[{tag}] WARNING: every retained checkpoint under {d} "
                f"failed to restore; trying the next source")
    state = fresh_state()
    info.update({"step": None, "dir": None, "fallback": False,
                 "mtime": None, "digest": params_digest(state.params)})
    log(f"[{tag}] WARNING: no restorable checkpoint found, "
        f"using random init")
    return model, state


def restore_pipelined(cfg, model, ckpt, x, step: int | None = None):
    """Restore a pipeline-trained checkpoint (params = {stem, stages})
    and build the monolithic serving state from the converted layout.
    Serves the EMA copy when the run trained with one."""
    import jax

    from deep_vision_tpu.core.optim import build_optimizer
    from deep_vision_tpu.core.state import TrainState
    from deep_vision_tpu.parallel import make_mesh
    from deep_vision_tpu.parallel.pipelined import PipelinedModel

    try:
        pm = PipelinedModel.for_model(
            model, make_mesh({"data": 1, "pipe": 1},
                             devices=jax.devices()[:1]))
    except TypeError as e:
        raise SystemExit(
            f"checkpoint stores a pipeline layout but config "
            f"'{cfg.name}' builds no pipelined family: {e}") from e
    pv = jax.jit(functools.partial(pm.init, train=False))(
        {"params": jax.random.PRNGKey(0)}, x)
    has_ema = ckpt.has_state_key("ema_params", step)
    pstate = TrainState.create(
        apply_fn=pm.apply, params=pv["params"],
        tx=build_optimizer(cfg.optimizer),
        batch_stats=pv.get("batch_stats", {}), ema=has_ema)
    pstate, _ = ckpt.restore(pstate, step=step)
    params = pstate.ema_params if has_ema else pstate.params
    merged = pm.export_monolithic_variables(params, pstate.batch_stats)
    return TrainState.create(
        apply_fn=model.apply, params=merged["params"],
        tx=build_optimizer(cfg.optimizer),
        batch_stats=merged.get("batch_stats", {}))
