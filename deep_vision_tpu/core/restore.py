"""Checkpoint → serving-state restore, shared by every inference surface.

Extracted from ``cli/infer.py`` (where it was private to the demo CLI) so
the serving engine (``serve/registry.py``), the CLI, and any future
deployment path all build serving states through one function: workdir
checkpoint discovery (``checkpoints_best`` preferred), pipeline-layout →
monolithic conversion for runs trained with ``--mesh ...,pipe=p``, and the
EMA-params preference (serve the averaged copy — the weights eval scored).
"""

from __future__ import annotations

import functools
import os


def load_state(cfg, workdir, *, log=print, tag: str = "restore"):
    """Restore (model, TrainState) ready to serve from ``workdir``.

    Prefers ``checkpoints_best`` over ``checkpoints``; converts
    pipeline-trained layouts to monolithic; serves EMA params when the run
    trained with them.  Falls back to a fresh random init (with a warning)
    when no checkpoint exists — the synthetic / smoke-test path.
    """
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.core import checkpoint as ckpt_lib
    from deep_vision_tpu.core.optim import build_optimizer
    from deep_vision_tpu.core.state import TrainState

    model = cfg.model()
    x = jnp.zeros((1, cfg.image_size, cfg.image_size, cfg.channels))

    def fresh_state():
        variables = jax.jit(functools.partial(model.init, train=False))(
            {"params": jax.random.PRNGKey(0)}, x)
        return TrainState.create(
            apply_fn=model.apply, params=variables["params"],
            tx=build_optimizer(cfg.optimizer),
            batch_stats=variables.get("batch_stats", {}))

    for sub in ("checkpoints_best", "checkpoints"):
        d = os.path.join(workdir, sub)
        if os.path.isdir(d):
            ckpt = ckpt_lib.Checkpointer(d)
            if ckpt.latest_step() is not None:
                if ckpt.state_subtree_keys("params") == {"stem", "stages"}:
                    # pipeline-trained run (cli.train --mesh ...,pipe=p):
                    # restore the pipelined layout, convert to monolithic
                    # (no monolithic init needed — the merged variables
                    # build the serving state directly)
                    state = restore_pipelined(cfg, model, ckpt, x)
                    log(f"[{tag}] restored from {d} step "
                        f"{ckpt.latest_step()} (pipeline layout → "
                        f"monolithic)")
                    break
                state = fresh_state()
                if ckpt.has_state_key("ema_params"):
                    # serve the averaged copy — the weights eval scored
                    # and the deployment artifact (README: params EMA)
                    state = state.replace(
                        ema_params=jax.tree_util.tree_map(
                            jnp.array, state.params))
                    state, _ = ckpt.restore(state)
                    state = state.replace(params=state.ema_params)
                    log(f"[{tag}] restored from {d} step "
                        f"{ckpt.latest_step()} (EMA weights)")
                else:
                    state, _ = ckpt.restore(state)
                    log(f"[{tag}] restored from {d} step "
                        f"{ckpt.latest_step()}")
                break
    else:
        state = fresh_state()
        log(f"[{tag}] WARNING: no checkpoint found, using random init")
    return model, state


def restore_pipelined(cfg, model, ckpt, x):
    """Restore a pipeline-trained checkpoint (params = {stem, stages})
    and build the monolithic serving state from the converted layout.
    Serves the EMA copy when the run trained with one."""
    import jax

    from deep_vision_tpu.core.optim import build_optimizer
    from deep_vision_tpu.core.state import TrainState
    from deep_vision_tpu.parallel import make_mesh
    from deep_vision_tpu.parallel.pipelined import PipelinedModel

    try:
        pm = PipelinedModel.for_model(
            model, make_mesh({"data": 1, "pipe": 1},
                             devices=jax.devices()[:1]))
    except TypeError as e:
        raise SystemExit(
            f"checkpoint stores a pipeline layout but config "
            f"'{cfg.name}' builds no pipelined family: {e}") from e
    pv = jax.jit(functools.partial(pm.init, train=False))(
        {"params": jax.random.PRNGKey(0)}, x)
    has_ema = ckpt.has_state_key("ema_params")
    pstate = TrainState.create(
        apply_fn=pm.apply, params=pv["params"],
        tx=build_optimizer(cfg.optimizer),
        batch_stats=pv.get("batch_stats", {}), ema=has_ema)
    pstate, _ = ckpt.restore(pstate)
    params = pstate.ema_params if has_ema else pstate.params
    merged = pm.export_monolithic_variables(params, pstate.batch_stats)
    return TrainState.create(
        apply_fn=model.apply, params=merged["params"],
        tx=build_optimizer(cfg.optimizer),
        batch_stats=merged.get("batch_stats", {}))
