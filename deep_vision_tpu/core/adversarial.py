"""AdversarialTrainer: multi-model / multi-optimizer training.

Generalizes the Trainer to the reference's GAN loops — DCGAN's twin-tape
simultaneous G/D step (DCGAN/tensorflow/main.py:55-71) and CycleGAN's
generator-step → ImagePool → discriminator-step sequence
(CycleGAN/tensorflow/train.py:150-265).

Design: the GAN *task* owns the math as a pure function
``task.train_step(states: dict[str, TrainState], batch, rng) ->
(new_states, host_outputs, metrics)`` which is jitted whole (donated states).
Host-side state between steps (the ImagePool, kept outside ``@tf.function``
in the reference, utils.py:31) lives in ``task.host_update(outputs)`` which
runs between jitted steps and can rewrite the next batch.
"""

from __future__ import annotations

import os
import time
from typing import Iterable

import jax

from deep_vision_tpu.core import checkpoint as ckpt_lib
from deep_vision_tpu.core.state import DivergenceGuard, all_finite
from deep_vision_tpu.core.config import TrainConfig
from deep_vision_tpu.core.metrics import MetricLogger, ThroughputMeter
from deep_vision_tpu.core.optim import build_scheduler, set_learning_rate
from deep_vision_tpu.parallel import (
    make_mesh,
    replicate,
    shard_batch,
    shard_batch_stacked,
)


class AdversarialTrainer:
    def __init__(self, config: TrainConfig, task, mesh=None,
                 workdir: str | None = None, upload: str | None = None,
                 preprocess_fn=None):
        self.config = config
        # optional device-side input preprocessing run INSIDE the jitted
        # step (the GAN uint8 wire: ops/preprocess.make_gan_preprocess
        # reverses the (x-127.5)/127.5 scaling as a traced prologue);
        # signature (batch, rng, train) — same contract as Trainer
        self.preprocess_fn = preprocess_fn
        if getattr(config, "grad_accum_steps", 1) > 1:
            raise NotImplementedError(
                "grad_accum_steps applies to the single-optimizer Trainer "
                "only; adversarial steps update G and D from the same "
                "forward, so accumulate by lowering batch_size instead")
        self.task = task  # owns models, optimizers, and the step math
        self.mesh = mesh if mesh is not None else make_mesh()
        self.workdir = workdir or os.path.join("runs", config.name)
        self.logger = MetricLogger(self.workdir)
        self.scheduler = build_scheduler(
            config.scheduler.name, config.optimizer.learning_rate,
            **config.scheduler.kwargs)
        self.checkpointer = ckpt_lib.Checkpointer(
            os.path.join(self.workdir, "checkpoints"),
            max_to_keep=config.keep_checkpoints)
        self.uploader = None
        if upload:
            from deep_vision_tpu.core.upload import ArtifactUploader

            self.uploader = ArtifactUploader(upload)
        self._jit_step = None
        self._jit_multi = None
        self.start_epoch = 1
        self.start_step = 0
        self.guard = DivergenceGuard(config.max_bad_steps)
        self._preempted = False  # SIGTERM → step-boundary save + return
        # staged input pipeline — same DevicePrefetcher as the Trainer,
        # used by _epoch_steps for tasks that declare ``prefetch_safe``
        # (DCGAN: no host exchange between steps; CycleGAN's per-step
        # ImagePool injection must see the PREVIOUS step's fakes, so
        # staging its batches ahead would replay stale pools)
        self.prefetch_depth = max(1, int(getattr(config,
                                                 "prefetch_depth", 2)))
        self._prefetcher = None

    def init_states(self, sample_batch: dict) -> dict:
        if self.preprocess_fn is not None:
            # models must init on what the step actually feeds them
            # (uint8 wire batches decode inside the jitted step)
            sample_batch = self.preprocess_fn(
                sample_batch, jax.random.PRNGKey(0), train=False)
        states = self.task.init_states(
            jax.random.PRNGKey(self.config.seed), sample_batch)
        return {k: replicate(v, self.mesh) for k, v in states.items()}

    def _get_prefetcher(self):
        if self._prefetcher is None:
            from deep_vision_tpu.data.pipeline import DevicePrefetcher

            self._prefetcher = DevicePrefetcher(self.mesh,
                                                depth=self.prefetch_depth)
        return self._prefetcher

    def _log_input_stats(self, step: int, stats: dict, epoch: int):
        """Same input-goodput block as Trainer._log_input_stats — both
        trainers report identical series (docs/OBSERVABILITY.md)."""
        if not stats or not stats.get("batches"):
            return
        self.logger.log_input_block(step, stats)
        prod = stats.get("producer_ms", {})
        n = max(1, stats["batches"])
        print(f"[input] epoch {epoch} stall {stats['input_stall_frac']:.1%} "
              f"h2d {stats['h2d_bytes_per_step'] / 1e6:.2f} MB/step "
              f"prep {prod.get('prep_wait', 0.0) / n:.1f} "
              f"assemble {prod.get('assemble', 0.0) / n:.1f} "
              f"h2d {prod.get('h2d', 0.0) / n:.1f} ms/batch "
              f"(pool alloc {stats['pool']['allocated']} "
              f"reuse {stats['pool']['reused']})", flush=True)

    def maybe_resume(self, states: dict) -> dict:
        if self.checkpointer.latest_step() is None:
            return states
        states, extras = self.checkpointer.restore_tree(states)
        self.start_epoch = int(extras.get("epoch", 0)) + 1
        self.start_step = int(self.checkpointer.latest_step() or 0)
        if "scheduler" in extras:
            self.scheduler.load_state_dict(extras["scheduler"])
        first = next(iter(states.values()))
        self.guard.set_baseline(int(jax.device_get(first.bad_steps)))
        print(f"[resume] adversarial start_epoch={self.start_epoch} "
              f"step={self.start_step}")
        return {k: replicate(v, self.mesh) for k, v in states.items()}

    def _guarded_step(self, task_step):
        preprocess_fn = self.preprocess_fn

        def guarded(states, batch, rng):
            """Divergence guard around the task's multi-network step:
            if any loss or any updated network went non-finite, every
            network keeps its previous params/opt_state (GAN updates are
            coupled — applying half a step would unbalance G vs D).
            The optional traced preprocess prologue (uint8 wire decode)
            runs first; it consumes no randomness, so the task sees the
            SAME rng as the float-wire path."""
            if preprocess_fn is not None:
                batch = preprocess_fn(batch, rng, train=True)
            new_states, outputs, metrics = task_step(states, batch, rng)
            ok = all_finite(list(metrics.values())) & all_finite(
                {k: s.params for k, s in new_states.items()})
            merged = {k: new_states[k].keep_if(ok, states[k])
                      for k in new_states}
            first = next(iter(merged))
            metrics = dict(metrics, bad_steps=merged[first].bad_steps)
            return merged, outputs, metrics

        return guarded

    def train_step(self, states, batch, rng):
        if self._jit_step is None:
            # batch donated alongside the states (argnum 1): prefetched
            # device batches are single-use, so XLA may reuse their HBM;
            # host numpy batches (tests, the CycleGAN pool path) are
            # copied on device_put and unaffected
            self._jit_step = jax.jit(
                self._guarded_step(self.task.train_step),
                donate_argnums=(0, 1))
        return self._jit_step(states, shard_batch(batch, self.mesh), rng)

    def train_multi(self, states, stacked, rng):
        """K coupled G/D updates per device dispatch (``config.scan_steps``)
        for tasks that declare ``scan_safe`` (no host state between steps:
        DCGAN's twin-tape step; CycleGAN's per-step ImagePool exchange
        forces per-step dispatch).  Metrics come back (K,)-leaved so the
        divergence guard still sees every step.  The rng key threads
        through the scan carry with the SAME per-step split as the
        per-step path and comes back out, so scan_steps=K trains
        identically to scan_steps=1 (up to XLA float reassociation)."""
        if self._jit_multi is None:
            guarded = self._guarded_step(self.task.train_step)

            def multi(states, stacked, rng):
                def body(carry, batch):
                    states, rng = carry
                    rng, step_rng = jax.random.split(rng)
                    states, _, metrics = guarded(states, batch, step_rng)
                    return (states, rng), metrics

                (states, rng), metrics = jax.lax.scan(body, (states, rng),
                                                      stacked)
                return states, metrics, rng

            self._jit_multi = jax.jit(multi, donate_argnums=0)
        return self._jit_multi(
            states, shard_batch_stacked(stacked, self.mesh), rng)

    def fit(self, train_data: Iterable, epochs: int | None = None,
            states: dict | None = None, resume: bool = False,
            sample_hook=None) -> dict:
        cfg = self.config
        epochs = epochs or cfg.total_epochs
        if states is None:
            states = self.init_states(next(iter(train_data)))
        if resume:
            states = self.maybe_resume(states)
        rng = jax.random.PRNGKey(cfg.seed + 17)
        step = self.start_step  # continues past-resume step numbering
        from deep_vision_tpu.core.trainer import install_sigterm_flag

        self._preempted = False  # stale flag must not abort a fresh fit()
        restore = install_sigterm_flag(
            lambda: setattr(self, "_preempted", True))
        try:
            return self._fit_epochs(train_data, epochs, states, rng, step,
                                    sample_hook)
        finally:
            restore()
            # abandoned epochs must not leave a producer thread parked on
            # the queue or device batches pinned in it
            if self._prefetcher is not None:
                self._prefetcher.close()

    def _preempt_save(self, step, states, epoch):
        self.checkpointer.save_tree(
            step, states,
            extras={"epoch": epoch - 1,
                    "scheduler": self.scheduler.state_dict()})
        # block until durable: the preempt grace window is the one
        # place an async save must not still be in flight
        self.checkpointer.wait_until_finished()
        if self.uploader is not None:
            # the VM disappears seconds after SIGTERM — the preempt
            # save is the one that MUST reach off-host
            self.uploader.sync(self.checkpointer.directory, "checkpoints")
        print(f"[preempt] checkpoint saved at step {step}; "
              f"rerun with --resume to continue", flush=True)

    def _fit_epochs(self, train_data, epochs, states, rng, step, sample_hook):
        cfg = self.config
        K = getattr(cfg, "scan_steps", 1) or 1
        use_scan = K > 1 and getattr(self.task, "scan_safe", False)
        for epoch in range(self.start_epoch, epochs + 1):
            lr = self.scheduler.epoch_begin(epoch)
            states = {k: v.replace(
                opt_state=set_learning_rate(v.opt_state, lr))
                for k, v in states.items()}
            if hasattr(train_data, "set_epoch"):
                train_data.set_epoch(epoch)
            meter = ThroughputMeter()
            t0 = time.monotonic()
            if use_scan:
                states, rng, step, aborted = self._epoch_scan(
                    train_data, states, rng, step, epoch, K, meter)
            else:
                states, rng, step, aborted = self._epoch_steps(
                    train_data, states, rng, step, epoch, meter)
            if aborted:
                return states
            # drain the async dispatch chain (cheap scalar that depends on
            # every update) so the epoch time is wall truth, not queue depth
            int(jax.device_get(next(iter(states.values())).step))
            self.scheduler.step(epoch, None)
            print(f"Epoch {epoch} done in {time.monotonic() - t0:.1f}s", flush=True)
            self.logger.log("images_per_sec", step, meter.images_per_sec)
            if epoch % cfg.checkpoint_every_epochs == 0:
                self.checkpointer.save_tree(
                    step, states,
                    extras={"epoch": epoch,
                            "scheduler": self.scheduler.state_dict()})
                if self.uploader is not None:
                    # async save must land before the mirror copies it
                    self.checkpointer.wait_until_finished()
                    self.uploader.sync(self.checkpointer.directory,
                                       "checkpoints")
            if sample_hook is not None:
                sample_hook(epoch, states)
        return states

    def _log_step(self, epoch, step, metrics, meter):
        """Shared guard/log/print for one step's (host) metric dict."""
        m = {k: float(v) for k, v in jax.device_get(metrics).items()}
        self.guard.check(m)
        self.logger.log_dict(step, m)
        print(f"Epoch {epoch} Step {step} "
              + " ".join(f"{k}={v:.4f}" for k, v in m.items())
              + f" {meter.images_per_sec:.1f} img/s", flush=True)

    def _epoch_steps(self, train_data, states, rng, step, epoch, meter):
        """Per-step dispatch with the host_prepare/host_update exchange
        between steps (the CycleGAN ImagePool contract).

        Tasks that declare ``prefetch_safe`` (host_prepare is stateless —
        DCGAN) ride the staged ``DevicePrefetcher``: host_prepare runs
        producer-side before staging, batches arrive already on device,
        and the epoch reports the same input-goodput block as the
        Trainer.  Pool-coupled tasks (CycleGAN) keep direct per-step
        iteration — their host_prepare must see the fakes ``host_update``
        harvested from the IMMEDIATELY previous step, which depth-k
        staging would replay stale."""
        cfg = self.config
        stream = None
        if getattr(self.task, "prefetch_safe", False):
            stream = self._get_prefetcher().iterate(
                train_data, host_transform=self.task.host_prepare)
        try:
            for batch in (stream if stream is not None else train_data):
                rng, step_rng = jax.random.split(rng)
                if stream is None:
                    batch = self.task.host_prepare(batch)
                bs = len(next(iter(batch.values())))
                states, outputs, metrics = self.train_step(
                    states, batch, step_rng)
                self.task.host_update(outputs)
                meter.update(bs)
                step += 1
                if step % cfg.log_every_steps == 0:
                    self._log_step(epoch, step, metrics, meter)
                if self._preempted:
                    self._preempt_save(step, states, epoch)
                    return states, rng, step, True
            return states, rng, step, False
        finally:
            if stream is not None:
                self._log_input_stats(step, stream.stats(), epoch)

    def _epoch_scan(self, train_data, states, rng, step, epoch, K, meter):
        """K-step-per-dispatch epoch for scan_safe tasks: host batches are
        stacked K at a time, one jitted ``lax.scan`` applies all K coupled
        G/D updates (DCGAN at 28² is dispatch-bound — ~5 ms device step vs
        ~2 ms dispatch through the tunnel).  The previous group's metrics
        fetch stays in flight while the next group runs (the Trainer's
        pending pattern), the guard still sees every step, and a trailing
        ragged group falls back to per-step dispatch."""
        import numpy as np

        cfg = self.config
        buf: list[dict] = []
        pending = None  # (step_after_group, (K,)-leaved device metrics)

        def drain(pending):
            if pending is None:
                return
            at, dev_ms = pending
            ms = {k: np.asarray(v) for k, v in jax.device_get(dev_ms).items()}
            for j in range(next(iter(ms.values())).shape[0]):
                self.guard.check({k: float(v[j]) for k, v in ms.items()})
            self.logger.log_dict(at, {k: float(v[-1]) for k, v in ms.items()})
            print(f"Epoch {epoch} Step {at} "
                  + " ".join(f"{k}={v[-1]:.4f}" for k, v in ms.items())
                  + f" {meter.images_per_sec:.1f} img/s", flush=True)

        for batch in train_data:
            buf.append(self.task.host_prepare(batch))
            meter.update(len(next(iter(batch.values()))))
            if len(buf) == K:
                stacked = jax.tree_util.tree_map(
                    lambda *xs: np.stack(xs), *buf)
                states, dev_ms, rng = self.train_multi(states, stacked, rng)
                step += len(buf)
                buf = []
                drain(pending)  # previous group — overlaps current dispatch
                pending = (step, dev_ms)
            if self._preempted:
                drain(pending)
                pending = None
                for b in buf:  # partial group per-step for exactness
                    rng, srng = jax.random.split(rng)
                    states, _, _ = self.train_step(states, b, srng)
                    step += 1
                self._preempt_save(step, states, epoch)
                return states, rng, step, True
        drain(pending)
        for b in buf:  # ragged tail: per-step dispatch (same logging)
            rng, srng = jax.random.split(rng)
            states, outputs, metrics = self.train_step(states, b, srng)
            self.task.host_update(outputs)
            step += 1
            if step % cfg.log_every_steps == 0:
                self._log_step(epoch, step, metrics, meter)
            if self._preempted:
                self._preempt_save(step, states, epoch)
                return states, rng, step, True
        return states, rng, step, False
