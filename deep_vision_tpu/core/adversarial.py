"""AdversarialTrainer: multi-model / multi-optimizer training.

Generalizes the Trainer to the reference's GAN loops — DCGAN's twin-tape
simultaneous G/D step (DCGAN/tensorflow/main.py:55-71) and CycleGAN's
generator-step → ImagePool → discriminator-step sequence
(CycleGAN/tensorflow/train.py:150-265).

Design: the GAN *task* owns the math as a pure function
``task.train_step(states: dict[str, TrainState], batch, rng) ->
(new_states, host_outputs, metrics)`` which is jitted whole (donated states).
Host-side state between steps (the ImagePool, kept outside ``@tf.function``
in the reference, utils.py:31) lives in ``task.host_update(outputs)`` which
runs between jitted steps and can rewrite the next batch.
"""

from __future__ import annotations

import os
import time
from typing import Iterable

import jax

from deep_vision_tpu.core import checkpoint as ckpt_lib
from deep_vision_tpu.core.state import DivergenceGuard, all_finite
from deep_vision_tpu.core.config import TrainConfig
from deep_vision_tpu.core.metrics import MetricLogger, ThroughputMeter
from deep_vision_tpu.core.optim import build_scheduler, set_learning_rate
from deep_vision_tpu.parallel import make_mesh, replicate, shard_batch


class AdversarialTrainer:
    def __init__(self, config: TrainConfig, task, mesh=None,
                 workdir: str | None = None, upload: str | None = None):
        self.config = config
        self.task = task  # owns models, optimizers, and the step math
        self.mesh = mesh if mesh is not None else make_mesh()
        self.workdir = workdir or os.path.join("runs", config.name)
        self.logger = MetricLogger(self.workdir)
        self.scheduler = build_scheduler(
            config.scheduler.name, config.optimizer.learning_rate,
            **config.scheduler.kwargs)
        self.checkpointer = ckpt_lib.Checkpointer(
            os.path.join(self.workdir, "checkpoints"),
            max_to_keep=config.keep_checkpoints)
        self.uploader = None
        if upload:
            from deep_vision_tpu.core.upload import ArtifactUploader

            self.uploader = ArtifactUploader(upload)
        self._jit_step = None
        self.start_epoch = 1
        self.start_step = 0
        self.guard = DivergenceGuard(config.max_bad_steps)
        self._preempted = False  # SIGTERM → step-boundary save + return

    def init_states(self, sample_batch: dict) -> dict:
        states = self.task.init_states(
            jax.random.PRNGKey(self.config.seed), sample_batch)
        return {k: replicate(v, self.mesh) for k, v in states.items()}

    def maybe_resume(self, states: dict) -> dict:
        if self.checkpointer.latest_step() is None:
            return states
        states, extras = self.checkpointer.restore_tree(states)
        self.start_epoch = int(extras.get("epoch", 0)) + 1
        self.start_step = int(self.checkpointer.latest_step() or 0)
        if "scheduler" in extras:
            self.scheduler.load_state_dict(extras["scheduler"])
        first = next(iter(states.values()))
        self.guard.set_baseline(int(jax.device_get(first.bad_steps)))
        print(f"[resume] adversarial start_epoch={self.start_epoch} "
              f"step={self.start_step}")
        return {k: replicate(v, self.mesh) for k, v in states.items()}

    def train_step(self, states, batch, rng):
        if self._jit_step is None:
            task_step = self.task.train_step

            def guarded(states, batch, rng):
                """Divergence guard around the task's multi-network step:
                if any loss or any updated network went non-finite, every
                network keeps its previous params/opt_state (GAN updates are
                coupled — applying half a step would unbalance G vs D)."""
                new_states, outputs, metrics = task_step(states, batch, rng)
                ok = all_finite(list(metrics.values())) & all_finite(
                    {k: s.params for k, s in new_states.items()})
                merged = {k: new_states[k].keep_if(ok, states[k])
                          for k in new_states}
                first = next(iter(merged))
                metrics = dict(metrics, bad_steps=merged[first].bad_steps)
                return merged, outputs, metrics

            self._jit_step = jax.jit(guarded, donate_argnums=0)
        return self._jit_step(states, shard_batch(batch, self.mesh), rng)

    def fit(self, train_data: Iterable, epochs: int | None = None,
            states: dict | None = None, resume: bool = False,
            sample_hook=None) -> dict:
        cfg = self.config
        epochs = epochs or cfg.total_epochs
        if states is None:
            states = self.init_states(next(iter(train_data)))
        if resume:
            states = self.maybe_resume(states)
        rng = jax.random.PRNGKey(cfg.seed + 17)
        step = self.start_step  # continues past-resume step numbering
        from deep_vision_tpu.core.trainer import install_sigterm_flag

        self._preempted = False  # stale flag must not abort a fresh fit()
        restore = install_sigterm_flag(
            lambda: setattr(self, "_preempted", True))
        try:
            return self._fit_epochs(train_data, epochs, states, rng, step,
                                    sample_hook)
        finally:
            restore()

    def _fit_epochs(self, train_data, epochs, states, rng, step, sample_hook):
        cfg = self.config
        for epoch in range(self.start_epoch, epochs + 1):
            lr = self.scheduler.epoch_begin(epoch)
            states = {k: v.replace(
                opt_state=set_learning_rate(v.opt_state, lr))
                for k, v in states.items()}
            if hasattr(train_data, "set_epoch"):
                train_data.set_epoch(epoch)
            meter = ThroughputMeter()
            t0 = time.time()
            metrics = {}
            for batch in train_data:
                rng, step_rng = jax.random.split(rng)
                batch = self.task.host_prepare(batch)
                states, outputs, metrics = self.train_step(
                    states, batch, step_rng)
                self.task.host_update(outputs)
                bs = len(next(iter(batch.values())))
                meter.update(bs)
                step += 1
                if step % cfg.log_every_steps == 0:
                    m = {k: float(v) for k, v in
                         jax.device_get(metrics).items()}
                    self.guard.check(m)
                    self.logger.log_dict(step, m)
                    print(f"Epoch {epoch} Step {step} "
                          + " ".join(f"{k}={v:.4f}" for k, v in m.items())
                          + f" {meter.images_per_sec:.1f} img/s", flush=True)
                if self._preempted:
                    self.checkpointer.save_tree(
                        step, states,
                        extras={"epoch": epoch - 1,
                                "scheduler": self.scheduler.state_dict()})
                    if self.uploader is not None:
                        # the VM disappears seconds after SIGTERM — the
                        # preempt save is the one that MUST reach off-host
                        self.uploader.sync(self.checkpointer.directory,
                                           "checkpoints")
                    print(f"[preempt] checkpoint saved at step {step}; "
                          f"rerun with --resume to continue", flush=True)
                    return states
            self.scheduler.step(epoch, None)
            print(f"Epoch {epoch} done in {time.time() - t0:.1f}s", flush=True)
            if epoch % cfg.checkpoint_every_epochs == 0:
                self.checkpointer.save_tree(
                    step, states,
                    extras={"epoch": epoch,
                            "scheduler": self.scheduler.state_dict()})
                if self.uploader is not None:
                    self.uploader.sync(self.checkpointer.directory,
                                       "checkpoints")
            if sample_hook is not None:
                sample_hook(epoch, states)
        return states
