"""Persistent XLA compilation cache for the CLI entry points.

First TPU compiles here run 40-270 s (ResNet-50 step ~40 s, 4-stack
Hourglass ~4 min); with the cache a relaunch reloads the executable in
seconds.  The reference pays the full graph-build/cuDNN-autotune cost on
every process start — this is the XLA-native fix (verified on this
backend: 58 s cold → 2.6 s warm for a 2000² matmul program).
"""

from __future__ import annotations

import os


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point JAX at an on-disk program cache (idempotent).

    Default location ``~/.cache/deep_vision_tpu/xla``; opt out by
    setting ``DEEP_VISION_TPU_NO_COMPILE_CACHE=1`` (e.g. when the home
    directory is on slow/quota'd network storage).  Returns the cache
    path, or None when disabled or unsupported by the installed jax.
    """
    if os.environ.get("DEEP_VISION_TPU_NO_COMPILE_CACHE"):
        return None
    import jax

    path = path or os.path.join(os.path.expanduser("~"), ".cache",
                                "deep_vision_tpu", "xla")
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # only persist programs worth the disk round-trip
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:  # noqa: BLE001 — cache config unsupported on this jax: run uncached
        return None
    return path
