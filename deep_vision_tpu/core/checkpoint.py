"""Checkpoint/resume via Orbax.

One mechanism replacing the reference's four (see state.py docstring).
Payload = ``state.save_dict()`` + host-side extras (epoch, scheduler state,
metric history) so a resumed run continues the LR schedule and logger series
exactly like the reference's ``-c`` flag (ResNet/pytorch/train.py:293-307).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import orbax.checkpoint as ocp

from deep_vision_tpu.core.state import TrainState


class Checkpointer:
    """``async_save=True`` (the default) lets ``save()``/``save_tree()``
    return as soon as Orbax has snapshotted the arrays, with
    serialization finishing in the background — the train loop pays
    device→host copy time, not disk time (ROADMAP item: async
    checkpointing).  The wait moves to where durability is actually
    needed: the start of the NEXT save (at most one save in flight),
    every read/restore/introspection path, ``close()``, and explicit
    ``wait_until_finished()`` calls (the trainer's SIGTERM preempt path
    blocks on it before announcing the checkpoint durable).
    ``async_save=False`` restores the old save-then-wait behavior."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = True):
        self.directory = os.path.abspath(directory)
        self.async_save = bool(async_save)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
            # explicit handlers so item_metadata works BEFORE any
            # save/restore registers them (has_state_key introspection)
            item_handlers={
                "state": ocp.StandardCheckpointHandler(),
                "extras": ocp.JsonCheckpointHandler(),
            },
        )

    def wait_until_finished(self):
        """Block until any in-flight async save is durable on disk —
        the preempt/exit/upload barrier.  A no-op when nothing is
        pending (or when ``async_save=False``, where every save already
        waited)."""
        self._mgr.wait_until_finished()

    def save(self, step: int, state: TrainState, extras: dict | None = None,
             force: bool = False):
        """``extras`` must be JSON-serializable (epoch, scheduler, history)."""
        payload = {"state": state.save_dict()}
        # at most one save in flight: the previous async save must
        # finalize before this step starts writing
        self._mgr.wait_until_finished()
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(payload),
                extras=ocp.args.JsonSave(extras or {}),
            ),
            force=force,
        )
        if not self.async_save:
            self._mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        self._mgr.wait_until_finished()  # an in-flight save counts
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        """Retained checkpoint steps, ascending — the restore fallback
        (core/restore.py) walks these newest-first when the latest
        checkpoint is corrupt or partially written."""
        self._mgr.wait_until_finished()  # an in-flight save counts
        return sorted(self._mgr.all_steps())

    def _state_meta(self, step: int | None) -> dict:
        """The stored state payload's metadata dict ({} when absent) —
        the one place that knows the save() payload nesting."""
        self._mgr.wait_until_finished()  # metadata must be finalized
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return {}
        try:
            meta = self._mgr.item_metadata(step)["state"]["state"]
        except (KeyError, TypeError):
            return {}
        return meta if isinstance(meta, dict) else {}

    def has_state_key(self, key: str, step: int | None = None) -> bool:
        """True iff the stored state payload carries a NON-EMPTY ``key``
        subtree (e.g. ``ema_params``) — lets callers reconcile state
        fields the checkpoint may pre- or post-date before restoring."""
        return bool(self._state_meta(step).get(key))

    def state_subtree_keys(self, key: str, step: int | None = None) -> set:
        """Child keys of the stored ``state[key]`` subtree (empty set when
        absent) — layout introspection without a restore, e.g. telling a
        pipeline-trained params tree ({stem, stages}) from a monolithic
        one before choosing the restore template."""
        meta = self._state_meta(step).get(key)
        return set(meta.keys()) if isinstance(meta, dict) else set()

    def _restore_payload(self, step: int, template: dict) -> tuple[dict, dict]:
        """Restore ``template``-shaped payload + extras; keys the stored
        checkpoint predates (e.g. ``bad_steps``) are dropped from the
        template and left at their fresh-state values, so old checkpoints
        stay restorable after TrainState grows a field."""
        self._mgr.wait_until_finished()  # restore needs a durable step
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, template)
        try:
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract),
                    extras=ocp.args.JsonRestore(),
                ),
            )
            return restored["state"], dict(restored["extras"] or {})
        except (ValueError, KeyError):
            # structure mismatch: intersect the template with what the
            # checkpoint actually holds, then retry
            meta = self._mgr.item_metadata(step)["state"]

            def prune(tmpl, stored):
                if not isinstance(tmpl, dict):
                    return tmpl
                return {k: prune(v, stored[k]) for k, v in tmpl.items()
                        if stored is not None and k in stored}

            pruned = prune(abstract, meta)
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(pruned),
                    extras=ocp.args.JsonRestore(),
                ),
            )
            return restored["state"], dict(restored["extras"] or {})

    def restore(self, state: TrainState, step: int | None = None
                ) -> tuple[TrainState, dict]:
        """Restore into the structure of a freshly-initialized ``state``."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        payload, extras = self._restore_payload(
            step, {"state": state.save_dict()})
        new_state = state.load_dict(payload["state"])
        return new_state, extras

    # -- multi-state trees (AdversarialTrainer: {name: TrainState}) --------

    def save_tree(self, step: int, states: dict, extras: dict | None = None):
        payload = {k: v.save_dict() for k, v in states.items()}
        self._mgr.wait_until_finished()  # one save in flight, as save()
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(payload),
                extras=ocp.args.JsonSave(extras or {}),
            ),
        )
        if not self.async_save:
            self._mgr.wait_until_finished()

    def restore_tree(self, states: dict, step: int | None = None
                     ) -> tuple[dict, dict]:
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        payload, extras = self._restore_payload(
            step, {k: v.save_dict() for k, v in states.items()})
        new_states = {k: v.load_dict(payload[k]) for k, v in states.items()}
        return new_states, extras

    def close(self):
        # an async save still in flight must land before the manager
        # tears down its thread pool
        self._mgr.wait_until_finished()
        self._mgr.close()
