"""Metric history + logging.

Replaces the reference's three observability paths with one: the in-memory
``loggers`` dict-of-series that rode inside checkpoints
(ResNet/pytorch/train.py:260-285), per-epoch pickles
(ResNet/tensorflow/train.py:81-144), and per-batch stdout prints
(ResNet/pytorch/train.py:472-485).  History is a plain dict (checkpointable),
mirrored to a JSONL file for offline plotting (TensorBoard-free).
"""

from __future__ import annotations

import json
import os
import time
from typing import Mapping


class MetricLogger:
    def __init__(self, workdir: str | None = None,
                 filename: str = "metrics.jsonl", tensorboard: bool = True):
        self.history: dict[str, dict[str, list]] = {}
        self._workdir = workdir
        self._filename = filename
        self._tensorboard = tensorboard
        self._path = None
        self._tb = None
        self._tb_dir = None
        self._resolved = False

    def _resolve_paths(self):
        """Decide file destinations on FIRST log, not construction.

        Multi-process: history stays on every rank (plateau/best-val logic
        must agree), but files are written by process 0 only — otherwise N
        ranks interleave lines into one metrics.jsonl.  The process check
        initializes the JAX backend, so it must not run in ``__init__``:
        a Trainer is often constructed before
        ``jax.distributed.initialize()``, which requires a pristine
        backend.  By the first log a train step has long since run."""
        if self._resolved:
            return
        self._resolved = True
        if self._workdir is None:
            return
        import jax

        if jax.process_index() != 0:
            return
        os.makedirs(self._workdir, exist_ok=True)
        self._path = os.path.join(self._workdir, self._filename)
        if self._tensorboard:
            # lazy: the event file is only created on first log, so
            # never-logging components don't litter empty files
            self._tb_dir = os.path.join(self._workdir, "tensorboard")

    def _tb_writer(self):
        self._resolve_paths()
        if self._tb is None and self._tb_dir is not None:
            from deep_vision_tpu.core.tboard import TFEventWriter

            self._tb = TFEventWriter(self._tb_dir)
        return self._tb

    def _record(self, name: str, step: int, value: float):
        self._resolve_paths()
        series = self.history.setdefault(name, {"steps": [], "values": []})
        series["steps"].append(int(step))
        series["values"].append(float(value))
        if self._path:
            with open(self._path, "a") as f:
                f.write(json.dumps({"name": name, "step": int(step),
                                    "value": float(value), "time": time.time()}) + "\n")

    def log(self, name: str, step: int, value: float):
        self._record(name, step, value)
        tb = self._tb_writer()
        if tb is not None:
            tb.scalar(name, value, step)
            tb.flush()

    def log_dict(self, step: int, metrics: Mapping[str, float]):
        for k, v in metrics.items():
            self._record(k, step, v)
        tb = self._tb_writer()
        if tb is not None and metrics:
            tb.scalars(metrics, step)  # one batched event + one flush
            tb.flush()

    def latest(self, name: str) -> float | None:
        s = self.history.get(name)
        return s["values"][-1] if s and s["values"] else None

    def best(self, name: str, mode: str = "max") -> float | None:
        s = self.history.get(name)
        if not s or not s["values"]:
            return None
        return max(s["values"]) if mode == "max" else min(s["values"])

    def state_dict(self) -> dict:
        return self.history

    def load_state_dict(self, d: dict):
        self.history = {k: {"steps": list(v["steps"]), "values": list(v["values"])}
                        for k, v in d.items()}


class ThroughputMeter:
    """Images/sec with warmup exclusion — the reference printed this per-100
    batches (YOLO/tensorflow/train.py:217-223)."""

    def __init__(self, warmup_steps: int = 2):
        self.warmup_steps = warmup_steps
        self.reset()

    def reset(self):
        self._n = 0
        self._images = 0
        self._start = None

    def update(self, batch_size: int):
        self._n += 1
        if self._n == self.warmup_steps:
            self._start = time.perf_counter()
        elif self._n > self.warmup_steps:
            self._images += batch_size

    @property
    def images_per_sec(self) -> float:
        if self._start is None or self._images == 0:
            return 0.0
        return self._images / (time.perf_counter() - self._start)
