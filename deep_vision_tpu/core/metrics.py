"""Metric history + logging.

Replaces the reference's three observability paths with one: the in-memory
``loggers`` dict-of-series that rode inside checkpoints
(ResNet/pytorch/train.py:260-285), per-epoch pickles
(ResNet/tensorflow/train.py:81-144), and per-batch stdout prints
(ResNet/pytorch/train.py:472-485).  History is a plain dict (checkpointable),
mirrored to a JSONL file for offline plotting (TensorBoard-free).
"""

from __future__ import annotations

import json
import os
import time
from typing import Mapping


class MetricLogger:
    def __init__(self, workdir: str | None = None,
                 filename: str = "metrics.jsonl", tensorboard: bool = True):
        self.history: dict[str, dict[str, list]] = {}
        self._workdir = workdir
        self._filename = filename
        self._tensorboard = tensorboard
        self._path = None
        self._tb = None
        self._tb_dir = None
        self._resolved = False

    def _resolve_paths(self):
        """Decide file destinations on FIRST log, not construction.

        Multi-process: history stays on every rank (plateau/best-val logic
        must agree), but files are written by process 0 only — otherwise N
        ranks interleave lines into one metrics.jsonl.  The process check
        initializes the JAX backend, so it must not run in ``__init__``:
        a Trainer is often constructed before
        ``jax.distributed.initialize()``, which requires a pristine
        backend.  By the first log a train step has long since run."""
        if self._resolved:
            return
        self._resolved = True
        if self._workdir is None:
            return
        import jax

        if jax.process_index() != 0:
            return
        os.makedirs(self._workdir, exist_ok=True)
        self._path = os.path.join(self._workdir, self._filename)
        if self._tensorboard:
            # lazy: the event file is only created on first log, so
            # never-logging components don't litter empty files
            self._tb_dir = os.path.join(self._workdir, "tensorboard")

    def _tb_writer(self):
        self._resolve_paths()
        if self._tb is None and self._tb_dir is not None:
            from deep_vision_tpu.core.tboard import TFEventWriter

            self._tb = TFEventWriter(self._tb_dir)
        return self._tb

    def _record(self, name: str, step: int, value: float):
        self._resolve_paths()
        series = self.history.setdefault(name, {"steps": [], "values": []})
        series["steps"].append(int(step))
        series["values"].append(float(value))
        if self._path:
            with open(self._path, "a") as f:
                f.write(json.dumps({"name": name, "step": int(step),
                                    "value": float(value), "time": time.time()}) + "\n")

    def log(self, name: str, step: int, value: float):
        self._record(name, step, value)
        tb = self._tb_writer()
        if tb is not None:
            tb.scalar(name, value, step)
            tb.flush()

    def log_dict(self, step: int, metrics: Mapping[str, float]):
        for k, v in metrics.items():
            self._record(k, step, v)
        tb = self._tb_writer()
        if tb is not None and metrics:
            tb.scalars(metrics, step)  # one batched event + one flush
            tb.flush()

    def log_input_block(self, step: int, stats: dict):
        """The trainer's per-epoch input-goodput block (docs/OBSERVABILITY.md
        "Trainer input-goodput series"): stall fraction, H2D traffic, and
        per-stage producer timers from ``DevicePrefetcher`` epoch stats.
        Exporters prefix these with ``dvt_train_`` (e.g.
        ``dvt_train_input_stall_frac``)."""
        n = max(1, int(stats.get("batches", 0)))
        prod = stats.get("producer_ms", {})
        self.log_dict(step, {
            "input_stall_frac": float(stats.get("input_stall_frac", 0.0)),
            "input_h2d_bytes_per_step":
                float(stats.get("h2d_bytes_per_step", 0.0)),
            "input_prep_wait_ms": float(prod.get("prep_wait", 0.0)) / n,
            "input_assemble_ms": float(prod.get("assemble", 0.0)) / n,
            "input_h2d_ms": float(prod.get("h2d", 0.0)) / n,
        })

    def latest(self, name: str) -> float | None:
        s = self.history.get(name)
        return s["values"][-1] if s and s["values"] else None

    def best(self, name: str, mode: str = "max") -> float | None:
        s = self.history.get(name)
        if not s or not s["values"]:
            return None
        return max(s["values"]) if mode == "max" else min(s["values"])

    def state_dict(self) -> dict:
        return self.history

    def load_state_dict(self, d: dict):
        self.history = {k: {"steps": list(v["steps"]), "values": list(v["values"])}
                        for k, v in d.items()}


class LatencyHistogram:
    """Latency quantiles over fixed log-spaced bins (serving p50/p95/p99).

    Fixed bin edges (not reservoir sampling) keep ``record`` O(log bins),
    memory constant, and — because every instance built with the same
    bounds shares the same edges — ``state_dict``s from N serving workers
    sum counts elementwise into one fleet-wide histogram (``merge``).
    Quantiles are read from the cumulative counts and reported as the
    geometric midpoint of the containing bin, so the error is bounded by
    the bin ratio (~12% with the default 20 bins/decade).
    """

    def __init__(self, lo: float = 1e-4, hi: float = 1e3,
                 bins_per_decade: int = 20):
        import math

        decades = math.log10(hi / lo)
        n = max(1, int(round(decades * bins_per_decade)))
        ratio = (hi / lo) ** (1.0 / n)
        # edges[0]=lo .. edges[n]=hi; +2 overflow bins for <lo and >=hi
        self.edges = [lo * ratio ** i for i in range(n + 1)]
        self.counts = [0] * (n + 2)
        self.total = 0
        self.sum = 0.0

    def record(self, seconds: float):
        import bisect

        self.counts[bisect.bisect_right(self.edges, seconds)] += 1
        self.total += 1
        self.sum += seconds

    def quantile(self, q: float) -> float:
        """q in [0,1] → latency seconds (geometric bin midpoint)."""
        if self.total == 0:
            return 0.0
        rank = max(1, int(q * self.total + 0.999999))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                if i == 0:                       # underflow: below lo
                    return self.edges[0]
                if i > len(self.edges) - 1:      # overflow: above hi
                    return self.edges[-1]
                return (self.edges[i - 1] * self.edges[i]) ** 0.5
        return self.edges[-1]

    @property
    def mean(self) -> float:
        return self.sum / self.total if self.total else 0.0

    def percentiles(self) -> dict:
        """The serving dashboard tuple, in milliseconds."""
        return {"p50_ms": self.quantile(0.50) * 1e3,
                "p95_ms": self.quantile(0.95) * 1e3,
                "p99_ms": self.quantile(0.99) * 1e3,
                "mean_ms": self.mean * 1e3,
                "count": self.total}

    def state_dict(self) -> dict:
        return {"edges": list(self.edges), "counts": list(self.counts),
                "total": self.total, "sum": self.sum}

    def load_state_dict(self, d: dict):
        self.edges = list(d["edges"])
        self.counts = list(d["counts"])
        self.total = int(d["total"])
        self.sum = float(d["sum"])

    def merge(self, d: dict) -> "LatencyHistogram":
        """Sum another histogram's ``state_dict`` into this one."""
        if list(d["edges"]) != self.edges:
            raise ValueError("cannot merge histograms with different bins")
        self.counts = [a + b for a, b in zip(self.counts, d["counts"])]
        self.total += int(d["total"])
        self.sum += float(d["sum"])
        return self


def _prom_num(v) -> str:
    """Prometheus sample/edge value formatting: integers stay integral,
    floats use repr (deterministic, full precision — bucket ``le``
    labels must be byte-identical across scrapes or the series forks)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _prom_escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


class PromText:
    """Prometheus text-exposition (format 0.0.4) renderer — stdlib only.

    The serving ``/metrics`` endpoints (serve/http.py, serve/gateway.py)
    feed their existing counters/gauges and ``LatencyHistogram`` states
    through this instead of maintaining a parallel metric registry:
    the stats dicts stay the source of truth, this renders a snapshot.

    ``histogram`` renders a ``LatencyHistogram.state_dict`` as the
    cumulative ``le`` buckets Prometheus expects: bucket[le=edges[j]] =
    counts[0..j] summed (counts[0] is the <lo underflow bin, so it
    folds into the first edge), ``+Inf`` = total, plus ``_sum`` and
    ``_count``.  Every edge is always emitted — empty buckets included
    — so the bucket series are stable across scrapes and quantile
    recomputation (histogram_quantile) sees the full grid.
    """

    def __init__(self):
        self._lines: list[str] = []
        self._typed: set[str] = set()

    def _meta(self, name: str, typ: str, help_: str):
        if name in self._typed:
            return
        self._typed.add(name)
        if help_:
            self._lines.append(f"# HELP {name} {help_}")
        self._lines.append(f"# TYPE {name} {typ}")

    @staticmethod
    def _labels(labels: dict | None) -> str:
        if not labels:
            return ""
        inner = ",".join(f'{k}="{_prom_escape(str(v))}"'
                         for k, v in sorted(labels.items()))
        return "{" + inner + "}"

    def sample(self, name: str, value, labels: dict | None = None, *,
               typ: str = "gauge", help: str = ""):
        """One sample line; ``None`` values are skipped (an unknown
        gauge is absent, never fabricated as 0)."""
        if value is None:
            return
        self._meta(name, typ, help)
        self._lines.append(f"{name}{self._labels(labels)} "
                           f"{_prom_num(value)}")

    def counter(self, name: str, value, labels: dict | None = None,
                help: str = ""):
        self.sample(name, value, labels, typ="counter", help=help)

    def gauge(self, name: str, value, labels: dict | None = None,
              help: str = ""):
        self.sample(name, value, labels, typ="gauge", help=help)

    def histogram(self, name: str, state: dict,
                  labels: dict | None = None, help: str = ""):
        """Cumulative buckets from a ``LatencyHistogram.state_dict``
        (``le`` values in seconds, matching what ``record`` observes)."""
        self._meta(name, "histogram", help)
        labels = dict(labels or {})
        edges, counts = state["edges"], state["counts"]
        cum = 0
        for i, edge in enumerate(edges):
            cum += counts[i]
            self._lines.append(
                f"{name}_bucket"
                f"{self._labels({**labels, 'le': _prom_num(edge)})} {cum}")
        total = int(state["total"])
        self._lines.append(
            f"{name}_bucket{self._labels({**labels, 'le': '+Inf'})} "
            f"{total}")
        self._lines.append(f"{name}_sum{self._labels(labels)} "
                           f"{_prom_num(float(state['sum']))}")
        self._lines.append(f"{name}_count{self._labels(labels)} {total}")

    def render(self) -> str:
        return "\n".join(self._lines) + "\n"


class ThroughputMeter:
    """Images/sec with warmup exclusion — the reference printed this per-100
    batches (YOLO/tensorflow/train.py:217-223)."""

    def __init__(self, warmup_steps: int = 2):
        self.warmup_steps = warmup_steps
        self.reset()

    def reset(self):
        self._n = 0
        self._images = 0
        self._start = None

    def update(self, batch_size: int):
        self._n += 1
        if self._n == self.warmup_steps:
            self._start = time.perf_counter()
        elif self._n > self.warmup_steps:
            self._images += batch_size

    @property
    def images_per_sec(self) -> float:
        if self._start is None or self._images == 0:
            return 0.0
        return self._images / (time.perf_counter() - self._start)
