"""Checkpoint watcher + accuracy gate: new checkpoint → gated rollout.

``CheckpointWatcher`` runs one supervised daemon thread per watched
model, each polling ``checkpoint_fingerprint(workdir)`` on an
Event-paced monotonic interval.  Acting on a fingerprint requires it to
be STABLE ACROSS TWO CONSECUTIVE POLLS (debounce): async Orbax saves
materialize through ``*.orbax-checkpoint-tmp-*`` staging dirs that the
fingerprint already skips, and the debounce additionally absorbs any
step that is still changing between polls — a half-written checkpoint
can never deploy.  A fingerprint is acted on at most once (gate failure
included); publishing a NEW step re-arms the watcher.

The ``AccuracyGate`` stands between "new checkpoint" and "new version
serving traffic": the candidate is loaded (same restore path as a
reload) and evaluated on a held-out ``--gate-dir`` *.npy set — loaded
through ``serve/quant.py``'s calibration-batch loader, so the same
held-out data can drive both int8 calibration and deploy gating.  With
``labels.txt`` present the gate compares real top-1 accuracy candidate
vs active (pass: within ``max_accuracy_drop``); without labels it
gates on top-1 agreement (pass: ≥ ``min_agreement``); NaN outputs
always fail; non-classification outputs get the NaN check only.  Only
a passing candidate reaches ``plane.reload()`` — the normal
shadow/canary/promote path guards the rest.  A failing candidate is a
``FAILED`` ledger record carrying the eval delta; the active version
never stops serving.

``DeployPipeline`` is the one handle cli.serve and the HTTP layer
hold: plane + history + watcher + per-model autoscalers, with
``revert()`` recording the ledger entry around the plane's CAS'd
rollback.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.obs.log import event, get_logger
from deep_vision_tpu.serve.models import ACTIVE, FAILED

_log = get_logger("dvt.deploy.watcher")


class AccuracyGate:
    """Held-out eval between checkpoint and rollout.

    ``gate_dir`` follows the calibration-set layout (``*.npy`` uint8
    HWC images or NHWC batches, sorted order); ``labels.txt`` beside
    them (one int per image, same sorted order) upgrades the gate from
    agreement to real accuracy.  No ``gate_dir`` falls back to the
    deterministic synthetic batches — NaN screening and agreement still
    work there, which is exactly what smoke tests need."""

    def __init__(self, *, gate_dir: str | None = None,
                 batch_size: int = 8, n_batches: int = 2,
                 min_agreement: float = 0.8,
                 max_accuracy_drop: float = 0.02):
        self.gate_dir = gate_dir
        self.batch_size = int(batch_size)
        self.n_batches = int(n_batches)
        self.min_agreement = float(min_agreement)
        self.max_accuracy_drop = float(max_accuracy_drop)

    def _batches(self, model) -> list:
        from deep_vision_tpu.serve.quant import (
            load_calibration_dir,
            synthetic_calibration_batches,
        )

        shape = tuple(model.input_shape)
        if self.gate_dir:
            return load_calibration_dir(
                self.gate_dir, shape, n_batches=self.n_batches,
                batch_size=self.batch_size)
        return synthetic_calibration_batches(
            shape, n_batches=self.n_batches, batch_size=self.batch_size)

    def _labels(self) -> np.ndarray | None:
        if not self.gate_dir:
            return None
        p = os.path.join(self.gate_dir, "labels.txt")
        if not os.path.exists(p):
            return None
        return np.loadtxt(p, dtype=np.int64).reshape(-1)

    @staticmethod
    def _wire(model, batch: np.ndarray) -> np.ndarray:
        wire = np.dtype(str(model.wire_dtype))
        if wire == np.uint8:
            return batch
        # both sides see the identical float array — the comparison is
        # apples-to-apples even though /255 isn't the exact per-dataset
        # normalization the f32-wire client contract implies
        return batch.astype(np.float32) / 255.0

    def _predict(self, model, batches: list) -> tuple:
        """(per-image top-1 argmax or None, NaN seen?) for classifier-
        shaped output (a single (batch, classes) float leaf); anything
        else gets the NaN screen only."""
        import jax

        preds: list | None = []
        nan = False
        for b in batches:
            out = model.compile_bucket(len(b))(self._wire(model, b))
            leaves = [np.asarray(a) for a
                      in jax.tree_util.tree_leaves(out)]
            for a in leaves:
                if a.dtype.kind == "f" and np.isnan(a).any():
                    nan = True
            if preds is not None and len(leaves) == 1 \
                    and leaves[0].ndim == 2:
                preds.extend(int(np.argmax(r)) for r in leaves[0])
            else:
                preds = None
        return preds, nan

    def evaluate(self, candidate, active=None) -> dict:
        """``{"passed": bool, ...metrics...}`` — the history record's
        gate block.  ``active`` (the currently-serving ServingModel)
        enables the relative checks; without it only the NaN screen
        (and absolute accuracy, when labels exist) applies."""
        batches = self._batches(candidate)
        n_images = sum(len(b) for b in batches)
        out: dict = {"images": n_images,
                     "gate_dir": self.gate_dir or "synthetic"}
        cand, cand_nan = self._predict(candidate, batches)
        if cand_nan:
            out.update(passed=False, reason="candidate output has NaNs")
            return out
        if cand is None:
            # non-classification head: the NaN screen is the gate
            out.update(passed=True, reason="nan screen only "
                                           "(non-classification output)")
            return out
        labels = self._labels()
        if labels is not None:
            labels = labels[:n_images]
            cand_acc = float(np.mean(
                np.asarray(cand[:len(labels)]) == labels))
            out["candidate_acc"] = round(cand_acc, 4)
            active_acc = None
            if active is not None:
                act, act_nan = self._predict(active, batches)
                if act is not None and not act_nan:
                    active_acc = float(np.mean(
                        np.asarray(act[:len(labels)]) == labels))
                    out["active_acc"] = round(active_acc, 4)
                    out["delta"] = round(cand_acc - active_acc, 4)
            if active_acc is not None:
                passed = cand_acc >= active_acc - self.max_accuracy_drop
                out.update(passed=passed,
                           reason=None if passed else
                           f"accuracy {cand_acc:.4f} dropped more than "
                           f"{self.max_accuracy_drop} below active "
                           f"{active_acc:.4f}")
                return out
            out.update(passed=True, reason="no active baseline")
            return out
        if active is not None:
            act, act_nan = self._predict(active, batches)
            if act is not None and not act_nan:
                agree = float(np.mean(np.asarray(cand)
                                      == np.asarray(act)))
                out["agreement"] = round(agree, 4)
                passed = agree >= self.min_agreement
                out.update(passed=passed,
                           reason=None if passed else
                           f"top-1 agreement {agree:.4f} < "
                           f"{self.min_agreement}")
                return out
        out.update(passed=True, reason="no baseline to compare")
        return out

    def describe(self) -> dict:
        return {"gate_dir": self.gate_dir or "synthetic",
                "batch_size": self.batch_size,
                "n_batches": self.n_batches,
                "min_agreement": self.min_agreement,
                "max_accuracy_drop": self.max_accuracy_drop}


class CheckpointWatcher:
    """One supervised poll thread per watched model.

    ``poll_once(name)`` is the whole state machine and is public: tests
    and ``bench.py --deploy`` drive it synchronously; production runs
    it on Event-paced daemon threads that a supervisor restarts if they
    ever exit."""

    def __init__(self, plane, history, *, interval_s: float = 2.0,
                 gate: AccuracyGate | None = None, loader=None):
        self.plane = plane
        self.history = history
        self.interval_s = float(interval_s)
        self.gate = gate
        # test seam: loader(plane, name) → ready ServingModel;
        # default is the plane's own reload restore path
        self._loader = loader
        # name → {"candidate": fp-key sighted once,
        #         "acted": fp-key already deployed/gated}
        self._state: dict[str, dict] = {}  # guarded-by: _lock
        self._threads: dict[str, threading.Thread] = {}
        self._supervisor: threading.Thread | None = None
        self._stop_evt = threading.Event()
        self._lock = new_lock("deploy.watcher.CheckpointWatcher._lock")
        self.polls = 0  # guarded-by: _lock
        self.debounces = 0  # guarded-by: _lock
        self.deploys = 0  # guarded-by: _lock
        self.gate_failures = 0  # guarded-by: _lock

    def watch(self, name: str) -> "CheckpointWatcher":
        with self._lock:
            self._state.setdefault(name, {})
        return self

    # -- the state machine (one poll) --------------------------------------

    def poll_once(self, name: str) -> dict:
        """One debounced look at ``name``'s workdir.  Status values:
        ``no_workdir`` / ``no_checkpoint`` / ``current`` (serving this
        step) / ``debounce`` (first sighting — waiting for stability) /
        ``acted`` (this fingerprint is already decided) /
        ``gate_failed`` / ``promoted`` / ``rolled_back`` / ``failed``.
        """
        from deep_vision_tpu.core.restore import checkpoint_fingerprint

        with self._lock:
            self.polls += 1
        mv = self.plane.active_version(name)
        if mv.workdir is None:
            return {"status": "no_workdir", "model": name}
        fp = checkpoint_fingerprint(mv.workdir)
        if fp["step"] is None:
            return {"status": "no_checkpoint", "model": name}
        key = (fp["step"], fp["dir"], fp["mtime"])
        if fp["step"] == mv.model.restored_step:
            with self._lock:
                self._state.setdefault(name, {})["candidate"] = None
            return {"status": "current", "model": name,
                    "step": fp["step"]}
        with self._lock:
            st = self._state.setdefault(name, {})
            if st.get("acted") == key:
                return {"status": "acted", "model": name,
                        "step": fp["step"]}
            if st.get("candidate") != key:
                # first sighting (or still mutating): remember, wait for
                # the NEXT poll to see the identical (step, dir, mtime)
                st["candidate"] = key
                self.debounces += 1
                return {"status": "debounce", "model": name,
                        "step": fp["step"]}
            # stable across two polls: decide exactly once
            st["acted"] = key
        return self._deploy_candidate(name, mv, fp, key)

    def _deploy_candidate(self, name: str, mv, fp: dict,
                          key: tuple) -> dict:
        base = {"step": fp["step"], "mtime": fp["mtime"],
                "dir": fp["dir"]}
        try:
            sm = self._loader(self.plane, name) \
                if self._loader is not None \
                else self.plane.load_candidate(name)
        except Exception as e:  # noqa: BLE001 — an unrestorable candidate must not kill the watcher
            reason = f"{type(e).__name__}: {e}"
            self.history.record(name, "failed", reason=reason, **base)
            event(_log, "candidate_load_failed", model=name,
                  error=reason, **base)
            return {"status": "failed", "model": name, "reason": reason}
        base["digest"] = sm.params_digest
        self.history.record(name, "candidate", **base)
        if self.gate is not None:
            try:
                metrics = self.gate.evaluate(sm, mv.model)
            except Exception as e:  # noqa: BLE001 — gate infrastructure failure fails CLOSED
                metrics = {"passed": False,
                           "reason": f"gate error: "
                                     f"{type(e).__name__}: {e}"}
            if not metrics.get("passed"):
                with self._lock:
                    self.gate_failures += 1
                self.history.record(name, "gate_failed",
                                    outcome_state=FAILED, gate=metrics,
                                    **base)
                event(_log, "gate_failed", model=name,
                      reason=metrics.get("reason"), **base)
                return {"status": "gate_failed", "model": name,
                        "gate": metrics, **base}
            self.history.record(name, "gate_passed", gate=metrics,
                                **base)
        out = self.plane.reload(name, wait=True, _loader=lambda: sm)
        if out.get("status") != "done":
            # raced an operator reload: let the next new fingerprint
            # (or this one, re-armed) try again
            with self._lock:
                st = self._state.get(name, {})
                if st.get("acted") == key:
                    st.pop("acted", None)
            return {"status": out.get("status", "refused"),
                    "model": name}
        ver = out.get("version") or {}
        state = ver.get("state")
        if state == ACTIVE:
            with self._lock:
                self.deploys += 1
            outcome = "promoted"
        elif state == FAILED:
            outcome = "failed"
        else:  # rolled back through the canary/shadow gates
            outcome = "rolled_back"
        self.history.record(name, outcome, version=ver.get("version"),
                            reason=ver.get("state_reason"), **base)
        event(_log, "deploy_decided", model=name, outcome=outcome,
              version=ver.get("version"), **base)
        return {"status": outcome, "model": name,
                "version": ver.get("version"), **base}

    # -- threads -----------------------------------------------------------

    def start(self) -> "CheckpointWatcher":
        self._stop_evt.clear()
        with self._lock:
            names = list(self._state)
        for name in names:
            self._spawn(name)
        if self._supervisor is None or not self._supervisor.is_alive():
            self._supervisor = threading.Thread(
                target=self._supervise_loop, name="watcher-supervisor",
                daemon=True)
            self._supervisor.start()
        return self

    def _spawn(self, name: str):
        t = threading.Thread(target=self._watch_loop, args=(name,),
                             name=f"watcher-{name}", daemon=True)
        self._threads[name] = t
        t.start()

    def _watch_loop(self, name: str):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.poll_once(name)
            except Exception:  # noqa: BLE001 — a poll failure must not end the watch
                pass

    def _supervise_loop(self):
        # belt and braces: per-poll excepts should keep the loops alive
        # forever, but a thread that somehow exits is restarted here
        while not self._stop_evt.wait(self.interval_s):
            for name, t in list(self._threads.items()):
                if not t.is_alive() and not self._stop_evt.is_set():
                    event(_log, "watcher_restarted", model=name)
                    self._spawn(name)

    def stop(self, timeout: float = 5.0):
        self._stop_evt.set()
        sup = self._supervisor
        if sup is not None:
            sup.join(timeout)
            self._supervisor = None
        for t in self._threads.values():
            t.join(timeout)
        self._threads.clear()

    def stats(self) -> dict:
        with self._lock:
            per = {name: {"candidate": st.get("candidate"),
                          "acted": st.get("acted")}
                   for name, st in sorted(self._state.items())}
            out = {"interval_s": self.interval_s,
                   "polls": self.polls,
                   "debounces": self.debounces,
                   "deploys": self.deploys,
                   "gate_failures": self.gate_failures,
                   "models": per}
        if self.gate is not None:
            out["gate"] = self.gate.describe()
        return out


class DeployPipeline:
    """Plane + ledger + watcher + autoscalers behind one handle.

    This is what ``cli.serve --watch`` builds, what ``ServeServer``
    exposes at ``/v1/deploy/...``, and what tests drive."""

    def __init__(self, plane, *, history: "DeploymentHistory" = None,
                 watcher: CheckpointWatcher | None = None,
                 autoscalers: dict | None = None):
        from deep_vision_tpu.deploy.history import DeploymentHistory

        self.plane = plane
        self.history = history if history is not None \
            else DeploymentHistory()
        self.watcher = watcher
        self.autoscalers = dict(autoscalers or {})

    def entries(self, name: str, n: int | None = None) -> list[dict]:
        # unknown model → KeyError with the plane's standard message
        # (the HTTP layer turns it into the 404 body)
        self.plane.active_version(name)
        return self.history.entries(name, n)

    def revert(self, name: str) -> dict:
        """One-command rollback, recorded in the ledger.  Status map
        (the HTTP layer's contract): ``reverted`` 200 /
        ``in_progress``+``refused`` 409 / ``failed`` 500."""
        out = self.plane.revert(name)
        status = out.get("status")
        if status == "reverted":
            self.history.record(name, "reverted",
                                version=out.get("version"),
                                restores=out.get("restores"),
                                from_version=out.get("from_version"))
        elif status == "failed":
            self.history.record(name, "revert_failed",
                                reason=out.get("reason"))
        return out

    def start(self) -> "DeployPipeline":
        if self.watcher is not None:
            self.watcher.start()
        for scaler in self.autoscalers.values():
            scaler.start()
        return self

    def stop(self, timeout: float = 5.0):
        if self.watcher is not None:
            self.watcher.stop(timeout)
        for scaler in self.autoscalers.values():
            scaler.stop(timeout)

    def stats(self) -> dict:
        out = {"history": self.history.stats()}
        if self.watcher is not None:
            out["watcher"] = self.watcher.stats()
        if self.autoscalers:
            out["autoscale"] = {name: s.stats() for name, s
                                in sorted(self.autoscalers.items())}
        return out
