"""Append-only deployment ledger: every rollout decision, durable.

One JSONL file per model name under ``root`` (``<workdir>/_deploy`` in
production; ``root=None`` keeps the ledger in memory for tests).  Each
line is one immutable record — a candidate sighting, a gate verdict, a
promote/rollback/failure, a revert — carrying the checkpoint
fingerprint (step/dir/mtime), params digest, gate metrics, and a
wall-clock timestamp.  Records are appended, never rewritten: the file
IS the audit trail ``GET /v1/deploy/{name}/history`` serves, and the
map ``POST /v1/deploy/{name}/revert`` consults reads the live plane
table, not this file — the ledger observes, it never decides.

Crash-safety is line-granular: a torn tail line (killed mid-append) is
skipped on reload, everything before it survives.  The in-memory view
keeps the newest ``retain`` records per model; the file keeps them all.
"""

from __future__ import annotations

import glob
import json
import os
import time

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.obs.log import event, get_logger

_log = get_logger("dvt.deploy.history")


class DeploymentHistory:
    def __init__(self, root: str | None = None, retain: int = 256):
        self.root = root
        self.retain = int(retain)
        # name → newest-last list of record dicts
        self._entries: dict[str, list[dict]] = {}  # guarded-by: _lock
        self._lock = new_lock("deploy.history.DeploymentHistory._lock")
        self.records = 0  # guarded-by: _lock
        self.write_errors = 0  # guarded-by: _lock
        if root is not None:
            os.makedirs(root, exist_ok=True)
            self._load()

    def _path(self, name: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in name)
        return os.path.join(self.root, f"{safe}.jsonl")

    def _load(self):
        for p in sorted(glob.glob(os.path.join(self.root, "*.jsonl"))):
            loaded = []
            try:
                with open(p, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            loaded.append(json.loads(line))
                        except ValueError:
                            continue  # torn tail line from a crash
            except OSError:
                continue
            if not loaded:
                continue
            name = loaded[-1].get("model") or \
                os.path.splitext(os.path.basename(p))[0]
            with self._lock:
                lst = self._entries.setdefault(name, [])
                lst.extend(loaded)
                del lst[:-self.retain]

    def record(self, name: str, outcome: str, **fields) -> dict:
        """Append one immutable record (``outcome`` ∈ candidate /
        gate_passed / gate_failed / promoted / rolled_back / failed /
        reverted / revert_failed / scale_up / scale_down)."""
        entry = {"ts": round(time.time(), 3), "model": name,
                 "outcome": outcome}
        entry.update(fields)
        with self._lock:
            self.records += 1
            lst = self._entries.setdefault(name, [])
            lst.append(entry)
            del lst[:-self.retain]
        if self.root is not None:
            try:
                with open(self._path(name), "a", encoding="utf-8") as f:
                    f.write(json.dumps(entry, default=str) + "\n")
            except OSError as e:
                with self._lock:
                    self.write_errors += 1
                event(_log, "history_write_failed", model=name,
                      error=f"{type(e).__name__}: {e}")
        event(_log, "deployment", **entry)
        return entry

    def entries(self, name: str, n: int | None = None) -> list[dict]:
        """Newest-last records for ``name`` (the retained window; pass
        ``n`` for just the tail)."""
        with self._lock:
            lst = list(self._entries.get(name, []))
        return lst[-n:] if n else lst

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def last_outcome(self, name: str) -> str | None:
        with self._lock:
            lst = self._entries.get(name)
            return lst[-1]["outcome"] if lst else None

    def stats(self) -> dict:
        with self._lock:
            per = {name: {"records": len(lst),
                          "last_outcome": lst[-1]["outcome"] if lst
                          else None}
                   for name, lst in sorted(self._entries.items())}
            return {"records": self.records,
                    "write_errors": self.write_errors,
                    "root": self.root, "models": per}
