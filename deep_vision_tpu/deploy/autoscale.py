"""Demand-side elasticity: replica count follows observed load.

The admission controller already prices load — per-bucket exec EWMAs
and the shared queue's depth — so the autoscaler spends no new
measurement machinery.  Each tick reads three signals off the
``ReplicatedEngine``:

  pressure   ``queue_depth × exec_EWMA`` — the backlog expressed as
             device-time.  Sustained above ``high_water_ms`` for
             ``up_window`` consecutive ticks → ``add_replica()``.
  idleness   empty queue AND zero in-flight work, sustained for
             ``down_window`` consecutive ticks →
             ``remove_replica(drain_deadline=)`` (which drains before
             stopping — scale-down never drops admitted work).
  bounds     live replicas stay in [min_replicas, max_replicas].

Queue pressure is the wrong hot signal for THROUGHPUT workloads
(ROADMAP): a batchy-SLO engine (the "batchy" service class,
serve/workloads.py — generative models, and any engine the batch tier
saturates) runs flat out with an empty queue, because work arrives as
full cohorts that go straight in-flight.  For those engines the scaler
switches its hot signal to the engine's rolling compute **occupancy**
(``engine.occupancy()``, the same measurement the MFU denominator
uses): occupancy ≥ ``occupancy_high`` sustained for ``up_window`` →
scale up, and scale-down additionally requires occupancy ≤
``occupancy_low`` so the gap between two back-to-back shards can't
read as idle.  Interactive-SLO engines keep the original pressure
signal unchanged.

Stability is structural, not tuned: the two windows are hysteresis
(one hot tick can't scale up, one idle tick can't scale down; any
contrary tick resets the streak), and every action starts a
``cooldown_s`` during which no further action fires — so the replica
count is monotone within each window and the scaler cannot flap.
``tick()`` is public: tests (and ``bench.py --deploy``) drive it
synchronously; production runs it on an Event-paced daemon thread.
"""

from __future__ import annotations

import threading
import time

from deep_vision_tpu.obs.log import event, get_logger

_log = get_logger("dvt.deploy.autoscale")


class ReplicaAutoscaler:
    """Counters are written only by the tick thread (or the test
    driving ``tick()``) and read racily by ``stats()`` — no lock, by
    design: a torn gauge read costs nothing, and holding a lock across
    ``add_replica``/``remove_replica`` (which take the engine's lock)
    would add an ordering edge for zero benefit."""

    def __init__(self, engine, *, name: str | None = None,
                 min_replicas: int = 1, max_replicas: int | None = None,
                 interval_s: float = 0.5, high_water_ms: float = 50.0,
                 up_window: int = 3, down_window: int = 10,
                 cooldown_s: float = 5.0, drain_deadline_s: float = 5.0,
                 occupancy_high: float = 0.75,
                 occupancy_low: float = 0.2, history=None):
        if min_replicas < 1:
            raise ValueError(f"min_replicas {min_replicas}: need >= 1")
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError(f"max_replicas {max_replicas} < "
                             f"min_replicas {min_replicas}")
        # engine may be the ReplicatedEngine itself, or a zero-arg
        # callable resolving it per tick — the production wiring passes
        # ``lambda: plane.active_engine(name)`` so a hot reload's engine
        # swap doesn't leave the scaler ticking a retired engine
        self._engine = engine
        self.name = name or getattr(
            getattr(self.engine, "model", None), "name", "model")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas) if max_replicas is not None \
            else self.min_replicas
        self.interval_s = float(interval_s)
        self.high_water_ms = float(high_water_ms)
        self.up_window = int(up_window)
        self.down_window = int(down_window)
        self.cooldown_s = float(cooldown_s)
        self.drain_deadline_s = float(drain_deadline_s)
        self.occupancy_high = float(occupancy_high)
        self.occupancy_low = float(occupancy_low)
        self.history = history
        self._up_ticks = 0
        self._down_ticks = 0
        self._last_action: float | None = None  # monotonic
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.scale_errors = 0

    @property
    def engine(self):
        return self._engine() if callable(self._engine) else self._engine

    # -- the decision ------------------------------------------------------

    def signals(self) -> dict:
        """One coherent-enough snapshot of the engine's load signals."""
        eng = self.engine
        ewma = eng.admission.bucket_ewma_s() or 0.0
        depth = eng._queue.qsize()
        occ_fn = getattr(eng, "occupancy", None)
        wl = getattr(getattr(eng, "model", None), "workload", None)
        return {"queue_depth": depth,
                "exec_ewma_ms": round(ewma * 1e3, 3),
                "pressure_ms": round(depth * ewma * 1e3, 3),
                "inflight": eng.total_inflight(),
                "live": eng.live_replicas(),
                # rolling compute duty cycle; None on engines that
                # don't measure it (the pressure path still works)
                "occupancy": occ_fn() if callable(occ_fn) else None,
                # the signal switch: batchy-SLO engines scale on
                # occupancy, interactive ones on queue pressure
                "batchy": getattr(getattr(wl, "slo", None), "name",
                                  "") == "batchy"}

    def tick(self) -> dict | None:
        """One scaling decision; returns the action taken (or None).
        Exceptions from the engine (no spare device, last live replica)
        are absorbed — a failed action costs one cooldown, never the
        scaler."""
        self.ticks += 1
        sig = self.signals()
        live = sig["live"]
        use_occ = sig["batchy"] and sig["occupancy"] is not None
        hot = (sig["occupancy"] >= self.occupancy_high) if use_occ \
            else sig["pressure_ms"] > self.high_water_ms
        idle = sig["queue_depth"] == 0 and sig["inflight"] == 0
        if use_occ:
            # the gap between two back-to-back shards samples as
            # queue 0 / inflight 0; the rolling window doesn't lie
            idle = idle and sig["occupancy"] <= self.occupancy_low
        if hot and live < self.max_replicas:
            self._up_ticks += 1
            self._down_ticks = 0
        elif idle and live > self.min_replicas:
            self._down_ticks += 1
            self._up_ticks = 0
        else:
            self._up_ticks = 0
            self._down_ticks = 0
        now = time.monotonic()
        cooled = self._last_action is None \
            or now - self._last_action >= self.cooldown_s
        if not cooled:
            return None
        if self._up_ticks >= self.up_window:
            return self._act("scale_up", sig, now)
        if self._down_ticks >= self.down_window:
            return self._act("scale_down", sig, now)
        return None

    def _act(self, direction: str, sig: dict, now: float) -> dict | None:
        self._up_ticks = 0
        self._down_ticks = 0
        self._last_action = now  # a failed action also starts cooldown
        try:
            if direction == "scale_up":
                replica = self.engine.add_replica()
                self.scale_ups += 1
            else:
                replica = self.engine.remove_replica(
                    drain_deadline=self.drain_deadline_s)
                self.scale_downs += 1
        except Exception as e:  # noqa: BLE001 — a failed scale action must not kill the scaler
            self.scale_errors += 1
            event(_log, "autoscale_failed", model=self.name,
                  direction=direction,
                  error=f"{type(e).__name__}: {e}", **sig)
            return None
        action = {"action": direction, "replica": replica,
                  "live": self.engine.live_replicas(), **sig}
        event(_log, "autoscale", model=self.name, **action)
        if self.history is not None:
            self.history.record(self.name, direction, replica=replica,
                                live=action["live"],
                                pressure_ms=sig["pressure_ms"],
                                occupancy=sig["occupancy"])
        return action

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplicaAutoscaler":
        if self._thread is None or not self._thread.is_alive():
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop, name=f"autoscale-{self.name}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    def _loop(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the scaler thread never dies
                pass

    def stats(self) -> dict:
        out = {"model": self.name,
               "min_replicas": self.min_replicas,
               "max_replicas": self.max_replicas,
               "interval_s": self.interval_s,
               "high_water_ms": self.high_water_ms,
               "up_window": self.up_window,
               "down_window": self.down_window,
               "occupancy_high": self.occupancy_high,
               "occupancy_low": self.occupancy_low,
               "cooldown_s": self.cooldown_s,
               "ticks": self.ticks,
               "scale_ups": self.scale_ups,
               "scale_downs": self.scale_downs,
               "scale_errors": self.scale_errors}
        try:
            out.update(self.signals())
        except Exception as e:  # noqa: BLE001 — a torn engine swap must not break /v1/stats
            out["signals_error"] = f"{type(e).__name__}: {e}"
        return out
