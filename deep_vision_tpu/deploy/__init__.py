"""Continuous train→deploy pipeline (PR 11): the hands-off loop.

Everything downstream already existed in pieces — the trainer writes
async Orbax checkpoints, ``core/restore.py`` fingerprints them from
filesystem metadata alone, and the control plane (serve/models.py) does
shadow → canary → auto-promote/rollback.  This package closes the loop:

  watcher.py    a supervised thread per model polls the checkpoint
                fingerprint (debounced across two intervals, so an
                in-progress async save never deploys half a
                checkpoint), runs the held-out ACCURACY GATE on the
                candidate, and only on pass hands it to
                ``plane.reload()`` for the normal gradual rollout;
  history.py    an append-only JSONL ledger per model — every
                candidate, gate verdict, promote/rollback/revert, with
                fingerprint + digest + metrics — behind
                ``GET /v1/deploy/{name}/history``, and the state
                ``POST /v1/deploy/{name}/revert`` rolls back to;
  autoscale.py  demand-side elasticity: scale ``ReplicatedEngine``
                replicas between ``--min-replicas``/``--max-replicas``
                on the admission controller's observed load, with
                hysteresis windows and a cooldown so it never flaps.

All control logic is stdlib-only (threads, Events, JSON), mirroring
``serve/`` and ``obs/`` conventions; jax is touched only through the
serving models it manages.  See docs/DEPLOY.md.
"""

from deep_vision_tpu.deploy.autoscale import ReplicaAutoscaler
from deep_vision_tpu.deploy.history import DeploymentHistory
from deep_vision_tpu.deploy.watcher import (
    AccuracyGate,
    CheckpointWatcher,
    DeployPipeline,
)

__all__ = [
    "AccuracyGate",
    "CheckpointWatcher",
    "DeployPipeline",
    "DeploymentHistory",
    "ReplicaAutoscaler",
]
