"""Pallas TPU kernels for hot ops.

``serve_ingest``: the int8 serving prologue — uint8 decode + mean/std
normalize + symmetric activation quantize fused into one VMEM pass
(serve/quant.py, docs/SERVING.md "Wire format & inference dtype").  The
XLA formulation materializes the normalized f32 HWC tensor in HBM (4×
the wire bytes) before the quantize reads it back; this kernel streams
the uint8 rows through VMEM and writes int8 straight out, so the only
HBM traffic is wire-bytes in, wire-bytes out.  Layout: the NHWC batch
is viewed as (B·H, W·C) rows — per-channel mean/std tile along the
W·C lane axis — with rows tiled through the grid and lanes padded to
the 128-lane width.  CPU tests run the same kernel via
``interpret=True`` (the ``best_iou_max`` pattern below).

``best_iou_max``: for every predicted box, the max IoU against the image's
(padded, masked) ground-truth boxes — the YOLO ignore-mask inner loop
(tasks/detection.yolo_scale_loss).  The XLA formulation materializes a
(B, N, M) IoU tensor in HBM (N≈10.6k boxes across the 3 scales at 416²,
M=100 ⇒ ~4 MB/image/step written+read back); this kernel tiles N through
VMEM, broadcasts the tiny gt set per tile, and reduces to the (B, N) max
in-register — one HBM pass over the predictions.

Layout notes (TPU tiling):
- predictions arrive (B, N, 4) and are processed in (TILE_N, 4) VMEM
  blocks; coordinate columns are read as (TILE_N, 1) slices so the
  (TILE_N, M) broadcast needs no in-kernel transpose;
- ground truth is passed PRE-TRANSPOSED as (B, 4, M) so coordinate rows
  read as (1, M) slices — M is padded to the 128-lane width;
- CPU tests run the same kernel via ``interpret=True``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

TILE_N = 256
LANE = 128
#: serve_ingest row tile (sublane dim of the (B·H, W·C) view) — a
#: multiple of the int8 sublane granularity (32) so the quantized
#: output block tiles cleanly
INGEST_TILE_R = 256


def _ingest_norm_constants(kind: str, channels: int):
    """Per-channel (mean, std) f32 vectors for ``kind`` — the SAME
    values ops/preprocess.serve_normalize subtracts/divides, so the
    fused kernel is bit-compatible with the XLA prologue (imported from
    the data modules directly to keep ops.preprocess → pallas_ops a
    one-way dependency)."""
    from deep_vision_tpu.data.mnist import MEAN as MNIST_MEAN
    from deep_vision_tpu.data.mnist import STD as MNIST_STD
    from deep_vision_tpu.data.transforms import IMAGENET_MEAN, IMAGENET_STD

    if kind == "imagenet":
        mean = np.asarray(IMAGENET_MEAN, np.float32)
        std = np.asarray(IMAGENET_STD, np.float32)
    elif kind == "mnist":
        mean = np.full((channels,), MNIST_MEAN, np.float32)
        std = np.full((channels,), MNIST_STD, np.float32)
    elif kind == "unit":
        mean = np.zeros((channels,), np.float32)
        std = np.ones((channels,), np.float32)
    else:
        raise ValueError(f"unknown serve preprocess kind '{kind}'")
    if mean.shape[0] != channels:
        raise ValueError(
            f"'{kind}' normalization is {mean.shape[0]}-channel; "
            f"input has {channels}")
    return mean, std


def _serve_ingest_kernel(x_ref, mean_ref, std_ref, out_ref, *,
                         act_scale: float, quantize: bool):
    # dvtlint: traced
    # one (TILE_R, lanes) block: decode, normalize, quantize, store —
    # division (not reciprocal-multiply) keeps it bit-identical to the
    # XLA serve_normalize/quantize_activations path
    x = x_ref[...].astype(jnp.float32) / 255.0
    y = (x - mean_ref[...]) / std_ref[...]
    if quantize:
        q = jnp.clip(jnp.round(y / act_scale), -127.0, 127.0)
        out_ref[...] = q.astype(jnp.int8)
    else:
        out_ref[...] = y


@functools.partial(jax.jit, static_argnames=("kind", "act_scale",
                                             "quantize", "interpret"))
def serve_ingest(x, kind: str, act_scale: float = 1.0,
                 quantize: bool = True, interpret: bool = False):
    """uint8 NHWC wire batch → int8 activations (or normalized f32
    when ``quantize=False`` — the decode+normalize-only mode the parity
    tests compare exactly against serve_normalize).

    ``act_scale`` is the per-tensor symmetric activation scale from
    calibration (serve/quant.py): ``q = round(normalized/act_scale)``
    clipped to ±127.  Static per program — each int8 model's bucket
    programs bake their own scale in at AOT-compile time.
    """
    B, H, W, C = x.shape
    mean_c, std_c = _ingest_norm_constants(kind, C)
    rows, lanes = B * H, W * C
    r_pad = (-rows) % INGEST_TILE_R
    l_pad = (-lanes) % LANE
    rows_p, lanes_p = rows + r_pad, lanes + l_pad
    x2 = jnp.pad(x.reshape(rows, lanes), ((0, r_pad), (0, l_pad)))
    # per-lane constants: channel-fastest, matching the (W, C) flatten;
    # pad std with 1.0 so the dead lanes don't divide by zero
    mean_row = np.pad(np.tile(mean_c, W), (0, l_pad))[None, :]
    std_row = np.pad(np.tile(std_c, W), (0, l_pad),
                     constant_values=1.0)[None, :]
    out = pl.pallas_call(
        functools.partial(_serve_ingest_kernel,
                          act_scale=float(act_scale),
                          quantize=bool(quantize)),
        out_shape=jax.ShapeDtypeStruct(
            (rows_p, lanes_p), jnp.int8 if quantize else jnp.float32),
        grid=(rows_p // INGEST_TILE_R,),
        in_specs=[
            pl.BlockSpec((INGEST_TILE_R, lanes_p), lambda r: (r, 0)),
            pl.BlockSpec((1, lanes_p), lambda r: (0, 0)),
            pl.BlockSpec((1, lanes_p), lambda r: (0, 0)),
        ],
        out_specs=pl.BlockSpec((INGEST_TILE_R, lanes_p),
                               lambda r: (r, 0)),
        interpret=interpret,
    )(x2, jnp.asarray(mean_row, jnp.float32),
      jnp.asarray(std_row, jnp.float32))
    return out[:rows, :lanes].reshape(B, H, W, C)


def serve_ingest_auto(x, kind: str, act_scale: float = 1.0,
                      quantize: bool = True):
    """Pallas on TPU; interpret-mode elsewhere (tests, CPU serving)."""
    on_tpu = jax.default_backend() == "tpu"
    return serve_ingest(x, kind, act_scale=act_scale, quantize=quantize,
                        interpret=not on_tpu)


_INGEST_PARITY_CACHE: dict[tuple, bool] = {}


def ingest_parity_ok(shape: tuple, kind: str, act_scale: float,
                     interpret: bool = False) -> bool:
    """One-batch parity check of the compiled ingest kernel vs the pure
    jnp reference, gated per (shape, kind) before a bucket program
    selects the Pallas path on real hardware (the ``pallas_parity_ok``
    pattern: Mosaic lowering is environment- and shape-sensitive, so a
    compile failure or >1-step divergence falls back to XLA)."""
    key = (tuple(shape), kind, round(float(act_scale), 12))
    if key in _INGEST_PARITY_CACHE and not interpret:
        return _INGEST_PARITY_CACHE[key]
    try:
        B, H, W, C = shape
        raw = np.random.RandomState(7).randint(0, 256, shape, np.uint8)
        got = np.asarray(jax.device_get(
            serve_ingest(jnp.asarray(raw), kind, act_scale=act_scale,
                         interpret=interpret))).astype(np.int32)
        mean_c, std_c = _ingest_norm_constants(kind, C)
        y = (raw.astype(np.float32) / 255.0 - mean_c) / std_c
        want = np.clip(np.round(y / float(act_scale)), -127.0,
                       127.0).astype(np.int32)
        err = int(np.abs(got - want).max())
        ok = err <= 1  # one quantization step of rounding slack
        if not ok:
            print(f"[pallas] ingest parity FAILED (max err {err} steps)"
                  " — falling back to the XLA serve prologue")
    except Exception as e:  # noqa: BLE001 — compile/runtime failure → XLA fallback
        print(f"[pallas] ingest kernel unavailable "
              f"({type(e).__name__}: {e}) — falling back to the XLA "
              f"serve prologue")
        ok = False
    if not interpret:
        _INGEST_PARITY_CACHE[key] = ok
    return ok


def _gray_matrix(W: int, C: int, l_pad: int) -> np.ndarray:
    """(lanes_p, lanes_p) matrix turning the (rows, W·C) view into its
    per-pixel grayscale broadcast: ``(x @ G)[r, p·C+j] = Σ_i x[r, p·C+i]·
    GRAY[i]`` — the ``(x * GRAY).sum(-1)`` of the XLA jitter, expressed as
    a matmul so the kernel never needs an in-block (rows, W, C) reshape
    (MXU-friendly; pad lanes are zero columns so they stay zero)."""
    from deep_vision_tpu.ops.preprocess import _GRAY

    gray = (np.asarray(_GRAY, np.float32) if C == 3
            else np.full((C,), 1.0 / C, np.float32))  # C=1: identity → no-op
    lanes = W * C
    g = np.zeros((lanes + l_pad, lanes + l_pad), np.float32)
    pix = np.arange(W) * C
    for ci in range(C):
        for cj in range(C):
            g[pix + ci, pix + cj] = gray[ci]
    return g


def _train_ingest_kernel(x_ref, s_ref, mean_ref, std_ref, g_ref, out_ref):
    # dvtlint: traced
    # one (TILE_R, lanes) block: decode + the full color-jitter chain +
    # normalize, with the three per-image jitter factors and the
    # post-brightness image mean prebaked into per-ROW scalars (every row
    # of image i carries the same (fb, fc, fs, m) — computed in-trace by
    # train_ingest_factors, so no cross-row reduction happens in-kernel)
    x = x_ref[...].astype(jnp.float32) / 255.0
    fb = s_ref[:, 0:1]
    fc = s_ref[:, 1:2]
    fs = s_ref[:, 2:3]
    m = s_ref[:, 3:4]
    x = x * fb                     # brightness
    x = (x - m) * fc + m           # contrast about the per-image mean
    gray = jnp.dot(x, g_ref[...], preferred_element_type=jnp.float32)
    x = gray + (x - gray) * fs     # saturation toward per-pixel gray
    x = jnp.clip(x, 0.0, 1.0)
    out_ref[...] = (x - mean_ref[...]) / std_ref[...]


def train_ingest_factors(x, rng, brightness: float = 0.2,
                         contrast: float = 0.2, saturation: float = 0.2):
    # dvtlint: traced
    """Per-image jitter scalars (B, 4) = [fb, fc, fs, m] for the fused
    train-ingest kernel — the SAME rng split order and draw shapes as
    ops/preprocess.jitter_normalize, so both paths consume identical
    random factors from one key.  ``m`` is the post-brightness image mean
    the contrast op pivots about: brightness is a pure scale, so
    ``mean(fb·x) == fb·mean(x)`` and the (B,)-output mean over the uint8
    input is the only extra HBM pass the fused path pays."""
    b = x.shape[0]
    kb, kc, ks = jax.random.split(rng, 3)
    fb = jax.random.uniform(kb, (b, 1, 1, 1),
                            minval=max(0.0, 1 - brightness),
                            maxval=1 + brightness).reshape(b)
    fc = jax.random.uniform(kc, (b, 1, 1, 1),
                            minval=max(0.0, 1 - contrast),
                            maxval=1 + contrast).reshape(b)
    fs = jax.random.uniform(ks, (b, 1, 1, 1),
                            minval=max(0.0, 1 - saturation),
                            maxval=1 + saturation).reshape(b)
    m = fb * jnp.mean(x.astype(jnp.float32) / 255.0, axis=(1, 2, 3))
    return jnp.stack([fb, fc, fs, m], axis=1)


@functools.partial(jax.jit, static_argnames=("kind", "interpret"))
def train_ingest(x, factors, kind: str = "imagenet",
                 interpret: bool = False):
    """uint8 NHWC train batch + (B, 4) jitter factors → jittered,
    normalized float32 — ``serve_ingest`` extended with the train-time
    color-jitter chain (brightness → contrast → saturation → clip) fused
    into the same single VMEM pass, so the f32 HWC intermediate the XLA
    ``jitter_normalize`` materializes in HBM between ops never exists.

    Same (B·H, W·C) row view as ``serve_ingest``; the per-image factor
    quadruple is repeated per row (every row of image i shares it) and
    saturation's per-pixel gray is a matmul against a prebaked
    block-diagonal matrix (no in-kernel reshape).  CPU tests run with
    ``interpret=True``; real use goes through the per-shape parity gate
    (``train_ingest_parity_ok``) with jitter_normalize as the fallback.
    """
    B, H, W, C = x.shape
    mean_c, std_c = _ingest_norm_constants(kind, C)
    rows, lanes = B * H, W * C
    r_pad = (-rows) % INGEST_TILE_R
    l_pad = (-lanes) % LANE
    rows_p, lanes_p = rows + r_pad, lanes + l_pad
    x2 = jnp.pad(x.reshape(rows, lanes), ((0, r_pad), (0, l_pad)))
    s_rows = jnp.pad(jnp.repeat(factors.astype(jnp.float32), H, axis=0),
                     ((0, r_pad), (0, 0)))
    mean_row = np.pad(np.tile(mean_c, W), (0, l_pad))[None, :]
    std_row = np.pad(np.tile(std_c, W), (0, l_pad),
                     constant_values=1.0)[None, :]
    out = pl.pallas_call(
        _train_ingest_kernel,
        out_shape=jax.ShapeDtypeStruct((rows_p, lanes_p), jnp.float32),
        grid=(rows_p // INGEST_TILE_R,),
        in_specs=[
            pl.BlockSpec((INGEST_TILE_R, lanes_p), lambda r: (r, 0)),
            pl.BlockSpec((INGEST_TILE_R, 4), lambda r: (r, 0)),
            pl.BlockSpec((1, lanes_p), lambda r: (0, 0)),
            pl.BlockSpec((1, lanes_p), lambda r: (0, 0)),
            pl.BlockSpec((lanes_p, lanes_p), lambda r: (0, 0)),
        ],
        out_specs=pl.BlockSpec((INGEST_TILE_R, lanes_p),
                               lambda r: (r, 0)),
        interpret=interpret,
    )(x2, s_rows, jnp.asarray(mean_row, jnp.float32),
      jnp.asarray(std_row, jnp.float32),
      jnp.asarray(_gray_matrix(W, C, l_pad)))
    return out[:rows, :lanes].reshape(B, H, W, C)


def train_ingest_auto(x, factors, kind: str = "imagenet"):
    """Pallas on TPU; interpret-mode elsewhere (tests, CPU dryruns)."""
    on_tpu = jax.default_backend() == "tpu"
    return train_ingest(x, factors, kind, interpret=not on_tpu)


def train_ingest_sharded(x, factors, mesh, kind: str = "imagenet"):
    """:func:`train_ingest_auto` under a sharded mesh — same shard_map
    escape hatch as ``best_iou_max_sharded`` (``pallas_call`` has no
    GSPMD rule; the jitter chain is per-image independent, and the
    factors were drawn GLOBALLY before the shard_map so per-image
    randomness matches the unsharded path bit-for-bit)."""
    from jax.sharding import PartitionSpec as P

    from deep_vision_tpu.parallel.mesh import DATA_AXIS

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    fn = functools.partial(train_ingest_auto, kind=kind)
    spec = P(DATA_AXIS)
    try:
        wrapped = shard_map(fn, mesh=mesh, in_specs=(spec, spec),
                            out_specs=spec, check_vma=False)
    except TypeError:  # older jax without check_vma
        wrapped = shard_map(fn, mesh=mesh, in_specs=(spec, spec),
                            out_specs=spec)
    return wrapped(x, factors)


_TRAIN_INGEST_PARITY_CACHE: dict[tuple, bool] = {}


def train_ingest_parity_ok(shape: tuple, kind: str = "imagenet",
                           brightness: float = 0.2, contrast: float = 0.2,
                           saturation: float = 0.2,
                           interpret: bool = False,
                           tol: float = 1e-4) -> bool:
    """One-batch parity check of the fused train-ingest kernel vs the XLA
    ``jitter_normalize`` path, gated per (shape, kind, jitter params)
    before the trainer's preprocess_fn selects the Pallas path (the PR 10
    ``ingest_parity_ok`` pattern: Mosaic lowering is environment- and
    shape-sensitive, so a compile failure or numeric divergence beyond
    ``tol`` falls back to XLA — never a silent accuracy change)."""
    from deep_vision_tpu.ops.preprocess import jitter_normalize

    key = (tuple(shape), kind,
           round(float(brightness), 6), round(float(contrast), 6),
           round(float(saturation), 6))
    if key in _TRAIN_INGEST_PARITY_CACHE and not interpret:
        return _TRAIN_INGEST_PARITY_CACHE[key]
    try:
        B, H, W, C = shape
        raw = np.random.RandomState(11).randint(0, 256, shape, np.uint8)
        rng = jax.random.PRNGKey(23)
        mean_c, std_c = _ingest_norm_constants(kind, C)
        factors = train_ingest_factors(jnp.asarray(raw), rng,
                                       brightness, contrast, saturation)
        got = np.asarray(jax.device_get(
            train_ingest(jnp.asarray(raw), factors, kind,
                         interpret=interpret)))
        want = np.asarray(jax.device_get(jitter_normalize(
            jnp.asarray(raw), rng, True, mean=mean_c, std=std_c,
            brightness=brightness, contrast=contrast,
            saturation=saturation)))
        err = float(np.abs(got - want).max())
        ok = err <= tol
        if not ok:
            print(f"[pallas] train-ingest parity FAILED (max err {err:.2e})"
                  " — falling back to the XLA jitter_normalize prologue")
    except Exception as e:  # noqa: BLE001 — compile/runtime failure → XLA fallback
        print(f"[pallas] train-ingest kernel unavailable "
              f"({type(e).__name__}: {e}) — falling back to the XLA "
              f"jitter_normalize prologue")
        ok = False
    if not interpret:
        _TRAIN_INGEST_PARITY_CACHE[key] = ok
    return ok


def _best_iou_kernel(pred_ref, gt_ref, mask_ref, out_ref):
    # blocks carry the FULL batch (out tiling rule: the sublane dim of the
    # (B, N) output block must equal B); grid runs over N tiles only.
    # pred_ref: (B, TILE_N, 4); gt_ref: (B, 4, M); mask_ref: (B, 1, M)
    px1 = pred_ref[:, :, 0:1]   # (B, T, 1)
    py1 = pred_ref[:, :, 1:2]
    px2 = pred_ref[:, :, 2:3]
    py2 = pred_ref[:, :, 3:4]
    gx1 = gt_ref[:, 0:1, :]     # (B, 1, M)
    gy1 = gt_ref[:, 1:2, :]
    gx2 = gt_ref[:, 2:3, :]
    gy2 = gt_ref[:, 3:4, :]
    mask = mask_ref[:, 0:1, :]  # (B, 1, M)

    inter_w = jnp.maximum(jnp.minimum(px2, gx2) - jnp.maximum(px1, gx1), 0.0)
    inter_h = jnp.maximum(jnp.minimum(py2, gy2) - jnp.maximum(py1, gy1), 0.0)
    inter = inter_w * inter_h                            # (B, T, M)
    area_p = jnp.maximum(px2 - px1, 0.0) * jnp.maximum(py2 - py1, 0.0)
    area_g = jnp.maximum(gx2 - gx1, 0.0) * jnp.maximum(gy2 - gy1, 0.0)
    iou = inter / (area_p + area_g - inter + 1e-9)       # (B, T, M)
    iou = jnp.where(mask > 0, iou, 0.0)
    out_ref[:, :] = jnp.max(iou, axis=2)


@functools.partial(jax.jit, static_argnames=("interpret",))
def best_iou_max(pred_boxes, gt_boxes, gt_mask, interpret: bool = False):
    """(B,N,4) corner preds × (B,M,4) corner gts + (B,M) mask → (B,N) max IoU.

    Matches ``broadcast_iou(...).max(-1)`` with masked gts scoring 0.
    """
    B, N, _ = pred_boxes.shape
    M = gt_boxes.shape[1]
    n_pad = (-N) % TILE_N
    m_pad = (-M) % LANE
    pred = jnp.pad(pred_boxes, ((0, 0), (0, n_pad), (0, 0)))
    gt_t = jnp.pad(jnp.swapaxes(gt_boxes, 1, 2), ((0, 0), (0, 0), (0, m_pad)))
    mask = jnp.pad(gt_mask, ((0, 0), (0, m_pad)))[:, None, :]
    Np, Mp = N + n_pad, M + m_pad

    out = pl.pallas_call(
        _best_iou_kernel,
        out_shape=jax.ShapeDtypeStruct((B, Np), jnp.float32),
        grid=(Np // TILE_N,),
        in_specs=[
            pl.BlockSpec((B, TILE_N, 4), lambda n: (0, n, 0)),
            pl.BlockSpec((B, 4, Mp), lambda n: (0, 0, 0)),
            pl.BlockSpec((B, 1, Mp), lambda n: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((B, TILE_N), lambda n: (0, n)),
        interpret=interpret,
    )(pred.astype(jnp.float32), gt_t.astype(jnp.float32),
      mask.astype(jnp.float32))
    return out[:, :N]


def best_iou_max_auto(pred_boxes, gt_boxes, gt_mask):
    """Pallas on TPU; interpret-mode elsewhere (tests, CPU dryruns)."""
    on_tpu = jax.default_backend() == "tpu"
    return best_iou_max(pred_boxes, gt_boxes, gt_mask, interpret=not on_tpu)


def best_iou_max_sharded(pred_boxes, gt_boxes, gt_mask, mesh):
    """:func:`best_iou_max_auto` under a sharded mesh.

    ``pallas_call`` has no GSPMD partitioning rule, but the reduction is
    per-image independent — so a ``shard_map`` over the ``data`` axis runs
    the kernel on each device's batch shard and keeps the fused path alive
    on multi-chip meshes (round-3 verdict weak #4: without this, pod-scale
    detection silently fell back to the (B,N,M)-intermediate XLA path).
    Other mesh axes (model/pipe) see replicated inputs.
    """
    from jax.sharding import PartitionSpec as P

    from deep_vision_tpu.parallel.mesh import DATA_AXIS

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(DATA_AXIS)
    try:
        # pallas_call can't annotate varying-manual-axes on its outputs,
        # so disable the VMA type check (sound here: no collectives inside,
        # every input/output is batch-sharded the same way)
        fn = shard_map(best_iou_max_auto, mesh=mesh,
                       in_specs=(spec, spec, spec), out_specs=spec,
                       check_vma=False)
    except TypeError:  # older jax without check_vma
        fn = shard_map(best_iou_max_auto, mesh=mesh,
                       in_specs=(spec, spec, spec), out_specs=spec)
    return fn(pred_boxes, gt_boxes, gt_mask)


_PARITY_CACHE: dict[tuple, bool] = {}


def pallas_parity_ok(batch: int = 2, n_pred: int = 600, n_gt: int = 100,
                     tol: float = 1e-5, interpret: bool = False) -> bool:
    """One-batch parity check of the COMPILED kernel vs the XLA path.

    The Mosaic compilation of ``best_iou_max`` (block shapes with lane dim 4
    and full-batch sublane blocks) is environment- AND shape-sensitive, so
    callers must gate on the exact (batch, n_pred, n_gt) shapes training
    will use; results are cached per shape per process. A compile failure
    or numeric divergence disables the Pallas path.
    """
    key = (batch, n_pred, n_gt)
    if key in _PARITY_CACHE and not interpret:
        return _PARITY_CACHE[key]
    from deep_vision_tpu.ops.boxes import broadcast_iou

    try:
        rng = jax.random.PRNGKey(42)
        k1, k2, k3, k4, k5 = jax.random.split(rng, 5)
        p_xy = jax.random.uniform(k1, (batch, n_pred, 2))
        p_wh = jax.random.uniform(k2, (batch, n_pred, 2), minval=0.01,
                                  maxval=0.4)
        pred = jnp.concatenate([p_xy - p_wh / 2, p_xy + p_wh / 2], -1)
        g_xy = jax.random.uniform(k3, (batch, n_gt, 2))
        g_wh = jax.random.uniform(k4, (batch, n_gt, 2), minval=0.01,
                                  maxval=0.4)
        gt = jnp.concatenate([g_xy - g_wh / 2, g_xy + g_wh / 2], -1)
        mask = (jax.random.uniform(k5, (batch, n_gt)) > 0.3).astype(
            jnp.float32)
        got = best_iou_max(pred, gt, mask, interpret=interpret)
        iou = jnp.where(mask[:, None, :] > 0, broadcast_iou(pred, gt), 0.0)
        want = iou.max(-1)
        err = float(jax.device_get(jnp.abs(got - want).max()))
        ok = err < tol
        if not ok:
            print(f"[pallas] parity check FAILED (max err {err:.2e}) — "
                  "falling back to the XLA ignore-mask path")
    except Exception as e:  # noqa: BLE001 — compile/runtime failure → XLA fallback
        print(f"[pallas] kernel unavailable ({type(e).__name__}: {e}) — "
              "falling back to the XLA ignore-mask path")
        ok = False
    if not interpret:
        _PARITY_CACHE[key] = ok
    return ok
