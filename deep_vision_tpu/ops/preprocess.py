"""Device-side input preprocessing (jitter + normalize inside the jit step).

TPU-first split of the reference's cv2/torch host pipeline
(ResNet/pytorch/data_load.py:72-296): the host keeps only what must be
dynamic-shaped (JPEG decode, aspect-preserving rescale, crop — all uint8),
and the float work (ColorJitter :213-296, Normalize :197-210) moves into
the jitted train step where XLA fuses it into the first conv's HBM read.
Shipping uint8 instead of float32 also cuts host→device transfer 4×.

Semantics vs the host path: identical factor ranges; the three jitter ops
apply in a fixed order (brightness→contrast→saturation) instead of the
reference's shuffled order — a no-op in expectation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deep_vision_tpu.data.mnist import MEAN as MNIST_MEAN
from deep_vision_tpu.data.mnist import STD as MNIST_STD
from deep_vision_tpu.data.transforms import IMAGENET_MEAN, IMAGENET_STD

_GRAY = jnp.asarray([0.299, 0.587, 0.114])

#: normalization families the serving wire supports (docs/SERVING.md
#: "Wire format & inference dtype"); "unit" is plain [0,1] scaling,
#: "gan" the reference GAN pipelines' [-1,1] scaling
SERVE_KINDS = ("imagenet", "mnist", "unit", "gan")


def serve_preprocess_kind(task: str, channels: int) -> str:
    """Which normalization family a model's uint8 serving wire needs —
    derived from config metadata so the device prologue matches the
    host path that trained the model: classification RGB models were
    trained on ImageNet-standardized inputs (data/transforms.py),
    grayscale classification on MNIST stats (data/mnist.py), the
    detection/pose tasks on plain [0,1] images, and the GAN tasks on
    [-1,1] images (``make_gan_preprocess`` — the image-in CycleGAN
    serving wire reuses exactly that scaling)."""
    if task == "classification":
        return "mnist" if channels == 1 else "imagenet"
    if str(task).startswith("gan_"):
        return "gan"
    return "unit"


def serve_normalize(x, kind: str):  # dvtlint: traced
    """uint8 wire batch → normalized float32, IDENTICAL math to the host
    preprocess for ``kind`` (scale first, then standardize — same op
    order as data/transforms.normalize and data/mnist.preprocess, so
    uint8-wire outputs stay allclose to the float32 wire)."""
    if kind not in SERVE_KINDS:
        raise ValueError(f"unknown serve preprocess kind '{kind}' "
                         f"(have {SERVE_KINDS})")
    if kind == "gan":
        # GAN convention: (x - 127.5)/127.5, same op as the trainer's
        # make_gan_preprocess — NOT the /255-then-standardize chain
        return x.astype(jnp.float32) / 127.5 - 1.0
    x = x.astype(jnp.float32) / 255.0
    if kind == "imagenet":
        return (x - jnp.asarray(IMAGENET_MEAN)) / jnp.asarray(IMAGENET_STD)
    if kind == "mnist":
        return (x - MNIST_MEAN) / MNIST_STD
    return x  # "unit": [0,1] inputs (YOLO/CenterNet/hourglass)


def make_serve_preprocess(kind: str, wire_dtype, compute_dtype=jnp.float32):
    """Traced prologue for serving bucket programs (serve/registry.py).

    An integer ``wire_dtype`` means the client shipped raw 0–255 pixels
    and the server owns normalization: cast + scale + standardize run on
    device, fused by XLA into the first conv's HBM read (the H2D carried
    4× fewer bytes).  A float wire passes through untouched — those
    clients already normalized on the host (the pre-uint8 contract).
    Either way the batch lands in ``compute_dtype`` (bfloat16 for
    ``--infer-dtype bfloat16``, else float32)."""
    wire_is_int = jnp.issubdtype(jnp.dtype(wire_dtype), jnp.integer)

    def fn(x):  # dvtlint: traced
        if wire_is_int:
            x = serve_normalize(x, kind)
        return x.astype(compute_dtype)

    return fn


def quantize_activations(x, act_scale: float):  # dvtlint: traced
    """Normalized float activations → symmetric int8 with the per-tensor
    calibration scale (serve/quant.py): ``round(x/act_scale)`` clipped
    to ±127.  The XLA half of the int8 ingest — same math as the fused
    Pallas kernel, kept for parity testing and the float32 wire."""
    q = jnp.clip(jnp.round(x / act_scale), -127.0, 127.0)
    return q.astype(jnp.int8)


def make_int8_ingest(kind: str, wire_dtype, act_scale: float,
                     use_pallas: bool = True):
    """Traced int8 serve-prologue (``--infer-dtype int8`` bucket
    programs, serve/registry.py): the batch leaves as int8 activations
    the program dequantizes into its first conv.

    A uint8 wire takes the FUSED path — decode + normalize + quantize in
    one VMEM pass (ops/pallas_ops.serve_ingest; interpret-mode off-TPU)
    so the wire bytes never materialize as an f32 HWC tensor in HBM —
    unless ``use_pallas`` is False (the XLA fallback kept for parity
    testing, or a failed on-TPU parity gate).  A float wire was
    normalized by the client, so only the quantize runs.  The "gan"
    kind always takes the XLA path — the fused kernel's constant table
    (ops/pallas_ops._ingest_norm_constants) only bakes the mean/std
    families, and int8 generative serving is untested territory."""
    wire_is_int = jnp.issubdtype(jnp.dtype(wire_dtype), jnp.integer)
    if wire_is_int and use_pallas and kind != "gan":
        from deep_vision_tpu.ops.pallas_ops import serve_ingest_auto

        def fn(x):  # dvtlint: traced
            return serve_ingest_auto(x, kind, act_scale=act_scale)

        return fn

    def fn(x):  # dvtlint: traced
        if wire_is_int:
            x = serve_normalize(x, kind)
        return quantize_activations(x, act_scale)

    return fn


def jitter_normalize(images, rng, train: bool,
                     mean=IMAGENET_MEAN, std=IMAGENET_STD,
                     brightness: float = 0.2, contrast: float = 0.2,
                     saturation: float = 0.2):
    """uint8 (B,H,W,3) → normalized float32, with train-time color jitter.

    Already-float inputs pass through normalization only (so the same step
    works with host-normalized loaders — their floats are already
    standardized and this fn must NOT run; callers gate on dtype).
    """
    x = images.astype(jnp.float32) / 255.0
    if train:
        b = images.shape[0]
        kb, kc, ks = jax.random.split(rng, 3)
        fb = jax.random.uniform(kb, (b, 1, 1, 1),
                                minval=max(0.0, 1 - brightness),
                                maxval=1 + brightness)
        x = x * fb
        m = x.mean(axis=(1, 2, 3), keepdims=True)
        fc = jax.random.uniform(kc, (b, 1, 1, 1),
                                minval=max(0.0, 1 - contrast),
                                maxval=1 + contrast)
        x = (x - m) * fc + m
        gray = (x * _GRAY).sum(-1, keepdims=True)
        fs = jax.random.uniform(ks, (b, 1, 1, 1),
                                minval=max(0.0, 1 - saturation),
                                maxval=1 + saturation)
        x = gray + (x - gray) * fs
        x = jnp.clip(x, 0.0, 1.0)
    return (x - jnp.asarray(mean)) / jnp.asarray(std)


def make_scale_preprocess():
    """Trainer ``preprocess_fn`` for [0,1]-input tasks (YOLO, CenterNet):
    uint8 image batches scale to float32/255 inside the jitted step (4×
    smaller H2D payload — the loaders' ``device_normalize`` path); float
    batches (host-normalized) pass through untouched."""

    def fn(batch: dict, rng, train: bool) -> dict:
        img = batch["image"]
        if img.dtype != jnp.uint8:
            return batch
        out = dict(batch)
        out["image"] = img.astype(jnp.float32) / 255.0
        return out

    return fn


def make_imagenet_preprocess(brightness: float = 0.2, contrast: float = 0.2,
                             saturation: float = 0.2,
                             use_fused: bool = False,
                             fused_shape: tuple | None = None,
                             mesh=None):
    """Trainer ``preprocess_fn``: applied to uint8 image batches inside the
    jitted step; float batches (host-normalized path) pass through.

    With ``use_fused`` and a concrete ``fused_shape`` (the global
    (B, H, W, C) train batch), the train-time jitter chain goes through
    the fused Pallas ``train_ingest`` kernel instead of the multi-op XLA
    ``jitter_normalize`` — but only after the one-batch parity gate for
    that exact shape passes (ops/pallas_ops.train_ingest_parity_ok); a
    failed gate or kernel compile silently selects XLA, never a silent
    accuracy change.  On a multi-device ``mesh`` the kernel runs under
    shard_map per batch shard with globally-drawn factors.  The eval
    path is always the plain normalize (no jitter — nothing to fuse).
    """
    fused = False
    if use_fused and fused_shape is not None:
        from deep_vision_tpu.ops.pallas_ops import train_ingest_parity_ok

        on_tpu = jax.default_backend() == "tpu"
        fused = train_ingest_parity_ok(
            tuple(fused_shape), "imagenet", brightness, contrast,
            saturation, interpret=not on_tpu)
    multi = mesh is not None and mesh.devices.size > 1

    # dvtlint: hot
    def fn(batch: dict, rng, train: bool) -> dict:  # dvtlint: traced
        img = batch["image"]
        if img.dtype != jnp.uint8:
            return batch
        out = dict(batch)
        if fused and train:
            from deep_vision_tpu.ops.pallas_ops import (
                train_ingest_auto, train_ingest_factors,
                train_ingest_sharded)

            factors = train_ingest_factors(img, rng, brightness, contrast,
                                           saturation)
            if multi:
                out["image"] = train_ingest_sharded(img, factors, mesh)
            else:
                out["image"] = train_ingest_auto(img, factors)
        else:
            out["image"] = jitter_normalize(
                img, rng, train, brightness=brightness, contrast=contrast,
                saturation=saturation)
        return out

    fn.fused = fused  # introspectable: tests + CLI log which path won
    return fn


def make_mnist_preprocess():
    """Trainer ``preprocess_fn`` for the grayscale classification path:
    uint8 wire batches (data/mnist.load_mnist ``device_normalize=True``)
    standardize with the MNIST stats inside the jitted step — the H2D
    carried 1 byte/pixel and XLA fuses the normalize into the first
    conv's read; float batches (host-normalized) pass through."""

    def fn(batch: dict, rng, train: bool) -> dict:  # dvtlint: traced
        img = batch["image"]
        if img.dtype != jnp.uint8:
            return batch
        out = dict(batch)
        out["image"] = serve_normalize(img, "mnist")
        return out

    return fn


def make_gan_preprocess():
    """Trainer ``preprocess_fn`` for the GAN tasks (DCGAN/CycleGAN): the
    reference pipelines ship float32 in [-1, 1] (``(x - 127.5)/127.5``);
    the uint8 wire defers exactly that scaling to a traced prologue, so
    the host batches, prefetch queue, and H2D DMA carry 1 byte/pixel.
    Applies to every ``image*`` key (``image``, ``image_a``, ``image_b``
    — the unpaired loader carries two domains); float keys and non-image
    keys (pooled fakes, masks) pass through untouched."""

    def fn(batch: dict, rng, train: bool) -> dict:  # dvtlint: traced
        out = dict(batch)
        for key, val in batch.items():
            if key.startswith("image") and val.dtype == jnp.uint8:
                out[key] = val.astype(jnp.float32) / 127.5 - 1.0
        return out

    return fn
