"""TPU-friendly ops: static-shape box/NMS/heatmap primitives."""

from deep_vision_tpu.ops.boxes import (
    batched_nms,
    broadcast_iou,
    xywh_to_corners,
)

__all__ = ["batched_nms", "broadcast_iou", "xywh_to_corners"]
