"""Box utilities + batched NMS — parity with YOLO/tensorflow/utils.py
(``xywh_to_x1x2y1y2`` :4-12, ``broadcast_iou`` :31-74) and
postprocess.py's greedy NMS (:38-96).

The reference's NMS is a per-image python-style ``tf.while_loop`` picking
argmax and suppressing by IoU, mapped over the batch with ``tf.map_fn`` —
dynamic control flow that cannot batch on TPU.  Here NMS is a fixed-size,
fully-batched ``lax.while_loop``-free formulation: K rounds of
(argmax → record → suppress) expressed with ``lax.scan``, identical results
for the top-K boxes, static shapes throughout (SURVEY §7 hard-part 2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def xywh_to_corners(box):
    """(cx, cy, w, h) → (x1, y1, x2, y2), any leading dims."""
    xy, wh = box[..., :2], box[..., 2:4]
    return jnp.concatenate([xy - wh / 2.0, xy + wh / 2.0], axis=-1)


def broadcast_iou(box_a, box_b, eps: float = 1e-9):
    """IoU of every a-box against every b-box.

    box_a: (..., N, 4) corners; box_b: (..., M, 4) corners → (..., N, M).
    """
    a = box_a[..., :, None, :]
    b = box_b[..., None, :, :]
    inter_lo = jnp.maximum(a[..., :2], b[..., :2])
    inter_hi = jnp.minimum(a[..., 2:], b[..., 2:])
    inter_wh = jnp.maximum(inter_hi - inter_lo, 0.0)
    inter = inter_wh[..., 0] * inter_wh[..., 1]
    area_a = jnp.maximum(box_a[..., 2] - box_a[..., 0], 0.0) * \
        jnp.maximum(box_a[..., 3] - box_a[..., 1], 0.0)
    area_b = jnp.maximum(box_b[..., 2] - box_b[..., 0], 0.0) * \
        jnp.maximum(box_b[..., 3] - box_b[..., 1], 0.0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return inter / (union + eps)


#: class-offset magnitude for class-wise NMS: boxes are normalized to
#: [0, 1] (clipped decode keeps them within a few units), so shifting
#: each box by ``class_id * 4`` puts different classes on disjoint
#: diagonals — their IoU is exactly 0 and they can never suppress each
#: other, while same-class IoU is unchanged (the standard batched
#: class-aware NMS trick, static shapes preserved)
_CLASS_OFFSET = 4.0


def _per_class_cap(idx, valid, classes, max_per_class: int):
    """Invalidate selections past the ``max_per_class``-th VALID box of
    each class, in selection (descending-score) order.

    idx/valid: (K,) the scan's outputs; classes: (N,) per-box labels.
    Rank is computed with a (K, K) lower-triangular same-class mask —
    K is the small static output count, so the quadratic is trivial and
    shapes stay static (no sort, no segment ops)."""
    k = idx.shape[0]
    sel_cls = classes[idx]  # (K,) class of each selection
    same = sel_cls[:, None] == sel_cls[None, :]
    earlier = jnp.tril(jnp.ones((k, k), bool))  # j <= i
    # 1-based occurrence index among VALID same-class selections
    rank = jnp.sum(same & earlier & (valid > 0.0)[None, :], axis=1)
    return valid * (rank <= max_per_class).astype(valid.dtype)


def nms_single(boxes, scores, max_outputs: int, iou_threshold: float = 0.5,
               score_threshold: float = 0.0, classes=None,
               soft: str = "off", soft_sigma: float = 0.5,
               max_per_class: int = 0):
    """Greedy NMS for one image, static output size.

    boxes: (N, 4) corners; scores: (N,).  Returns (idx, sel_scores, valid):
    (K,) selected indices, their scores, and a 0/1 validity mask.
    ``classes`` (N,) int switches to CLASS-WISE suppression: boxes only
    suppress same-class neighbours (via the class-offset trick above);
    None keeps the class-agnostic reference behavior.

    ``soft`` picks the suppression rule (Bodla et al. 2017, Soft-NMS):
    "off" is the reference hard rule (overlap past ``iou_threshold`` →
    score killed); "gaussian" decays every overlapping neighbour by
    ``exp(-iou² / soft_sigma)``; "linear" scales neighbours past the
    IoU threshold by ``1 - iou``.  Soft-decayed boxes die only when
    their score falls below ``score_threshold``, so heavily-overlapped
    but high-scoring boxes survive with reduced rank — the reported
    ``sel_scores`` are the DECAYED scores, matching the paper.  The
    class-offset trick composes for free: cross-class IoU is exactly 0,
    so the decay factor is exp(0)=1 (no cross-class decay).

    ``max_per_class > 0`` (needs ``classes``) caps how many boxes each
    class may keep — the per-class K that stops one dense class from
    monopolizing the fixed K-row epilogue output.
    """
    scores = jnp.where(scores >= score_threshold, scores, -jnp.inf)
    iou_boxes = boxes
    if classes is not None:
        iou_boxes = boxes + (classes.astype(boxes.dtype)
                             * _CLASS_OFFSET)[..., None]
    iou = broadcast_iou(iou_boxes, iou_boxes)  # (N, N)
    n = scores.shape[0]

    if soft == "off":
        def step(live_scores, _):
            i = jnp.argmax(live_scores)
            best = live_scores[i]
            valid = jnp.isfinite(best)
            # suppress neighbours of the chosen box + the box itself
            suppress = (iou[i] > iou_threshold) | (jnp.arange(n) == i)
            live_scores = jnp.where(valid & suppress, -jnp.inf,
                                    live_scores)
            return live_scores, (i, jnp.where(valid, best, 0.0),
                                 valid.astype(jnp.float32))
    else:
        if soft not in ("gaussian", "linear"):
            raise ValueError(
                f"soft must be 'off', 'gaussian' or 'linear', "
                f"got {soft!r}")

        def step(live_scores, _):
            i = jnp.argmax(live_scores)
            best = live_scores[i]
            valid = jnp.isfinite(best)
            if soft == "gaussian":
                decay = jnp.exp(-(iou[i] ** 2) / soft_sigma)
            else:
                decay = jnp.where(iou[i] > iou_threshold,
                                  1.0 - iou[i], 1.0)
            decayed = live_scores * decay
            # decayed scores under the floor die; the chosen box
            # always leaves the pool
            decayed = jnp.where(decayed >= score_threshold, decayed,
                                -jnp.inf)
            decayed = jnp.where(jnp.arange(n) == i, -jnp.inf, decayed)
            live_scores = jnp.where(valid, decayed, live_scores)
            return live_scores, (i, jnp.where(valid, best, 0.0),
                                 valid.astype(jnp.float32))

    _, (idx, sel, valid) = lax.scan(step, scores, None, length=max_outputs)
    if max_per_class and max_per_class > 0 and classes is not None:
        valid = _per_class_cap(idx, valid, classes, int(max_per_class))
        sel = sel * valid
    return idx, sel, valid


def batched_nms(boxes, scores, max_outputs: int, iou_threshold: float = 0.5,
                score_threshold: float = 0.0, classes=None,
                soft: str = "off", soft_sigma: float = 0.5,
                max_per_class: int = 0):
    """vmap of nms_single over the batch: (B,N,4),(B,N) → (B,K) each.
    ``classes`` (B,N) int enables class-wise suppression per image;
    ``soft``/``soft_sigma``/``max_per_class`` thread straight through
    (static knobs, baked into the traced program)."""
    if classes is not None:
        return jax.vmap(
            lambda b, s, c: nms_single(
                b, s, max_outputs, iou_threshold, score_threshold,
                classes=c, soft=soft, soft_sigma=soft_sigma,
                max_per_class=max_per_class)
        )(boxes, scores, classes)
    return jax.vmap(
        lambda b, s: nms_single(b, s, max_outputs, iou_threshold,
                                score_threshold, soft=soft,
                                soft_sigma=soft_sigma))(boxes, scores)
