"""Result drawing — the reference's demo-notebook role
(YOLO/tensorflow/demo_mscoco.ipynb box plots,
Hourglass/tensorflow/demo_hourglass_pose.ipynb keypoint plots), as a
library + ``infer detect/pose --out annotated.jpg`` instead of notebooks:
one command turns an image into an annotated image, no jupyter needed.

Pure PIL (no matplotlib): draws straight onto the uint8 array and returns
a new array, so callers can save, grid, or further process.
"""

from __future__ import annotations

import numpy as np

# a 12-color wheel distinct enough for overlays (tab10-ish RGB values)
_PALETTE = (
    (31, 119, 180), (255, 127, 14), (44, 160, 44), (214, 39, 40),
    (148, 103, 189), (140, 86, 75), (227, 119, 194), (127, 127, 127),
    (188, 189, 34), (23, 190, 207), (255, 187, 120), (152, 223, 138))

# MPII 16-joint order (Datasets/MPII/tfrecords_mpii.py feature semantics):
# 0 r.ankle 1 r.knee 2 r.hip 3 l.hip 4 l.knee 5 l.ankle 6 pelvis 7 thorax
# 8 neck 9 head-top 10 r.wrist 11 r.elbow 12 r.shoulder 13 l.shoulder
# 14 l.elbow 15 l.wrist
MPII_SKELETON = (
    (0, 1), (1, 2), (2, 6), (5, 4), (4, 3), (3, 6),      # legs → pelvis
    (6, 7), (7, 8), (8, 9),                               # spine → head
    (10, 11), (11, 12), (12, 7), (7, 13), (13, 14), (14, 15))  # arms


def _color(i: int) -> tuple:
    return _PALETTE[int(i) % len(_PALETTE)]


def draw_detections(image: np.ndarray, boxes: np.ndarray,
                    scores: np.ndarray, classes: np.ndarray,
                    class_names: list[str] | None = None,
                    min_score: float = 0.0) -> np.ndarray:
    """Overlay detection results on an RGB uint8 image.

    ``boxes`` are normalized (x1, y1, x2, y2) corners (the postprocess/NMS
    output, tasks/detection.py:271-295) and are scaled to the image's own
    resolution, so annotations land correctly on the ORIGINAL photo, not
    just the model's resized input."""
    from PIL import Image, ImageDraw

    im = Image.fromarray(np.ascontiguousarray(image))
    draw = ImageDraw.Draw(im)
    h, w = image.shape[:2]
    lw = max(2, round(min(h, w) / 200))
    for box, score, cls in zip(np.atleast_2d(boxes), np.atleast_1d(scores),
                               np.atleast_1d(classes)):
        if score < min_score:
            continue
        x1, y1, x2, y2 = (float(box[0]) * w, float(box[1]) * h,
                          float(box[2]) * w, float(box[3]) * h)
        color = _color(cls)
        draw.rectangle([x1, y1, x2, y2], outline=color, width=lw)
        name = class_names[int(cls)] if class_names and \
            0 <= int(cls) < len(class_names) else f"class {int(cls)}"
        label = f"{name} {float(score):.2f}"
        tb = draw.textbbox((x1, y1), label)
        ty = y1 - (tb[3] - tb[1]) - 2 * lw
        if ty < 0:  # label would leave the image: draw inside the box
            ty = y1 + lw
        tb = draw.textbbox((x1, ty), label)
        draw.rectangle([tb[0] - lw, tb[1] - lw, tb[2] + lw, tb[3] + lw],
                       fill=color)
        draw.text((x1, ty), label, fill=(255, 255, 255))
    return np.asarray(im)


def draw_keypoints(image: np.ndarray, keypoints: np.ndarray,
                   visible: np.ndarray | None = None,
                   skeleton=MPII_SKELETON) -> np.ndarray:
    """Overlay pose keypoints (K, 2) [x, y] in IMAGE pixels + skeleton
    edges on an RGB uint8 image.  ``visible`` masks joints (<=0 hidden);
    edges draw only when both endpoints are visible."""
    from PIL import Image, ImageDraw

    im = Image.fromarray(np.ascontiguousarray(image))
    draw = ImageDraw.Draw(im)
    h, w = image.shape[:2]
    r = max(2, round(min(h, w) / 100))
    kp = np.asarray(keypoints, np.float32)
    vis = np.ones(len(kp)) if visible is None else np.asarray(visible)
    for a, b in skeleton or ():
        if a < len(kp) and b < len(kp) and vis[a] > 0 and vis[b] > 0:
            draw.line([tuple(kp[a]), tuple(kp[b])], fill=_color(a),
                      width=max(1, r // 2))
    for k, (x, y) in enumerate(kp):
        if vis[k] <= 0:
            continue
        draw.ellipse([x - r, y - r, x + r, y + r], fill=_color(k),
                     outline=(255, 255, 255))
    return np.asarray(im)
