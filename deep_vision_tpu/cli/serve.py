"""Serving CLI — boot the dynamic-batching inference engine over HTTP.

    # serve a trained workdir (best checkpoint, EMA weights if trained)
    python -m deep_vision_tpu.cli.serve -m resnet50 --workdir runs/r50

    # serve a StableHLO export (cli.infer export artifact)
    python -m deep_vision_tpu.cli.serve -m resnet50 --workdir runs/r50 \\
        --stablehlo model.stablehlo

    # tuning: batch buckets, drain window, queue bound
    python -m deep_vision_tpu.cli.serve -m yolov3_voc --workdir runs/y \\
        --max-batch 16 --max-wait-ms 8 --max-queue 512 --warmup

    # wire + compute dtype: clients ship raw uint8 pixels by default
    # (normalization runs on device); bf16 halves the compute footprint
    python -m deep_vision_tpu.cli.serve -m resnet50 --workdir runs/r50 \\
        --infer-dtype bfloat16
    python -m deep_vision_tpu.cli.serve -m resnet50 --workdir runs/r50 \\
        --wire-dtype float32   # the pre-uint8 host-normalized contract

    # chaos: boot with a deterministic fault spec (docs/SERVING.md)
    python -m deep_vision_tpu.cli.serve -m lenet5 --workdir runs/l \\
        --faults 'compute:exception:times=1' --fault-seed 0

    # multi-device: one engine replica per chip behind one queue, or
    # shard each padded batch across all chips (docs/SERVING.md)
    python -m deep_vision_tpu.cli.serve -m resnet50 --workdir runs/r50 \\
        --serve-devices 0
    python -m deep_vision_tpu.cli.serve -m resnet50 --workdir runs/r50 \\
        --shard-batches --max-batch 256

    # multi-model: serve the zoo behind one process with the model
    # control plane — per-model workdir subdirs, an HBM weight-cache
    # budget, and hot-reload/canary lifecycle endpoints
    # (docs/SERVING.md "Model lifecycle & weight cache")
    python -m deep_vision_tpu.cli.serve --models lenet5,yolov3_toy \\
        --workdir runs --hbm-budget-mb 512 --canary-frac 0.1

    # offline batch tier: POST bulk job manifests to /v1/jobs; shards
    # drain through the same engines strictly below interactive
    # traffic and checkpoint to JSONL so a restarted server resumes
    # mid-job (docs/BATCH.md)
    python -m deep_vision_tpu.cli.serve -m resnet50 --workdir runs/r50 \\
        --jobs-dir runs/r50/jobs

    # continuous deploy: watch each model's workdir for new
    # checkpoints, gate them on held-out data, roll out through
    # shadow/canary, and autoscale replicas with demand
    # (docs/DEPLOY.md)
    python -m deep_vision_tpu.cli.serve --models lenet5 --workdir runs \\
        --watch --gate-dir data/holdout --min-replicas 1 \\
        --max-replicas 4

Knobs and architecture: docs/SERVING.md.  Smoke: ``make serve-smoke``;
chaos suite: ``make serve-chaos``; deploy loop: ``make deploy-smoke``.
"""

from __future__ import annotations

import argparse


def _edge_kwargs(args):
    """Shared ServeServer edge wiring for both build paths.

    The selector event loop is the default front-end; --thread-server
    restores the thread-per-request baseline (the A/B foil in
    docs/PERF.md).  The response cache and tenant QoS stay OFF unless
    asked for, so single-purpose smokes keep their exact span/counter
    expectations."""
    from deep_vision_tpu.serve.admission import TenantQoS
    from deep_vision_tpu.serve.cache import ResponseCache

    cache_mb = float(getattr(args, "response_cache_mb", 0.0) or 0.0)
    qos_spec = getattr(args, "qos", None)
    return dict(
        edge=not getattr(args, "thread_server", False),
        max_connections=int(getattr(args, "max_connections", 1024)),
        http_workers=int(getattr(args, "http_workers", 8)),
        response_cache=ResponseCache(int(cache_mb * 2**20))
        if cache_mb > 0 else None,
        qos=TenantQoS.parse(qos_spec) if qos_spec else None)


def _batch_tier(args, resolve):
    """``--jobs-dir`` → (JobStore, started BatchScheduler) or
    (None, None).

    ``resolve(model_name) -> (model, engine)`` is the routing closure
    each build path supplies (engines dict or control plane); the
    scheduler fails a job terminally when it raises KeyError.  The
    shard size defaults to the engine's max batch — one shard is one
    full cohort, the unit the trough check reasons about
    (docs/BATCH.md)."""
    jobs_dir = getattr(args, "jobs_dir", None)
    if jobs_dir is None:
        return None, None
    from deep_vision_tpu.serve.batch_sched import BatchScheduler
    from deep_vision_tpu.serve.jobs import JobStore

    shard = int(getattr(args, "batch_shard_size", 0) or 0) \
        or int(args.max_batch)
    store = JobStore(jobs_dir or None, shard_size=shard,
                     max_cached_shards=int(
                         getattr(args, "batch_cache_shards", 64) or 0))
    sched = BatchScheduler(
        store, resolve,
        interval_s=float(getattr(args, "batch_interval_ms", 20.0) or
                         20.0) / 1e3,
        max_interactive_depth=int(getattr(args, "batch_max_depth", 0)
                                  or 0),
        pressure_high_ms=float(getattr(args, "batch_pressure_ms", 10.0)
                               or 10.0))
    sched.start()
    return store, sched


def _brownout(args, engines_provider):
    """``--brownout`` → started BrownoutController or None.

    ``engines_provider`` is the zero-arg callable the controller polls
    each tick (engines dict values or the plane's active engines), so a
    hot reload swaps the observed engine automatically.  The controller
    is wired into every optional-work producer by the caller — the
    ladder itself only reads signals and steps a level."""
    if not getattr(args, "brownout", False):
        return None
    from deep_vision_tpu.serve.brownout import BrownoutController

    bc = BrownoutController(
        engines_provider,
        interval_s=float(getattr(args, "brownout_interval_ms", 250.0)
                         or 250.0) / 1e3,
        l1_pressure_ms=float(getattr(args, "brownout_l1_ms", 50.0)),
        l2_pressure_ms=float(getattr(args, "brownout_l2_ms", 150.0)),
        l3_pressure_ms=float(getattr(args, "brownout_l3_ms", 400.0)),
        occupancy_high=float(getattr(args, "brownout_occupancy", 0.97)),
        shed_rate_high=float(getattr(args, "brownout_shed_rate", 0.10)),
        up_window=int(getattr(args, "brownout_up_window", 2)),
        down_window=int(getattr(args, "brownout_down_window", 8)),
        cooldown_s=float(getattr(args, "brownout_cooldown_s", 2.0)))
    force = int(getattr(args, "brownout_force", -1)
                if getattr(args, "brownout_force", -1) is not None
                else -1)
    if force >= 0:
        bc.force(force)
    bc.start()
    return bc


def _parse_mesh_arg(spec: str) -> tuple[int, int]:
    """``--mesh D,M`` (data,model) → (D, M); a single value N means
    N,1 — pure batch sharding, same as --shard-batches over N."""
    parts = [s.strip() for s in str(spec).split(",") if s.strip()]
    try:
        sizes = [int(s) for s in parts]
    except ValueError:
        sizes = []
    if len(sizes) == 1:
        sizes.append(1)
    if len(sizes) != 2 or any(n < 1 for n in sizes):
        raise ValueError(
            f"--mesh '{spec}': expected 'data,model' positive axis "
            "sizes (e.g. '2,2', '4,1', '1,4')")
    return sizes[0], sizes[1]


def _detect_knobs(args) -> dict:
    """The ``--detect-*`` flags as registry.load_checkpoint kwargs —
    getattr'd so programmatic Namespace callers (smokes, tests) that
    predate the knobs keep the device-decode defaults."""
    return dict(
        detect_decode=str(getattr(args, "detect_decode", "device")),
        detect_topk=int(getattr(args, "detect_topk", 100) or 100),
        detect_score_threshold=float(
            getattr(args, "detect_score_threshold", 0.05)),
        detect_iou_threshold=float(
            getattr(args, "detect_iou_threshold", 0.5)),
        detect_soft_nms=str(getattr(args, "detect_soft_nms", "off")
                            or "off"),
        detect_soft_sigma=float(
            getattr(args, "detect_soft_sigma", 0.5)),
        detect_max_per_class=int(
            getattr(args, "detect_max_per_class", 0) or 0))


def build_server(args):
    """argparse namespace → (engine, ServeServer); shared with the smoke
    test so `make serve-smoke` boots exactly the production wiring.

    Device scaling (docs/SERVING.md "Multi-device serving"):
    ``--serve-devices N`` replicates the engine over the first N local
    devices behind one queue (N=0 → all local devices; default 1 keeps
    the single-engine path byte-for-byte); ``--shard-batches`` instead
    builds ONE engine whose padded batches span the data axis of a mesh
    over those devices (mutually exclusive by construction — replication
    parallelizes many small batches, sharding one large batch);
    ``--mesh D,M`` generalizes to a 2-D data×model mesh — batches split
    D ways while the partition rules (``--partition-rules``) lay the
    params over the M-chip model axis (docs/SERVING.md "2-D mesh
    serving")."""
    from deep_vision_tpu.obs.trace import Tracer
    from deep_vision_tpu.serve.admission import AdmissionController
    from deep_vision_tpu.serve.engine import BatchingEngine, sharded_buckets
    from deep_vision_tpu.serve.faults import FaultPlane
    from deep_vision_tpu.serve.http import ServeServer
    from deep_vision_tpu.serve.registry import ModelRegistry
    from deep_vision_tpu.serve.replicas import ReplicatedEngine, local_devices

    registry = ModelRegistry()
    # uint8 is the production serving wire (4× smaller H2D payloads,
    # normalization fused into the bucket programs); the registry's
    # programmatic default stays float32 so direct callers keep the old
    # host-normalized contract (docs/SERVING.md "Wire format")
    wire_dtype = getattr(args, "wire_dtype", "uint8") or "uint8"
    infer_dtype = getattr(args, "infer_dtype", "float32") or "float32"
    models_arg = getattr(args, "models", None)
    if models_arg:
        if args.stablehlo:
            raise ValueError("--stablehlo serves one exported blob; "
                             "multi-model serving (--models) is "
                             "checkpoint-path only")
        return _build_plane_server(args, registry, wire_dtype,
                                   infer_dtype)
    if getattr(args, "watch", False) \
            or int(getattr(args, "max_replicas", 0) or 0):
        raise ValueError("--watch / --max-replicas need the model "
                         "control plane (--models ...): the deploy "
                         "pipeline rolls candidates through its "
                         "version table")
    calib_batches = int(getattr(args, "calib_batches", 2) or 2)
    calib_dir = getattr(args, "calib_dir", None)
    if args.stablehlo:
        # blobs were traced at float32 with host-side normalization —
        # the wire knob doesn't apply (describe() shows the real wire);
        # a non-f32 --infer-dtype is rejected by the registry with the
        # single "f32-wire/f32-compute only" error
        wire_dtype = "float32"
        sm = registry.load_exported(args.model, args.stablehlo,
                                    args.workdir,
                                    infer_dtype=infer_dtype)
    else:
        sm = registry.load_checkpoint(args.model, args.workdir,
                                      wire_dtype=wire_dtype,
                                      infer_dtype=infer_dtype,
                                      calib_batches=calib_batches,
                                      calib_dir=calib_dir,
                                      **_detect_knobs(args))
    buckets = [int(b) for b in args.buckets.split(",")] if args.buckets \
        else None
    fault_spec = getattr(args, "faults", None)
    faults = FaultPlane(fault_spec, getattr(args, "fault_seed", 0)) \
        if fault_spec else None  # None → engine reads DVT_SERVE_FAULTS
    serve_devices = int(getattr(args, "serve_devices", 1))
    shard_batches = bool(getattr(args, "shard_batches", False))
    mesh_arg = getattr(args, "mesh", None)
    if mesh_arg and shard_batches:
        raise ValueError("--mesh subsumes --shard-batches (a D×1 mesh "
                         "IS batch sharding); pass one")
    if mesh_arg:
        n_data, n_model = _parse_mesh_arg(mesh_arg)
        try:
            devices = local_devices(n_data * n_model)
        except ValueError:
            # re-raise under the flag the operator actually typed
            import jax

            raise ValueError(
                f"--mesh {n_data},{n_model} needs "
                f"{n_data * n_model} device(s); only "
                f"{len(jax.local_devices())} local device(s) present "
                f"— shrink an axis or add hosts") from None
    elif shard_batches:
        # shard over N devices (0/1 → every local device)
        devices = local_devices(serve_devices if serve_devices > 1
                                else None)
    elif serve_devices != 1:
        # replicate over N devices (0 → every local device)
        devices = local_devices(serve_devices or None)
    else:
        devices = None  # the PR 1–3 single-engine path, untouched
    tracer = Tracer(ring=getattr(args, "trace_ring", 256),
                    slow_ms=getattr(args, "slow_trace_ms", 250.0),
                    enabled=not getattr(args, "no_trace", False))
    engine_kwargs = dict(
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        buckets=buckets,
        tracer=tracer,
        pipeline_depth=getattr(args, "pipeline_depth", 2),
        faults=faults,
        watchdog_interval_s=getattr(args, "watchdog_interval_ms", 50.0)
        / 1e3,
        restart_budget=getattr(args, "restart_budget", 3),
        exec_timeout_k=getattr(args, "exec_timeout_k", 10.0),
        exec_timeout_min_s=getattr(args, "exec_timeout_min_s", 2.0),
        retry_budget=getattr(args, "retry_budget", 16),
        degraded_after=getattr(args, "degraded_after", 1),
        dead_after=getattr(args, "dead_after", 5),
        # per-workload SLO class (serve/workloads.py): the operator's
        # --max-queue capped by the model's workload — generative
        # batches hold the device longer, so their class bounds the
        # queue tighter (shed early, not after stacked deadline misses)
        admission=AdmissionController(
            max_queue=sm.workload.slo.bound_queue(args.max_queue),
            max_wait_ms=args.max_wait_ms))
    if mesh_arg:
        # 2-D data×model serving: batches split over ``data``, params
        # laid out over ``model`` by the partition rules — buckets key
        # off the DATA-axis size only (docs/SERVING.md "2-D mesh
        # serving")
        from deep_vision_tpu.parallel.mesh import make_mesh
        from deep_vision_tpu.parallel.partition import (
            parse_partition_rules,
        )

        mesh = make_mesh({"data": n_data, "model": n_model},
                         devices=devices)
        rules_arg = getattr(args, "partition_rules", None)
        rules = parse_partition_rules(rules_arg) if rules_arg else None
        if engine_kwargs["buckets"] is None:
            engine_kwargs["buckets"] = sharded_buckets(
                args.max_batch, n_data)
        engine = BatchingEngine(
            sm.for_mesh(mesh, partition_rules=rules,
                        strict=bool(getattr(args, "partition_strict",
                                            False)),
                        min_shard_dim=int(getattr(
                            args, "partition_min_dim", 1024) or 1024)),
            **engine_kwargs)
    elif shard_batches:
        from deep_vision_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"data": len(devices)}, devices=devices)
        if engine_kwargs["buckets"] is None:
            engine_kwargs["buckets"] = sharded_buckets(
                args.max_batch, len(devices))
        engine = BatchingEngine(sm.for_mesh(mesh), **engine_kwargs)
    elif devices is not None and len(devices) > 1:
        engine = ReplicatedEngine(sm, devices=devices, **engine_kwargs)
    else:
        engine = BatchingEngine(sm, **engine_kwargs)
    engine.start()
    if args.warmup:
        print(f"[serve] warming {engine.buckets} ...")
        engine.warmup()
    socket_timeout_s = getattr(args, "socket_timeout_s", 30.0)
    engines = {sm.name: engine}

    def resolve(name, _engines=engines):
        eng = _engines[name]  # KeyError → job fails terminally
        return registry.get(name), eng

    jobs, batch_sched = _batch_tier(args, resolve)
    brownout = _brownout(args, lambda: engines.values())
    if brownout is not None:
        if batch_sched is not None:
            batch_sched.brownout = brownout  # L1+: freeze the batch tier
        # L1+: stop paying for slow-trace serialization under overload
        tracer.suppress_slow = lambda: brownout.at_least(1)
    server = ServeServer(
        registry, engines, host=args.host, port=args.port,
        verbose=args.verbose,
        max_body_bytes=int(getattr(args, "max_body_mb", 32) * 2**20),
        socket_timeout_s=socket_timeout_s if socket_timeout_s > 0
        else None,
        tracer=tracer, jobs=jobs, batch_sched=batch_sched,
        brownout=brownout,
        **_edge_kwargs(args))
    return engine, server


def _build_plane_server(args, registry, wire_dtype: str,
                        infer_dtype: str):
    """``--models a,b,c`` → (ModelControlPlane, ServeServer).

    Per-model checkpoints restore from ``<workdir>/<name>`` subdirs
    (the multi-model workdir layout); every model's engine is built by
    one shared factory so hot-reloaded versions boot the same wiring as
    the originals.  The returned plane exposes the engine surface
    ``main()`` prints and stops through (``model``/``buckets``/
    ``faults``/``stop``)."""
    import os

    from deep_vision_tpu.obs.trace import Tracer
    from deep_vision_tpu.serve.admission import AdmissionController
    from deep_vision_tpu.serve.engine import BatchingEngine
    from deep_vision_tpu.serve.faults import FaultPlane
    from deep_vision_tpu.serve.http import ServeServer
    from deep_vision_tpu.serve.models import (
        CanaryPolicy,
        ModelControlPlane,
        WeightCache,
    )
    from deep_vision_tpu.serve.replicas import (
        ReplicatedEngine,
        local_devices,
    )

    names = [s.strip() for s in args.models.split(",") if s.strip()]
    if not names:
        raise ValueError("--models needs at least one config name")
    cascade_spec = None
    if getattr(args, "cascade", None):
        from deep_vision_tpu.serve.cascade import CascadeSpec

        cascade_spec = CascadeSpec.parse(
            args.cascade,
            min_agreement=float(getattr(args, "cascade_min_agreement",
                                        0.98)),
            sample_period=int(getattr(args, "cascade_sample_period",
                                      10)),
            min_sample=int(getattr(args, "cascade_min_sample", 200)),
            topk=int(getattr(args, "cascade_topk", 5)),
            per_class=bool(getattr(args, "cascade_per_class", False)),
            class_min_sample=int(getattr(args,
                                         "cascade_class_min_sample",
                                         50)))
        for tier in cascade_spec.tiers:
            if tier not in names:
                raise ValueError(
                    f"--cascade tier '{tier}' is not served; --models "
                    f"must include every cascade tier (got {names})")
        # every tier must speak the SAME verb (the chain escalates one
        # request through all of them), and the verb needs a
        # CascadeWorkloadRule (classify/detect today) — checked here,
        # before any checkpoint restore
        from deep_vision_tpu.core.config import get_config
        from deep_vision_tpu.serve.workloads import workload_for_task

        tier_verbs = {t: workload_for_task(get_config(t).task).verb
                      for t in cascade_spec.tiers}
        if len(set(tier_verbs.values())) > 1:
            raise ValueError(
                f"--cascade tiers must share one workload verb, got "
                f"{tier_verbs}")
        verb = tier_verbs[cascade_spec.big]
        if workload_for_task(
                get_config(cascade_spec.big).task).cascade_rule() \
                is None:
            raise ValueError(
                f"--cascade: the '{verb}' workload has no cascade "
                f"rule (classify and detect cascade today)")
    buckets = [int(b) for b in args.buckets.split(",")] if args.buckets \
        else None
    fault_spec = getattr(args, "faults", None)
    faults = FaultPlane(fault_spec, getattr(args, "fault_seed", 0)) \
        if fault_spec else None
    serve_devices = int(getattr(args, "serve_devices", 1))
    if getattr(args, "shard_batches", False):
        raise ValueError("--shard-batches is single-model only; "
                         "--models replicates per engine instead "
                         "(--serve-devices N)")
    if getattr(args, "mesh", None):
        raise ValueError("--mesh is single-model only; --models "
                         "replicates per engine instead "
                         "(--serve-devices N)")
    min_replicas = int(getattr(args, "min_replicas", 0) or 0)
    max_replicas = int(getattr(args, "max_replicas", 0) or 0)
    if max_replicas and not min_replicas:
        min_replicas = 1
    if max_replicas and max_replicas < min_replicas:
        raise ValueError(f"--max-replicas {max_replicas} < "
                         f"--min-replicas {min_replicas}")
    if min_replicas:
        if serve_devices != 1:
            raise ValueError("--min-replicas and --serve-devices both "
                             "set the replica floor; use one")
        # the autoscaler needs the elastic engine even at one replica
        devices = local_devices(min_replicas)
    else:
        devices = local_devices(serve_devices or None) \
            if serve_devices != 1 else None
    replicated = devices is not None and (len(devices) > 1
                                          or max_replicas > 1)
    tracer = Tracer(ring=getattr(args, "trace_ring", 256),
                    slow_ms=getattr(args, "slow_trace_ms", 250.0),
                    enabled=not getattr(args, "no_trace", False))
    engine_kwargs = dict(
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        buckets=buckets, tracer=tracer,
        pipeline_depth=getattr(args, "pipeline_depth", 2),
        faults=faults,
        watchdog_interval_s=getattr(args, "watchdog_interval_ms", 50.0)
        / 1e3,
        restart_budget=getattr(args, "restart_budget", 3),
        exec_timeout_k=getattr(args, "exec_timeout_k", 10.0),
        exec_timeout_min_s=getattr(args, "exec_timeout_min_s", 2.0),
        retry_budget=getattr(args, "retry_budget", 16),
        degraded_after=getattr(args, "degraded_after", 1),
        dead_after=getattr(args, "dead_after", 5))

    # one admission controller per model NAME, shared across its
    # versions: the per-bucket exec EWMAs and queue accounting survive a
    # hot reload instead of resetting with each new engine
    admissions: dict = {}

    def admission_for(name: str) -> AdmissionController:
        adm = admissions.get(name)
        if adm is None:
            # the model's workload SLO class caps the queue bound
            # (serve/workloads.py); registry lookup can only miss for
            # engines built outside the plane's deploy path — keep the
            # operator's bound there
            try:
                max_queue = registry.get(name).workload.slo.bound_queue(
                    args.max_queue)
            except (KeyError, AttributeError):
                max_queue = args.max_queue
            adm = admissions[name] = AdmissionController(
                max_queue=max_queue,
                max_wait_ms=args.max_wait_ms, name=name)
        return adm

    def engine_factory(model):
        kwargs = dict(engine_kwargs,
                      admission=admission_for(model.name))
        if replicated:
            return ReplicatedEngine(model, devices=devices, **kwargs)
        return BatchingEngine(model, **kwargs)

    cache = WeightCache(
        int(float(getattr(args, "hbm_budget_mb", 0) or 0) * 2**20))
    policy = CanaryPolicy(
        canary_frac=float(getattr(args, "canary_frac", 0.1)),
        min_requests=int(getattr(args, "canary_min_requests", 20)),
        max_error_rate=float(getattr(args, "canary_max_error_rate",
                                     0.0)),
        max_p99_ratio=float(getattr(args, "canary_max_p99_ratio", 3.0)),
        shadow_frac=float(getattr(args, "shadow_frac", 0.0)),
        phase_timeout_s=float(getattr(args, "phase_timeout_s", 30.0)))
    plane = ModelControlPlane(registry, engine_factory, cache=cache,
                              policy=policy,
                              admission_factory=admission_for)
    for name in names:
        workdir = os.path.join(args.workdir, name)
        # every NON-FINAL cascade tier fuses the (top1_idx, top1_prob)
        # confidence epilogue into its bucket programs (classify; the
        # detect decode epilogue already carries the signal); the big
        # tier keeps its plain outputs so escalated answers are
        # bit-identical to big-only serving (serve/cascade.py)
        front_k = cascade_spec.topk if cascade_spec is not None \
            and name in cascade_spec.tiers \
            and name != cascade_spec.big else 0
        tier_infer = infer_dtype
        tier_calib = getattr(args, "calib_dir", None)
        if cascade_spec is not None \
                and getattr(args, "cascade_quant_front", False) \
                and name == cascade_spec.front:
            # --cascade-quant-front: tier 0 serves int8-resident
            # weights, PTQ-calibrated at boot on the same held-out
            # directory the accuracy gate uses (synthetic when neither
            # is given).  The other tiers keep --infer-dtype.
            tier_infer = "int8"
            tier_calib = tier_calib or getattr(args, "gate_dir", None)
        sm = registry.load_checkpoint(
            name, workdir, wire_dtype=wire_dtype,
            infer_dtype=tier_infer,
            calib_batches=int(getattr(args, "calib_batches", 2) or 2),
            calib_dir=tier_calib,
            cascade_topk=front_k,
            **_detect_knobs(args))
        plane.deploy(sm, workdir=workdir)
    cascade = None
    if cascade_spec is not None:
        from deep_vision_tpu.serve.cascade import CascadeRouter

        # built AFTER the boot deploys: the router's version listener
        # only needs to see RELOADS (boot state is uncalibrated anyway).
        # The ledger root gives calibration restart durability — a
        # rebooted server reloads its threshold instead of failing
        # closed to all-big for another min_sample requests
        cascade = CascadeRouter(plane, cascade_spec,
                                root=os.path.join(args.workdir,
                                                  "_cascade"))
    if args.warmup:
        for name, eng in plane.active_engines().items():
            print(f"[serve] warming {name} {eng.buckets} ...")
        plane.warmup()

    # deploy pipeline (deploy/__init__.py, docs/DEPLOY.md): the ledger
    # always rides along with a watcher or autoscaler; --watch adds the
    # per-model checkpoint watcher + accuracy gate, --max-replicas adds
    # one autoscaler per (elastic) engine
    pipeline = None
    if getattr(args, "watch", False) or max_replicas > min_replicas:
        from deep_vision_tpu.deploy import (
            AccuracyGate,
            CheckpointWatcher,
            DeploymentHistory,
            DeployPipeline,
            ReplicaAutoscaler,
        )

        history = DeploymentHistory(os.path.join(args.workdir,
                                                 "_deploy"))
        watcher = None
        if getattr(args, "watch", False):
            gate = AccuracyGate(
                gate_dir=getattr(args, "gate_dir", None),
                min_agreement=float(getattr(args, "gate_min_agreement",
                                            0.8)))
            watcher = CheckpointWatcher(
                plane, history,
                interval_s=float(getattr(args, "watch_interval_s",
                                         2.0)),
                gate=gate)
            for name in names:
                watcher.watch(name)
        autoscalers = {}
        if max_replicas > min_replicas:
            for name in names:
                # resolve the engine per tick: a hot reload swaps the
                # active engine and the scaler must follow it
                autoscalers[name] = ReplicaAutoscaler(
                    lambda name=name: plane.active_engine(name),
                    name=name, min_replicas=min_replicas or 1,
                    max_replicas=max_replicas, history=history)
        pipeline = DeployPipeline(plane, history=history,
                                  watcher=watcher,
                                  autoscalers=autoscalers or None)
        pipeline.start()
    socket_timeout_s = getattr(args, "socket_timeout_s", 30.0)

    def resolve(name):
        # per-shard re-resolution: a hot reload swaps the active
        # engine and the NEXT shard follows it (KeyError → job fails)
        model = plane.resolve(name)
        return model, plane.active_engine(model.name)

    jobs, batch_sched = _batch_tier(args, resolve)
    brownout = _brownout(
        args, lambda: plane.active_engines().values())
    if brownout is not None:
        plane.brownout = brownout    # L1+: pause shadow duplication
        if cascade is not None:
            cascade.brownout = brownout  # L1 sample pause, L2 degrade
        if batch_sched is not None:
            batch_sched.brownout = brownout  # L1+: freeze the batch tier
        tracer.suppress_slow = lambda: brownout.at_least(1)
    server = ServeServer(
        registry, plane.active_engines(), host=args.host,
        port=args.port, verbose=args.verbose,
        max_body_bytes=int(getattr(args, "max_body_mb", 32) * 2**20),
        socket_timeout_s=socket_timeout_s if socket_timeout_s > 0
        else None,
        tracer=tracer, plane=plane, deploy=pipeline,
        jobs=jobs, batch_sched=batch_sched, cascade=cascade,
        brownout=brownout,
        **_edge_kwargs(args))
    return plane, server


def main(argv=None):
    p = argparse.ArgumentParser(
        description="deep_vision_tpu dynamic-batching inference server")
    p.add_argument("-m", "--model", default=None,
                   help="config name (see cli.train --list); required "
                        "unless --models boots the multi-model plane")
    p.add_argument("--models", default=None,
                   help="comma-separated config names: serve several "
                        "models behind one process via the model "
                        "control plane (versioned table, weight cache, "
                        "hot reload; docs/SERVING.md).  Checkpoints "
                        "restore from <workdir>/<name> subdirs")
    p.add_argument("--workdir", required=True,
                   help="training workdir (checkpoint restore; also "
                        "supplies variables for --stablehlo)")
    p.add_argument("--stablehlo", default=None,
                   help="serve this exported blob instead of re-jitting "
                        "the checkpoint (fixed batch = export batch)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="0 = pick a free port")
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="batch drain window: latency floor under load, "
                        "batching opportunity at low load")
    p.add_argument("--buckets", default=None,
                   help="comma-separated batch buckets (default: powers "
                        "of two up to --max-batch)")
    p.add_argument("--max-queue", type=int, default=256,
                   help="admission bound; beyond this requests shed 429")
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="dispatched-but-undrained batch window: 1 = "
                        "synchronous, 2 = overlap batch N+1 formation/"
                        "H2D with batch N compute (docs/SERVING.md)")
    p.add_argument("--wire-dtype", choices=("uint8", "float32"),
                   default="uint8",
                   help="client wire format: uint8 = raw 0-255 pixels, "
                        "normalization runs on device inside the bucket "
                        "programs (4x smaller H2D; the default); "
                        "float32 = host-preprocessed floats (the "
                        "pre-uint8 contract).  StableHLO blobs always "
                        "serve their exported float32 signature")
    p.add_argument("--infer-dtype",
                   choices=("float32", "bfloat16", "int8"),
                   default="float32",
                   help="on-device compute dtype: bfloat16 casts params "
                        "once at load and runs bucket programs in bf16 "
                        "with float32 outputs (docs/SERVING.md bf16 "
                        "caveats); int8 post-training-quantizes weights "
                        "at load (per-channel scales, calibrated "
                        "activation scale, fused Pallas ingest, f32 "
                        "outputs — docs/SERVING.md int8 section); "
                        "checkpoint path only")
    p.add_argument("--calib-batches", type=int, default=2,
                   help="int8 calibration: batches run through the "
                        "instrumented forward to collect activation "
                        "absmax ranges (--infer-dtype int8 only)")
    p.add_argument("--calib-dir", default=None,
                   help="int8 calibration: directory of held-out uint8 "
                        "*.npy images (HWC or NHWC); default = "
                        "deterministic synthetic batches — fine for "
                        "latency work, use real data before trusting "
                        "the accuracy gate (docs/SERVING.md)")
    p.add_argument("--serve-devices", type=int, default=1,
                   help="replicate the engine over this many local "
                        "devices behind one queue (0 = all; default 1 "
                        "= single-device engine); params are copied "
                        "per device once, batches route to the least-"
                        "loaded replica")
    p.add_argument("--shard-batches", action="store_true",
                   help="instead of replicating, shard each padded "
                        "batch across the data axis of a mesh over "
                        "--serve-devices devices (0/1 = all) — one "
                        "logical big batch uses every chip; buckets "
                        "become multiples of the device count")
    p.add_argument("--mesh", default=None,
                   help="2-D data×model serving mesh as 'D,M' axis "
                        "sizes (needs D×M local devices): batches "
                        "split D ways over data, params shard M ways "
                        "over model per --partition-rules; buckets "
                        "become multiples of D (subsumes "
                        "--shard-batches: 'N,1' is pure batch "
                        "sharding)")
    p.add_argument("--partition-rules", default=None,
                   help="how --mesh lays params over the model axis: "
                        "a built-in table name ('classifier', 'gan') "
                        "or ';'-separated regex=axes entries matched "
                        "against /-joined param paths, e.g. "
                        "'head/kernel=-,model;.*=' (default: shard "
                        "the first dim ≥1024 divisible by the model "
                        "axis, replicate the rest)")
    p.add_argument("--partition-strict", action="store_true",
                   help="every param leaf must match exactly one "
                        "--partition-rules entry (layout drift fails "
                        "at load, not silently at runtime)")
    p.add_argument("--partition-min-dim", type=int, default=1024,
                   help="fallback sharder only touches dims >= this "
                        "(small leaves replicate — sharding them "
                        "trades ICI latency for no HBM win); lower "
                        "it for small test models")
    p.add_argument("--warmup", action="store_true",
                   help="compile every bucket before accepting traffic")
    p.add_argument("--verbose", action="store_true",
                   help="per-request HTTP access logs")
    # -- fault tolerance (docs/SERVING.md "Failure model & operations") --
    p.add_argument("--faults", default=None,
                   help="deterministic fault-injection spec, e.g. "
                        "'compute:exception:times=1;d2h:latency:"
                        "delay_ms=20' (default: env DVT_SERVE_FAULTS; "
                        "empty = disabled)")
    p.add_argument("--fault-seed", type=int, default=0,
                   help="seed for probabilistic (p=) fault firing")
    p.add_argument("--watchdog-interval-ms", type=float, default=50.0,
                   help="supervision tick; 0 disables the watchdog "
                        "(thread restarts + exec-timeout fast-fail)")
    p.add_argument("--restart-budget", type=int, default=3,
                   help="watchdog thread restarts before the engine "
                        "goes sticky-DEAD (healthz 503)")
    p.add_argument("--exec-timeout-k", type=float, default=10.0,
                   help="a batch older than k × its bucket's exec EWMA "
                        "fast-fails the in-flight window")
    p.add_argument("--exec-timeout-min-s", type=float, default=2.0,
                   help="exec-timeout floor (also the pre-EWMA bound)")
    p.add_argument("--retry-budget", type=int, default=16,
                   help="bisect-retry executions per failed batch before "
                        "the remainder is quarantined")
    p.add_argument("--degraded-after", type=int, default=1,
                   help="consecutive batch failures before DEGRADED "
                        "(healthz 503)")
    p.add_argument("--dead-after", type=int, default=5,
                   help="consecutive batch failures before DEAD")
    # -- model control plane (docs/SERVING.md "Model lifecycle") --
    p.add_argument("--hbm-budget-mb", type=float, default=0.0,
                   help="device-memory byte budget for the weight "
                        "cache: least-recently-served models spill "
                        "their params to host RAM and re-admit on "
                        "demand (0 = unbounded; --models only)")
    p.add_argument("--canary-frac", type=float, default=0.1,
                   help="fraction of live traffic a reloading version "
                        "serves while in CANARY (deterministic every "
                        "1/frac-th request)")
    p.add_argument("--canary-min-requests", type=int, default=20,
                   help="canary answers required before the promote "
                        "gates are judged")
    p.add_argument("--canary-max-error-rate", type=float, default=0.0,
                   help="auto-rollback when the canary error rate "
                        "(failures, quarantines, NaN outputs) exceeds "
                        "this")
    p.add_argument("--canary-max-p99-ratio", type=float, default=3.0,
                   help="auto-rollback when canary p99 latency exceeds "
                        "this multiple of the active version's")
    p.add_argument("--shadow-frac", type=float, default=0.0,
                   help="before CANARY, duplicate this fraction of live "
                        "requests onto the candidate, compare top-1 "
                        "agreement, and DISCARD the outputs (0 skips "
                        "the shadow phase)")
    p.add_argument("--phase-timeout-s", type=float, default=30.0,
                   help="max seconds a shadow/canary phase may wait for "
                        "its request quota before rolling back")
    # -- continuous deploy pipeline (docs/DEPLOY.md) --
    p.add_argument("--watch", action="store_true",
                   help="watch each model's <workdir>/<name> for new "
                        "checkpoints (debounced across two polls so an "
                        "in-progress async save never half-deploys), "
                        "gate them on held-out data, and roll passing "
                        "candidates through shadow/canary/promote "
                        "automatically (--models only)")
    p.add_argument("--watch-interval-s", type=float, default=2.0,
                   help="checkpoint-fingerprint poll interval")
    p.add_argument("--gate-dir", default=None,
                   help="held-out eval set for the deploy accuracy "
                        "gate: uint8 *.npy images (HWC or NHWC) plus "
                        "an optional labels.txt (one int per image); "
                        "without labels the gate scores top-1 "
                        "AGREEMENT against the active version; default "
                        "= deterministic synthetic batches (NaN screen "
                        "+ agreement only)")
    p.add_argument("--gate-min-agreement", type=float, default=0.8,
                   help="label-free gate: minimum candidate-vs-active "
                        "top-1 agreement to deploy")
    p.add_argument("--min-replicas", type=int, default=0,
                   help="boot each model's engine with this many "
                        "per-device replicas — the autoscaler's floor "
                        "(0 = use --serve-devices; --models only)")
    p.add_argument("--max-replicas", type=int, default=0,
                   help="autoscale replicas up to this ceiling on "
                        "queue-pressure, back down to --min-replicas "
                        "when idle (0 disables autoscaling; --models "
                        "only)")
    p.add_argument("--drain-deadline", type=float, default=5.0,
                   help="shutdown grace: reject new submits immediately, "
                        "finish admitted work up to this many seconds")
    p.add_argument("--max-body-mb", type=float, default=32.0,
                   help="reject request bodies over this size with 413")
    p.add_argument("--socket-timeout-s", type=float, default=30.0,
                   help="per-connection socket timeout: a stalled "
                        "client (slow-loris) is closed / answered 408 "
                        "instead of pinning a handler thread; 0 "
                        "disables")
    # -- async edge (docs/SERVING.md "Async edge, response cache &
    #    tenant QoS") --
    p.add_argument("--thread-server", action="store_true",
                   help="serve with the original thread-per-request "
                        "ThreadingHTTPServer instead of the selector "
                        "event loop (the A/B baseline in docs/PERF.md; "
                        "no keep-alive pooling, no connection bound)")
    p.add_argument("--max-connections", type=int, default=1024,
                   help="edge loop: open-connection ceiling — at "
                        "capacity the oldest fully-idle keep-alive "
                        "connection is evicted, else accepting pauses "
                        "until a slot frees")
    p.add_argument("--http-workers", type=int, default=8,
                   help="edge loop: worker threads running handler "
                        "logic off the event loop")
    p.add_argument("--response-cache-mb", type=float, default=0.0,
                   help="content-addressed response cache budget: "
                        "identical payloads to the same model VERSION "
                        "(wire/infer dtype included in the key) answer "
                        "from memory; promote/rollback changes the "
                        "version digest so stale hits are impossible "
                        "(0 = off)")
    p.add_argument("--qos", default=None,
                   help="per-tenant QoS spec, e.g. 'premium:rate=0,"
                        "shed_at=1.0;standard:rate=200,burst=50,"
                        "shed_at=0.8,tenants=acme|globex;default="
                        "standard' — X-DVT-Tenant maps tenants to "
                        "classes with token-bucket quotas and "
                        "pressure-weighted shedding (docs/SERVING.md; "
                        "empty = off)")
    # -- confidence-routed cascade (docs/SERVING.md "Cascaded
    #    serving") --
    p.add_argument("--cascade", default=None,
                   help="'t0:t1:...:big' — route classify/detect "
                        "requests addressed to the BIG model through "
                        "the chain of cheaper tiers first, escalating "
                        "past each hop whose confidence falls below "
                        "that hop's threshold, calibrated from live "
                        "tier-vs-big dual-run samples; every name "
                        "must appear in --models and share one verb "
                        "(serve/cascade.py; an uncalibrated hop "
                        "escalates through — fully uncalibrated = "
                        "all-big)")
    p.add_argument("--cascade-min-agreement", type=float, default=0.98,
                   help="calibration target: smallest confidence "
                        "threshold whose measured front-vs-big top-1 "
                        "agreement (above it) still clears this")
    p.add_argument("--cascade-sample-period", type=int, default=10,
                   help="every N-th cascade request dual-runs BOTH "
                        "tiers to feed the agreement histogram (the "
                        "big answer is returned, so sampling costs no "
                        "correctness)")
    p.add_argument("--cascade-min-sample", type=int, default=200,
                   help="calibration samples required before any "
                        "traffic may stop at the front tier; below it "
                        "the cascade fails closed to all-big")
    p.add_argument("--cascade-topk", type=int, default=5,
                   help="entries in the cheap tiers' fused device-side "
                        "top-k confidence epilogue (bounds top_k in "
                        "cheap-tier-served responses)")
    p.add_argument("--cascade-quant-front", action="store_true",
                   help="serve tier 0 with int8-resident weights: PTQ "
                        "at boot (serve/quant.py) calibrated on "
                        "--calib-dir, falling back to the --gate-dir "
                        "holdout, then deterministic synthetic batches "
                        "— the cheapest front the stack can build "
                        "without retraining")
    p.add_argument("--cascade-per-class", action="store_true",
                   help="calibrate a per-CLASS threshold axis at every "
                        "hop: classes with enough of their own "
                        "dual-run sample get their own threshold, so "
                        "a class the cheap tier is systematically "
                        "wrong about escalates even at confidences "
                        "the pooled threshold would serve")
    p.add_argument("--cascade-class-min-sample", type=int, default=50,
                   help="dual-run samples a single class needs before "
                        "its own threshold activates (below it the "
                        "class uses the pooled threshold)")
    # -- detect decode (docs/SERVING.md "Workloads") --
    p.add_argument("--detect-decode", choices=("device", "host"),
                   default="device",
                   help="where detection models decode: 'device' "
                        "(default) fuses decode → score floor → top-k "
                        "→ class-wise NMS into the bucket programs so "
                        "D2H ships K fixed-size boxes per image (≥100× "
                        "fewer bytes than the dense pyramid at 416²); "
                        "'host' keeps the dense head outputs on the "
                        "wire and decodes per request (the pre-fusion "
                        "baseline)")
    p.add_argument("--detect-topk", type=int, default=100,
                   help="max detections per image in the fused detect "
                        "decode (the K of the fixed-size output and "
                        "the D2H bytes/image ≈ K·28)")
    p.add_argument("--detect-score-threshold", type=float, default=0.05,
                   help="compiled score FLOOR of the fused detect "
                        "decode — per-request 'score_threshold' values "
                        "above it trim host-side, values below it "
                        "clamp to it (sub-floor boxes never survived "
                        "NMS on device)")
    p.add_argument("--detect-iou-threshold", type=float, default=0.5,
                   help="IoU threshold of the fused class-wise NMS "
                        "(YOLO family; CenterNet's peak decode is "
                        "NMS-free)")
    p.add_argument("--detect-soft-nms", choices=("off", "gaussian",
                                                 "linear"),
                   default="off",
                   help="suppression rule of the fused NMS: 'off' "
                        "(default) is hard greedy NMS; 'gaussian' / "
                        "'linear' switch to Soft-NMS score decay "
                        "(Bodla et al. 2017) — overlapping boxes "
                        "survive with decayed scores instead of dying "
                        "at the IoU threshold")
    p.add_argument("--detect-soft-sigma", type=float, default=0.5,
                   help="gaussian Soft-NMS decay width "
                        "exp(-iou²/sigma); ignored for 'off'/'linear'")
    p.add_argument("--detect-max-per-class", type=int, default=0,
                   help="cap detections per class in the fused decode "
                        "output (0 = uncapped) — stops one dense class "
                        "from monopolizing the fixed K rows")
    # -- offline batch tier (docs/BATCH.md) --
    p.add_argument("--jobs-dir", default=None,
                   help="enable the offline batch-inference tier "
                        "(POST /v1/jobs) and checkpoint job progress "
                        "as append-only JSONL under this directory — "
                        "a restarted server resumes unfinished jobs "
                        "from their last durable shard ('' = enabled "
                        "but memory-only, no restart durability)")
    p.add_argument("--batch-shard-size", type=int, default=0,
                   help="images per batch job shard — the durability "
                        "AND scheduling unit (0 = --max-batch, one "
                        "engine cohort; the worst interference any "
                        "interactive request can see)")
    p.add_argument("--batch-interval-ms", type=float, default=20.0,
                   help="batch scheduler poll pacing while deferred "
                        "behind interactive load")
    p.add_argument("--batch-max-depth", type=int, default=0,
                   help="max interactive queue depth at which a batch "
                        "shard may still be submitted (default 0: any "
                        "waiting interactive request parks the batch "
                        "tier)")
    p.add_argument("--batch-pressure-ms", type=float, default=10.0,
                   help="interactive pressure ceiling (queue_depth x "
                        "exec EWMA, ms) for the trough check; above "
                        "it batch work defers")
    p.add_argument("--batch-cache-shards", type=int, default=64,
                   help="per-job completed-shard payloads kept in "
                        "memory; with --jobs-dir the rest spill to the "
                        "JSONL ledger (LRU) and GET /v1/jobs/<id>/"
                        "results streams them back from disk (0 = "
                        "unbounded; memory-only stores never evict)")
    # -- overload brownout (docs/SERVING.md "Overload & brownout") --
    p.add_argument("--brownout", action="store_true",
                   help="arm the brownout degradation ladder: a "
                        "per-process controller polls queue pressure / "
                        "engine occupancy / shed rate and steps "
                        "L0→L3 — L1 sheds optional work (cascade "
                        "sampling, shadow duplication, batch tier, "
                        "slow traces), L2 degrades quality (forced "
                        "front-tier answers, stale cache hits, marked "
                        "X-DVT-Degraded), L3 hard-sheds lower QoS "
                        "classes so premium tenants keep answering "
                        "(docs/SERVING.md runbook)")
    p.add_argument("--brownout-interval-ms", type=float, default=250.0,
                   help="ladder evaluation tick")
    p.add_argument("--brownout-l1-ms", type=float, default=50.0,
                   help="queue pressure (depth × exec EWMA, ms) that "
                        "votes for L1")
    p.add_argument("--brownout-l2-ms", type=float, default=150.0,
                   help="queue pressure that votes for L2")
    p.add_argument("--brownout-l3-ms", type=float, default=400.0,
                   help="queue pressure that votes for L3")
    p.add_argument("--brownout-occupancy", type=float, default=0.97,
                   help="engine occupancy above this votes ≥L1")
    p.add_argument("--brownout-shed-rate", type=float, default=0.10,
                   help="interval shed fraction above this votes ≥L2")
    p.add_argument("--brownout-up-window", type=int, default=2,
                   help="consecutive hot ticks before the ladder "
                        "ENGAGES (jumps straight to the target level)")
    p.add_argument("--brownout-down-window", type=int, default=8,
                   help="consecutive cool ticks before the ladder "
                        "releases ONE level (hysteresis: engage fast, "
                        "release slow)")
    p.add_argument("--brownout-cooldown-s", type=float, default=2.0,
                   help="minimum dwell after any transition before a "
                        "release may happen")
    p.add_argument("--brownout-force", type=int, default=-1,
                   help="pin the ladder at this level at boot (0..3; "
                        "-1 = signals in control; also settable live "
                        "via POST /v1/brownout {\"force\": N|null})")
    # -- observability (docs/OBSERVABILITY.md) --
    p.add_argument("--log-level", default="info",
                   choices=("debug", "info", "warning", "error"),
                   help="structured-log threshold for the dvt.serve.* "
                        "loggers (one JSON line per event on stderr)")
    p.add_argument("--trace-ring", type=int, default=256,
                   help="per-request spans kept in memory for "
                        "GET /v1/traces")
    p.add_argument("--slow-trace-ms", type=float, default=250.0,
                   help="requests slower than this emit their full span "
                        "as a slow_request log line; 0 disables")
    p.add_argument("--no-trace", action="store_true",
                   help="disable per-request span collection entirely "
                        "(tracing costs ~one dict per request; this "
                        "removes even that)")
    args = p.parse_args(argv)
    if not args.model and not args.models:
        p.error("one of -m/--model or --models is required")
    if args.cascade and not args.models:
        p.error("--cascade routes across the multi-model plane; use "
                "--models front,big")

    from deep_vision_tpu.core.compile_cache import enable_compile_cache
    from deep_vision_tpu.obs.log import configure_logging

    configure_logging(args.log_level)
    enable_compile_cache()
    engine, server = build_server(args)
    sm = engine.model
    served = args.models or args.model
    print(f"[serve] {served} listening on "
          f"http://{server.host}:{server.port} "
          f"(buckets={engine.buckets}, max_wait={args.max_wait_ms}ms, "
          f"max_queue={args.max_queue}, "
          f"pipeline_depth={engine.pipeline_depth}, "
          f"wire={sm.wire_dtype}, infer={sm.infer_dtype})")
    if args.models:
        budget = getattr(args, "hbm_budget_mb", 0.0)
        print(f"[serve] model control plane: {served} "
              f"(hbm_budget={budget or 'unbounded'}"
              f"{'MB' if budget else ''}, "
              f"canary_frac={args.canary_frac}, "
              f"shadow_frac={args.shadow_frac}) — reload: curl -XPOST "
              f"http://{server.host}:{server.port}"
              f"/v1/models/<name>/reload")
    cascade = getattr(server.httpd, "cascade", None)
    if cascade is not None:
        print(f"[serve] cascade: "
              f"{' -> '.join(cascade.spec.tiers)} — requests "
              f"for '{cascade.spec.big}' answer from the cheapest "
              f"tier whose calibrated confidence allows "
              f"(min_agreement={cascade.spec.min_agreement}, "
              f"sample_period={cascade.spec.sample_period}, "
              f"min_sample={cascade.spec.min_sample}"
              + (", per_class" if cascade.spec.per_class else "")
              + (", int8 front"
                 if getattr(args, "cascade_quant_front", False)
                 else "")
              + "; uncalibrated hops escalate through)")
    deploy = getattr(server.httpd, "deploy", None)
    if deploy is not None:
        bits = []
        if deploy.watcher is not None:
            bits.append(f"watch every {args.watch_interval_s}s"
                        + (f", gate={args.gate_dir}" if args.gate_dir
                           else ", gate=synthetic"))
        if deploy.autoscalers:
            bits.append(f"autoscale {args.min_replicas or 1}.."
                        f"{args.max_replicas} replicas")
        print(f"[serve] deploy pipeline: {'; '.join(bits)} — history: "
              f"curl http://{server.host}:{server.port}"
              f"/v1/deploy/<name>/history")
    if hasattr(engine, "replicas"):
        print(f"[serve] {len(engine.replicas)} replicas: "
              + ", ".join(r.model.placement_desc() or "default"
                          for r in engine.replicas))
    elif getattr(engine.model, "placement", None) is not None:
        mesh_shape = engine.model.mesh_shape() \
            if hasattr(engine.model, "mesh_shape") else None
        if mesh_shape and mesh_shape.get("model", 1) > 1:
            print(f"[serve] 2-D mesh "
                  f"{mesh_shape.get('data', 1)}×"
                  f"{mesh_shape.get('model', 1)} data×model: "
                  f"{engine.model.placement_desc()}; per-chip params "
                  f"{engine.model.param_bytes():,} B of "
                  f"{engine.model.param_global_bytes():,} B logical")
        else:
            print("[serve] sharded batches: "
                  f"{engine.model.placement_desc()}")
    bo = getattr(server.httpd, "brownout", None)
    if bo is not None:
        print(f"[serve] brownout ladder armed: "
              f"L1@{args.brownout_l1_ms:g}ms "
              f"L2@{args.brownout_l2_ms:g}ms "
              f"L3@{args.brownout_l3_ms:g}ms queue pressure "
              f"(occupancy>{args.brownout_occupancy:g} → ≥L1, "
              f"shed_rate>{args.brownout_shed_rate:g} → ≥L2) — "
              f"override: curl -XPOST http://{server.host}:"
              f"{server.port}/v1/brownout -d '{{\"force\": 2}}'")
    jobs = getattr(server.httpd, "jobs", None)
    if jobs is not None:
        print(f"[serve] batch tier: POST http://{server.host}:"
              f"{server.port}/v1/jobs "
              f"(jobs_dir={jobs.root or 'memory-only'}, "
              f"shard_size={jobs.default_shard_size}, "
              f"max_depth={args.batch_max_depth}, "
              f"pressure={args.batch_pressure_ms}ms — docs/BATCH.md)")
    if engine.faults.enabled:
        print(f"[serve] FAULT INJECTION ACTIVE: '{engine.faults.spec}' "
              f"(seed {engine.faults.seed})")
    print(f"[serve] try: curl http://{server.host}:{server.port}/v1/healthz")
    print(f"[serve] metrics: curl http://{server.host}:{server.port}/metrics")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("[serve] shutting down")
    finally:
        if deploy is not None:
            # the watcher/autoscaler threads stop BEFORE the engines
            # drain — no scale action or rollout races the shutdown
            deploy.stop()
        batch_sched = getattr(server.httpd, "batch_sched", None)
        if batch_sched is not None:
            # likewise the batch scheduler: no shard submit may race
            # engine.stop(); in-flight shard results past this point
            # shed and replay from the JSONL checkpoint on next boot
            batch_sched.stop()
        brownout = getattr(server.httpd, "brownout", None)
        if brownout is not None:
            # the ladder polls engine signals — stop it before the
            # engines it reads drain away
            brownout.stop()
        server.shutdown()
        engine.stop(drain_deadline=args.drain_deadline)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
