"""Inference CLI — the reference's per-model demo paths in one place:
classification notebooks (ResNet/pytorch/notebooks/*), YOLO demo + NMS
(YOLO/tensorflow/postprocess.py via demo_mscoco.ipynb), CycleGAN sample
generation (CycleGAN/tensorflow/inference.py:11-77), DCGAN sampling
(DCGAN/tensorflow/inference.py:7-32), plus StableHLO export
(the TFLite path, CycleGAN/tensorflow/convert.py:7-16).

    python -m deep_vision_tpu.cli.infer classify -m resnet50 --workdir runs/x \\
        --images a.jpg b.jpg
    python -m deep_vision_tpu.cli.infer detect -m yolov3_voc --workdir ... \\
        --images street.jpg --score-threshold 0.3
    python -m deep_vision_tpu.cli.infer sample -m dcgan --workdir ... -n 16 \\
        --out samples.png
    python -m deep_vision_tpu.cli.infer export -m resnet50 --workdir ... \\
        --out model.stablehlo
"""

from __future__ import annotations

import argparse
import os


def _load_state(cfg, workdir):
    # shared restore path (core/restore.py) — same code the serving
    # registry uses, so CLI demos and the serve engine can't drift
    from deep_vision_tpu.core.restore import load_state

    return load_state(cfg, workdir, tag="infer")


def _read_image(path, size, channels=3):
    import numpy as np
    from PIL import Image

    if channels == 1:  # grayscale models (LeNet): MNIST-style preprocessing
        from deep_vision_tpu.data.mnist import preprocess

        img = np.asarray(Image.open(path).convert("L").resize((size - 4,
                                                               size - 4)))
        return preprocess(img[None])[0][:size, :size]
    img = np.asarray(Image.open(path).convert("RGB"))
    from deep_vision_tpu.data.transforms import eval_transform, imagenet_resize_for

    return eval_transform(img, size, imagenet_resize_for(size))


def main(argv=None):
    p = argparse.ArgumentParser(description="deep_vision_tpu inference")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("classify", "detect", "pose", "sample", "translate",
                 "export", "eval"):
        s = sub.add_parser(name)
        s.add_argument("-m", "--model", required=True)
        s.add_argument("--workdir", required=True)
        if name in ("classify", "detect", "pose", "translate"):
            s.add_argument("--images", nargs="+", required=True)
        if name == "detect":
            s.add_argument("--score-threshold", type=float, default=0.3)
        if name in ("detect", "pose"):
            s.add_argument("--out", default=None,
                           help="write annotated image(s) — boxes/keypoints "
                                "drawn on the ORIGINAL photo (the demo-"
                                "notebook role); multiple inputs get "
                                "-<stem> suffixes")
            s.add_argument("--names", default=None,
                           help="class-names file (one per line; default: "
                                "VOC names for 20-class models)")
        if name == "eval":
            s.add_argument("--pretrained", default=None,
                           help="evaluate imported torch-format weights "
                                "(.pth) instead of a workdir checkpoint — "
                                "the import→eval harness; expected numbers "
                                "per recipe: docs/ACCURACY.md")
            s.add_argument("--data-root", default=None,
                           help="dvrec shards (cli.prepare_data output), "
                                "flat image dir, or MNIST idx dir")
            s.add_argument("--synthetic", action="store_true")
            s.add_argument("--synthetic-size", type=int, default=64)
            s.add_argument("--batch-size", type=int, default=None)
            s.add_argument("--split", default="val")
            s.add_argument("--num-workers", type=int, default=4)
            s.add_argument("--tf-preprocessing", action="store_true",
                           help="evaluate with the TF 'ResNet "
                                "preprocessing' pipeline (match what the "
                                "checkpoint was trained with)")
        if name == "sample":
            s.add_argument("-n", type=int, default=16)
            s.add_argument("--out", default="samples.png")
        if name == "translate":
            s.add_argument("--direction", default="a2b")
            s.add_argument("--out-dir", default="translated")
        if name == "export":
            s.add_argument("--out", default="model.stablehlo")
    args = p.parse_args(argv)

    from deep_vision_tpu.core.compile_cache import enable_compile_cache

    enable_compile_cache()

    from deep_vision_tpu.core.config import get_config

    cfg = get_config(args.model)
    import jax.numpy as jnp
    import numpy as np

    if args.cmd == "classify":
        model, state = _load_state(cfg, args.workdir)
        x = jnp.asarray(np.stack([_read_image(f, cfg.image_size,
                                              cfg.channels)
                                  for f in args.images]))
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, x, train=False)
        top5 = np.argsort(np.asarray(logits), -1)[:, -5:][:, ::-1]
        for f, t in zip(args.images, top5):
            print(f"{f}: top-5 classes {t.tolist()}")
    elif args.cmd == "detect":
        from deep_vision_tpu.tasks.detection import postprocess

        model, state = _load_state(cfg, args.workdir)
        # detection uses [0,1] inputs, not imagenet-normalized
        from PIL import Image

        from deep_vision_tpu.data.detection import resize_square

        raw = [resize_square(np.asarray(Image.open(f).convert("RGB")),
                             cfg.image_size).astype(np.float32) / 255.0
               for f in args.images]
        x = jnp.asarray(np.stack(raw))
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        outs = model.apply(variables, x, train=False)
        boxes, scores, classes, valid = postprocess(
            outs, cfg.num_classes, score_threshold=args.score_threshold)
        names = _class_names(args, cfg)
        for i, f in enumerate(args.images):
            n = int(np.asarray(valid[i]).sum())
            print(f"{f}: {n} detections")
            for j in range(n):
                b = np.asarray(boxes[i, j]).round(3).tolist()
                name = names[int(classes[i, j])] if names else \
                    int(classes[i, j])
                print(f"  class={name} "
                      f"score={float(scores[i, j]):.3f} box={b}")
            if args.out:
                from deep_vision_tpu.viz import draw_detections

                orig = np.asarray(Image.open(f).convert("RGB"))
                ann = draw_detections(
                    orig, np.asarray(boxes[i, :n]), np.asarray(scores[i, :n]),
                    np.asarray(classes[i, :n]), class_names=names)
                dst = _out_path(args.out, f, i, len(args.images))
                Image.fromarray(ann).save(dst)
                print(f"  annotated -> {dst}")
    elif args.cmd == "pose":
        # Hourglass demo path (demo_hourglass_pose.ipynb): heatmap argmax
        # → keypoints drawn on the original photo
        from PIL import Image

        from deep_vision_tpu.data.detection import resize_square
        from deep_vision_tpu.tasks.pose import heatmap_argmax
        from deep_vision_tpu.viz import draw_keypoints

        model, state = _load_state(cfg, args.workdir)
        raw = [resize_square(np.asarray(Image.open(f).convert("RGB")),
                             cfg.image_size).astype(np.float32) / 255.0
               for f in args.images]
        x = jnp.asarray(np.stack(raw))
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        heat = np.asarray(model.apply(variables, x, train=False)[-1])
        for i, f in enumerate(args.images):
            kp_hm = heatmap_argmax(heat[i])            # (K, 2) heatmap px
            orig = np.asarray(Image.open(f).convert("RGB"))
            oh, ow = orig.shape[:2]
            hh, hw = heat.shape[1:3]
            kp_img = kp_hm * np.array([ow / hw, oh / hh], np.float32)
            conf = heat[i].max(axis=(0, 1))            # per-joint peak
            print(f"{f}: " + " ".join(
                f"j{k}=({kp_img[k, 0]:.0f},{kp_img[k, 1]:.0f})"
                for k in range(len(kp_img))))
            if args.out:
                ann = draw_keypoints(orig, kp_img, visible=(conf > 0.2))
                dst = _out_path(args.out, f, i, len(args.images))
                Image.fromarray(ann).save(dst)
                print(f"  annotated -> {dst}")
    elif args.cmd == "sample":
        import jax

        from deep_vision_tpu.core.adversarial import AdversarialTrainer
        from deep_vision_tpu.models import gan as gan_models
        from deep_vision_tpu.tasks.gan import DCGANTask

        task = DCGANTask(gan_models.DCGANGenerator(),
                         gan_models.DCGANDiscriminator(), opt=cfg.optimizer)
        trainer = AdversarialTrainer(cfg, task, workdir=args.workdir)
        states = task.init_states(
            jax.random.PRNGKey(0),
            {"image": np.zeros((1, cfg.image_size, cfg.image_size,
                                cfg.channels), np.float32)})
        states, _ = trainer.checkpointer.restore_tree(states)
        imgs = task.sample(states, args.n, jax.random.PRNGKey(1))
        _save_grid(imgs, args.out)
        print(f"wrote {args.n} samples to {args.out}")
    elif args.cmd == "translate":
        import jax

        from deep_vision_tpu.core.adversarial import AdversarialTrainer
        from deep_vision_tpu.models import gan as gan_models
        from deep_vision_tpu.tasks.gan import CycleGANTask
        from deep_vision_tpu.data.detection import resize_square
        from PIL import Image

        task = CycleGANTask(lambda: gan_models.CycleGANGenerator(),
                            lambda: gan_models.PatchGANDiscriminator(),
                            opt=cfg.optimizer)
        trainer = AdversarialTrainer(cfg, task, workdir=args.workdir)
        sample = np.zeros((1, cfg.image_size, cfg.image_size, 3), np.float32)
        states = task.init_states(jax.random.PRNGKey(0),
                                  {"image_a": sample, "image_b": sample})
        states, _ = trainer.checkpointer.restore_tree(states)
        os.makedirs(args.out_dir, exist_ok=True)
        for f in args.images:
            img = resize_square(np.asarray(Image.open(f).convert("RGB")),
                                cfg.image_size)
            x = img.astype(np.float32) / 127.5 - 1.0
            out = task.translate(states, x[None], args.direction)[0]
            out8 = ((out + 1) * 127.5).clip(0, 255).astype(np.uint8)
            dst = os.path.join(args.out_dir, os.path.basename(f))
            Image.fromarray(out8).save(dst)
            print(f"{f} -> {dst}")
    elif args.cmd == "eval":
        return _cmd_eval(args, cfg)
    elif args.cmd == "export":
        from deep_vision_tpu.core.export import export_forward

        model, state = _load_state(cfg, args.workdir)
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        n = export_forward(model, variables,
                           (1, cfg.image_size, cfg.image_size, cfg.channels),
                           args.out)
        print(f"exported {n} bytes of StableHLO to {args.out}")
    return 0


def _cmd_eval(args, cfg):
    """Held-out evaluation from a restored checkpoint: detection/centernet
    report VOC mAP@0.5 AND COCO mAP@[.5:.95] (the evaluation the
    reference's YOLO README lists as "WIP", finished to the modern
    standard), classification reports top-1/top-5 (the reference's
    ``validate()``), pose reports val loss."""
    from deep_vision_tpu.core.trainer import Trainer

    batch = args.batch_size or cfg.eval_batch_size
    if cfg.task == "classification":
        task, loader, n = _classification_eval_loader(args, cfg, batch)
    elif cfg.task == "pose":
        task, loader, n = _pose_eval_loader(args, cfg, batch)
    elif cfg.task in ("detection", "centernet"):
        task, loader, n = _detection_eval_loader(args, cfg, batch)
    else:
        raise SystemExit(f"eval does not support task '{cfg.task}'")
    if args.pretrained:
        model, state = _load_pretrained_state(cfg, args)
    else:
        model, state = _load_state(cfg, args.workdir)
    trainer = Trainer(cfg, model, task, workdir=args.workdir)
    # the restored state lives on one device; eval batches shard over the
    # full mesh — replicate or the jit rejects the device mismatch
    from deep_vision_tpu.parallel import replicate

    state = replicate(state, trainer.mesh)
    metrics = trainer.evaluate(state, loader)
    print(f"eval[{args.split}] n={n} "
          + " ".join(f"{k}={v:.4f}" for k, v in sorted(metrics.items())))
    return 0


def _load_pretrained_state(cfg, args):
    """Fresh state + imported torch-format weights (the import→eval
    harness, docs/ACCURACY.md): no checkpoint needed, so a user can verify
    a published recipe's top-1/top-5 straight from its .pth file."""
    import functools

    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.core.optim import build_optimizer
    from deep_vision_tpu.core.state import TrainState
    from deep_vision_tpu.models.pretrained import (
        ARCH_IMPORTERS,
        import_pretrained,
    )

    if args.model not in ARCH_IMPORTERS:
        raise SystemExit(
            f"--pretrained supports {sorted(ARCH_IMPORTERS)} (torch-format "
            f"checkpoints); '{args.model}' has a different param tree")
    model = cfg.model()
    x = jnp.zeros((1, cfg.image_size, cfg.image_size, cfg.channels))
    variables = jax.jit(functools.partial(model.init, train=False))(
        {"params": jax.random.PRNGKey(0)}, x)
    fresh = {"params": variables["params"],
             "batch_stats": variables.get("batch_stats", {})}
    merged, head_kept = import_pretrained(args.pretrained, args.model, fresh)
    head = ("with checkpoint head" if head_kept
            else "head left fresh (class-count mismatch)")
    print(f"[eval] imported {args.model} weights from {args.pretrained} "
          f"({head})")
    state = TrainState.create(
        apply_fn=model.apply, params=merged["params"],
        tx=build_optimizer(cfg.optimizer),
        batch_stats=merged["batch_stats"])
    return model, state


def _classification_eval_loader(args, cfg, batch):
    from deep_vision_tpu.tasks.classification import ClassificationTask

    task = ClassificationTask(cfg.num_classes, cfg.label_smoothing)
    if args.synthetic:
        from deep_vision_tpu.data.loader import ArrayLoader
        from deep_vision_tpu.data.synthetic import synthetic_classification

        data = synthetic_classification(args.synthetic_size, cfg.image_size,
                                        cfg.channels, cfg.num_classes, seed=2)
        return task, ArrayLoader(data, batch, shuffle=False, drop_last=False,
                                 pad_last=True), args.synthetic_size
    assert args.data_root, "--data-root required without --synthetic"
    from deep_vision_tpu.cli.train import build_classification_val_loader

    # same wiring as the train CLI's val loader (records-vs-folder/MNIST
    # dispatch, resize formula, preprocessing choice) so eval can't drift
    loader, n = build_classification_val_loader(
        cfg, args.data_root, args.split, batch,
        num_workers=args.num_workers,
        preprocessing="tf" if args.tf_preprocessing else "torch")
    return task, loader, n


def _pose_eval_loader(args, cfg, batch):
    from deep_vision_tpu.data.pose import PoseLoader, synthetic_pose_dataset
    from deep_vision_tpu.tasks.pose import PoseTask

    task = PoseTask()
    if args.synthetic:
        samples = synthetic_pose_dataset(args.synthetic_size, cfg.image_size,
                                         cfg.num_classes, seed=2)
    else:
        from deep_vision_tpu.data.records import load_pose_records

        assert args.data_root, "--data-root required without --synthetic"
        samples = load_pose_records(args.data_root, args.split)
    loader = PoseLoader(samples, batch, cfg.image_size, cfg.image_size // 4,
                        cfg.num_classes, train=False)
    return task, loader, len(samples)


def _detection_eval_loader(args, cfg, batch):
    from deep_vision_tpu.data.detection import (
        CenterNetLoader,
        DetectionLoader,
        synthetic_detection_dataset,
    )

    if cfg.task == "centernet":
        from deep_vision_tpu.tasks.centernet import CenterNetTask

        task, loader_cls = CenterNetTask(cfg.num_classes), CenterNetLoader
    else:
        from deep_vision_tpu.tasks.detection import YoloTask

        task, loader_cls = YoloTask(cfg.num_classes), DetectionLoader
    if args.synthetic:
        samples = synthetic_detection_dataset(
            args.synthetic_size, cfg.image_size, min(cfg.num_classes, 3),
            seed=2)
    else:
        from deep_vision_tpu.data.records import load_detection_records

        assert args.data_root, "--data-root required without --synthetic"
        samples = load_detection_records(args.data_root, args.split)
    loader = loader_cls(samples, batch, cfg.num_classes, cfg.image_size,
                        train=False)
    return task, loader, len(samples)


def _class_names(args, cfg) -> list[str] | None:
    """--names file, else VOC names for 20-class models, else None
    (generic ``class N`` labels)."""
    if getattr(args, "names", None):
        with open(args.names) as f:
            return [ln.strip() for ln in f if ln.strip()]
    if cfg.num_classes == 20:
        from deep_vision_tpu.data.prep import VOC_CLASSES

        return list(VOC_CLASSES)
    return None


def _out_path(out: str, src: str, i: int, n: int) -> str:
    """One input → ``out`` verbatim; several → stem-suffixed siblings."""
    if n == 1:
        return out
    base, ext = os.path.splitext(out)
    stem = os.path.splitext(os.path.basename(src))[0]
    return f"{base}-{stem}{ext or '.jpg'}"


def _save_grid(imgs, path, cols: int = 4):
    import numpy as np
    from PIL import Image

    imgs = ((np.asarray(imgs) + 1) * 127.5).clip(0, 255).astype(np.uint8)
    n, h, w, c = imgs.shape
    rows = (n + cols - 1) // cols
    grid = np.zeros((rows * h, cols * w, c), np.uint8)
    for i, im in enumerate(imgs):
        r, col = divmod(i, cols)
        grid[r * h:(r + 1) * h, col * w:(col + 1) * w] = im
    if c == 1:
        grid = grid[..., 0]
    Image.fromarray(grid).save(path)


if __name__ == "__main__":
    raise SystemExit(main())
