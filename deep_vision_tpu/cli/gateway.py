"""Gateway CLI — one endpoint over N backend serve processes.

    # two backends on this host (each a full cli.serve process) ...
    python -m deep_vision_tpu.cli.serve -m resnet50 --workdir runs/r50 \\
        --port 8001 &
    python -m deep_vision_tpu.cli.serve -m resnet50 --workdir runs/r50 \\
        --port 8002 &

    # ... behind one gateway: health-routed, retrying, failing over
    python -m deep_vision_tpu.cli.gateway --port 8000 \\
        --backend 127.0.0.1:8001 --backend 127.0.0.1:8002

    # tail hedging: duplicate slow requests to a second backend
    python -m deep_vision_tpu.cli.gateway --port 8000 \\
        --backend 127.0.0.1:8001 --backend 127.0.0.1:8002 --hedge

Clients talk to the gateway exactly like a single backend —
``/v1/classify``, ``/v1/detect``, ``/v1/healthz``, ``/v1/stats`` — and
survive any single backend dying (SIGKILL included; see
docs/SERVING.md "Cross-host gateway").  Zero-downtime restarts: POST
``/v1/drain`` on a backend, wait for the gateway to stop routing there,
restart it, repeat.
"""

from __future__ import annotations

import argparse


def build_gateway(args):
    """argparse namespace → (Gateway, GatewayServer); shared with
    ``tests/gateway_smoke.py`` so the smoke boots production wiring."""
    from deep_vision_tpu.obs.trace import Tracer
    from deep_vision_tpu.serve.faults import FaultPlane
    from deep_vision_tpu.serve.gateway import Gateway, GatewayServer

    tracer = Tracer(ring=getattr(args, "trace_ring", 256),
                    slow_ms=getattr(args, "slow_trace_ms", 250.0),
                    enabled=not getattr(args, "no_trace", False))
    fault_spec = getattr(args, "faults", None)
    faults = FaultPlane(fault_spec, getattr(args, "fault_seed", 0)) \
        if fault_spec else None
    gw = Gateway(
        list(args.backend),
        tracer=tracer,
        probe_interval_s=getattr(args, "probe_interval_ms", 250.0) / 1e3,
        probe_timeout_s=getattr(args, "probe_timeout_s", 1.0),
        request_timeout_s=getattr(args, "request_timeout_s", 30.0),
        retry_budget=getattr(args, "retry_budget", 3),
        backoff_ms=getattr(args, "backoff_ms", 10.0),
        backoff_max_ms=getattr(args, "backoff_max_ms", 250.0),
        breaker_threshold=getattr(args, "breaker_threshold", 3),
        breaker_cooldown_s=getattr(args, "breaker_cooldown_s", 1.0),
        degraded_after=getattr(args, "degraded_after", 1),
        dead_after=getattr(args, "dead_after", 5),
        hedge=getattr(args, "hedge", False),
        hedge_after_ms=getattr(args, "hedge_after_ms", None),
        affinity=getattr(args, "affinity", False),
        retry_budget_ratio=getattr(args, "retry_budget_ratio", 0.1),
        retry_budget_burst=getattr(args, "retry_budget_burst", 10.0),
        faults=faults)
    gw.start()
    socket_timeout_s = getattr(args, "socket_timeout_s", 30.0)
    server = GatewayServer(
        gw, host=args.host, port=args.port,
        verbose=getattr(args, "verbose", False),
        max_body_bytes=int(getattr(args, "max_body_mb", 32) * 2**20),
        socket_timeout_s=socket_timeout_s if socket_timeout_s > 0
        else None,
        edge=not getattr(args, "thread_server", False),
        max_connections=int(getattr(args, "max_connections", 1024)),
        http_workers=int(getattr(args, "http_workers", 8)))
    return gw, server


def main(argv=None):
    p = argparse.ArgumentParser(
        description="deep_vision_tpu serving gateway: health-routed "
                    "failover over backend serve processes")
    p.add_argument("--backend", action="append", required=True,
                   help="backend address host:port; repeat per backend")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="0 = pick a free port")
    p.add_argument("--probe-interval-ms", type=float, default=250.0,
                   help="active /v1/healthz probe period per backend — "
                        "also bounds how long a dead backend keeps "
                        "receiving first-attempt traffic")
    p.add_argument("--probe-timeout-s", type=float, default=1.0)
    p.add_argument("--request-timeout-s", type=float, default=30.0,
                   help="per-attempt backend timeout; a timeout counts "
                        "as a failure and the request fails over")
    p.add_argument("--retry-budget", type=int, default=3,
                   help="extra attempts per request after the first "
                        "(connect error / timeout / 5xx → retry on a "
                        "different backend when one is routable)")
    p.add_argument("--retry-budget-ratio", type=float, default=0.1,
                   help="per-backend retry BUDGET refill: each real "
                        "success adds this many retry tokens (capped "
                        "at --retry-budget-burst), each retried "
                        "attempt spends one — bounds the steady-state "
                        "retry RATIO, so a dying fleet sees at most "
                        "~ratio extra load instead of a retry storm "
                        "multiplying it (--retry-budget still caps "
                        "attempts per request)")
    p.add_argument("--retry-budget-burst", type=float, default=10.0,
                   help="retry-token bucket depth per backend (also "
                        "the boot balance, so cold-start blips can "
                        "retry before any success has refilled)")
    p.add_argument("--backoff-ms", type=float, default=10.0,
                   help="base retry backoff; doubles per attempt with "
                        "full jitter, capped at --backoff-max-ms")
    p.add_argument("--backoff-max-ms", type=float, default=250.0)
    p.add_argument("--breaker-threshold", type=int, default=3,
                   help="consecutive failures (probe or request) that "
                        "open a backend's circuit breaker")
    p.add_argument("--breaker-cooldown-s", type=float, default=1.0,
                   help="OPEN → HALF_OPEN delay; the next probe or one "
                        "trial request then decides close vs re-open")
    p.add_argument("--degraded-after", type=int, default=1,
                   help="consecutive failures before a backend reports "
                        "DEGRADED in /v1/stats")
    p.add_argument("--dead-after", type=int, default=5,
                   help="consecutive failures before DEAD")
    p.add_argument("--hedge", action="store_true",
                   help="tail hedging: duplicate a request to a second "
                        "backend once the primary is slower than the "
                        "gateway's observed p99; first answer wins")
    p.add_argument("--hedge-after-ms", type=float, default=None,
                   help="fixed hedge delay instead of the learned p99")
    p.add_argument("--affinity", action="store_true",
                   help="rendezvous-hash backend choice on the payload "
                        "digest: identical payloads land on the same "
                        "healthy backend, maximizing its response-cache "
                        "hit rate; failover falls to the next-highest "
                        "hash")
    p.add_argument("--thread-server", action="store_true",
                   help="serve clients with the thread-per-request "
                        "baseline instead of the selector event loop")
    p.add_argument("--max-connections", type=int, default=1024,
                   help="edge loop: open client-connection ceiling")
    p.add_argument("--http-workers", type=int, default=8,
                   help="edge loop: worker threads forwarding requests")
    p.add_argument("--max-body-mb", type=float, default=32.0)
    p.add_argument("--socket-timeout-s", type=float, default=30.0,
                   help="per-connection client socket timeout (0 "
                        "disables); same slow-loris guard as the "
                        "backends")
    p.add_argument("--verbose", action="store_true")
    # -- chaos (docs/SERVING.md "Failure model & operations") --
    p.add_argument("--faults", default=None,
                   help="deterministic gateway-hop fault spec, e.g. "
                        "'gateway:conn_reset:p=0.3' or "
                        "'gateway:blackhole:hang_s=2:times=1' — "
                        "injects NETWORK failures (conn_reset / "
                        "slow_drip / blackhole) into the gateway's "
                        "per-attempt backend calls so the breaker and "
                        "retry budget exercise their tested paths")
    p.add_argument("--fault-seed", type=int, default=0,
                   help="seed for probabilistic (p=) fault firing")
    # -- observability (docs/OBSERVABILITY.md) --
    p.add_argument("--log-level", default="info",
                   choices=("debug", "info", "warning", "error"),
                   help="structured-log threshold for the dvt.serve.* "
                        "loggers (one JSON line per event on stderr)")
    p.add_argument("--trace-ring", type=int, default=256,
                   help="per-request spans kept in memory for "
                        "GET /v1/traces")
    p.add_argument("--slow-trace-ms", type=float, default=250.0,
                   help="requests slower than this emit their full span "
                        "as a slow_request log line; 0 disables")
    p.add_argument("--no-trace", action="store_true",
                   help="disable per-request span collection")
    args = p.parse_args(argv)

    from deep_vision_tpu.obs.log import configure_logging

    configure_logging(args.log_level)
    gw, server = build_gateway(args)
    ok, health = gw.healthz()
    print(f"[gateway] listening on http://{server.host}:{server.port} "
          f"-> {len(gw.backends)} backend(s), "
          f"routable now: {health['routable'] or 'NONE'}")
    print(f"[gateway] retry_budget={gw.retry_budget} "
          f"retry_ratio={gw.retry_budget_ratio:g}"
          f"(burst {gw.retry_budget_burst:g}) "
          f"probe_interval={gw.probe_interval_s * 1e3:.0f}ms "
          f"breaker={gw.backends[0].breaker_threshold}"
          f"/{gw.backends[0].breaker_cooldown_s}s "
          f"hedge={'on' if gw.hedge else 'off'}")
    if gw.faults is not None and gw.faults.enabled:
        print(f"[gateway] FAULT INJECTION ACTIVE: '{gw.faults.spec}' "
              f"(seed {gw.faults.seed})")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("[gateway] shutting down")
    finally:
        server.shutdown()
        gw.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
