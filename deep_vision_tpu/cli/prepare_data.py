"""Dataset preparation CLI — replaces the reference's per-dataset scripts
(Datasets/*/tfrecords*.py, build_imagenet_tfrecord.py, CycleGAN
tfrecords.py/celeba.py) with one entry point:

    python -m deep_vision_tpu.cli.prepare_data voc --voc-root VOCdevkit \\
        --out ./records --split train
    python -m deep_vision_tpu.cli.prepare_data coco \\
        --annotations instances_train2017.json --images train2017 --out ...
    python -m deep_vision_tpu.cli.prepare_data mpii --annotations train.json \\
        --images images --out ...
    python -m deep_vision_tpu.cli.prepare_data imagenet --src train_flat \\
        --labels imagenet_2012_metadata.txt --out ...
    python -m deep_vision_tpu.cli.prepare_data unpaired --dir-a trainA \\
        --dir-b trainB --out ...
    python -m deep_vision_tpu.cli.prepare_data celeba --attr list_attr.txt \\
        --images img_align_celeba --out-a male --out-b female
"""

from __future__ import annotations

import argparse


def main(argv=None):
    p = argparse.ArgumentParser(description="deep_vision_tpu data prep")
    sub = p.add_subparsers(dest="cmd", required=True)

    voc = sub.add_parser("voc")
    voc.add_argument("--voc-root", required=True)
    voc.add_argument("--year", default="2007")
    voc.add_argument("--names", default=None)

    coco = sub.add_parser("coco")
    coco.add_argument("--annotations", required=True)
    coco.add_argument("--images", required=True)

    mpii = sub.add_parser("mpii")
    mpii.add_argument("--annotations", required=True)
    mpii.add_argument("--images", required=True)

    imagenet = sub.add_parser("imagenet")
    imagenet.add_argument("--src", required=True)
    imagenet.add_argument("--labels", required=True)
    imagenet.add_argument("--bbox-csv", default=None,
                          help="imagenet-bboxes output; boxes go into "
                               "record headers")
    imagenet.add_argument("--store", choices=("jpeg", "raw"), default="jpeg",
                          help="raw: decode+rescale at build time, store "
                               "uint8 — decode-free read path that feeds a "
                               "TPU from one host core (bigger shards)")
    imagenet.add_argument("--resize", type=int, default=256,
                          help="shorter-side rescale target for --store raw")
    for s_, r_ in ((voc, 416), (coco, 416), (mpii, 384)):
        s_.add_argument("--store", choices=("jpeg", "raw"), default="jpeg",
                        help="raw: decode+rescale at build time, store "
                             "uint8 — decode-free read path (labels are "
                             "rescale-invariant/rescaled at build)")
        s_.add_argument("--resize", type=int, default=r_,
                        help="shorter-side rescale target for --store raw")

    # XML bbox tree → relative-coords CSV (process_bounding_boxes.py role)
    bboxes = sub.add_parser("imagenet-bboxes")
    bboxes.add_argument("--xml-dir", required=True)
    bboxes.add_argument("--out-csv", required=True)
    bboxes.add_argument("--synsets", default=None,
                        help="restrict to challenge synsets (one id/line)")

    # raw download → flat loader layout (untar/flatten-script.sh roles)
    ftrain = sub.add_parser("imagenet-flatten-train")
    ftrain.add_argument("--src", required=True,
                        help="dir of per-synset tars or subdirectories")
    ftrain.add_argument("--dest", required=True)
    fval = sub.add_parser("imagenet-flatten-val")
    fval.add_argument("--src", required=True)
    fval.add_argument("--dest", required=True)
    fval.add_argument("--ground-truth", default=None,
                      help="ILSVRC2012 validation ground-truth file "
                           "(needed for the flat official layout)")
    fval.add_argument("--synsets", default=None)

    unpaired = sub.add_parser("unpaired")
    unpaired.add_argument("--dir-a", required=True)
    unpaired.add_argument("--dir-b", required=True)

    celeba = sub.add_parser("celeba")
    celeba.add_argument("--attr", required=True)
    celeba.add_argument("--images", required=True)
    celeba.add_argument("--out-a", required=True)
    celeba.add_argument("--out-b", required=True)
    celeba.add_argument("--attribute", default="Male")

    for s in (voc, coco, mpii, imagenet, unpaired):
        s.add_argument("--out", required=True)
        s.add_argument("--split", default="train")
        s.add_argument("--num-shards", type=int, default=8)
        s.add_argument("--num-workers", type=int, default=8)

    args = p.parse_args(argv)
    from deep_vision_tpu.data import prep

    if args.cmd == "voc":
        n = prep.prepare_voc(args.voc_root, args.out, args.split, args.names,
                             args.num_shards, args.num_workers, args.year,
                             store=args.store, resize=args.resize)
    elif args.cmd == "coco":
        n = prep.prepare_coco(args.annotations, args.images, args.out,
                              args.split, args.num_shards, args.num_workers,
                              store=args.store, resize=args.resize)
    elif args.cmd == "mpii":
        n = prep.prepare_mpii(args.annotations, args.images, args.out,
                              args.split, args.num_shards, args.num_workers,
                              store=args.store, resize=args.resize)
    elif args.cmd == "imagenet":
        n = prep.prepare_imagenet(args.src, args.labels, args.out, args.split,
                                  args.num_shards, args.num_workers,
                                  bbox_csv=args.bbox_csv, store=args.store,
                                  resize=args.resize)
    elif args.cmd == "imagenet-bboxes":
        stats = prep.process_imagenet_bboxes(args.xml_dir, args.out_csv,
                                             args.synsets)
        print(f"prepared: {stats}")
        return 0
    elif args.cmd == "imagenet-flatten-train":
        print(f"prepared: {prep.flatten_imagenet_train(args.src, args.dest)}")
        return 0
    elif args.cmd == "imagenet-flatten-val":
        n = prep.flatten_imagenet_val(args.src, args.dest,
                                      args.ground_truth, args.synsets)
        print(f"prepared: {n}")
        return 0
    elif args.cmd == "unpaired":
        n = prep.prepare_unpaired(args.dir_a, args.dir_b, args.out,
                                  args.split, args.num_shards,
                                  args.num_workers)
    else:
        n = prep.split_celeba_by_attribute(args.attr, args.images, args.out_a,
                                           args.out_b, args.attribute)
    print(f"prepared: {n}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
