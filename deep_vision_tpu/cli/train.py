"""Training CLI — the one entry point replacing every per-model ``train.py``.

Parity with ``python train.py -m <model> [-c]`` (ResNet/pytorch/train.py:541-562)
plus dataset/workdir flags that the reference hard-coded per directory.

Usage:
    python -m deep_vision_tpu.cli.train -m lenet5 --data-root ~/mnist
    python -m deep_vision_tpu.cli.train -m lenet5 --synthetic --epochs 2
    python -m deep_vision_tpu.cli.train -m resnet50 --resume
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="deep_vision_tpu trainer")
    p.add_argument("-m", "--model", required=True,
                   help="config name (see --list)")
    p.add_argument("--data-root", default=None, help="dataset directory")
    p.add_argument("--data-format", choices=("folder", "records"),
                   default="folder",
                   help="classification input: flat image dir (folder) or "
                        "prepare_data imagenet dvrec shards (records)")
    p.add_argument("--synthetic", action="store_true",
                   help="synthetic data smoke run (no dataset needed)")
    p.add_argument("--synthetic-size", type=int, default=1024)
    p.add_argument("-c", "--resume", action="store_true",
                   help="resume from latest checkpoint in workdir")
    p.add_argument("--workdir", default=None)
    p.add_argument("--epochs", type=int, default=None, help="override config")
    p.add_argument("--batch-size", type=int, default=None, help="override config")
    p.add_argument("--scan-steps", type=int, default=None,
                   help="train steps per device dispatch (lax.scan "
                        "multi-step; amortizes host dispatch overhead)")
    p.add_argument("--grad-accum", type=int, default=None,
                   help="gradient-accumulation microbatches per optimizer "
                        "update (full recipe batch on a fraction of HBM)")
    p.add_argument("--ema-decay", type=float, default=None,
                   help="params EMA decay (e.g. 0.9999); eval/serving "
                        "use the averaged copy")
    p.add_argument("--momentum-dtype", choices=("bfloat16",), default=None,
                   help="store the SGD momentum accumulator in bf16 "
                        "(halves optimizer-state HBM; ~1e-3 update "
                        "numerics change — OFF for parity recipes)")
    p.add_argument("--image-size", type=int, default=None,
                   help="override config (smoke runs at low res)")
    p.add_argument("--mesh", default=None,
                   help="mesh spec like 'data=8', 'data=4,model=2', "
                        "'data=2,spatial=4' (image rows sharded over "
                        "'spatial'; GSPMD inserts the conv halo exchanges "
                        "— the activation-memory lever, docs/PERF.md), or "
                        "'data=2,pipe=4' (GPipe pipeline over the stacked "
                        "families: hourglass pose, CenterNet detection)")
    p.add_argument("--microbatches", type=int, default=None,
                   help="pipeline microbatches per step (with a pipe mesh "
                        "axis; default = pipe axis size)")
    p.add_argument("--num-workers", type=int, default=16,
                   help="decode/augment worker processes (ImageNet, "
                        "detection, and pose loaders; 0 = inline prep, "
                        "which also switches record datasets to "
                        "decode-once caching)")
    p.add_argument("--host-normalize", action="store_true",
                   help="float32 jitter+normalize on the HOST (reference "
                        "semantics) instead of fused device preprocessing")
    p.add_argument("--prefetch-depth", type=int, default=None,
                   help="staged H2D prefetch depth: batches resident on "
                        "device ahead of the consuming step (default 2; "
                        "1 = classic double buffering)")
    p.add_argument("--tf-preprocessing", action="store_true",
                   help="TF 'ResNet preprocessing' pipeline (aspect-"
                        "preserving resize + mean subtraction, no jitter) "
                        "instead of the cv2/torch one")
    p.add_argument("--upload", default=None,
                   help="sync checkpoints to this URI after each save "
                        "(path, file://, or gs://)")
    p.add_argument("--pretrained", default=None,
                   help="torch-format state_dict (.pth) to start from "
                        "(the load_model_weights role; any published-"
                        "accuracy arch — see models/pretrained.py); head "
                        "kept only when the class count matches")
    p.add_argument("--profile", action="store_true",
                   help="jax.profiler trace of steps 10-20 → workdir/profile")
    p.add_argument("--list", action="store_true", help="list configs and exit")
    return p


def parse_mesh_spec(spec: str | None):
    from deep_vision_tpu.parallel import make_mesh

    if spec is None:
        return make_mesh()
    sizes = {}
    for part in spec.split(","):
        k, v = part.split("=")
        sizes[k.strip()] = int(v)
    return make_mesh(sizes)


def main(argv=None):
    args = build_parser().parse_args(argv)

    from deep_vision_tpu.core.config import get_config, list_configs

    if args.list:
        print("\n".join(list_configs()))
        return 0

    from deep_vision_tpu.core.compile_cache import enable_compile_cache

    enable_compile_cache()

    cfg = get_config(args.model)
    if args.epochs is not None:
        cfg.total_epochs = args.epochs
    if args.batch_size is not None:
        cfg.batch_size = cfg.eval_batch_size = args.batch_size
    if args.scan_steps is not None:
        cfg.scan_steps = args.scan_steps
    if args.grad_accum is not None:
        cfg.grad_accum_steps = args.grad_accum
    if args.ema_decay is not None:
        cfg.ema_decay = args.ema_decay
    if args.momentum_dtype is not None:
        cfg.optimizer.momentum_dtype = args.momentum_dtype
    if args.image_size is not None:
        cfg.image_size = args.image_size
    if args.prefetch_depth is not None:
        cfg.prefetch_depth = args.prefetch_depth

    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.loader import ArrayLoader
    from deep_vision_tpu.tasks.classification import ClassificationTask

    mesh = parse_mesh_spec(args.mesh)
    print(f"devices: {mesh.devices.ravel().tolist()} mesh={dict(mesh.shape)}")

    if cfg.task in ("detection", "centernet"):
        return _main_detection(args, cfg, mesh)
    if cfg.task == "pose":
        return _main_pose(args, cfg, mesh)
    if cfg.task.startswith("gan_"):
        return _main_gan(args, cfg, mesh)
    if cfg.task != "classification":
        raise NotImplementedError(
            f"task '{cfg.task}' CLI wiring lands with its stack")

    task = ClassificationTask(cfg.num_classes, cfg.label_smoothing)
    preprocess_fn = None

    if args.synthetic:
        from deep_vision_tpu.data.synthetic import synthetic_classification

        train_data = synthetic_classification(
            args.synthetic_size, cfg.image_size, cfg.channels,
            cfg.num_classes, seed=1)
        val_data = synthetic_classification(
            max(args.synthetic_size // 4, cfg.batch_size), cfg.image_size,
            cfg.channels, cfg.num_classes, seed=2)
        train_loader = ArrayLoader(train_data, cfg.batch_size, seed=cfg.seed)
        val_loader = ArrayLoader(val_data, cfg.eval_batch_size, shuffle=False,
                                 drop_last=False, pad_last=True)
    elif args.model == "lenet5":
        from deep_vision_tpu.data.mnist import load_mnist

        assert args.data_root, "--data-root required without --synthetic"
        # uint8 wire by default: raw padded bytes cross H2D (4× smaller),
        # the /255 normalize runs as the traced prologue
        dev_norm = not args.host_normalize
        train_data = load_mnist(args.data_root, "train",
                                device_normalize=dev_norm)
        val_data = load_mnist(args.data_root, "test",
                              device_normalize=dev_norm)
        train_loader = ArrayLoader(train_data, cfg.batch_size, seed=cfg.seed)
        val_loader = ArrayLoader(val_data, cfg.eval_batch_size, shuffle=False,
                                 drop_last=False, pad_last=True)
        if dev_norm:
            from deep_vision_tpu.ops.preprocess import make_mnist_preprocess

            preprocess_fn = make_mnist_preprocess()
    else:
        # ImageNet flattened-dir layout (Datasets/ILSVRC2012 prep output):
        # <root>/train/, <root>/val/, <root>/imagenet_2012_metadata.txt
        import os

        from deep_vision_tpu.data.imagenet import ImageNetLoader
        from deep_vision_tpu.data.transforms import imagenet_resize_for

        assert args.data_root, "--data-root required without --synthetic"
        labels = os.path.join(args.data_root, "imagenet_2012_metadata.txt")
        resize = imagenet_resize_for(cfg.image_size)
        # uint8 host pipeline + device-side jitter/normalize (fused into
        # the jit step): 4× less H2D, ~30% less host CPU per image
        if args.tf_preprocessing and args.host_normalize:
            raise SystemExit("--tf-preprocessing and --host-normalize pick "
                             "contradictory pipelines; pass only one")
        preprocessing = "tf" if args.tf_preprocessing else "torch"
        dev_norm = not args.host_normalize and preprocessing == "torch"
        common = dict(train=True, seed=cfg.seed, image_size=cfg.image_size,
                      resize=resize, num_workers=args.num_workers,
                      device_normalize=dev_norm, preprocessing=preprocessing)
        if args.data_format == "records":
            # dvrec shard consumption (the reference's TFRecord trainer path)
            train_loader = ImageNetLoader.from_records(
                args.data_root, "train", cfg.batch_size, **common)
        else:
            train_loader = ImageNetLoader(
                os.path.join(args.data_root, "train"), labels,
                cfg.batch_size, **common)
        val_loader, _ = build_classification_val_loader(
            cfg, args.data_root, "val", cfg.eval_batch_size,
            num_workers=args.num_workers, preprocessing=preprocessing,
            device_normalize=dev_norm, data_format=args.data_format)
        if dev_norm:
            from deep_vision_tpu.ops.preprocess import make_imagenet_preprocess

            # try the fused Pallas train-ingest (decode+jitter+normalize in
            # one VMEM pass) at the REAL per-shard compiled shape — the
            # factory parity-gates it and falls back to the XLA path.
            # cfg.batch_size is per-host; the data axis spans all hosts.
            import jax as _jax

            global_batch = cfg.batch_size * _jax.process_count()
            per_shard = max(
                global_batch // mesh.shape.get("data", 1), 1)
            preprocess_fn = make_imagenet_preprocess(
                use_fused=True,
                fused_shape=(per_shard, cfg.image_size, cfg.image_size, 3),
                mesh=mesh)
            print(f"[input] train ingest: "
                  f"{'fused pallas' if preprocess_fn.fused else 'xla'}")

    trainer = Trainer(cfg, cfg.model(), task, mesh=mesh, workdir=args.workdir,
                      preprocess_fn=preprocess_fn, upload=args.upload)
    if args.profile:
        trainer.profile_steps = (10, 20)
    state = None
    if args.pretrained:
        state = _load_pretrained_state(args, cfg, trainer, train_loader)
    state = trainer.fit(train_loader, val_loader, state=state,
                        resume=args.resume)
    final = trainer.evaluate(state, val_loader)
    print("final:", " ".join(f"{k}={v:.4f}" for k, v in final.items()))
    return 0


def build_classification_val_loader(cfg, data_root: str, split: str,
                                    batch: int, num_workers: int = 4,
                                    preprocessing: str = "torch",
                                    device_normalize: bool = False,
                                    data_format: str | None = None):
    """One place for the records-vs-folder/labels/resize wiring shared by
    the train CLI's val loader and ``infer eval`` (so the two can't
    drift).  ``data_format=None`` autodetects dvrec shards; lenet5/MNIST
    roots (idx-ubyte files) get the MNIST loader.
    Returns ``(loader, dataset_size)``."""
    import os

    from deep_vision_tpu.data.imagenet import ImageNetLoader
    from deep_vision_tpu.data.records import list_shards
    from deep_vision_tpu.data.transforms import imagenet_resize_for

    import glob as _glob

    # MNIST root sniff: any idx-ubyte naming variant load_mnist accepts
    # (plain / .gz / dot-idx)
    if _glob.glob(os.path.join(data_root, "t10k-images*idx3-ubyte*")):
        from deep_vision_tpu.data.loader import ArrayLoader
        from deep_vision_tpu.data.mnist import load_mnist

        data = load_mnist(data_root, "train" if split == "train" else "test")
        loader = ArrayLoader(data, batch, shuffle=False, drop_last=False,
                             pad_last=True)
        return loader, len(next(iter(data.values())))
    common = dict(train=False, image_size=cfg.image_size,
                  resize=imagenet_resize_for(cfg.image_size),
                  num_workers=num_workers, preprocessing=preprocessing,
                  device_normalize=device_normalize)
    use_records = data_format == "records" or (
        data_format is None and list_shards(data_root, split))
    if use_records:
        loader = ImageNetLoader.from_records(data_root, split, batch,
                                             **common)
    else:
        labels = os.path.join(data_root, "imagenet_2012_metadata.txt")
        loader = ImageNetLoader(os.path.join(data_root, split), labels,
                                batch, **common)
    return loader, len(loader.ds)


def _load_pretrained_state(args, cfg, trainer, train_loader):
    """Initialize, overlay a torch-format checkpoint, re-place on mesh —
    the reference's pretrained start (resnet50v2.py:137-153)."""
    import jax

    from deep_vision_tpu.models.pretrained import (
        ARCH_IMPORTERS,
        import_pretrained,
    )
    from deep_vision_tpu.parallel import replicate

    if args.model not in ARCH_IMPORTERS:
        raise SystemExit(
            f"--pretrained supports {sorted(ARCH_IMPORTERS)} (torch-format "
            f"checkpoints); '{args.model}' has a different param tree")
    state = trainer.init_state(next(iter(train_loader)))
    merged, head_kept = import_pretrained(
        args.pretrained, args.model,
        {"params": jax.device_get(state.params),
         "batch_stats": jax.device_get(state.batch_stats)})
    print(f"[pretrained] loaded {args.model} weights from {args.pretrained} "
          f"(head {'kept' if head_kept else 'fresh'})")
    return replicate(
        state.replace(params=merged["params"],
                      batch_stats=merged["batch_stats"]), trainer.mesh)


def _maybe_pipelined(model, mesh, args):
    """Wrap ``model`` for pipeline-parallel training when the mesh has a
    pipe axis; clean CLI error for families with no stage sequence."""
    if mesh.shape.get("pipe", 1) <= 1:
        return model
    from deep_vision_tpu.parallel.pipelined import PipelinedModel

    try:
        model = PipelinedModel.for_model(
            model, mesh, num_microbatches=args.microbatches)
    except TypeError as e:
        raise SystemExit(f"--mesh pipe axis: {e}") from e
    print(f"[pipeline] {model.num_stages} stages over pipe="
          f"{mesh.shape['pipe']}, {model.num_microbatches} microbatches")
    return model


def _main_detection(args, cfg, mesh):
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.detection import synthetic_detection_dataset
    if cfg.task == "centernet":
        from deep_vision_tpu.data.detection import CenterNetLoader as LoaderCls
        from deep_vision_tpu.tasks.centernet import CenterNetTask

        task = CenterNetTask(cfg.num_classes)
    else:
        import jax

        from deep_vision_tpu.data.detection import DetectionLoader as LoaderCls
        from deep_vision_tpu.tasks.detection import YoloTask

        # pallas ignore-mask kernel: TPU only, gated on a parity check;
        # sharded meshes route it through a data-axis shard_map
        # (best_iou_max_sharded), so multi-chip keeps the fused path
        use_pallas = jax.default_backend() == "tpu"
        if use_pallas:
            from deep_vision_tpu.ops.pallas_ops import pallas_parity_ok
            from deep_vision_tpu.tasks.detection import MAX_BOXES

            # check at the REAL compiled shapes — Mosaic tiling/VMEM limits
            # are shape-dependent, so toy shapes prove nothing; the loss
            # calls the kernel once PER SCALE with that scale's n_pred, and
            # under shard_map the kernel sees the PER-SHARD batch.
            # cfg.batch_size is per-HOST, the data axis spans all hosts —
            # the global batch is per-host × process_count; grad accum then
            # splits each shard into microbatches INSIDE the step, so the
            # kernel's real compiled batch divides by that too
            global_batch = cfg.batch_size * jax.process_count()
            accum = max(1, getattr(cfg, "grad_accum_steps", 1))
            per_shard = max(
                global_batch // mesh.shape.get("data", 1) // accum, 1)
            use_pallas = all(
                pallas_parity_ok(batch=per_shard,
                                 n_pred=3 * (cfg.image_size // s) ** 2,
                                 n_gt=MAX_BOXES)
                for s in (8, 16, 32))
        task = YoloTask(cfg.num_classes, use_pallas=use_pallas,
                        mesh=mesh if mesh.devices.size > 1 else None)
    if args.synthetic:
        train_samples = synthetic_detection_dataset(
            args.synthetic_size, cfg.image_size,
            min(cfg.num_classes, 3), seed=1)
        val_samples = synthetic_detection_dataset(
            max(args.synthetic_size // 4, cfg.batch_size), cfg.image_size,
            min(cfg.num_classes, 3), seed=2)
    else:
        from deep_vision_tpu.data.records import load_detection_records

        assert args.data_root, "--data-root required without --synthetic"
        # train split decodes in the worker pool (bounded memory); the val
        # split is revisited every epoch with no pool, so cache decodes
        train_samples = load_detection_records(
            args.data_root, "train", cache_decoded=args.num_workers == 0)
        val_samples = load_detection_records(args.data_root, "val",
                                             cache_decoded=True)
    # uint8 host batches + on-device /255 by default (4× smaller H2D,
    # no host f32 convert); --host-normalize restores the all-host path
    dev_norm = not args.host_normalize
    preprocess_fn = None
    if dev_norm:
        from deep_vision_tpu.ops.preprocess import make_scale_preprocess

        preprocess_fn = make_scale_preprocess()
    train_loader = LoaderCls(train_samples, cfg.batch_size,
                             cfg.num_classes, cfg.image_size,
                             train=True, seed=cfg.seed,
                             device_normalize=dev_norm,
                             # synthetic samples are in-memory (no decode)
                             # — a pool only adds pickle traffic
                             num_workers=0 if args.synthetic
                             else args.num_workers)
    val_loader = LoaderCls(val_samples, cfg.batch_size,
                           cfg.num_classes, cfg.image_size, train=False,
                           device_normalize=dev_norm)
    # pipeline-parallel training mode (stacked families only — CenterNet
    # here; YOLO has no same-shape stage sequence and exits cleanly)
    model = _maybe_pipelined(cfg.model(), mesh, args)
    trainer = Trainer(cfg, model, task, mesh=mesh, workdir=args.workdir,
                      preprocess_fn=preprocess_fn, upload=args.upload)
    try:
        state = trainer.fit(train_loader, val_loader, resume=args.resume)
        final = trainer.evaluate(state, val_loader)
    finally:
        train_loader.close()
    print("final:", " ".join(f"{k}={v:.4f}" for k, v in final.items()))
    return 0


def _main_pose(args, cfg, mesh):
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.pose import PoseLoader, synthetic_pose_dataset
    from deep_vision_tpu.tasks.pose import PoseTask

    task = PoseTask()
    heatmap_size = cfg.image_size // 4
    if args.synthetic:
        train_samples = synthetic_pose_dataset(
            args.synthetic_size, cfg.image_size, cfg.num_classes, seed=1)
        val_samples = synthetic_pose_dataset(
            max(args.synthetic_size // 4, cfg.batch_size), cfg.image_size,
            cfg.num_classes, seed=2)
    else:
        from deep_vision_tpu.data.records import load_pose_records

        assert args.data_root, "--data-root required without --synthetic"
        # train split decodes in the worker pool (bounded memory); the val
        # split is revisited every epoch with no pool, so cache decodes
        train_samples = load_pose_records(
            args.data_root, "train", cache_decoded=args.num_workers == 0)
        val_samples = load_pose_records(args.data_root, "val",
                                        cache_decoded=True)
    dev_norm = not args.host_normalize
    preprocess_fn = None
    if dev_norm:
        from deep_vision_tpu.ops.preprocess import make_scale_preprocess

        preprocess_fn = make_scale_preprocess()
    train_loader = PoseLoader(train_samples, cfg.batch_size, cfg.image_size,
                              heatmap_size, cfg.num_classes, train=True,
                              seed=cfg.seed, device_normalize=dev_norm,
                              num_workers=0 if args.synthetic
                              else args.num_workers)
    val_loader = PoseLoader(val_samples, cfg.batch_size, cfg.image_size,
                            heatmap_size, cfg.num_classes, train=False,
                            device_normalize=dev_norm)
    # pipeline-parallel training mode: a pipe mesh axis shards the
    # hourglass stacks over devices (GPipe microbatch pipeline) — the
    # monolithic config's num_stack/filters/order carry over unchanged
    model = _maybe_pipelined(cfg.model(), mesh, args)
    trainer = Trainer(cfg, model, task, mesh=mesh, workdir=args.workdir,
                      preprocess_fn=preprocess_fn, upload=args.upload)
    try:
        state = trainer.fit(train_loader, val_loader, resume=args.resume)
        final = trainer.evaluate(state, val_loader)
    finally:
        train_loader.close()
    print("final:", " ".join(f"{k}={v:.4f}" for k, v in final.items()))
    return 0


def _main_gan(args, cfg, mesh):
    import jax.numpy as jnp

    from deep_vision_tpu.core.adversarial import AdversarialTrainer
    from deep_vision_tpu.models import gan as gan_models
    from deep_vision_tpu.tasks.gan import CycleGANTask, DCGANTask

    dtype = jnp.bfloat16 if cfg.half_precision else jnp.float32
    # uint8 wire by default: the loaders ship raw 0–255 bytes and the
    # (x-127.5)/127.5 scaling runs as the traced GAN prologue — 4× less
    # H2D per step; --host-normalize restores the all-host f32 wire
    dev_norm = not args.host_normalize
    preprocess_fn = None
    if dev_norm:
        from deep_vision_tpu.ops.preprocess import make_gan_preprocess

        preprocess_fn = make_gan_preprocess()
    if cfg.task == "gan_dcgan":
        from deep_vision_tpu.data.gan import GANLoader, mnist_gan_data

        if not args.synthetic:
            assert args.data_root, "--data-root required without --synthetic"
        images = mnist_gan_data(None if args.synthetic else args.data_root,
                                n_synthetic=args.synthetic_size,
                                device_normalize=dev_norm)
        loader = GANLoader(images, cfg.batch_size, seed=cfg.seed)
        task = DCGANTask(gan_models.DCGANGenerator(dtype=dtype),
                         gan_models.DCGANDiscriminator(dtype=dtype),
                         opt=cfg.optimizer)
    else:
        from deep_vision_tpu.data.gan import UnpairedLoader, synthetic_unpaired

        if args.synthetic:
            a, b = synthetic_unpaired(args.synthetic_size, cfg.image_size,
                                      device_normalize=dev_norm)
        else:
            a, b = _load_unpaired_records(args.data_root, cfg.image_size,
                                          device_normalize=dev_norm)
        loader = UnpairedLoader(a, b, cfg.batch_size, seed=cfg.seed)
        task = CycleGANTask(
            lambda: gan_models.CycleGANGenerator(dtype=dtype),
            lambda: gan_models.PatchGANDiscriminator(dtype=dtype),
            opt=cfg.optimizer)

    trainer = AdversarialTrainer(cfg, task, mesh=mesh, workdir=args.workdir,
                                 preprocess_fn=preprocess_fn,
                                 upload=args.upload)
    states = trainer.fit(loader, epochs=cfg.total_epochs, resume=args.resume)
    print("done: trained", ", ".join(states))
    return 0


def _load_unpaired_records(data_root, image_size,
                           device_normalize: bool = False):
    """train_a/train_b dvrec shards (cli.prepare_data unpaired) →
    two [-1,1] float arrays, or raw uint8 0–255 arrays when
    ``device_normalize`` defers the scaling to the traced prologue."""
    import io

    import numpy as np
    from PIL import Image

    from deep_vision_tpu.data.detection import resize_square
    from deep_vision_tpu.data.records import list_shards, read_records

    assert data_root, "--data-root required without --synthetic"
    out = []
    for tag in ("a", "b"):
        shards = list_shards(data_root, f"train_{tag}")
        if not shards:
            raise FileNotFoundError(
                f"no train_{tag}-*.dvrec under {data_root} "
                "(run cli.prepare_data unpaired)")
        imgs = []
        for sh in shards:
            for _, payload in read_records(sh):
                img = np.asarray(Image.open(io.BytesIO(payload))
                                 .convert("RGB"))
                sq = resize_square(img, image_size)
                imgs.append(sq.astype(np.uint8) if device_normalize
                            else sq.astype(np.float32) / 127.5 - 1.0)
        out.append(np.stack(imgs))
    return out[0], out[1]


if __name__ == "__main__":
    raise SystemExit(main())
