"""DVT003 (host sync in a hot path) and DVT004 (side effects in traced code).

DVT003 scans functions annotated ``# dvtlint: hot`` — the engine
compute/dispatch path, replica routing, and the gateway proxy loop — for
calls that force a device->host synchronization: ``jax.device_get``,
``.block_until_ready()``, ``np.asarray``, ``.item()``, ``float()``. A value
already fetched by ``jax.device_get`` is host memory, so statements that
mention such a name are exempt from the np/item/float checks (the drainer's
single bulk fetch is whitelisted at the fetch itself with an explicit
``# dvtlint: disable=DVT003``).

DVT004 scans traced code — functions passed to ``jax.jit`` in the same
module, ``@jax.jit``/``@functools.partial(jax.jit, ...)`` decorated
functions, and functions annotated ``# dvtlint: traced`` (the AOT-lowered
bucket programs and the serve preprocess prologue) — for Python-level side
effects that silently bake into (or worse, vanish from) the compiled
program: ``time.*``, non-PRNG randomness, I/O, and attribute mutation.
``jax.random`` is fine: explicit keys are pure.
"""

from __future__ import annotations

import ast

from .framework import Finding, attr_chain

_SYNC_CALLS = {"jax.device_get", "np.asarray", "numpy.asarray"}
_ALWAYS_FLAG = {"jax.device_get"}  # host-derived exemption never applies


def _enclosing_stmt(ctx, node):
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = ctx.parents.get(cur)
    return cur


def _names_in(node):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def check_dvt003(ctx):
    out = []
    for fi in ctx.functions:
        if not fi.is_hot:
            continue
        # names bound from jax.device_get(...) are host values
        host_names = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and attr_chain(node.value.func) == "jax.device_get":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        host_names.add(tgt.id)

        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            label = None
            exemptable = True
            if chain in _SYNC_CALLS:
                label = chain
                exemptable = chain not in _ALWAYS_FLAG
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "block_until_ready":
                label = ".block_until_ready()"
                exemptable = False
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                label = ".item()"
            elif isinstance(node.func, ast.Name) and node.func.id == "float" \
                    and node.args and not isinstance(node.args[0], ast.Constant):
                label = "float()"
            if label is None:
                continue
            if exemptable and host_names:
                stmt = _enclosing_stmt(ctx, node)
                if stmt is not None and (_names_in(stmt) & host_names):
                    continue  # operates on an already-fetched host value
            out.append((
                Finding(
                    "DVT003", ctx.rel, node.lineno,
                    f"{label} in hot function {fi.qualname} forces a "
                    "device->host sync on the serving hot path",
                ),
                ctx, node,
            ))
    return out


# -- DVT004 ------------------------------------------------------------------


def _jit_target_names(ctx):
    """Names of locally defined functions passed to jax.jit(...) anywhere in
    the module (covers ``jax.jit(apply, ...)`` in the AOT bucket compile)."""
    names = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and attr_chain(node.func) == "jax.jit":
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
    return names


def _is_jit_decorated(fi):
    for dec in getattr(fi.node, "decorator_list", []):
        chain = attr_chain(dec if not isinstance(dec, ast.Call) else dec.func)
        if chain == "jax.jit":
            return True
        # functools.partial(jax.jit, ...) / partial(jax.jit, ...)
        if isinstance(dec, ast.Call) and chain in ("functools.partial", "partial"):
            if dec.args and attr_chain(dec.args[0]) == "jax.jit":
                return True
    return False


_IO_BUILTINS = {"print", "open", "input"}
_RANDOM_PREFIXES = ("random.", "np.random.", "numpy.random.")


def check_dvt004(ctx):
    jit_names = _jit_target_names(ctx)
    out = []
    for fi in ctx.functions:
        traced = fi.is_traced or fi.name in jit_names or _is_jit_decorated(fi)
        if not traced:
            continue
        for node in ast.walk(fi.node):
            label = None
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain is not None:
                    if chain == "time" or chain.startswith("time."):
                        label = f"{chain}() (trace-time constant, not a clock)"
                    elif any(chain.startswith(p) for p in _RANDOM_PREFIXES):
                        label = f"{chain}() (use jax.random with explicit keys)"
                if isinstance(node.func, ast.Name) and \
                        node.func.id in _IO_BUILTINS:
                    label = f"{node.func.id}() (I/O)"
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    if isinstance(tgt, ast.Attribute):
                        label = f"attribute store to .{tgt.attr} (Python mutation)"
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                label = f"{type(node).__name__.lower()} statement (Python mutation)"
            if label is None:
                continue
            out.append((
                Finding(
                    "DVT004", ctx.rel, node.lineno,
                    f"side effect in traced function {fi.qualname}: {label}",
                ),
                ctx, node,
            ))
    return out
