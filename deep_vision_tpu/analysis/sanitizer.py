"""Runtime lock-order sanitizer — the dynamic half of dvtlint.

``new_lock(name)`` is the seam every threaded serving module creates its
locks through. Disabled (the default), it returns a plain
``threading.Lock`` — the cost of the instrumentation is one module-level
bool check at *construction* time and exactly nothing on the acquire/release
hot path. Enabled (``DVT_LOCK_SANITIZER=1`` in the environment, or
``enable(True)`` from a test fixture before the locks are constructed), it
returns a ``SanitizedLock`` that records per-thread acquisition order into a
global graph keyed by lock *name* — all instances of one lock site share a
node, so the graph captures ordering between lock classes, which is what
deadlocks care about.

On acquiring B while holding A, the sanitizer adds the edge A -> B; if B can
already reach A in the graph, two code paths take these locks in opposite
orders — a real deadlock under the right interleaving — so it records a
violation and raises ``LockOrderViolation`` *before* blocking (the test sees
an exception, not a hang). Same-name edges (two instances of one site, e.g.
two engine replicas) are skipped: instance ordering within a site is not
statically knowable and the serving tier never nests same-class locks.

Violations are also kept in a global list so a conftest fixture can assert
cleanliness at teardown even when a worker thread swallowed the raise.
"""

from __future__ import annotations

import os
import threading

_ENABLED = os.environ.get("DVT_LOCK_SANITIZER", "") == "1"

_graph_mu = threading.Lock()
_edges: dict[str, set] = {}          # name -> names acquired while held
_edge_site: dict[tuple, str] = {}    # (a, b) -> thread that first added it
_violations: list[str] = []
_tls = threading.local()


class LockOrderViolation(RuntimeError):
    """Acquiring this lock here inverts an already-observed lock order."""


def enable(flag: bool = True) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


def enabled() -> bool:
    return _ENABLED


def reset() -> None:
    """Clear the order graph and recorded violations (per-test isolation)."""
    with _graph_mu:
        _edges.clear()
        _edge_site.clear()
        _violations.clear()


def violations() -> list:
    with _graph_mu:
        return list(_violations)


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _reaches(src: str, dst: str) -> bool:
    # caller holds _graph_mu
    stack, seen = [src], {src}
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        for b in _edges.get(n, ()):
            if b not in seen:
                seen.add(b)
                stack.append(b)
    return False


def _check_and_record(name: str) -> None:
    held = _held()
    if not held:
        return
    thread = threading.current_thread().name
    with _graph_mu:
        for a in held:
            if a == name:
                continue  # same lock site (another instance): no ordering
            if _reaches(name, a):
                chain = f"{name} -> ... -> {a}"
                msg = (
                    f"lock-order inversion: thread {thread!r} acquires "
                    f"{name!r} while holding {a!r}, but the graph already "
                    f"has {chain} (first seen in "
                    f"{_edge_site.get((name, a), '?')!r})"
                )
                _violations.append(msg)
                raise LockOrderViolation(msg)
            if name not in _edges.setdefault(a, set()):
                _edges[a].add(name)
                _edge_site.setdefault((a, name), thread)


class SanitizedLock:
    """Drop-in for ``threading.Lock`` that sanity-checks acquisition order."""

    def __init__(self, name: str):
        self.name = name
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _check_and_record(self.name)  # raises before we can deadlock
        got = self._inner.acquire(blocking, timeout)
        if got:
            _held().append(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        held = _held()
        # remove the most recent occurrence (locks may unwind out of order)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self.name:
                del held[i]
                break

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<SanitizedLock {self.name!r} locked={self.locked()}>"


def new_lock(name: str):
    """The serving tier's lock constructor: plain Lock unless sanitizing."""
    if _ENABLED:
        return SanitizedLock(name)
    return threading.Lock()
