"""DVT005 (wall-clock durations), DVT006 (broad-except hygiene), and
DVT007 (unbounded blocking calls).

DVT005: ``time.time()`` is the wall clock — NTP can step it backwards, so
any *interval* computed from it (EWMAs, deadlines, histograms) is wrong by
construction. Durations must use ``time.monotonic()``; ``time.time()`` is
allowed only as a pass-through record timestamp (log lines, TensorBoard
events). The rule flags subtraction involving a ``time.time()`` call or a
name/attribute bound from one.

DVT006: ``except Exception`` / bare ``except`` / ``except BaseException``
must carry the repo's justification convention on the same line:
``# noqa: BLE001 — <reason>``. A bare ``# noqa: BLE001`` with no reason is
also a finding — the reason is the point.

DVT007: a zero-argument ``.get()`` / ``.wait()`` / ``.join()`` blocks its
thread FOREVER when the peer stalls — the exact failure mode the serving
watchdogs, drain deadlines, and gateway blackhole faults exist to bound.
(``dict.get`` always takes a key, so a zero-arg ``.get()`` can only be a
queue/future.)  Connection constructors (``HTTPConnection``,
``socket.create_connection``) without a ``timeout`` are the same bug one
layer down: a black-holed dial pins the thread at connect.  Deliberate
forever-blocks (process shutdown joins, ``Pool.join`` which has no
timeout parameter) annotate ``# dvtlint: disable=DVT007`` with a reason
comment.
"""

from __future__ import annotations

import ast

from .framework import Finding, NOQA_BLE_RE, attr_chain


def _is_wall_call(node) -> bool:
    return isinstance(node, ast.Call) and attr_chain(node.func) == "time.time"


def check_dvt005(ctx):
    # names (and self-attributes) bound from time.time(), per enclosing scope
    wall_names: set[str] = set()
    wall_attrs: set[str] = set()     # "self.<attr>" chains, tracked per class
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and _is_wall_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    wall_names.add(tgt.id)
                else:
                    chain = attr_chain(tgt)
                    if chain:
                        wall_attrs.add(chain)

    def is_wall(expr) -> bool:
        if _is_wall_call(expr):
            return True
        if isinstance(expr, ast.Name):
            return expr.id in wall_names
        chain = attr_chain(expr)
        return chain is not None and chain in wall_attrs

    out = []
    for node in ast.walk(ctx.tree):
        operands = []
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
            operands = [node.left, node.right]
        elif isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Sub):
            operands = [node.value]
        if any(is_wall(op) for op in operands):
            out.append((
                Finding(
                    "DVT005", ctx.rel, node.lineno,
                    "elapsed interval computed from time.time(); wall clock "
                    "can step backwards — use time.monotonic() for durations",
                ),
                ctx, node,
            ))
    return out


_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for item in types:
        chain = attr_chain(item)
        if chain is not None and chain.rsplit(".", 1)[-1] in _BROAD:
            return True
    return False


def check_dvt006(ctx):
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler) or not _is_broad(node):
            continue
        comment = ctx.comments.get(node.lineno, "")
        m = NOQA_BLE_RE.search(comment)
        if m and m.group(1):
            continue  # justified: "# noqa: BLE001 — <reason>"
        if m:
            msg = ("broad except has `# noqa: BLE001` but no reason — the "
                   "convention is `# noqa: BLE001 — <reason>`")
        else:
            what = "bare except" if node.type is None else "except Exception"
            msg = (f"{what} without justification — narrow it or annotate "
                   "`# noqa: BLE001 — <reason>` on the except line")
        out.append((Finding("DVT006", ctx.rel, node.lineno, msg), ctx, node))
    return out


# attribute-call methods that block forever when called with no arguments
# (queue.Queue.get, AsyncResult.get, Event/Condition.wait, Thread.join,
# Popen.wait — never dict.get or str.join, which require a positional)
_BLOCKING_METHODS = {"get", "wait", "join"}
# dial calls -> the positional index their timeout parameter occupies
_DIAL_CALLS = {"HTTPConnection": 2, "HTTPSConnection": 2,
               "create_connection": 1}


def check_dvt007(ctx):
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        kwargs = {kw.arg for kw in node.keywords}
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _BLOCKING_METHODS \
                and not node.args and "timeout" not in kwargs:
            out.append((
                Finding(
                    "DVT007", ctx.rel, node.lineno,
                    f"{node.func.attr}() with no timeout blocks this "
                    "thread forever if the peer stalls — pass timeout= "
                    "(deliberate forever-blocks annotate "
                    "`# dvtlint: disable=DVT007` with the reason)",
                ),
                ctx, node,
            ))
            continue
        chain = attr_chain(node.func)
        name = chain.rsplit(".", 1)[-1] if chain else None
        if name in _DIAL_CALLS and "timeout" not in kwargs \
                and len(node.args) <= _DIAL_CALLS[name]:
            out.append((
                Finding(
                    "DVT007", ctx.rel, node.lineno,
                    f"{name}(...) without a connect timeout — a "
                    "black-holed peer pins this thread at dial; "
                    "pass timeout=",
                ),
                ctx, node,
            ))
    return out
