"""dvtlint core: source model, annotations, findings, and the rule runner.

The analyzer is pure stdlib (ast + tokenize) — it never imports jax or any
serving module, so ``make lint`` is safe on a box with no accelerator and
costs no device init.

Annotation surface (all trailing comments, parsed from the token stream so
strings can't fool us):

  ``# guarded-by: _lock``        on a ``self.x = ...`` line in ``__init__``:
                                 declares the attribute writable only under
                                 ``with self._lock`` (DVT001).
  ``# dvtlint: hot``             on (or directly above) a ``def`` line:
                                 marks the function a serving hot path
                                 (DVT003 scans it for host syncs).
  ``# dvtlint: traced``          on (or directly above) a ``def`` line:
                                 marks a function that is traced/AOT-lowered
                                 even though the ``jax.jit`` call is not
                                 syntactically visible (DVT004 scans it).
  ``# dvtlint: holds=_lock``     on a ``def`` line: the function is only
                                 ever called with ``self._lock`` held
                                 (same contract as the ``_locked`` suffix).
  ``# dvtlint: lock=<name>``     on a ``with`` line: names a lock acquired
                                 through a non-``self`` receiver so DVT002
                                 can place it in the global order graph.
  ``# dvtlint: disable=CODE[,CODE]``
                                 escape hatch; suppresses the listed codes
                                 on that line (or, when placed on a ``def``
                                 line, for the whole function).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path

GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
DISABLE_RE = re.compile(r"#\s*dvtlint:\s*disable=([A-Z0-9,\s]+)")
HOT_RE = re.compile(r"#\s*dvtlint:\s*hot\b")
TRACED_RE = re.compile(r"#\s*dvtlint:\s*traced\b")
HOLDS_RE = re.compile(r"#\s*dvtlint:\s*holds=([A-Za-z_][A-Za-z0-9_]*)")
LOCKNAME_RE = re.compile(r"#\s*dvtlint:\s*lock=([A-Za-z_][A-Za-z0-9_.]*)")
# The justification convention DVT006 enforces: a broad except must carry
# "# noqa: BLE001 — <reason>" (em dash, en dash, or "--"/"-" accepted).
NOQA_BLE_RE = re.compile(r"#\s*noqa:\s*BLE001\b\s*(?:[—–-]{1,2}\s*(\S.*))?")


@dataclasses.dataclass
class Finding:
    code: str
    path: str
    line: int
    message: str
    suppressed: bool = False

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.code} {self.message}{tag}"


@dataclasses.dataclass
class FunctionInfo:
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    name: str
    qualname: str  # "<module>.<Class>.<name>" or "<module>.<name>"
    class_name: str | None
    is_hot: bool = False
    is_traced: bool = False
    holds: frozenset = frozenset()


class FileContext:
    """One parsed source file plus its comment-borne annotations."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, filename=str(path))
        self.module = rel[:-3].replace("/", ".") if rel.endswith(".py") else rel
        # module name without the package prefix or __init__ suffix, e.g.
        # "serve.engine" — this is what DVT002 lock names are keyed on.
        short = self.module
        for prefix in ("deep_vision_tpu.",):
            if short.startswith(prefix):
                short = short[len(prefix):]
        if short.endswith(".__init__"):
            short = short[: -len(".__init__")]
        self.short_module = short

        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass

        self.disables: dict[int, set] = {}
        for lineno, comment in self.comments.items():
            m = DISABLE_RE.search(comment)
            if m:
                codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
                self.disables.setdefault(lineno, set()).update(codes)

        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

        self.functions: list[FunctionInfo] = []
        self._index_functions()

    # -- annotation helpers -------------------------------------------------

    def _def_comment_lines(self, node) -> list[int]:
        """Candidate comment lines for a def: the def line itself, each
        decorator line, and the line immediately above the first of those."""
        lines = [node.lineno]
        for dec in getattr(node, "decorator_list", []):
            lines.append(dec.lineno)
        lines.append(min(lines) - 1)
        return lines

    def _index_functions(self) -> None:
        def visit(node, class_name, qual):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name, f"{qual}.{child.name}")
                elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    comments = [
                        self.comments.get(ln, "")
                        for ln in self._def_comment_lines(child)
                    ]
                    blob = "\n".join(comments)
                    holds = frozenset(HOLDS_RE.findall(blob))
                    if child.name.endswith("_locked"):
                        # repo convention: *_locked helpers are only called
                        # with the instance lock already held
                        holds = holds | {"_lock"}
                    self.functions.append(
                        FunctionInfo(
                            node=child,
                            name=child.name,
                            qualname=f"{qual}.{child.name}",
                            class_name=class_name,
                            is_hot=bool(HOT_RE.search(blob)),
                            is_traced=bool(TRACED_RE.search(blob)),
                            holds=holds,
                        )
                    )
                    visit(child, class_name, f"{qual}.{child.name}")
                else:
                    visit(child, class_name, qual)

        visit(self.tree, None, self.short_module)

    # -- queries ------------------------------------------------------------

    def enclosing_function(self, node) -> FunctionInfo | None:
        by_node = {fi.node: fi for fi in self.functions}
        cur = node
        while cur is not None:
            if cur in by_node:
                return by_node[cur]
            cur = self.parents.get(cur)
        return None

    def is_disabled(self, code: str, node) -> bool:
        lines = {getattr(node, "lineno", 0)}
        end = getattr(node, "end_lineno", None)
        if end is not None:
            lines.add(end)
        fi = self.enclosing_function(node)
        if fi is not None:
            lines.update(self._def_comment_lines(fi.node)[:-1])
        for ln in lines:
            if code in self.disables.get(ln, set()):
                return True
        return False


def attr_chain(node) -> str | None:
    """Render Name/Attribute chains as dotted strings ("self._lock",
    "jax.device_get"); anything else returns None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass
class Report:
    findings: list  # unsuppressed, sorted
    suppressed: list  # escape-hatched findings, counted and reported
    files: int

    def summary(self) -> str:
        def tally(items):
            counts: dict[str, int] = {}
            for f in items:
                counts[f.code] = counts.get(f.code, 0) + 1
            return ", ".join(f"{c} x{n}" for c, n in sorted(counts.items()))

        parts = [f"dvtlint: {self.files} file(s)"]
        if self.findings:
            parts.append(f"{len(self.findings)} finding(s) [{tally(self.findings)}]")
        else:
            parts.append("0 findings")
        if self.suppressed:
            parts.append(
                f"{len(self.suppressed)} suppressed via escape hatch "
                f"[{tally(self.suppressed)}]"
            )
        return "; ".join(parts)


def load_context(path: Path, root: Path) -> FileContext | Finding:
    try:
        rel = str(path.relative_to(root))
    except ValueError:
        rel = str(path)
    try:
        source = path.read_text()
        return FileContext(path, rel, source)
    except (SyntaxError, UnicodeDecodeError, OSError) as e:
        return Finding("DVT000", rel, getattr(e, "lineno", 0) or 0,
                       f"could not parse: {e}")


def collect_files(paths) -> list[Path]:
    out = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            out.append(p)
    return out


def run_paths(paths, root=None) -> Report:
    """Run every rule over the given files/directories.

    DVT002's lock-order graph is global across all analyzed files; all other
    rules are per-file.
    """
    from . import rules_hygiene, rules_jax, rules_locks

    files = collect_files(paths)
    if root is None:
        root = Path.cwd()
    root = Path(root)

    contexts: list[FileContext] = []
    findings: list[Finding] = []
    for path in files:
        ctx = load_context(path, root)
        if isinstance(ctx, Finding):
            findings.append(ctx)
        else:
            contexts.append(ctx)

    per_file_rules = (
        rules_locks.check_dvt001,
        rules_jax.check_dvt003,
        rules_jax.check_dvt004,
        rules_hygiene.check_dvt005,
        rules_hygiene.check_dvt006,
        rules_hygiene.check_dvt007,
    )
    raw: list[tuple[Finding, FileContext, ast.AST]] = []
    for ctx in contexts:
        for rule in per_file_rules:
            raw.extend(rule(ctx))
    raw.extend(rules_locks.check_dvt002(contexts))

    suppressed: list[Finding] = []
    for finding, ctx, node in raw:
        if ctx is not None and node is not None and ctx.is_disabled(finding.code, node):
            finding.suppressed = True
            suppressed.append(finding)
        else:
            findings.append(finding)

    key = lambda f: (f.path, f.line, f.code)  # noqa: E731
    return Report(sorted(findings, key=key), sorted(suppressed, key=key),
                  len(files))
