"""DVT001 (guarded-attribute lock discipline) and DVT002 (lock-order graph).

DVT001: an attribute whose ``__init__`` assignment carries a
``# guarded-by: <lock>`` comment may only be written while lexically inside
``with self.<lock>:`` (or from a ``*_locked`` helper / a function annotated
``# dvtlint: holds=<lock>``, which the repo convention defines as "caller
already holds the lock"). ``__init__`` itself is exempt — construction
happens-before publication.

DVT002: builds a global acquisition-order digraph. Nodes are lock *sites*
("<module>.<Class>.<attr>"); an edge A -> B means some thread can acquire B
while holding A — either a lexically nested ``with``, or a call made under A
to a function that (transitively) acquires B. Any cycle is a potential
deadlock. Non-``self`` receivers can be named with ``# dvtlint: lock=<name>``
on the ``with`` line; unnamed ones become per-site "?" nodes that can't
create false cycles across modules.
"""

from __future__ import annotations

import ast

from .framework import Finding, GUARDED_RE, LOCKNAME_RE, attr_chain


def _self_attr_writes(node):
    """Yield (attr_name, node) for stores to self.<attr> (including
    self.<attr>[k] = v and augmented assigns)."""

    def target_attr(tgt):
        if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
                and tgt.value.id == "self":
            return tgt.attr
        if isinstance(tgt, ast.Subscript):
            return target_attr(tgt.value)
        return None

    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            targets = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) else [tgt]
            for t in targets:
                attr = target_attr(t)
                if attr:
                    yield attr, node
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        attr = target_attr(node.target)
        if attr:
            yield attr, node


def _guarded_attrs(ctx, cls):
    """Map attr -> lock name from ``# guarded-by:`` comments in __init__."""
    guarded = {}
    for item in cls.body:
        if isinstance(item, ast.FunctionDef) and item.name == "__init__":
            for node in ast.walk(item):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                comment = ctx.comments.get(node.lineno, "") + \
                    ctx.comments.get(getattr(node, "end_lineno", node.lineno), "")
                m = GUARDED_RE.search(comment)
                if not m:
                    continue
                for attr, _ in _self_attr_writes(node):
                    guarded[attr] = m.group(1)
    return guarded


def _under_with_lock(ctx, node, func_node, lock_name):
    """True when node is lexically inside ``with self.<lock_name>`` within
    func_node (crossing into a nested def/lambda breaks the containment —
    closures may run after the lock is released)."""
    cur = ctx.parents.get(node)
    while cur is not None and cur is not func_node:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        if isinstance(cur, ast.With):
            for item in cur.items:
                if attr_chain(item.context_expr) == f"self.{lock_name}":
                    return True
        cur = ctx.parents.get(cur)
    return False


def check_dvt001(ctx):
    out = []
    for cls in [n for n in ast.walk(ctx.tree) if isinstance(n, ast.ClassDef)]:
        guarded = _guarded_attrs(ctx, cls)
        if not guarded:
            continue
        for fi in ctx.functions:
            if fi.class_name != cls.name or fi.name == "__init__":
                continue
            # only direct methods of this class, not nested helpers
            if ctx.parents.get(fi.node) is not cls:
                continue
            for node in ast.walk(fi.node):
                for attr, stmt in _self_attr_writes(node):
                    lock = guarded.get(attr)
                    if lock is None:
                        continue
                    if lock in fi.holds:
                        continue
                    if _under_with_lock(ctx, stmt, fi.node, lock):
                        continue
                    out.append((
                        Finding(
                            "DVT001", ctx.rel, stmt.lineno,
                            f"write to self.{attr} (guarded-by {lock}) outside "
                            f"`with self.{lock}` in {fi.qualname}",
                        ),
                        ctx, stmt,
                    ))
    return out


# -- DVT002 ------------------------------------------------------------------

_LOCKISH = ("lock",)


def _lock_name_for_with_item(ctx, item, class_name):
    """Resolve a with-item to a lock-site name, or None if it isn't a lock."""
    chain = attr_chain(item.context_expr)
    if chain is None:
        return None
    leaf = chain.rsplit(".", 1)[-1]
    if not any(k in leaf.lower() for k in _LOCKISH):
        return None
    # explicit annotation wins
    with_node = ctx.parents.get(item)
    for ln in (getattr(with_node, "lineno", 0),):
        m = LOCKNAME_RE.search(ctx.comments.get(ln, ""))
        if m:
            return m.group(1)
    if chain.startswith("self.") and class_name:
        return f"{ctx.short_module}.{class_name}.{leaf}"
    # unresolved receiver: site-local node (unique, cannot alias across files)
    return f"{ctx.short_module}.?{getattr(item.context_expr, 'lineno', 0)}.{leaf}"


class _FuncFacts:
    def __init__(self):
        self.acquires = set()       # lock names acquired anywhere in body
        self.nested_edges = []      # (held, acquired, lineno)
        self.calls_under = []       # (held_lock, call_node, lineno)
        self.calls = []             # every call node in body


def _attr_types(contexts):
    """Lightweight constructor-based type inference: for each class, map
    ``self.<attr>`` to the class name it is constructed with in __init__
    (``self.x = Foo(...)``). Returns {class_name: {attr: type_name}}."""
    out = {}
    for ctx in contexts:
        for cls in [n for n in ast.walk(ctx.tree) if isinstance(n, ast.ClassDef)]:
            amap = out.setdefault(cls.name, {})
            for item in cls.body:
                if isinstance(item, ast.FunctionDef) and item.name == "__init__":
                    for node in ast.walk(item):
                        if isinstance(node, ast.Assign) and \
                                isinstance(node.value, ast.Call):
                            ctor = attr_chain(node.value.func)
                            if ctor is None:
                                continue
                            ctor = ctor.rsplit(".", 1)[-1]
                            for tgt in node.targets:
                                if isinstance(tgt, ast.Attribute) and \
                                        isinstance(tgt.value, ast.Name) and \
                                        tgt.value.id == "self":
                                    amap[tgt.attr] = ctor
    return out


def _collect_facts(ctx, fi):
    facts = _FuncFacts()

    def visit(node, held):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                # nested defs run later, outside the current lock scope;
                # their own acquisitions are attributed to their FunctionInfo
                continue
            new_held = held
            if isinstance(child, ast.With):
                acquired = []
                for item in child.items:
                    name = _lock_name_for_with_item(ctx, item, fi.class_name)
                    if name is not None:
                        facts.acquires.add(name)
                        for h in held + acquired:
                            facts.nested_edges.append((h, name, child.lineno))
                        acquired.append(name)
                new_held = held + acquired
            if isinstance(child, ast.Call):
                facts.calls.append(child)
                for h in new_held:
                    facts.calls_under.append((h, child, child.lineno))
            visit(child, new_held)

    held0 = []
    if fi.class_name and fi.holds:
        held0 = [f"{ctx.short_module}.{fi.class_name}.{h}" for h in sorted(fi.holds)]
    visit(fi.node, held0)
    return facts


def _resolve_call(call, ctx, fi, attr_types, methods_by_qual, funcs_by_module):
    """Resolve a call to candidate function qualnames. Precise resolutions
    only (self.m(), typed self.attr.m(), Class(...).m is out of scope,
    bare same-module f()) — imprecise fallbacks are skipped rather than
    risking false lock-order edges."""
    func = call.func
    if isinstance(func, ast.Name):
        qual = f"{ctx.short_module}.{func.id}"
        return [qual] if qual in funcs_by_module else []
    if not isinstance(func, ast.Attribute):
        return []
    chain = attr_chain(func)
    if chain is None:
        return []
    parts = chain.split(".")
    if parts[0] == "self" and fi.class_name:
        if len(parts) == 2:  # self.meth()
            qual = f"{ctx.short_module}.{fi.class_name}.{parts[1]}"
            return [qual] if qual in methods_by_qual else []
        if len(parts) == 3:  # self.attr.meth() with constructor-typed attr
            typ = attr_types.get(fi.class_name, {}).get(parts[1])
            if typ:
                cands = [q for q in methods_by_qual
                         if q.endswith(f".{typ}.{parts[2]}")]
                return cands
    return []


def check_dvt002(contexts):
    """Global pass: build the acquisition graph over every analyzed file,
    then report each lock-order cycle once."""
    attr_types = _attr_types(contexts)
    facts = {}        # qualname -> (_FuncFacts, ctx, fi)
    for ctx in contexts:
        for fi in ctx.functions:
            facts[fi.qualname] = (_collect_facts(ctx, fi), ctx, fi)
    methods_by_qual = {q for q, (_, _, fi) in facts.items() if fi.class_name}
    funcs_by_module = {q for q, (_, _, fi) in facts.items() if not fi.class_name}

    resolved_calls = {}   # qualname -> [callee qualnames] (whole body)
    for qual, (f, ctx, fi) in facts.items():
        callees = []
        for call in f.calls:
            callees.extend(_resolve_call(call, ctx, fi, attr_types,
                                         methods_by_qual, funcs_by_module))
        resolved_calls[qual] = callees

    # transitive lock acquisitions, to fixpoint (handles recursion)
    trans = {qual: set(f.acquires) for qual, (f, _, _) in facts.items()}
    changed = True
    while changed:
        changed = False
        for qual, callees in resolved_calls.items():
            before = len(trans[qual])
            for c in callees:
                trans[qual] |= trans.get(c, set())
            if len(trans[qual]) != before:
                changed = True

    edges = {}   # (a, b) -> (rel, lineno, via)
    for qual, (f, ctx, fi) in facts.items():
        for a, b, ln in f.nested_edges:
            edges.setdefault((a, b), (ctx.rel, ln, qual))
        for held, call, ln in f.calls_under:
            for callee in _resolve_call(call, ctx, fi, attr_types,
                                        methods_by_qual, funcs_by_module):
                for b in trans.get(callee, ()):
                    edges.setdefault((held, b),
                                     (ctx.rel, ln, f"{qual} -> {callee}"))

    graph = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    # cycle detection (includes self-loops: re-acquiring the same lock site)
    out = []
    seen_cycles = set()
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in set(graph) | {b for bs in graph.values() for b in bs}}
    stack = []

    def dfs(n):
        color[n] = GRAY
        stack.append(n)
        for b in sorted(graph.get(n, ())):
            if color.get(b, WHITE) == GRAY:
                cyc = tuple(stack[stack.index(b):] + [b])
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    rel, ln, via = edges[(n, b)]
                    out.append((
                        Finding(
                            "DVT002", rel, ln,
                            "lock-order cycle: " + " -> ".join(cyc) +
                            f" (edge via {via})",
                        ),
                        None, None,
                    ))
            elif color.get(b, WHITE) == WHITE:
                dfs(b)
        stack.pop()
        color[n] = BLACK

    for n in sorted(color):
        if color[n] == WHITE:
            dfs(n)
    return out
