"""CLI: ``python -m deep_vision_tpu.analysis [--strict] [paths...]``.

With no paths, analyzes the deep_vision_tpu package itself. Prints one line
per finding plus a summary that counts escape-hatch suppressions. Exit
status: 0 when clean; with ``--strict``, any finding (including a DVT000
parse failure) exits 1 — that is the CI contract behind ``make lint``.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .framework import run_paths


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m deep_vision_tpu.analysis")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the package)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 on any finding (CI mode)")
    parser.add_argument("--root", default=None,
                        help="root for relative paths in the report")
    args = parser.parse_args(argv)

    pkg_dir = Path(__file__).resolve().parent.parent
    paths = args.paths or [pkg_dir]
    root = Path(args.root) if args.root else pkg_dir.parent

    report = run_paths(paths, root=root)
    for f in report.findings:
        print(f.render())
    print(report.summary())
    if report.findings and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
