"""dvtlint — project-specific static analysis + runtime lock sanitizer.

Static rules (see docs/ANALYSIS.md for the catalog and annotation guide):

  DVT001  guarded attribute written outside its ``with self._lock`` block
  DVT002  cycle in the static lock-acquisition-order graph
  DVT003  device->host sync inside a ``# dvtlint: hot`` function
  DVT004  Python side effect inside jit-traced / AOT-lowered code
  DVT005  elapsed interval computed from ``time.time()`` (wall clock)
  DVT006  broad except without a ``# noqa: BLE001 — <reason>`` justification
  DVT007  blocking call with no timeout (zero-arg ``.get()``/``.wait()``/
          ``.join()``, timeout-less connection dial)

Run with ``python -m deep_vision_tpu.analysis --strict`` (what ``make lint``
does), or programmatically via :func:`run_paths`. The runtime half lives in
:mod:`deep_vision_tpu.analysis.sanitizer`.

This package is stdlib-only by design — importing it (e.g. for
``sanitizer.new_lock``) must never pull in jax.
"""

from .framework import Finding, Report, run_paths

RULE_CODES = ("DVT001", "DVT002", "DVT003", "DVT004", "DVT005", "DVT006",
              "DVT007")

__all__ = ["Finding", "Report", "run_paths", "RULE_CODES"]
