"""Detection experiments — parity with YOLO/tensorflow/train.py:13-17
(batch 16/replica, 416², 300 epochs, COCO 80 classes) and its hand-rolled
epoch-table LR decay (:56-68)."""

import jax.numpy as jnp

from deep_vision_tpu.core.config import (
    OptimizerConfig,
    SchedulerConfig,
    TrainConfig,
    register_config,
)
from deep_vision_tpu.models.yolo import YoloV3


def _yolo(name, num_classes, batch):
    return TrainConfig(
        name=name,
        model=lambda: YoloV3(num_classes=num_classes, dtype=jnp.bfloat16),
        task="detection",
        batch_size=batch,
        total_epochs=300,
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-3,
                                  grad_clip_norm=10.0),
        scheduler=SchedulerConfig(
            name="epoch_table",
            kwargs=dict(table={1: 1e-3, 40: 1e-4, 60: 1e-5})),
        image_size=416,
        num_classes=num_classes,
    )


@register_config("yolov3_coco")
def yolov3_coco():
    # 8×V100 reference ran global batch 8×16 (train.py:281-296)
    return _yolo("yolov3_coco", 80, 128)


@register_config("yolov3_voc")
def yolov3_voc():
    return _yolo("yolov3_voc", 20, 16)


@register_config("yolov3_toy416")
def yolov3_toy416():
    """Tiny-width YOLOv3 at the REAL 416² input (no reference
    counterpart — test infrastructure): the fixture for the serving
    D2H-reduction gate, where the dense 3-scale pyramid is the full
    10,647-anchor shape (52²+26²+13² grids × 3 anchors) but the model
    body stays cheap enough to AOT-compile on a CPU host."""
    return TrainConfig(
        name="yolov3_toy416",
        model=lambda: YoloV3(num_classes=3, dtype=jnp.float32,
                             width=0.125, blocks=(1, 1, 1, 1, 1)),
        task="detection",
        batch_size=4,
        total_epochs=60,
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-3,
                                  grad_clip_norm=10.0),
        image_size=416,
        num_classes=3,
        half_precision=False,
    )


@register_config("yolov3_toy")
def yolov3_toy():
    """Tiny-width YOLOv3 at 64² for smoke runs, convergence tests, and
    small custom datasets (no reference counterpart — test infrastructure)."""
    return TrainConfig(
        name="yolov3_toy",
        model=lambda: YoloV3(num_classes=3, dtype=jnp.float32,
                             width=0.125, blocks=(1, 1, 1, 1, 1)),
        task="detection",
        batch_size=8,
        total_epochs=60,
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-3,
                                  grad_clip_norm=10.0),
        image_size=64,
        num_classes=3,
        half_precision=False,
    )
