"""GAN experiments — parity with DCGAN/tensorflow/main.py (Adam 1e-4,
batch 256, 100 epochs, checkpoint every 2) and CycleGAN/tensorflow/train.py
(Adam 2e-4 β1=0.5, batch 4? — reference BATCH_SIZE=1 per GPU, 200 epochs,
LinearDecay from epoch 100 — utils.py:5-28)."""

import jax.numpy as jnp

from deep_vision_tpu.core.config import (
    OptimizerConfig,
    SchedulerConfig,
    TrainConfig,
    register_config,
)
from deep_vision_tpu.models import gan as gan_models


@register_config("dcgan")
def dcgan():
    return TrainConfig(
        name="dcgan",
        model=lambda: gan_models.DCGANGenerator(dtype=jnp.bfloat16),
        task="gan_dcgan",
        batch_size=256,
        total_epochs=100,
        checkpoint_every_epochs=2,  # main.py:80-83
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-4),
        scheduler=SchedulerConfig(name="constant"),
        image_size=28,
        channels=1,
        num_classes=0,
    )


@register_config("cyclegan")
def cyclegan():
    return TrainConfig(
        name="cyclegan",
        model=lambda: gan_models.CycleGANGenerator(dtype=jnp.bfloat16),
        task="gan_cyclegan",
        batch_size=1,
        total_epochs=200,
        checkpoint_every_epochs=2,
        optimizer=OptimizerConfig(name="adam", learning_rate=2e-4, b1=0.5),
        scheduler=SchedulerConfig(
            name="linear_decay",
            kwargs=dict(total_epochs=200, decay_start=100)),
        image_size=256,
        num_classes=0,
    )
