"""CenterNet experiment — the reference's ObjectsAsPoints trainer was never
wired (train.py:248 commented out); config follows the Objects-as-Points
paper recipe (Adam 2.5e-4, 512²→128² in the paper; COCO 80 classes; here
256²→64² matching the reference's Input(256) model.py:130)."""

import jax.numpy as jnp

from deep_vision_tpu.core.config import (
    OptimizerConfig,
    SchedulerConfig,
    TrainConfig,
    register_config,
)
from deep_vision_tpu.models.centernet import CenterNet


@register_config("centernet_toy")
def centernet_toy():
    """Small CenterNet at 64²→16² for smoke runs and the serving
    device-decode tests (no reference counterpart — test
    infrastructure): order-3 hourglass (2³ = 8 ≤ 64/4), one stack,
    float32 so CPU tests skip the bf16 cast."""
    return TrainConfig(
        name="centernet_toy",
        model=lambda: CenterNet(num_classes=3, num_stack=1, order=3,
                                filters=(16, 16, 24, 24),
                                dtype=jnp.float32),
        task="centernet",
        batch_size=8,
        total_epochs=60,
        optimizer=OptimizerConfig(name="adam", learning_rate=2.5e-4),
        image_size=64,
        num_classes=3,
        half_precision=False,
    )


@register_config("centernet")
def centernet():
    return TrainConfig(
        name="centernet",
        model=lambda: CenterNet(num_classes=80, dtype=jnp.bfloat16),
        task="centernet",
        batch_size=32,
        total_epochs=140,
        optimizer=OptimizerConfig(name="adam", learning_rate=2.5e-4),
        scheduler=SchedulerConfig(
            name="epoch_table",
            kwargs=dict(table={1: 2.5e-4, 90: 2.5e-5, 120: 2.5e-6})),
        image_size=256,
        num_classes=80,
    )
