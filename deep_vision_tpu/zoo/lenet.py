"""LeNet-5/MNIST experiment — config parity with
LeNet/pytorch/train.py:15-32 (Adam lr=1e-3, batch 64, 50 epochs,
ReduceLROnPlateau factor=0.1 mode='max')."""

from deep_vision_tpu.core.config import (
    OptimizerConfig,
    SchedulerConfig,
    TrainConfig,
    register_config,
)
from deep_vision_tpu.models.lenet import LeNet5, LeNet5Big, LeNet5Nano


@register_config("lenet5_nano")
def lenet5_nano() -> TrainConfig:
    """The N-tier cascade's tier 0 below lenet5: identical wire
    contract (32×32×1, 10 classes) at ~12× less compute than LeNet-5 —
    the front of the lenet5_nano:lenet5:lenet5_big chain
    ``bench.py --serve-cascade --tiers 3`` and the cascade smoke run
    (serve/cascade.py)."""
    return TrainConfig(
        name="lenet5_nano",
        model=lambda: LeNet5Nano(),
        task="classification",
        batch_size=64,
        total_epochs=50,
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
        scheduler=SchedulerConfig(
            name="plateau", kwargs=dict(mode="max", factor=0.1, patience=10)),
        half_precision=False,
        image_size=32,
        channels=1,
        num_classes=10,
    )


@register_config("lenet5")
def lenet5() -> TrainConfig:
    return TrainConfig(
        name="lenet5",
        model=lambda: LeNet5(),
        task="classification",
        batch_size=64,
        total_epochs=50,
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
        scheduler=SchedulerConfig(
            name="plateau", kwargs=dict(mode="max", factor=0.1, patience=10)),
        half_precision=False,  # MNIST-scale; f32 is fine
        image_size=32,
        channels=1,
        num_classes=10,
    )


@register_config("lenet5_big")
def lenet5_big() -> TrainConfig:
    """The cascade's BIG tier opposite lenet5: identical wire contract
    (32×32×1, 10 classes) at ~50× the compute — the cheap-front /
    heavy-big pair ``bench.py --serve-cascade`` and the cascade smoke
    serve behind one plane (serve/cascade.py)."""
    return TrainConfig(
        name="lenet5_big",
        model=lambda: LeNet5Big(),
        task="classification",
        batch_size=64,
        total_epochs=50,
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
        scheduler=SchedulerConfig(
            name="plateau", kwargs=dict(mode="max", factor=0.1, patience=10)),
        half_precision=False,
        image_size=32,
        channels=1,
        num_classes=10,
    )
