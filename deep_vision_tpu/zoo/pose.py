"""Pose experiments — parity with Hourglass/tensorflow/main.py:21-40
(Adam lr 1e-3, batch 32, 100 epochs) + the trainer's
ReduceOnPlateau-by-hand on val loss (train.py:46-58, ÷10 after patience)."""

import jax.numpy as jnp

from deep_vision_tpu.core.config import (
    OptimizerConfig,
    SchedulerConfig,
    TrainConfig,
    register_config,
)
from deep_vision_tpu.models.hourglass import StackedHourglass


@register_config("hourglass_toy")
def hourglass_toy():
    """Shrunken stack (order-2, 16 filters, 64² input) for smoke runs and
    the pipeline-mode tests — same structure, minutes not hours."""
    return TrainConfig(
        name="hourglass_toy",
        model=lambda: StackedHourglass(num_stack=4, num_heatmap=8,
                                       filters=16, order=2,
                                       dtype=jnp.float32),
        task="pose",
        batch_size=16,
        total_epochs=2,
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
        image_size=64,
        num_classes=8,
        half_precision=False,
    )


@register_config("hourglass104")
def hourglass104():
    return TrainConfig(
        name="hourglass104",
        model=lambda: StackedHourglass(num_stack=4, num_heatmap=16,
                                       dtype=jnp.bfloat16),
        task="pose",
        batch_size=32,
        total_epochs=100,
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
        scheduler=SchedulerConfig(
            name="plateau", kwargs=dict(mode="max", factor=0.1, patience=5)),
        image_size=256,
        num_classes=16,  # heatmap channels
    )
