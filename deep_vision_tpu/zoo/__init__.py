"""Experiment zoo: registers a TrainConfig per model, replacing the
reference's per-directory ``training_config`` dicts."""

import deep_vision_tpu.zoo.centernet  # noqa: F401
import deep_vision_tpu.zoo.classifiers  # noqa: F401
import deep_vision_tpu.zoo.detection  # noqa: F401
import deep_vision_tpu.zoo.gan  # noqa: F401
import deep_vision_tpu.zoo.lenet  # noqa: F401
import deep_vision_tpu.zoo.pose  # noqa: F401
import deep_vision_tpu.zoo.resnet  # noqa: F401
