"""ResNet experiments — config parity with the reference's
``training_config['resnet50']`` etc. (ResNet/pytorch/train.py:26-215):
SGD momentum 0.9, weight decay 1e-4, batch 256 (50/152) / 512 (34),
ReduceLROnPlateau(max, 0.1, patience=10) on val top-1.

``resnet50_modern`` is the parity-plus recipe for the 76% top-1 target
(BASELINE.md north star): warmup+cosine, label smoothing 0.1, bf16.
"""

import jax.numpy as jnp

from deep_vision_tpu.core.config import (
    OptimizerConfig,
    SchedulerConfig,
    TrainConfig,
    register_config,
)
from deep_vision_tpu.models import resnet


def _base(name, model_fn, batch_size, lr):
    return TrainConfig(
        name=name,
        model=model_fn,
        task="classification",
        batch_size=batch_size,
        total_epochs=100,
        optimizer=OptimizerConfig(name="sgd", learning_rate=lr, momentum=0.9,
                                  weight_decay=1e-4),
        scheduler=SchedulerConfig(
            name="plateau", kwargs=dict(mode="max", factor=0.1, patience=10)),
        image_size=224,
        num_classes=1000,
    )


@register_config("resnet34")
def resnet34():
    # reference ran global batch 512 on 8 GPUs, lr 0.1 (train.py:141-148)
    return _base("resnet34", lambda: resnet.ResNet34(dtype=jnp.bfloat16), 512, 0.1)


@register_config("resnet50")
def resnet50():
    # reference: batch 256, lr 0.1 (train.py:166-184)
    return _base("resnet50", lambda: resnet.ResNet50(dtype=jnp.bfloat16), 256, 0.1)


@register_config("resnet152")
def resnet152():
    return _base("resnet152", lambda: resnet.ResNet152(dtype=jnp.bfloat16), 256, 0.1)


@register_config("resnet50v2")
def resnet50v2():
    return _base("resnet50v2", lambda: resnet.ResNet50V2(dtype=jnp.bfloat16), 256, 0.1)


@register_config("resnet50_modern")
def resnet50_modern():
    cfg = _base("resnet50_modern",
                lambda: resnet.ResNet50(dtype=jnp.bfloat16), 1024, 0.4)
    cfg.total_epochs = 90
    # linear LR scaling: 0.1 × (1024/256); 5-epoch warmup (Goyal et al.)
    cfg.scheduler = SchedulerConfig(
        name="warmup_cosine", kwargs=dict(total_epochs=90, warmup_epochs=5))
    cfg.label_smoothing = 0.1
    return cfg
