"""Classifier experiment configs — hyperparameter parity with the
reference's ``training_config`` dicts (identical copies live in
AlexNet/VGG/Inception/MobileNet/ShuffleNet ``pytorch/train.py:26-215``):

- alexnet1/2:  SGD lr 0.01 mom 0.9 wd 5e-4, batch 128, plateau(max, 0.1)
- vgg16/19:    SGD lr 0.01 mom 0.9 wd 5e-4, batch 128, StepLR(10, 0.5)
- inception1:  SGD lr 0.01 mom 0.9 wd 2e-4, batch 128, sqrt-poly LambdaLR
- mobilenet1:  RMSprop lr 0.045 alpha 0.9 eps 1.0, batch 128, StepLR(2, 0.94)
- shufflenet/inception_v3: reference left these unfinished (empty model file /
  5-line stub); configs here follow their papers.
"""

import jax.numpy as jnp

from deep_vision_tpu.core.config import (
    OptimizerConfig,
    SchedulerConfig,
    TrainConfig,
    register_config,
)
from deep_vision_tpu.models import alexnet, inception, mobilenet, shufflenet, vgg

_BF16 = jnp.bfloat16


def _cfg(name, model_fn, *, batch=128, epochs=200, opt=None, sched=None,
         image_size=224, **kw):
    return TrainConfig(
        name=name, model=model_fn, task="classification",
        batch_size=batch, total_epochs=epochs,
        optimizer=opt or OptimizerConfig(name="sgd", learning_rate=0.01,
                                         momentum=0.9, weight_decay=5e-4),
        scheduler=sched or SchedulerConfig(
            name="plateau", kwargs=dict(mode="max", factor=0.1, patience=10)),
        image_size=image_size, num_classes=1000, **kw)


@register_config("alexnet1")
def alexnet1():
    return _cfg("alexnet1", lambda: alexnet.AlexNetV1(dtype=_BF16))


@register_config("alexnet2")
def alexnet2():
    return _cfg("alexnet2", lambda: alexnet.AlexNetV2(dtype=_BF16))


@register_config("vgg16")
def vgg16():
    return _cfg("vgg16", lambda: vgg.VGG16(dtype=_BF16),
                sched=SchedulerConfig(name="step",
                                      kwargs=dict(step_size=10, gamma=0.5)))


@register_config("vgg19")
def vgg19():
    return _cfg("vgg19", lambda: vgg.VGG19(dtype=_BF16),
                sched=SchedulerConfig(name="step",
                                      kwargs=dict(step_size=10, gamma=0.5)))


@register_config("inception1")
def inception1():
    return _cfg("inception1", lambda: inception.InceptionV1(dtype=_BF16),
                opt=OptimizerConfig(name="sgd", learning_rate=0.01,
                                    momentum=0.9, weight_decay=2e-4),
                sched=SchedulerConfig(name="sqrt_poly",
                                      kwargs=dict(horizon=60)))


@register_config("inception3")
def inception3():
    # proper V3 (reference stub); RMSprop recipe from the V3 paper
    return _cfg("inception3", lambda: inception.InceptionV3(dtype=_BF16),
                image_size=299,
                opt=OptimizerConfig(name="rmsprop", learning_rate=0.045,
                                    rms_decay=0.9, eps=1.0),
                sched=SchedulerConfig(name="step",
                                      kwargs=dict(step_size=2, gamma=0.94)))


@register_config("mobilenet1")
def mobilenet1():
    return _cfg("mobilenet1", lambda: mobilenet.MobileNetV1(dtype=_BF16),
                opt=OptimizerConfig(name="rmsprop", learning_rate=0.045,
                                    rms_decay=0.9, eps=1.0),
                sched=SchedulerConfig(name="step",
                                      kwargs=dict(step_size=2, gamma=0.94)))


@register_config("shufflenet1")
def shufflenet1():
    # ShuffleNet paper: SGD, linear decay over 240 epochs, wd 4e-5
    return _cfg("shufflenet1", lambda: shufflenet.ShuffleNetV1(dtype=_BF16),
                batch=256, epochs=240,
                opt=OptimizerConfig(name="sgd", learning_rate=0.1,
                                    momentum=0.9, weight_decay=4e-5),
                sched=SchedulerConfig(name="linear_decay",
                                      kwargs=dict(total_epochs=240,
                                                  decay_start=1)))
