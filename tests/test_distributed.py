"""Multi-PROCESS initialization for real (VERDICT r2 #6): two local
processes + a coordinator form a CPU 'pod'; initialize() and
make_pod_mesh() must agree on the global mesh and a cross-process
collective must produce the global answer on both ranks.  And one level
up (VERDICT r3 weak #3): a full Trainer.fit epoch loop with per-process
data shards, coordinated Orbax checkpointing, and a resume."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_distributed_two_processes():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "dist_worker.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, worker, coord, str(pid), "2"], env=env, cwd=repo,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out}"
        # both ranks saw the full 2-process, 4-device sum (2·1 + 2·2)
        assert f"RESULT pid={pid} sum=6.0" in out, out


def _run_fit_workers(worker_name: str, tmp_path) -> list[str]:
    """Launch a 2-process pod running ``worker_name`` against a shared
    workdir; return each rank's RESULT payload after asserting rank
    success."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", worker_name)
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, worker, coord, str(pid), "2", str(tmp_path)],
        env=env, cwd=repo,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    results = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out}"
        line = [ln for ln in out.splitlines()
                if ln.startswith(f"RESULT pid={pid}")]
        assert line, out
        results.append(line[0].split(f"RESULT pid={pid} ")[1])
    return results


@pytest.mark.slow
def test_distributed_trainer_fit(tmp_path):
    """2-process CPU pod runs Trainer.fit end to end: local data shards →
    process-spanning global batches, epoch loop + eval, process-0 Orbax
    checkpointing, then a fresh-process resume that continues the run —
    the semantics a real multi-host pod depends on."""
    results = _run_fit_workers("dist_fit_worker.py", tmp_path)
    # global metrics: every rank computed the SAME final step and loss
    assert results[0] == results[1], results
    # exactly one metrics.jsonl stream (process 0), plus the checkpoints
    assert (tmp_path / "metrics.jsonl").exists()
    assert (tmp_path / "checkpoints").is_dir()


@pytest.mark.slow
def test_distributed_pipeline_fit(tmp_path):
    """Multi-process × pipeline composition (VERDICT r4 weak #3): 2
    processes × 2 local virtual devices train the stacked hourglass on
    {data:2 across procs, pipe:2 local} — the actual v4-32 topology for
    the deep stacks — through fit, process-0 checkpoint, and a
    fresh-trainer resume.  The worker also asserts the stage params stay
    pipe-sharded through placement AND restore."""
    results = _run_fit_workers("dist_pipe_worker.py", tmp_path)
    assert results[0] == results[1], results
    assert (tmp_path / "metrics.jsonl").exists()
    assert (tmp_path / "checkpoints").is_dir()


class _PodView:
    """Proxy for the ``jax`` module that fakes a 2-process pod for code
    inside core/trainer.py ONLY (parallel/mesh.py keeps the real module,
    so batch sharding stays single-process)."""

    def __init__(self, rank: int):
        self._rank = rank

    def process_count(self) -> int:
        return 2

    def process_index(self) -> int:
        return self._rank

    def __getattr__(self, name):
        import jax
        return getattr(jax, name)


def test_eval_rank0_gate_and_broadcast(monkeypatch, tmp_path, mesh1):
    """The multi-process eval gate, validated without a pod: with the
    trainer seeing a faked 2-process view, the host-side mAP accumulator
    must feed on rank 0 and stay EMPTY on rank 1, rank 1 must still
    report every scalar metric key (received via broadcast), and the
    rank-0 numbers must match the plain single-process sweep (the fake
    allgather is an identity, so the math is directly comparable)."""
    import jax
    import numpy as np
    from jax.experimental import multihost_utils

    from deep_vision_tpu.core import trainer as trainer_mod
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.detection import (
        DetectionLoader,
        synthetic_detection_dataset,
    )
    from deep_vision_tpu.tasks.detection import YoloTask

    cfg = get_config("yolov3_toy")
    samples = synthetic_detection_dataset(8, 64, 3, seed=5)
    val = DetectionLoader(samples, 4, 3, 64, train=False)

    task = YoloTask(3)
    feeds = {"n": 0}
    real_make = task.make_host_evaluator

    def counting_make():
        ev = real_make()
        orig = ev.add_batch

        def add_batch(batch):
            feeds["n"] += 1
            return orig(batch)

        ev.add_batch = add_batch
        return ev

    task.make_host_evaluator = counting_make

    trainer = Trainer(cfg, cfg.model(), task, mesh=mesh1,
                      workdir=str(tmp_path))
    state = trainer.init_state(next(iter(val)))

    # ground truth: the plain single-process sweep
    baseline = trainer.evaluate(state, val)
    assert feeds["n"] > 0 and "mAP50_95" in baseline

    calls = {"broadcast": 0}

    def fake_allgather(tree, tiled=False):
        return jax.tree.map(np.asarray, tree)  # 1 process: identity

    def fake_broadcast(x):
        calls["broadcast"] += 1
        return np.asarray(x)

    results = {}
    for rank in (0, 1):
        feeds["n"] = 0
        with monkeypatch.context() as m:
            m.setattr(trainer_mod, "jax", _PodView(rank))
            m.setattr(multihost_utils, "process_allgather", fake_allgather)
            m.setattr(multihost_utils, "broadcast_one_to_all",
                      fake_broadcast)
            results[rank] = trainer.evaluate(state, val)
        if rank == 0:
            assert feeds["n"] > 0, "rank 0 must feed the accumulator"
        else:
            assert feeds["n"] == 0, \
                "rank 1 fed the accumulator — the sweep must be rank-0 only"
    assert calls["broadcast"] == 2  # both ranks took the broadcast path

    # rank 0 reproduces the single-process metrics exactly
    for k, v in baseline.items():
        if isinstance(v, (int, float)):
            assert results[0][k] == pytest.approx(v), k
    # rank 1 reports every scalar key rank 0 has (broadcast contract)
    scalar = {k for k, v in results[0].items() if isinstance(v, (int, float))}
    assert scalar <= set(results[1]), scalar - set(results[1])
    assert np.isfinite(results[1]["loss"])


@pytest.mark.slow
def test_distributed_eval_rank0_broadcast(tmp_path):
    """Multi-process eval no longer replicates the host-side mAP sweep
    on every rank: the detection extras are allgathered (collectively)
    but only process 0 feeds the accumulator; the scalar metrics are
    broadcast so both ranks report IDENTICAL loss and mAP.  The worker
    asserts rank 1's accumulator never saw a batch."""
    results = _run_fit_workers("dist_eval_worker.py", tmp_path)
    assert results[0] == results[1], results
    assert "mAP50_95=" in results[0]


@pytest.mark.slow
def test_distributed_detection_fit(tmp_path):
    """Multi-process DETECTION (VERDICT r4 weak #3's second half): 2
    ranks feed per-host detection shards (host-side 3-scale label encode
    each) into a data-parallel YOLO-toy fit; eval runs decode+NMS on
    device and allgathers every rank's detections into the host mAP
    accumulator, so both ranks report identical global loss AND mAP."""
    results = _run_fit_workers("dist_det_worker.py", tmp_path)
    assert results[0] == results[1], results
    assert "mAP50_95=" in results[0]
