"""Multi-PROCESS initialization for real (VERDICT r2 #6): two local
processes + a coordinator form a CPU 'pod'; initialize() and
make_pod_mesh() must agree on the global mesh and a cross-process
collective must produce the global answer on both ranks."""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_distributed_two_processes():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "dist_worker.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, worker, coord, str(pid), "2"], env=env, cwd=repo,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out}"
        # both ranks saw the full 2-process, 4-device sum (2·1 + 2·2)
        assert f"RESULT pid={pid} sum=6.0" in out, out
