"""`make batch-smoke`: the offline batch tier end to end through the
real CLI wiring (cli.serve.build_server with --jobs-dir) on a random
port — POST a bulk job manifest over HTTP while interactive requests
keep answering 200, poll the job handle to completion, stream the
chunked ndjson results, and find the batch goodput series in /metrics;
then boot a SECOND server over the same jobs directory and watch it
resume an unfinished job straight from the JSONL checkpoint — no HTTP
resubmit, no duplicated results (docs/BATCH.md).
Run directly, not under pytest; chained into `make serve-smoke`."""

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/batch_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _args(workdir: str) -> argparse.Namespace:
    return argparse.Namespace(
        model="lenet5", workdir=workdir, stablehlo=None,
        host="127.0.0.1", port=0, max_batch=4, max_wait_ms=2.0,
        buckets=None, max_queue=64, warmup=False, verbose=False,
        pipeline_depth=2, faults="", fault_seed=0, serve_devices=1,
        shard_batches=False, wire_dtype="uint8", infer_dtype="float32",
        jobs_dir=os.path.join(workdir, "jobs"), batch_shard_size=2,
        batch_interval_ms=2.0, batch_max_depth=0,
        batch_pressure_ms=10.0)


def _get(base: str, path: str):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        return r.status, json.loads(r.read())


def _post(base: str, path: str, payload: dict):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read())


def _poll_done(base: str, job_id: str, timeout: float = 120.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        _, st = _get(base, f"/v1/jobs/{job_id}")
        if st["state"] in ("done", "failed"):
            return st
        time.sleep(0.01)
    raise AssertionError(f"job {job_id} never finished")


def _manifest(n: int) -> list:
    return [{"pixels": np.random.default_rng(i).integers(
        0, 256, (32, 32, 1)).tolist()} for i in range(n)]


def smoke(workdir: str) -> None:
    from deep_vision_tpu.cli.serve import build_server

    engine, server = build_server(_args(workdir))
    server.start_background()
    base = f"http://{server.host}:{server.port}"
    try:
        # the bulk job rides the same engine the interactive tier uses
        status, view = _post(base, "/v1/jobs",
                             {"model": "lenet5", "items": _manifest(8)})
        assert status == 202 and view["n_shards"] == 4, view
        jid = view["job_id"]
        # interactive traffic keeps answering 200 while the job drains
        px = np.random.default_rng(9).integers(0, 256, (32, 32, 1))
        for _ in range(4):
            s, out = _post(base, "/v1/classify", {"pixels": px.tolist()})
            assert s == 200 and len(out["top"]) == 5, out
        st = _poll_done(base, jid)
        assert st["state"] == "done" and st["images_done"] == 8, st

        # chunked ndjson results: every index exactly once, in order,
        # with the terminal status line
        with urllib.request.urlopen(base + f"/v1/jobs/{jid}/results",
                                    timeout=60) as r:
            assert r.headers.get("Transfer-Encoding") == "chunked", \
                dict(r.headers)
            lines = [json.loads(ln) for ln in r.read().splitlines()]
        assert [ln["index"] for ln in lines[:-1]] == list(range(8)), \
            [ln.get("index") for ln in lines]
        assert all(len(ln["top"]) == 5 for ln in lines[:-1])
        assert lines[-1]["status"]["state"] == "done"

        _, stats = _get(base, "/v1/stats")
        batch = stats["batch"]
        assert batch["jobs"]["images_done"] == 8, batch["jobs"]
        assert batch["scheduler"]["shards_done"] == 4, batch["scheduler"]
        with urllib.request.urlopen(base + "/metrics", timeout=60) as r:
            text = r.read().decode()
        assert "dvt_batch_images_total 8" in text
        assert "dvt_batch_occupancy" in text
        print(f"batch-smoke PASS (submit+drain): job {jid} done, "
              f"8/8 images, {batch['scheduler']['shards_done']} shards, "
              f"interactive 200s throughout, chunked results + metrics "
              f"from port {server.port}")
    finally:
        server.shutdown()
        sched = getattr(server.httpd, "batch_sched", None)
        if sched is not None:
            sched.stop()
        engine.stop(drain_deadline=5.0)

    # -- restart resume: an unfinished job in the ledger drains on boot --
    # submit straight into the durable store with NO scheduler attached —
    # the stand-in for a server killed right after accepting the job
    from deep_vision_tpu.serve.jobs import JobStore

    store = JobStore(os.path.join(workdir, "jobs"))
    jid2 = store.submit("lenet5", "classify", _manifest(4),
                        shard_size=2)["job_id"]
    del store

    engine, server = build_server(_args(workdir))
    server.start_background()
    base = f"http://{server.host}:{server.port}"
    try:
        st = _poll_done(base, jid2)  # drained with zero HTTP resubmits
        assert st["state"] == "done" and st["images_done"] == 4, st
        _, stats = _get(base, "/v1/stats")
        jobs = stats["batch"]["jobs"]
        assert jobs["resumed"] == 1, jobs  # picked up from the ledger
        # the finished job from server #1 replayed durable and was NOT
        # re-run: this server's scheduler only drained job #2's shards
        assert stats["batch"]["scheduler"]["shards_done"] == 2, stats
        assert jobs["states"]["done"] == 2, jobs
        with urllib.request.urlopen(base + f"/v1/jobs/{jid2}/results",
                                    timeout=60) as r:
            lines = [json.loads(ln) for ln in r.read().splitlines()]
        assert [ln["index"] for ln in lines[:-1]] == list(range(4))
        print(f"batch-smoke PASS (restart resume): job {jid2} resumed "
              f"from the JSONL checkpoint and drained 4/4 images, "
              f"prior job replayed without re-execution")
    finally:
        server.shutdown()
        sched = getattr(server.httpd, "batch_sched", None)
        if sched is not None:
            sched.stop()
        engine.stop(drain_deadline=5.0)


def main():
    with tempfile.TemporaryDirectory() as workdir:
        smoke(workdir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
