"""`make cascade-smoke`: the confidence-routed cascade end to end
through the real CLI wiring (cli.serve.build_server) on random ports.

Lane 1 — a THREE-tier int8-fronted classify chain (--models
lenet5_nano,lenet5,lenet5_big --cascade lenet5_nano:lenet5:lenet5_big
--cascade-quant-front) with an injected transient compute fault.
Clients address the BIG model; the smoke hammers it from threads while
asserting: fail-closed all-big service before calibration, per-hop
dual-run calibration flipping hops to serve (X-DVT-Tier front / t1),
an always-big QoS tenant (X-DVT-Tenant) never leaving the big tier,
/v1/models carrying the per-tier ``cascade`` block, a mid-load FRONT
reload resetting ONLY hop 0 (hop 1's sample survives) then
RE-calibrating, a mid-load MID reload resetting ONLY hop 1 (hop 0
stays calibrated) — all with zero client errors — and every /metrics
line parsing as prometheus text with the per-hop dvt_cascade_* series
present (docs/SERVING.md "Cascaded serving").

Lane 2 — a detect cascade (yolov3_toy:centernet_toy) with the
Soft-NMS + per-class-K epilogue knobs on, proving the cascade routes
non-classify verbs through the device-decoded signal.

Run directly, not under pytest; chained into `make serve-smoke`."""

import argparse
import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/cascade_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NANO, FRONT, BIG = "lenet5_nano", "lenet5", "lenet5_big"
DET_FRONT, DET_BIG = "yolov3_toy", "centernet_toy"

# prometheus text exposition: `name{labels} value` / `# HELP|TYPE ...`
_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$")


def _args(workdir: str) -> argparse.Namespace:
    return argparse.Namespace(
        model=None, models=f"{NANO},{FRONT},{BIG}", workdir=workdir,
        stablehlo=None, host="127.0.0.1", port=0, max_batch=4,
        max_wait_ms=2.0, buckets=None, max_queue=64, warmup=True,
        verbose=False, pipeline_depth=2,
        # one transient compute failure: the cascade must ride the
        # engine's bisect-retry without surfacing a client error
        faults="compute:exception:times=1", fault_seed=0,
        serve_devices=1, shard_batches=False, wire_dtype="float32",
        infer_dtype="float32",
        # random-init tiers rarely agree, so the smoke calibrates on
        # machinery, not quality: ANY observed agreement qualifies.
        # min_sample=6 lets the starved MIDDLE hop (it only sees
        # traffic while hop 0 is uncalibrated) reach calibration
        cascade=f"{NANO}:{FRONT}:{BIG}", cascade_min_agreement=0.0,
        cascade_sample_period=3, cascade_min_sample=6, cascade_topk=3,
        cascade_quant_front=True,
        # fast canary so the mid-load reloads promote in seconds; the
        # phase timeout stays under the client HTTP timeout so a
        # starved canary resolves instead of hanging wait=True
        hbm_budget_mb=0.0, canary_frac=0.5, canary_min_requests=3,
        canary_max_error_rate=1.0, canary_max_p99_ratio=50.0,
        shadow_frac=0.0, phase_timeout_s=20.0,
        qos=("premium:rate=0,always_big=1,tenants=acme;"
             "standard:rate=0;default=standard"))


def _detect_args(workdir: str) -> argparse.Namespace:
    return argparse.Namespace(
        model=None, models=f"{DET_FRONT},{DET_BIG}", workdir=workdir,
        stablehlo=None, host="127.0.0.1", port=0, max_batch=2,
        max_wait_ms=2.0, buckets=None, max_queue=64, warmup=True,
        verbose=False, pipeline_depth=2,
        faults="compute:exception:times=1", fault_seed=0,
        serve_devices=1, shard_batches=False, wire_dtype="float32",
        infer_dtype="float32",
        cascade=f"{DET_FRONT}:{DET_BIG}", cascade_min_agreement=0.0,
        cascade_sample_period=3, cascade_min_sample=6, cascade_topk=4,
        # the detect epilogue variants ride the same CLI wiring
        detect_soft_nms="gaussian", detect_soft_sigma=0.5,
        detect_max_per_class=2,
        hbm_budget_mb=0.0, canary_frac=0.5, canary_min_requests=3,
        canary_max_error_rate=1.0, canary_max_p99_ratio=50.0,
        shadow_frac=0.0, phase_timeout_s=60.0)


def _get(base: str, path: str):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        return r.status, json.loads(r.read())


def _post(base: str, path: str, payload: dict, headers: dict = None,
          timeout: float = 60):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(), headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _cascade_stats(base: str) -> dict:
    _, stats = _get(base, "/v1/stats")
    assert "cascade" in stats, sorted(stats)
    return stats["cascade"]


def _wait_for(what: str, predicate, deadline_s: float = 60.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        out = predicate()
        if out is not None:
            return out
        time.sleep(0.05)
    raise AssertionError(f"timed out after {deadline_s}s waiting for {what}")


def _check_metrics(base: str, required: tuple) -> str:
    """Every /metrics line must parse; the named series must exist."""
    with urllib.request.urlopen(base + "/metrics", timeout=60) as r:
        text = r.read().decode()
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        assert _METRIC_LINE.match(ln), f"unparseable metric: {ln!r}"
        float(ln.rsplit(" ", 1)[1])  # value must be a number
    for series in required:
        assert series in text, f"missing {series} in /metrics"
    return text


def smoke(workdir: str) -> None:
    from deep_vision_tpu.cli.serve import build_server

    plane, server = build_server(_args(workdir))
    server.start_background()
    base = f"http://{server.host}:{server.port}"
    rng = np.random.default_rng(0)
    imgs = [rng.uniform(0.0, 1.0, (32, 32, 1)).tolist()
            for _ in range(8)]
    try:
        # -- fail closed: uncalibrated chain serves everything big ----
        cas = _cascade_stats(base)
        assert cas["tiers"] == [NANO, FRONT, BIG], cas["tiers"]
        assert len(cas["hops"]) == 2, cas["hops"]
        assert all(h["threshold"] is None for h in cas["hops"]), cas
        s, out, hdrs = _post(base, f"/v1/models/{BIG}/classify",
                             {"pixels": imgs[0]})
        assert s == 200 and out["top"], out
        assert hdrs.get("X-DVT-Tier") == "big", hdrs

        # -- /v1/models: every chain member carries its cascade block -
        _, models = _get(base, "/v1/models")
        entries = models["models"]
        assert entries[NANO]["cascade"]["role"] == "front"
        assert entries[NANO]["cascade"]["hop"] == 0
        assert entries[NANO]["model"]["infer_dtype"] == "int8", \
            entries[NANO]["model"]  # --cascade-quant-front
        assert entries[FRONT]["cascade"]["role"] == "mid"
        assert entries[FRONT]["cascade"]["hop"] == 1
        assert entries[BIG]["cascade"]["role"] == "big"

        # -- hammer the big model's route; every failure is a bug -----
        errors, served = [], [0]
        tiers = {"front": 0, "t1": 0, "big": 0}
        stop = threading.Event()
        lock = threading.Lock()

        def hammer():
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    s, out, hdrs = _post(
                        base, f"/v1/models/{BIG}/classify",
                        {"pixels": imgs[i % len(imgs)]})
                    assert s == 200 and out["top"], out
                    tier = hdrs.get("X-DVT-Tier")
                    assert tier in tiers, hdrs
                    with lock:
                        served[0] += 1
                        tiers[tier] += 1
                except Exception as e:  # noqa: BLE001 — any failure is a lost request
                    errors.append(repr(e))

        def direct_hammer():
            # paced direct-route traffic on the MIDDLE tier: once the
            # chain calibrates, almost nothing reaches lenet5 through
            # the router, and its reload canary would starve without
            # its own route carrying requests
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    s, out, _ = _post(
                        base, f"/v1/models/{FRONT}/classify",
                        {"pixels": imgs[i % len(imgs)]})
                    assert s == 200 and out["top"], out
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))
                time.sleep(0.05)

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(2)]
        threads.append(threading.Thread(target=direct_hammer,
                                        daemon=True))
        for t in threads:
            t.start()

        # dual-run sampling calibrates hop 0 under live load
        cas = _wait_for(
            "hop 0 calibration from dual-run samples",
            lambda: (lambda c: c if c["hops"][0]["calibrated"]
                     else None)(_cascade_stats(base)))
        assert cas["samples"] >= 6 and cas["calibrations"] >= 1, cas
        # min_agreement=0 calibrates at the lowest POPULATED bin, so
        # the int8 front tier now answers confident traffic directly
        _wait_for("front tier serving past calibration",
                  lambda: tiers["front"] or None)

        # -- always-big tenant: premium QoS never leaves the big tier -
        for _ in range(5):
            s, out, hdrs = _post(base, f"/v1/models/{BIG}/classify",
                                 {"pixels": imgs[0]},
                                 headers={"X-DVT-Tenant": "acme"})
            assert s == 200 and hdrs.get("X-DVT-Tier") == "big", hdrs
        cas = _cascade_stats(base)
        assert cas["forced_big"] >= 5, cas

        # the NANO tier still answers its own direct route, int8
        # weights and all (the cascade serves the BIG name only)
        s, out, hdrs = _post(base, f"/v1/models/{NANO}/classify",
                             {"pixels": imgs[0]})
        assert s == 200 and out["top"], out
        assert "X-DVT-Tier" not in hdrs, hdrs

        # -- mid-load FRONT reload: hop 0 resets ALONE, hop 1's -------
        # sample survives, and the pass-through traffic while hop 0
        # recalibrates feeds hop 1 to ITS calibration
        hop1_samples = cas["hops"][1]["samples"]
        resets_before = cas["resets"]
        s, out, _ = _post(base, f"/v1/models/{NANO}/reload",
                          {"force": True, "wait": True}, timeout=300)
        assert s == 200, out
        cas = _wait_for(
            "hop 0 reset after front reload",
            lambda: (lambda c: c if c["resets"] > resets_before
                     else None)(_cascade_stats(base)))
        assert cas["hops"][1]["samples"] >= hop1_samples, \
            (cas["hops"], hop1_samples)  # per-hop reset: hop 1 kept
        cas = _wait_for(
            "hop 0 recalibration + hop 1 calibration after reload",
            lambda: (lambda c: c
                     if c["hops"][0]["calibrated"]
                     and c["hops"][1]["calibrated"]
                     and c["calibrations"] >= 2
                     else None)(_cascade_stats(base)))
        # while hop 0 was uncalibrated its traffic escalated THROUGH
        # to the now-calibrated middle tier, which served some of it
        _wait_for("middle tier serving (X-DVT-Tier: t1)",
                  lambda: tiers["t1"] or None)

        # -- mid-load MID reload: hop 1 resets ALONE ------------------
        resets_before = cas["resets"]
        s, out, _ = _post(base, f"/v1/models/{FRONT}/reload",
                          {"force": True, "wait": True}, timeout=300)
        assert s == 200, out
        cas = _wait_for(
            "hop 1 reset after mid reload",
            lambda: (lambda c: c if c["resets"] > resets_before
                     else None)(_cascade_stats(base)))
        assert cas["hops"][0]["calibrated"], cas["hops"]  # hop 0 kept
        assert cas["hops"][1]["threshold"] is None, cas["hops"]

        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert len(errors) == 0, errors[:5]
        assert served[0] > 0 and tiers["front"] > 0 and tiers["t1"] > 0, \
            (served, tiers)

        # -- /metrics: every line parses; per-hop series present ------
        text = _check_metrics(base, (
            "dvt_cascade_requests_total",
            "dvt_cascade_escalations_total",
            "dvt_cascade_threshold",
            'hop="0"',  # per-hop labels (alphabetical label order)
            "dvt_cascade_hop_agreement",
            "dvt_cascade_hop_escalations_total",
            "dvt_cascade_calibrated",
            "dvt_cascade_calibration_samples_total",
            "dvt_cascade_forced_big_total",
            "dvt_cascade_recalibrations_total",
            "dvt_cascade_latency_seconds"))
        assert 'tier="t1"' in text, "missing mid-tier labels in /metrics"
        print(f"cascade-smoke PASS (classify): {served[0]} requests "
              f"(front {tiers['front']}, t1 {tiers['t1']}, "
              f"big {tiers['big']}), 0 errors through a fault-injected "
              f"3-tier int8-fronted chain with mid-load front AND mid "
              f"reloads; per-hop resets/recalibrations verified "
              f"({cas['calibrations']} calibrations, {cas['resets']} "
              f"resets); always-big tenant pinned; all /metrics lines "
              f"parsed from port {server.port}")
    finally:
        server.shutdown()
        plane.stop(drain_deadline=5.0)


def detect_smoke(workdir: str) -> None:
    """Lane 2: the cascade routes the detect verb on device-decoded
    rows (valid-count + max-score signal, greedy-IoU agreement), with
    the Soft-NMS/per-class-K epilogue knobs live."""
    from deep_vision_tpu.cli.serve import build_server

    plane, server = build_server(_detect_args(workdir))
    server.start_background()
    base = f"http://{server.host}:{server.port}"
    rng = np.random.default_rng(1)
    imgs = [rng.uniform(0.0, 1.0, (64, 64, 3)).tolist()
            for _ in range(4)]
    try:
        cas = _cascade_stats(base)
        assert cas["tiers"] == [DET_FRONT, DET_BIG], cas["tiers"]
        s, out, hdrs = _post(base, f"/v1/models/{DET_BIG}/detect",
                             {"pixels": imgs[0]})
        assert s == 200 and "num_detections" in out, out
        assert hdrs.get("X-DVT-Tier") == "big", hdrs

        # the Soft-NMS knobs made it through the CLI to the epilogue
        _, models = _get(base, "/v1/models")
        entries = models["models"]
        det = entries[DET_FRONT]["model"]["detect"]
        assert det["soft_nms"] == "gaussian" and det["max_per_class"] == 2
        assert entries[DET_FRONT]["cascade"]["role"] == "front"

        errors, served, fronted = [], [0], [0]
        stop = threading.Event()
        lock = threading.Lock()

        def hammer():
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    s, out, hdrs = _post(
                        base, f"/v1/models/{DET_BIG}/detect",
                        {"pixels": imgs[i % len(imgs)]})
                    assert s == 200 and "num_detections" in out, out
                    with lock:
                        served[0] += 1
                        if hdrs.get("X-DVT-Tier") == "front":
                            fronted[0] += 1
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(2)]
        for t in threads:
            t.start()

        cas = _wait_for(
            "detect cascade calibration from device-decoded samples",
            lambda: (lambda c: c if c["calibrated"] else None)(
                _cascade_stats(base)), deadline_s=120.0)
        assert cas["samples"] >= 6, cas
        _wait_for("front detect tier serving",
                  lambda: fronted[0] or None, deadline_s=120.0)

        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert len(errors) == 0, errors[:5]

        _check_metrics(base, ("dvt_cascade_requests_total",
                              "dvt_cascade_threshold",
                              "dvt_cascade_hop_agreement"))
        print(f"cascade-smoke PASS (detect): {served[0]} requests "
              f"({fronted[0]} served by the front detector), 0 errors; "
              f"device-decoded signal calibrated the chain "
              f"(threshold {cas['threshold']:.2f}) with gaussian "
              f"Soft-NMS + per-class-K epilogues on port {server.port}")
    finally:
        server.shutdown()
        plane.stop(drain_deadline=5.0)


def main():
    with tempfile.TemporaryDirectory() as workdir:
        for name in (NANO, FRONT, BIG):
            os.makedirs(os.path.join(workdir, name), exist_ok=True)
        smoke(workdir)
    with tempfile.TemporaryDirectory() as workdir:
        for name in (DET_FRONT, DET_BIG):
            os.makedirs(os.path.join(workdir, name), exist_ok=True)
        detect_smoke(workdir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
