"""`make cascade-smoke`: the confidence-routed cascade end to end
through the real CLI wiring (cli.serve.build_server with --models
lenet5,lenet5_big --cascade lenet5:lenet5_big) on a random port, with
an injected transient compute fault.  Clients address the BIG model;
the smoke hammers it from threads while asserting: fail-closed all-big
service before calibration, live dual-run calibration flipping the
router to the front tier (X-DVT-Tier header), an always-big QoS tenant
(X-DVT-Tenant) never leaving the big tier, a mid-load front-tier
reload resetting and then RE-calibrating the threshold with zero
client errors, and every /metrics line parsing as prometheus text with
the dvt_cascade_* series present (docs/SERVING.md "Cascaded serving").
Run directly, not under pytest; chained into `make serve-smoke`."""

import argparse
import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/cascade_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FRONT, BIG = "lenet5", "lenet5_big"

# prometheus text exposition: `name{labels} value` / `# HELP|TYPE ...`
_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$")


def _args(workdir: str) -> argparse.Namespace:
    return argparse.Namespace(
        model=None, models=f"{FRONT},{BIG}", workdir=workdir,
        stablehlo=None, host="127.0.0.1", port=0, max_batch=4,
        max_wait_ms=2.0, buckets=None, max_queue=64, warmup=True,
        verbose=False, pipeline_depth=2,
        # one transient compute failure: the cascade must ride the
        # engine's bisect-retry without surfacing a client error
        faults="compute:exception:times=1", fault_seed=0,
        serve_devices=1, shard_batches=False, wire_dtype="float32",
        infer_dtype="float32",
        # random-init tiers rarely agree, so the smoke calibrates on
        # machinery, not quality: ANY observed agreement qualifies
        cascade=f"{FRONT}:{BIG}", cascade_min_agreement=0.0,
        cascade_sample_period=3, cascade_min_sample=10, cascade_topk=3,
        # fast canary so the mid-load reload promotes in seconds
        hbm_budget_mb=0.0, canary_frac=0.5, canary_min_requests=3,
        canary_max_error_rate=1.0, canary_max_p99_ratio=50.0,
        shadow_frac=0.0, phase_timeout_s=60.0,
        qos=("premium:rate=0,always_big=1,tenants=acme;"
             "standard:rate=0;default=standard"))


def _get(base: str, path: str):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        return r.status, json.loads(r.read())


def _post(base: str, path: str, payload: dict, headers: dict = None):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(), headers=hdrs)
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _cascade_stats(base: str) -> dict:
    _, stats = _get(base, "/v1/stats")
    assert "cascade" in stats, sorted(stats)
    return stats["cascade"]


def _wait_for(what: str, predicate, deadline_s: float = 60.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        out = predicate()
        if out is not None:
            return out
        time.sleep(0.05)
    raise AssertionError(f"timed out after {deadline_s}s waiting for {what}")


def smoke(workdir: str) -> None:
    from deep_vision_tpu.cli.serve import build_server

    plane, server = build_server(_args(workdir))
    server.start_background()
    base = f"http://{server.host}:{server.port}"
    rng = np.random.default_rng(0)
    imgs = [rng.uniform(0.0, 1.0, (32, 32, 1)).tolist()
            for _ in range(8)]
    try:
        # -- fail closed: uncalibrated router serves everything big ---
        cas = _cascade_stats(base)
        assert cas["calibrated"] is False and cas["threshold"] is None, cas
        s, out, hdrs = _post(base, f"/v1/models/{BIG}/classify",
                             {"pixels": imgs[0]})
        assert s == 200 and out["top"], out
        assert hdrs.get("X-DVT-Tier") == "big", hdrs

        # -- hammer the big model's route; every failure is a bug -----
        errors, served, tiers = [], [0], {"front": 0, "big": 0}
        stop = threading.Event()
        lock = threading.Lock()

        def hammer():
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    s, out, hdrs = _post(
                        base, f"/v1/models/{BIG}/classify",
                        {"pixels": imgs[i % len(imgs)]})
                    assert s == 200 and out["top"], out
                    tier = hdrs.get("X-DVT-Tier")
                    assert tier in ("front", "big"), hdrs
                    with lock:
                        served[0] += 1
                        tiers[tier] += 1
                except Exception as e:  # noqa: BLE001 — any failure is a lost request
                    errors.append(repr(e))

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()

        # dual-run sampling calibrates the threshold under live load
        cas = _wait_for(
            "threshold calibration from dual-run samples",
            lambda: (lambda c: c if c["calibrated"] else None)(
                _cascade_stats(base)))
        assert cas["samples"] >= 10 and cas["calibrations"] >= 1, cas
        # min_agreement=0 calibrates at the lowest POPULATED bin, so
        # the front tier now answers confident traffic directly
        _wait_for("front tier serving past calibration",
                  lambda: tiers["front"] or None)

        # -- always-big tenant: premium QoS never sees the front ------
        for _ in range(5):
            s, out, hdrs = _post(base, f"/v1/models/{BIG}/classify",
                                 {"pixels": imgs[0]},
                                 headers={"X-DVT-Tenant": "acme"})
            assert s == 200 and hdrs.get("X-DVT-Tier") == "big", hdrs
        cas = _cascade_stats(base)
        assert cas["forced_big"] >= 5, cas

        # the FRONT tier still answers its own direct route, epilogue
        # and all (dict rows respond identically to dense ones)
        s, out, hdrs = _post(base, f"/v1/models/{FRONT}/classify",
                             {"pixels": imgs[0]})
        assert s == 200 and out["top"], out
        assert "X-DVT-Tier" not in hdrs, hdrs  # cascade serves BIG only

        # -- mid-load front-tier reload: reset, then REcalibrate ------
        resets_before = cas["resets"]
        errors_before = len(errors)
        s, out, _ = _post(base, f"/v1/models/{FRONT}/reload",
                          {"force": True, "wait": True})
        assert s == 200, out
        cas = _wait_for(
            "cascade reset after front reload",
            lambda: (lambda c: c if c["resets"] > resets_before
                     else None)(_cascade_stats(base)))
        cas = _wait_for(
            "recalibration after front reload",
            lambda: (lambda c: c
                     if c["calibrated"] and c["calibrations"] >= 2
                     else None)(_cascade_stats(base)))
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert len(errors) == errors_before == 0, errors[:5]
        assert served[0] > 0 and tiers["front"] > 0, (served, tiers)

        # -- /metrics: every line parses; cascade series present ------
        with urllib.request.urlopen(base + "/metrics", timeout=60) as r:
            text = r.read().decode()
        for ln in text.splitlines():
            if not ln or ln.startswith("#"):
                continue
            assert _METRIC_LINE.match(ln), f"unparseable metric: {ln!r}"
            float(ln.rsplit(" ", 1)[1])  # value must be a number
        for series in ("dvt_cascade_requests_total",
                       "dvt_cascade_escalations_total",
                       "dvt_cascade_threshold",
                       "dvt_cascade_calibrated",
                       "dvt_cascade_calibration_samples_total",
                       "dvt_cascade_forced_big_total",
                       "dvt_cascade_recalibrations_total",
                       "dvt_cascade_latency_seconds"):
            assert series in text, f"missing {series} in /metrics"
        print(f"cascade-smoke PASS: {served[0]} requests "
              f"(front {tiers['front']}, big {tiers['big']}), 0 errors "
              f"through a fault-injected mid-load front reload; "
              f"threshold {cas['threshold']:.2f} recalibrated "
              f"({cas['calibrations']} calibrations, {cas['resets']} "
              f"resets); always-big tenant pinned to the big tier; "
              f"all /metrics lines parsed from port {server.port}")
    finally:
        server.shutdown()
        plane.stop(drain_deadline=5.0)


def main():
    with tempfile.TemporaryDirectory() as workdir:
        for name in (FRONT, BIG):
            os.makedirs(os.path.join(workdir, name), exist_ok=True)
        smoke(workdir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
