"""Preemption-safe checkpointing: SIGTERM (what preemptible TPU VMs get
before eviction) must produce a step-boundary checkpoint + clean return,
and --resume must continue from it.  The reference could only resume from
its last end-of-epoch save."""

import os
import signal

import numpy as np
import pytest

from deep_vision_tpu.core.config import get_config
from deep_vision_tpu.core.trainer import Trainer
from deep_vision_tpu.data.loader import ArrayLoader
from deep_vision_tpu.data.mnist import synthetic_mnist
from deep_vision_tpu.tasks.classification import ClassificationTask


class SigtermAfter:
    """Loader wrapper that sends SIGTERM to this process after N batches —
    the handler runs in the main thread between steps, like a real
    preemption notice arriving mid-epoch."""

    def __init__(self, inner, after: int):
        self.inner = inner
        self.after = after

    def set_epoch(self, epoch):
        self.inner.set_epoch(epoch)

    def __len__(self):
        return len(self.inner)

    def __iter__(self):
        for i, batch in enumerate(self.inner):
            if i == self.after:
                os.kill(os.getpid(), signal.SIGTERM)
            yield batch


def make_trainer(tmp_path, mesh, epochs=3):
    cfg = get_config("lenet5")
    cfg.total_epochs = epochs
    cfg.batch_size = 32
    cfg.log_every_steps = 1
    return cfg, Trainer(cfg, cfg.model(), ClassificationTask(10), mesh=mesh,
                        workdir=str(tmp_path))


def test_sigterm_saves_and_resumes(tmp_path, mesh1):
    cfg, trainer = make_trainer(tmp_path, mesh1)
    data = synthetic_mnist(256)  # 8 batches/epoch
    train = SigtermAfter(ArrayLoader(data, cfg.batch_size, seed=1), after=3)
    state = trainer.fit(train, None)
    step_at_preempt = int(np.asarray(state.step))
    # stopped mid-run, not after the full 3 epochs
    assert 0 < step_at_preempt < 3 * 8
    assert trainer.checkpointer.latest_step() == step_at_preempt

    # resume: picks up the interrupted epoch with the preempted params
    cfg2, trainer2 = make_trainer(tmp_path, mesh1)
    clean_train = ArrayLoader(data, cfg2.batch_size, seed=1)
    state2 = trainer2.init_state(next(iter(clean_train)))
    state2 = trainer2.maybe_resume(state2)
    assert int(np.asarray(state2.step)) == step_at_preempt
    import jax

    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(state.params)),
            jax.tree_util.tree_leaves(jax.device_get(state2.params))):
        np.testing.assert_allclose(a, b)
    # the handler was restored after fit() returned
    assert signal.getsignal(signal.SIGTERM) in (
        signal.SIG_DFL, signal.default_int_handler) or callable(
        signal.getsignal(signal.SIGTERM))


def test_async_save_restores_identically(tmp_path, mesh1):
    """async_save=True (the default): save() returns with serialization
    still in flight, and every read path (latest_step/restore) blocks on
    the in-flight save first — so back-to-back saves and an immediate
    restore see exactly the synchronous result."""
    import jax

    from deep_vision_tpu.core.checkpoint import Checkpointer

    cfg, trainer = make_trainer(tmp_path, mesh1, epochs=1)
    data = synthetic_mnist(64)
    state = trainer.init_state(
        next(iter(ArrayLoader(data, cfg.batch_size, seed=1))))

    ckpt = Checkpointer(str(tmp_path / "async"))
    assert ckpt.async_save
    ckpt.save(1, state, extras={"epoch": 0})
    ckpt.save(2, state, extras={"epoch": 1})  # waits for save 1 first
    ckpt.wait_until_finished()  # the explicit preempt/exit barrier
    assert ckpt.all_steps() == [1, 2]
    restored, extras = ckpt.restore(state)
    assert extras["epoch"] == 1
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(state.params)),
            jax.tree_util.tree_leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ckpt.close()

    # async_save=False keeps the old save-then-wait behavior
    sync = Checkpointer(str(tmp_path / "sync"), async_save=False)
    assert not sync.async_save
    sync.save(3, state, extras={"epoch": 2})
    assert sync.latest_step() == 3
    sync.close()


def test_sigterm_handler_restored(tmp_path, mesh1):
    sentinel = lambda *_: None  # noqa: E731
    prev = signal.signal(signal.SIGTERM, sentinel)
    try:
        cfg, trainer = make_trainer(tmp_path, mesh1, epochs=1)
        data = synthetic_mnist(64)
        trainer.fit(ArrayLoader(data, cfg.batch_size, seed=1), None)
        assert signal.getsignal(signal.SIGTERM) is sentinel
    finally:
        signal.signal(signal.SIGTERM, prev)
