"""Brownout ladder contract (CPU, tier-1 fast): the controller engages
fast (up_window hot ticks jump straight to the target level) and
releases slowly (one level at a time through down_window + cooldown),
holds inside the hysteresis band, survives engines that raise
mid-teardown, and honors the operator force pin immediately.  Plus the
two degradation mechanisms the ladder drives that have no engine
dependency: the response cache's version-stale L2 path and the cascade
calibration ledger's restore / fail-closed semantics.

Everything drives ``tick()`` synchronously over fake engines — ladder
correctness is decision logic, not thread timing.  The end-to-end
overload episode (real engines, gateway hop, injected network faults)
lives in tests/brownout_smoke.py.
"""

import json
import os
import types

import pytest

from deep_vision_tpu.serve.brownout import (
    HARD_SHED_PRESSURE,
    LEVEL_NAMES,
    MAX_LEVEL,
    BrownoutController,
)
from deep_vision_tpu.serve.cache import ResponseCache
from deep_vision_tpu.serve.cascade import CascadeRouter, CascadeSpec

pytestmark = pytest.mark.brownout


class FakeEngine:
    """Just the signal surface the controller samples: queue_depth,
    admission counters/EWMA, occupancy."""

    def __init__(self, ewma_s=0.01):
        self.queue_depth = 0
        self._occ = 0.0
        self.admission = types.SimpleNamespace(
            bucket_ewma_s=lambda: ewma_s,
            shed_queue_full=0, shed_deadline=0, admitted=0)

    def occupancy(self):
        return self._occ


def _controller(eng, **kw):
    kw.setdefault("up_window", 1)
    kw.setdefault("down_window", 2)
    kw.setdefault("cooldown_s", 0.0)
    return BrownoutController([eng], **kw)


# -- the ladder -------------------------------------------------------------


def test_engage_jumps_straight_to_target_level():
    """A hard spike must not climb one level per tick — the target is
    taken in one transition once up_window ticks confirm it."""
    eng = FakeEngine()           # 10 ms of pressure per queued request
    bc = _controller(eng)
    assert bc.level == 0 and bc.tick() == 0
    eng.queue_depth = 50         # 500 ms >= l3_pressure_ms
    assert bc.tick() == 3
    assert bc.transitions_up == 1          # ONE jump, not three steps
    assert bc.stats()["level_entries"] == {"L1": 1, "L2": 1, "L3": 1}
    assert bc.at_least(1) and bc.at_least(3)


def test_up_window_debounces_single_tick_spikes():
    eng = FakeEngine()
    bc = _controller(eng, up_window=2)
    eng.queue_depth = 50
    assert bc.tick() == 0        # one hot tick is noise
    eng.queue_depth = 0
    assert bc.tick() == 0        # streak broken: still normal
    eng.queue_depth = 50
    bc.tick()
    assert bc.tick() == 3        # two consecutive hot ticks engage


def test_release_steps_one_level_at_a_time():
    eng = FakeEngine()
    bc = _controller(eng, down_window=2)
    eng.queue_depth = 50
    bc.tick()
    assert bc.level == 3
    eng.queue_depth = 0
    assert bc.tick() == 3        # first cool tick: not yet
    assert bc.tick() == 2        # down_window reached: ONE level
    bc.tick()
    assert bc.tick() == 1
    bc.tick()
    assert bc.tick() == 0
    assert bc.transitions_down == 3
    assert LEVEL_NAMES[bc.level] == "normal"


def test_hysteresis_band_holds_level():
    """Signals below the engage bar but above down_ratio × it neither
    engage nor release — no flapping at the boundary."""
    eng = FakeEngine()
    bc = _controller(eng, down_window=1)
    eng.queue_depth = 6          # 60 ms >= l1
    bc.tick()
    assert bc.level == 1
    eng.queue_depth = 3          # 30 ms: < l1 (50) but >= 0.5*l1 (25)
    for _ in range(20):
        assert bc.tick() == 1


def test_cooldown_blocks_release():
    eng = FakeEngine()
    bc = _controller(eng, down_window=1, cooldown_s=60.0)
    eng.queue_depth = 6
    bc.tick()
    assert bc.level == 1
    eng.queue_depth = 0
    for _ in range(10):
        assert bc.tick() == 1    # cool ticks satisfied, cooldown not


def test_occupancy_and_shed_rate_engage_l1():
    eng = FakeEngine()
    bc = _controller(eng)
    eng._occ = 0.99              # saturated without backlog
    assert bc.tick() == 1
    eng._occ = 0.0
    eng2 = FakeEngine()
    bc2 = _controller(eng2)
    bc2.tick()                   # establish the counter baseline
    eng2.admission.shed_queue_full = 50
    eng2.admission.admitted = 50
    assert bc2.tick() == 1       # 50% shed rate over the tick window
    assert bc2.stats()["signals"]["shed_rate"] == pytest.approx(0.5)


def test_forced_pin_applies_immediately_and_releases_via_ladder():
    eng = FakeEngine()
    bc = _controller(eng, down_window=1)
    bc.force(2)
    assert bc.level == 2         # no tick needed: effective immediately
    eng.queue_depth = 50
    assert bc.tick() == 2        # signals scream L3; the pin wins
    bc.force(None)
    assert bc.tick() == 3        # signals back in control
    eng.queue_depth = 0
    bc.tick()
    assert bc.level == 2         # released ONE level, not snapped to 0
    st = bc.stats()
    assert st["forced"] is None and st["level_name"] == "degrade_quality"
    bc.force(99)
    assert bc.forced == MAX_LEVEL  # clamped


def test_qos_pressure_floor_only_at_l3():
    eng = FakeEngine()
    bc = _controller(eng)
    assert bc.qos_pressure_floor() == 0.0
    bc.force(2)
    assert bc.qos_pressure_floor() == 0.0
    bc.force(3)
    assert bc.qos_pressure_floor() == HARD_SHED_PRESSURE


def test_engine_errors_never_stall_the_ladder():
    class Exploding:
        @property
        def admission(self):
            raise RuntimeError("mid-teardown")

    eng = FakeEngine()
    eng.queue_depth = 50
    bc = BrownoutController([Exploding(), eng], up_window=1,
                            down_window=2, cooldown_s=0.0)
    assert bc.tick() == 3        # the healthy engine's signal got read
    assert bc.signal_errors == 1
    assert bc.stats()["signal_errors"] == 1


def test_threshold_validation():
    with pytest.raises(ValueError):
        BrownoutController([], l1_pressure_ms=200.0, l2_pressure_ms=100.0)
    with pytest.raises(ValueError):
        BrownoutController([], down_ratio=1.5)


# -- L2: version-stale response cache ---------------------------------------


def _key(digest, body="aa"):
    return ResponseCache.key("/v1/classify", "m", digest, "uint8",
                             "float32", body)


def test_stale_hit_serves_retired_version_only_on_request():
    cache = ResponseCache(1 << 20)
    cache.put(_key("v1"), b'{"old": 1}')
    # normal operation: a new params version misses — version purity
    assert cache.get(_key("v2")) is None
    # L2 path: the same payload under ANY retired version answers
    assert cache.get_stale(_key("v2")) == b'{"old": 1}'
    assert cache.stats()["stale_hits"] == 1
    # never for a different payload or route
    assert cache.get_stale(_key("v2", body="bb")) is None
    # the CURRENT version is not "stale" — exact get covers it
    cache.put(_key("v2"), b'{"new": 1}')
    assert cache.get_stale(_key("v2")) is None


def test_stale_alias_pruned_with_eviction():
    cache = ResponseCache(20)    # fits one 12-byte entry
    cache.put(_key("v1"), b"x" * 12)
    cache.put(_key("v1", body="bb"), b"y" * 12)   # evicts the first
    assert cache.get_stale(_key("v2")) is None
    assert cache.get_stale(_key("v2", body="bb")) == b"y" * 12
    cache.clear()
    assert cache.get_stale(_key("v2", body="bb")) is None


# -- cascade calibration persistence ----------------------------------------


class PersistPlane:
    """Resolvable models with params digests — the surface _restore and
    _append_ledger consult; no traffic runs through it."""

    def __init__(self, digests):
        self.digests = dict(digests)
        self.listeners = []

    def add_version_listener(self, fn):
        self.listeners.append(fn)

    def resolve(self, name):
        return types.SimpleNamespace(params_digest=self.digests[name])

    def canary_active(self, name):
        return False


def _spec(**kw):
    kw.setdefault("sample_period", 1000)
    kw.setdefault("min_sample", 5)
    kw.setdefault("min_agreement", 0.9)
    return CascadeSpec("small", "large", **kw)


def _calibrated_router(root, digests):
    router = CascadeRouter(PersistPlane(digests), _spec(), root=root)
    for _ in range(5):
        router.hist.record(0.8, True)
    router._recalibrate()
    assert router.threshold is not None
    return router


def test_calibration_survives_restart(tmp_path):
    root = str(tmp_path / "_cascade")
    digests = {"small": "f1", "large": "b1"}
    first = _calibrated_router(root, digests)
    ledger = first._ledger_path()
    assert os.path.exists(ledger)
    rec = json.loads(open(ledger).read().splitlines()[-1])
    assert rec["event"] == "calibrated" and rec["digest"] == "f1+b1"
    # a new process over the same workdir adopts the calibration
    second = CascadeRouter(PersistPlane(digests), _spec(), root=root)
    assert second.restored is True
    assert second.threshold == first.threshold
    assert second.stats()["restored"] is True


def test_restore_fails_closed_on_digest_mismatch(tmp_path):
    root = str(tmp_path / "_cascade")
    _calibrated_router(root, {"small": "f1", "large": "b1"})
    # the big tier reloaded while the server was down
    router = CascadeRouter(PersistPlane({"small": "f1", "large": "b2"}),
                           _spec(), root=root)
    assert router.restored is False and router.threshold is None


def test_restore_skips_torn_tail_line(tmp_path):
    root = str(tmp_path / "_cascade")
    first = _calibrated_router(root, {"small": "f1", "large": "b1"})
    with open(first._ledger_path(), "a") as f:
        f.write('{"event": "calib')       # crash mid-append
    router = CascadeRouter(PersistPlane({"small": "f1", "large": "b1"}),
                           _spec(), root=root)
    assert router.restored is True and router.threshold is not None


def test_trailing_reset_stays_fail_closed(tmp_path):
    root = str(tmp_path / "_cascade")
    first = _calibrated_router(root, {"small": "f1", "large": "b1"})
    first._on_version_swap("small")       # reload logged before crash
    router = CascadeRouter(PersistPlane({"small": "f1", "large": "b1"}),
                           _spec(), root=root)
    assert router.restored is False and router.threshold is None


def test_restore_rederives_threshold_under_new_knobs(tmp_path):
    """Retuned --cascade-min-sample applies to the restored sample: a
    sample now too thin stays fail-closed instead of trusting the
    stored threshold."""
    root = str(tmp_path / "_cascade")
    _calibrated_router(root, {"small": "f1", "large": "b1"})
    strict = CascadeSpec("small", "large", sample_period=1000,
                         min_sample=500, min_agreement=0.9)
    router = CascadeRouter(PersistPlane({"small": "f1", "large": "b1"}),
                           strict, root=root)
    assert router.restored is False and router.threshold is None


def test_ledger_write_failures_counted_never_raised(tmp_path):
    root = str(tmp_path / "_cascade")
    router = CascadeRouter(PersistPlane({"small": "f1", "large": "b1"}),
                           _spec(), root=root)
    os.makedirs(router._ledger_path())    # open(..., "a") now OSErrors
    for _ in range(5):
        router.hist.record(0.8, True)
    router._recalibrate()                 # must not raise
    assert router.threshold is not None   # the ledger observes only
    assert router.stats()["ledger_write_errors"] == 1


def test_memory_only_router_never_touches_disk(tmp_path):
    router = CascadeRouter(PersistPlane({"small": "f1", "large": "b1"}),
                           _spec(), root=None)
    for _ in range(5):
        router.hist.record(0.8, True)
    router._recalibrate()
    assert router.threshold is not None
    assert router.restored is False
    assert router.stats()["ledger_root"] is None
