"""Train-input pipeline: staged prefetcher, uint8 wire, fused ingest.

Covers the input-side acceptance bar: uint8-vs-float32 wire parity (same
eval metric, 4× smaller image DMA), `train_ingest` interpret-mode parity
vs `jitter_normalize`, staging-buffer reuse bounds, stage timers summing
to wall time, donation safety, and abandoned-epoch cleanup (no leaked
producer thread, no pinned device batches).
"""

import gc
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.data.pipeline import DevicePrefetcher

pytestmark = pytest.mark.input_pipeline


def _batches(n_batches: int, batch: int = 16, size: int = 8,
             dtype=np.uint8, seed: int = 0):
    rng = np.random.default_rng(seed)
    for _ in range(n_batches):
        img = rng.integers(0, 256, size=(batch, size, size, 3))
        yield {"image": img.astype(dtype),
               "label": rng.integers(0, 10, size=batch).astype(np.int32)}


# -- staging pool + prefetcher plumbing --------------------------------------


def test_staging_pool_reuse_bounded(mesh1):
    """N batches must NOT allocate N buffers: steady state holds at most
    depth+2 staging buffers per distinct leaf shape (depth+1 plus one for
    CPU zero-copy deferred release), and a second epoch through the same
    prefetcher reuses the pool instead of growing it."""
    depth = 2
    pf = DevicePrefetcher(mesh1, depth=depth)
    try:
        for b in pf.iterate(_batches(16)):
            jax.block_until_ready(b["image"])
        # 2 pooled leaf shapes (image, label) × at most depth+2 each
        bound = (depth + 2) * 2
        assert pf.pool.allocated <= bound
        del b
        gc.collect()  # return zero-copy-deferred buffers before epoch 2
        for b in pf.iterate(_batches(16)):
            jax.block_until_ready(b["image"])
        st = pf.pool.stats()
        assert st["allocated"] <= bound  # epoch 2 rode the same pool
        assert st["reused"] >= 16  # far more reuse than allocation
    finally:
        pf.close()


def test_h2d_bytes_accounted_per_key(mesh1):
    """uint8 wire carries exactly 1/4 the image bytes of the f32 wire —
    measured on the image key alone, not diluted by labels."""
    def run(dtype):
        pf = DevicePrefetcher(mesh1, depth=1)
        try:
            stream = pf.iterate(_batches(4, dtype=dtype))
            for b in stream:
                jax.block_until_ready(b["image"])
            return stream.stats()["h2d_bytes_by_key"]
        finally:
            pf.close()

    u8, f32 = run(np.uint8), run(np.float32)
    assert f32["image"] == 4 * u8["image"]
    assert f32["label"] == u8["label"]  # labels int32 on both wires


def test_stage_timers_sum_to_wall(mesh1):
    """Consumer-side stall + step spans the whole epoch wall time (the
    Span construction guarantees each side's stages sum exactly); the
    producer reports all four of its stages."""
    import time

    pf = DevicePrefetcher(mesh1, depth=2)
    try:
        t0 = time.perf_counter()
        stream = pf.iterate(_batches(6))
        for b in stream:
            jax.block_until_ready(b["image"])
            time.sleep(0.01)  # a visible "step" so both sides are nonzero
        wall_ms = (time.perf_counter() - t0) * 1e3
        st = stream.stats()
    finally:
        pf.close()
    assert st["batches"] == 6
    assert 0.0 <= st["input_stall_frac"] <= 1.0
    assert st["stall_ms"] + st["step_ms"] == pytest.approx(wall_ms, abs=60)
    for stage in ("prep_wait", "assemble", "h2d", "enqueue"):
        assert st["producer_ms"].get(stage, -1.0) >= 0.0
    assert st["h2d_bytes_per_step"] > 0


def test_abandoned_epoch_leaks_nothing(mesh1):
    """Abandoning iteration mid-epoch (preemption, divergence abort) must
    not leave a producer thread behind nor device batches pinned in the
    queue — the legacy `prefetch_to_device` bug this PR fixes."""
    gc.collect()
    base_threads = threading.active_count()
    base_arrays = len(jax.live_arrays())
    for _ in range(5):
        pf = DevicePrefetcher(mesh1, depth=4)
        stream = pf.iterate(_batches(64))
        next(stream)  # consume one batch, then walk away
        pf.close()
        assert not stream.alive
        del pf, stream
    gc.collect()
    assert threading.active_count() == base_threads
    # queued device batches were dropped by close(); nothing stays pinned
    assert len(jax.live_arrays()) <= base_arrays + 2


def test_legacy_shim_closes_producer_and_propagates_errors(mesh1):
    """The kept `prefetch_to_device` generator shim rides the new
    prefetcher: abandoning it tears the producer down, and a producer
    exception surfaces at the consumer."""
    from deep_vision_tpu.data.loader import prefetch_to_device

    base = threading.active_count()
    gen = prefetch_to_device(_batches(64), mesh1, depth=2)
    next(gen)
    gen.close()
    assert threading.active_count() == base

    def poisoned():
        yield from _batches(2)
        raise RuntimeError("loader exploded")

    with pytest.raises(RuntimeError, match="loader exploded"):
        for _ in prefetch_to_device(poisoned(), mesh1, depth=2):
            pass


def test_donated_batches_stay_correct_across_epochs(mesh1):
    """Device batches are donated into the jitted step (the trainer's
    donate_argnums=(0, 1)); the staging buffers they came from are reused
    every epoch.  Two epochs over identical data must produce identical
    losses — donation must never corrupt a buffer still in the pool."""

    def step(b):
        return jnp.sum(b["image"].astype(jnp.float32)) + jnp.sum(b["label"])

    donating = jax.jit(step, donate_argnums=(0,))

    def losses():
        pf = DevicePrefetcher(mesh1, depth=2)
        try:
            return [float(donating(b)) for b in pf.iterate(_batches(6))]
        finally:
            pf.close()

    assert losses() == losses()


# -- fused train-ingest kernel ------------------------------------------------


def test_train_ingest_interpret_parity():
    """Fused kernel == jitter_normalize at the PR 10 tolerance bar, for
    the production 3-channel shape and a non-square one."""
    from deep_vision_tpu.ops.pallas_ops import (
        train_ingest,
        train_ingest_factors,
    )
    from deep_vision_tpu.ops.preprocess import jitter_normalize

    for shape in ((4, 32, 32, 3), (2, 24, 40, 3)):
        x = jnp.asarray(np.random.default_rng(5).integers(
            0, 256, size=shape, dtype=np.uint8))
        rng = jax.random.PRNGKey(3)
        got = train_ingest(x, train_ingest_factors(x, rng), interpret=True)
        want = jitter_normalize(x, rng, train=True)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_train_ingest_parity_gate_and_fallback(monkeypatch):
    """The preprocess factory selects the fused kernel only when the
    one-batch parity gate passes; a failing gate silently selects the
    XLA path (no accuracy change either way)."""
    from deep_vision_tpu.ops import pallas_ops
    from deep_vision_tpu.ops.preprocess import (
        jitter_normalize,
        make_imagenet_preprocess,
    )

    shape = (4, 16, 16, 3)
    assert pallas_ops.train_ingest_parity_ok(shape, interpret=True)

    fn = make_imagenet_preprocess(use_fused=True, fused_shape=shape)
    assert fn.fused
    x = jnp.asarray(np.random.default_rng(2).integers(
        0, 256, size=shape, dtype=np.uint8))
    rng = jax.random.PRNGKey(11)
    got = fn({"image": x}, rng, train=True)["image"]
    want = jitter_normalize(x, rng, train=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    monkeypatch.setattr(pallas_ops, "train_ingest_parity_ok",
                        lambda *a, **k: False)
    fb = make_imagenet_preprocess(use_fused=True, fused_shape=shape)
    assert not fb.fused
    np.testing.assert_allclose(fb({"image": x}, rng, train=True)["image"],
                               want, rtol=1e-6, atol=1e-7)

    # float batches pass through untouched on both paths
    xf = jnp.ones(shape, jnp.float32)
    assert fn({"image": xf}, rng, train=True)["image"] is xf


# -- uint8 wire end to end ----------------------------------------------------


class _PlainXentTask:
    """Barrier-free classification task: this environment's jax build has
    no differentiation rule for ``optimization_barrier`` (the pre-existing
    test_trainer_mnist failures), so the wire-parity test supplies the
    same cross-entropy math without ``_materialize``."""

    monitor = "top1"

    def loss(self, outputs, batch):
        import optax

        labels = batch["label"]
        logits = outputs.astype(jnp.float32)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, {"top1": (jnp.argmax(logits, -1) == labels).mean()}

    def eval_metrics(self, outputs, batch):
        import optax

        labels = batch["label"]
        logits = outputs.astype(jnp.float32)
        w = batch.get("weight", jnp.ones(labels.shape[0], jnp.float32))
        xent = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels)
        return {"loss": (xent * w).sum(),
                "top1": ((jnp.argmax(logits, -1) == labels) * w).sum(),
                "count": w.sum()}


def test_uint8_wire_matches_f32_wire_eval_metric(tmp_path, mesh1):
    """Same pixels shipped as uint8 (device normalize) and as
    host-normalized float32 train to the same eval metric — the wire is
    a transport change, not a numerics change."""
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.loader import ArrayLoader
    from deep_vision_tpu.data.mnist import MEAN, STD
    from deep_vision_tpu.ops.preprocess import make_mnist_preprocess

    rng = np.random.default_rng(0)
    n = 96
    u8 = rng.integers(0, 256, size=(n, 32, 32, 1)).astype(np.uint8)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    f32 = ((u8.astype(np.float32) / 255.0) - MEAN) / STD

    def run(images, preprocess_fn, workdir):
        cfg = get_config("lenet5")
        cfg.total_epochs = 1
        cfg.batch_size = cfg.eval_batch_size = 32
        trainer = Trainer(cfg, cfg.model(), _PlainXentTask(),
                          mesh=mesh1, workdir=str(workdir),
                          preprocess_fn=preprocess_fn)
        data = {"image": images, "label": labels}
        loader = ArrayLoader(data, 32, seed=cfg.seed)
        val = ArrayLoader(data, 32, shuffle=False)
        state = trainer.fit(loader, val, resume=False)
        metrics = trainer.evaluate(state, val)
        return metrics, trainer

    m_u8, tr = run(u8, make_mnist_preprocess(), tmp_path / "u8")
    m_f32, _ = run(f32, None, tmp_path / "f32")
    assert m_u8["top1"] == pytest.approx(m_f32["top1"], abs=1e-6)
    assert m_u8["loss"] == pytest.approx(m_f32["loss"], rel=1e-4)
    # the trainer logged the input-goodput block for the epoch
    assert tr.logger.latest("input_stall_frac") is not None
    assert tr.logger.latest("input_h2d_bytes_per_step") > 0


def test_gan_uint8_wire_roundtrip():
    """GAN loaders' uint8 wire + traced prologue reproduces the host
    [-1,1] scaling exactly on representable values."""
    from deep_vision_tpu.data.gan import synthetic_unpaired, to_uint8_wire
    from deep_vision_tpu.ops.preprocess import make_gan_preprocess

    a_f, b_f = synthetic_unpaired(8, image_size=16, seed=3)
    a_u8, b_u8 = synthetic_unpaired(8, image_size=16, seed=3,
                                    device_normalize=True)
    assert a_u8.dtype == np.uint8 and b_u8.dtype == np.uint8
    assert np.array_equal(a_u8, to_uint8_wire(a_f))

    fn = make_gan_preprocess()
    out = fn({"image_a": jnp.asarray(a_u8), "image_b": jnp.asarray(b_u8)},
             jax.random.PRNGKey(0), train=True)
    # uint8 quantization is the only delta: within half a pixel step
    np.testing.assert_allclose(np.asarray(out["image_a"]), a_f,
                               atol=1.0 / 255.0)
    # float inputs pass through untouched
    xf = jnp.asarray(a_f)
    assert fn({"image_a": xf}, jax.random.PRNGKey(0), train=True)[
        "image_a"] is xf


def test_mnist_uint8_wire_matches_host_preprocess():
    from deep_vision_tpu.data.mnist import pad_uint8, preprocess
    from deep_vision_tpu.ops.preprocess import serve_normalize

    raw = np.random.default_rng(1).integers(
        0, 256, size=(4, 28, 28)).astype(np.uint8)
    wire = pad_uint8(raw)
    assert wire.dtype == np.uint8 and wire.shape == (4, 32, 32, 1)
    np.testing.assert_allclose(
        np.asarray(serve_normalize(jnp.asarray(wire), "mnist")),
        preprocess(raw), rtol=1e-6, atol=1e-6)
