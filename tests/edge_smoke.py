"""`make edge-smoke` (runs inside `make serve-smoke`): boot the real
cli.serve wiring — selector event loop, response cache and tenant QoS
all on — and assert the async-edge surface end to end over real
sockets: N requests down ONE keep-alive connection register as a
single accept with N-1 reuses, an identical payload answers from the
content-addressed cache without consuming engine capacity, the
starved QoS class 429s (with Retry-After) while premium keeps being
served, and a client that stalls mid-body is answered 408 by the
loop's deadline sweep while a header-less dribbler is closed silently.
Run directly, not under pytest."""

import argparse
import http.client
import json
import os
import socket
import sys
import tempfile
import time
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/edge_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _stats(base: str) -> dict:
    with urllib.request.urlopen(base + "/v1/stats", timeout=60) as r:
        return json.loads(r.read())


def main():
    from deep_vision_tpu.cli.serve import build_server

    with tempfile.TemporaryDirectory() as workdir:
        args = argparse.Namespace(
            model="lenet5", workdir=workdir, stablehlo=None,
            host="127.0.0.1", port=0, max_batch=4, max_wait_ms=2.0,
            buckets=None, max_queue=64, warmup=False, verbose=False,
            pipeline_depth=2, faults="", fault_seed=0,
            serve_devices=1, shard_batches=False,
            wire_dtype="float32", infer_dtype="float32",
            thread_server=False, max_connections=64, http_workers=4,
            response_cache_mb=16.0,
            qos="premium:rate=0,shed_at=1.0,tenants=vip;"
                "bronze:rate=0,shed_at=0.0;default=bronze")
        engine, server = build_server(args)
        # short deadlines so the slow-loris leg settles fast; set before
        # the first connection so every conn is swept on this budget
        server.httpd.socket_timeout_s = 0.4
        server.start_background()
        host, port = server.host, server.port
        base = f"http://{host}:{port}"
        try:
            # -- keep-alive: N requests, ONE accept, N-1 reuses --------
            body = json.dumps(
                {"pixels": np.zeros((32, 32, 1)).tolist()}).encode()
            conn = http.client.HTTPConnection(host, port, timeout=60)
            n = 4
            for _ in range(n):
                conn.request("POST", "/v1/classify", body,
                             {"Content-Type": "application/json",
                              "X-DVT-Tenant": "vip"})
                r = conn.getresponse()
                blob = r.read()
                assert r.status == 200, (r.status, blob)
                assert not r.will_close, "edge dropped keep-alive"
            conn.close()
            edge = _stats(base)["edge"]
            # 2 accepts: the keep-alive conn + the stats scrape itself
            assert edge["accepted"] == 2, edge
            assert edge["keepalive_reuses"] >= n - 1, edge
            assert edge["requests"] >= n, edge

            # -- response cache: byte-identical replay, no engine use --
            served_before = engine.served
            with urllib.request.urlopen(urllib.request.Request(
                    base + "/v1/classify", data=body,
                    headers={"Content-Type": "application/json",
                             "X-DVT-Tenant": "vip"}), timeout=60) as r:
                assert r.status == 200, r.status
                assert r.headers.get("X-DVT-Cache") == "hit", \
                    dict(r.headers)
            rcache = _stats(base)["response_cache"]
            assert rcache["hits"] >= 1, rcache
            assert rcache["insertions"] >= 1, rcache
            assert engine.served == served_before, \
                "cache hit consumed engine capacity"

            # -- tenant QoS: bronze sheds at its knee, premium serves --
            # (shed_at=0.0 puts bronze's knee at zero pressure, so the
            # weighted-shed verdict is deterministic without a real
            # overload; a fresh payload forces the cache-miss path the
            # pressure check guards)
            fresh = json.dumps(
                {"pixels": np.full((32, 32, 1), 7.0).tolist()}).encode()
            req = urllib.request.Request(
                base + "/v1/classify", data=fresh,
                headers={"Content-Type": "application/json",
                         "X-DVT-Tenant": "anon"})
            try:
                urllib.request.urlopen(req, timeout=60)
                raise AssertionError("bronze cache-miss was not shed")
            except urllib.error.HTTPError as e:
                assert e.code == 429, e.code
                assert e.headers.get("Retry-After"), dict(e.headers)
            with urllib.request.urlopen(urllib.request.Request(
                    base + "/v1/classify", data=fresh,
                    headers={"Content-Type": "application/json",
                             "X-DVT-Tenant": "vip"}), timeout=60) as r:
                assert r.status == 200, r.status
            qstats = _stats(base)["qos"]
            assert qstats["bronze"]["shed_priority"] >= 1, qstats
            assert qstats["premium"]["served"] >= 1, qstats

            # -- deadline sweep: stalled body → 408, dribbler → close --
            s = socket.create_connection((host, port), timeout=10)
            s.sendall(b"POST /v1/classify HTTP/1.1\r\n"
                      b"Host: x\r\nContent-Type: application/json\r\n"
                      b"Content-Length: 100\r\n\r\n{")  # then stall
            s.settimeout(5.0)
            head = s.recv(4096)
            assert head.startswith(b"HTTP/1.1 408"), head[:64]
            s.close()
            s2 = socket.create_connection((host, port), timeout=10)
            s2.sendall(b"GET /v1/healthz")  # no CRLF: mid-request-line
            s2.settimeout(5.0)
            assert s2.recv(4096) == b"", "loris got a reply, not a close"
            s2.close()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                edge = _stats(base)["edge"]
                if edge["timeouts_408"] >= 1 and edge["closed_idle"] >= 1:
                    break
                time.sleep(0.05)
            assert edge["timeouts_408"] >= 1, edge
            assert edge["closed_idle"] >= 1, edge
            print(f"edge-smoke PASS: {edge['requests']} requests over "
                  f"{edge['accepted']} accepts "
                  f"({edge['keepalive_reuses']} keep-alive reuses), "
                  f"cache {rcache['hits']} hit / "
                  f"{rcache['insertions']} inserted, bronze shed "
                  f"{qstats['bronze']['shed_priority']} with Retry-After "
                  f"while premium served {qstats['premium']['served']}, "
                  f"stalled body 408'd and loris closed in "
                  f"{server.httpd.socket_timeout_s}s")
        finally:
            server.shutdown()
            engine.stop(drain_deadline=5.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
