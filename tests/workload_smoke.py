"""`make workload-smoke`: boot the multi-model plane the way
`python -m deep_vision_tpu.cli.serve --models hourglass_toy,dcgan`
does (cli.serve.build_server's plane path) with an injected transient
compute fault, then prove the workload-generic serving surface end to
end over real HTTP:

  * POST /v1/pose answers decoded keypoints (the heatmap→argmax
    epilogue compiled INTO the bucket program — no heatmap ever
    crosses D2H) and /v1/generate answers a base64 uint8 image at
    1 byte/pixel (the output-side uint8 wire), both also via the
    per-model /v1/models/{name}/<verb> routes — zero client errors
    through the fault (bisect-retry absorbs it);
  * unknown verbs 404 with the registry-derived supported list, and
    the wrong verb for a model's workload 400s naming the right one;
  * hot-reload hourglass_toy under live pose traffic (reload →
    canary → explicit operator POST /promote, min_requests pinned
    high so auto-promote can't race the operator path) — v2 active,
    ZERO hammer errors;
  * /v1/stats is plane-shaped with per-workload engine stats
    (d2h_bytes > 0 on both engines, fault counters prove the
    injection fired AND was retried), and every /metrics line parses
    as Prometheus text — including dvt_serve_d2h_bytes_total carrying
    workload="pose" and workload="generate" labels.

Run directly, not under pytest."""

import argparse
import base64
import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/workload_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# a metric line: name{labels} value  (labels optional; the value is
# validated separately with float(), which accepts nan/inf spellings)
_PROM_LINE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? (\S+)$")


def _post(base, path, payload, timeout=120):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(base, path, timeout=60):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def smoke():
    from deep_vision_tpu.cli.serve import build_server

    with tempfile.TemporaryDirectory() as workdir:
        for name in ("hourglass_toy", "dcgan"):
            os.makedirs(os.path.join(workdir, name), exist_ok=True)
        args = argparse.Namespace(
            model=None, models="hourglass_toy,dcgan", workdir=workdir,
            stablehlo=None, host="127.0.0.1", port=0, max_batch=2,
            max_wait_ms=2.0, buckets=None, max_queue=64, warmup=False,
            verbose=False, pipeline_depth=2,
            # one transient compute failure somewhere in the mix: every
            # request below must still answer 200 through bisect-retry
            faults="compute:exception:times=1", fault_seed=0,
            serve_devices=1, shard_batches=False,
            # uint8 requested for BOTH: pose keeps it (unit prologue on
            # device), the generate workload overrides dcgan's latent
            # input to float32 — the codec contract under one flag
            wire_dtype="uint8", infer_dtype="float32",
            hbm_budget_mb=0.0, canary_frac=0.5,
            # pinned far above any traffic this test sends, so the
            # explicit operator /promote below is the ONLY way v2 goes
            # active (exercises the override path, not the auto-gate)
            canary_min_requests=10**6, canary_max_error_rate=0.0,
            canary_max_p99_ratio=50.0, shadow_frac=0.0,
            phase_timeout_s=120.0)
        plane, server = build_server(args)
        server.start_background()
        base = f"http://{server.host}:{server.port}"
        try:
            health = _get(base, "/v1/healthz")
            assert health["status"] == "ok", health
            assert sorted(health["engines"]) == \
                ["dcgan", "hourglass_toy"], health

            # pose: raw uint8 pixels in, decoded keypoints out — both
            # the flat verb route and the per-model path route
            pose_px = np.random.default_rng(0).integers(
                0, 256, (64, 64, 3)).tolist()
            for path, body in (
                    ("/v1/pose", {"model": "hourglass_toy",
                                  "pixels": pose_px}),
                    ("/v1/models/hourglass_toy/pose",
                     {"pixels": pose_px})):
                status, out = _post(base, path, body)
                assert status == 200, (path, out)
                assert out["space"] == "heatmap", out
                kps = out["keypoints"]
                assert len(kps) == 8, out
                assert all({"x", "y", "score"} <= set(k) for k in kps)

            # generate: latent-in (seeded server-side), wire-ready
            # uint8 image out at 1 byte/pixel
            for path, body in (
                    ("/v1/generate", {"model": "dcgan", "seed": 7}),
                    ("/v1/models/dcgan/generate", {"seed": 7})):
                status, out = _post(base, path, body)
                assert status == 200, (path, out)
                img = out["image"]
                assert img["dtype"] == "uint8", img
                assert img["shape"] == [28, 28, 1], img
                raw = base64.b64decode(img["b64"])
                assert len(raw) == 28 * 28 * 1, len(raw)
            # deterministic codec: same seed → byte-identical image
            _, again = _post(base, "/v1/generate",
                             {"model": "dcgan", "seed": 7})
            assert again["image"]["b64"] == img["b64"]

            # registry-driven routing: unknown verbs 404 with the
            # supported list; the wrong verb for a workload 400s
            for path in ("/v1/frobnicate",
                         "/v1/models/dcgan/frobnicate"):
                try:
                    _post(base, path, {"seed": 0})
                    raise AssertionError(f"{path} should 404")
                except urllib.error.HTTPError as e:
                    assert e.code == 404, (path, e.code)
                    body = json.loads(e.read())
                    verbs = body["supported_verbs"]
                    assert {"classify", "detect", "pose", "generate",
                            "reload", "promote",
                            "rollback"} <= set(verbs), verbs
            try:
                _post(base, "/v1/classify",
                      {"model": "dcgan", "seed": 0})
                raise AssertionError("wrong verb should 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400, e.code
                assert "/v1/generate" in json.loads(e.read())["error"]

            # the injected fault fired on the FIRST executed batch
            # and bisect-retry absorbed it (every request above was a
            # 200) — asserted BEFORE the rollout, because promote
            # retires the v1 engine that took the hit
            pre = _get(base, "/v1/stats")
            pre_health = {n: m["engine"]["health"]
                          for n, m in pre["models"].items()}
            assert sum(h["batch_failures"]
                       for h in pre_health.values()) >= 1, pre_health
            assert sum(h["retry_executions"]
                       for h in pre_health.values()) >= 1, pre_health
            failures = sum(h["batch_failures"]
                           for h in pre_health.values())
            retries = sum(h["retry_executions"]
                          for h in pre_health.values())

            # hot-reload hourglass_toy under live pose traffic:
            # reload → canary → explicit operator promote, zero errors
            errors, served = [], [0]
            stop = threading.Event()

            def hammer():
                while not stop.is_set():
                    try:
                        status, out = _post(
                            base, "/v1/pose",
                            {"model": "hourglass_toy",
                             "pixels": pose_px}, timeout=60)
                        assert status == 200 and out["keypoints"], out
                        served[0] += 1
                    except Exception as e:  # noqa: BLE001 — any failure is a lost request
                        errors.append(repr(e))

            t = threading.Thread(target=hammer, daemon=True)
            t.start()
            status, out = _post(base, "/v1/models/hourglass_toy/reload",
                                {"force": True})
            assert status == 200 and out["status"] == "reloading", out
            deadline = time.monotonic() + 120
            canary_seen = None
            while time.monotonic() < deadline:
                table = _get(base, "/v1/models")["models"]
                versions = table["hourglass_toy"]["versions"]
                canary_seen = [v for v in versions
                               if v["state"] == "canary"]
                if canary_seen and canary_seen[0].get(
                        "canary", {}).get("requests", 0) >= 2:
                    break
                time.sleep(0.05)
            assert canary_seen, versions
            status, out = _post(base,
                                "/v1/models/hourglass_toy/promote", {})
            assert status == 200 and out["status"] == "promoted", out
            assert out["version"] == 2, out
            while time.monotonic() < deadline:
                if _get(base, "/v1/models")["models"]["hourglass_toy"][
                        "active_version"] == 2:
                    break
                time.sleep(0.05)
            # v2 serves through the same fused epilogue
            status, out = _post(base, "/v1/pose",
                                {"model": "hourglass_toy",
                                 "pixels": pose_px})
            assert status == 200 and len(out["keypoints"]) == 8, out
            stop.set()
            t.join(60)
            assert not errors, \
                f"rollout lost {len(errors)}: {errors[:3]}"

            # plane-shaped stats: per-workload engines, D2H accounted
            stats = _get(base, "/v1/stats")
            assert set(stats) >= {"models", "plane"}, set(stats)
            assert stats["plane"]["promotions"] == 1, stats["plane"]
            engines = {n: m["engine"]
                       for n, m in stats["models"].items()}
            assert engines["hourglass_toy"]["workload"] == "pose"
            assert engines["dcgan"]["workload"] == "generate"
            for n, e in engines.items():
                assert e["pipeline"]["d2h_bytes"] > 0, (n, e["pipeline"])
                assert e["pipeline"]["d2h_bytes_by_bucket"], n
            # pose D2H is keypoints, not heatmaps: strictly under the
            # 16*16*8*4-byte-per-image stack it replaced
            pose_pipe = engines["hourglass_toy"]["pipeline"]
            assert pose_pipe["d2h_bytes"] < \
                engines["hourglass_toy"]["served"] * 16 * 16 * 8 * 4

            # /metrics: every line parses; the per-workload D2H series
            # exists for both workloads
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=60) as r:
                text = r.read().decode()
            for line in text.splitlines():
                if not line or line.startswith("#"):
                    continue
                m = _PROM_LINE.match(line)
                assert m, f"bad metric line: {line}"
                float(m.group(2))  # ValueError = unparseable sample
            d2h_lines = [ln for ln in text.splitlines()
                         if ln.startswith("dvt_serve_d2h_bytes_total")]
            assert any('workload="pose"' in ln for ln in d2h_lines), \
                d2h_lines
            assert any('workload="generate"' in ln
                       for ln in d2h_lines), d2h_lines
            print(f"workload-smoke PASS: pose+generate from port "
                  f"{server.port}; reload under load promoted "
                  f"hourglass_toy v2 with {served[0]} client requests "
                  f"and 0 errors; fault fired ({failures} batch "
                  f"failure(s), {retries} retried); pose D2H "
                  f"{pose_pipe['d2h_bytes']}B for "
                  f"{engines['hourglass_toy']['served']} served, "
                  f"generate D2H "
                  f"{engines['dcgan']['pipeline']['d2h_bytes']}B; "
                  f"{len(text.splitlines())} metric lines parsed")
        finally:
            server.shutdown()
            plane.stop(drain_deadline=5.0)
    return 0


def main():
    # pin the platform before jax initializes (site config can override
    # the env var alone, so set it at the config level too)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    return smoke()


if __name__ == "__main__":
    sys.exit(main())
