"""Dataset-prep tests on synthetic raw layouts (VOC XML, COCO JSON,
MPII JSON, CelebA attrs) — no dataset downloads."""

import json
import os

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402

from deep_vision_tpu.data import prep  # noqa: E402
from deep_vision_tpu.data.records import (  # noqa: E402
    load_detection_records,
    load_pose_records,
    read_records,
    list_shards,
)


def _save_jpg(path, h=40, w=60):
    rng = np.random.default_rng(0)
    Image.fromarray(rng.integers(0, 255, (h, w, 3), dtype=np.uint8)).save(path)


@pytest.fixture
def voc_layout(tmp_path):
    base = tmp_path / "VOC2007"
    (base / "Annotations").mkdir(parents=True)
    (base / "JPEGImages").mkdir()
    for i in range(3):
        name = f"img{i:03d}.jpg"
        _save_jpg(base / "JPEGImages" / name, 100, 200)
        xml = f"""<annotation>
  <filename>{name}</filename>
  <size><width>200</width><height>100</height><depth>3</depth></size>
  <object><name>dog</name>
    <bndbox><xmin>20</xmin><ymin>10</ymin><xmax>120</xmax><ymax>80</ymax></bndbox>
  </object>
  <object><name>person</name>
    <bndbox><xmin>100</xmin><ymin>5</ymin><xmax>190</xmax><ymax>95</ymax></bndbox>
  </object>
</annotation>"""
        (base / "Annotations" / f"img{i:03d}.xml").write_text(xml)
    return str(tmp_path)


def test_prepare_voc(voc_layout, tmp_path):
    out = str(tmp_path / "recs")
    n = prep.prepare_voc(voc_layout, out, "train", num_shards=2,
                         num_workers=1)
    assert n == 3
    samples = load_detection_records(out, "train")
    assert len(samples) == 3
    s = samples[0]
    assert s["boxes"].shape == (2, 4)
    np.testing.assert_allclose(s["boxes"][0], [0.1, 0.1, 0.6, 0.8], atol=1e-6)
    # voc class map: dog=11, person=14
    assert s["classes"].tolist() == [11, 14]
    assert s["image"].shape == (100, 200, 3)


def test_prepare_coco(tmp_path):
    img_dir = tmp_path / "images"
    img_dir.mkdir()
    _save_jpg(img_dir / "000000000001.jpg", 50, 100)
    coco = {
        "images": [{"id": 1, "file_name": "000000000001.jpg",
                    "width": 100, "height": 50}],
        # sparse 1-based category ids get re-indexed densely
        "categories": [{"id": 1, "name": "person"}, {"id": 17, "name": "cat"}],
        "annotations": [
            {"image_id": 1, "category_id": 17, "bbox": [10, 5, 30, 20]},
            {"image_id": 1, "category_id": 1, "bbox": [50, 25, 40, 20]},
        ],
    }
    anno = tmp_path / "instances.json"
    anno.write_text(json.dumps(coco))
    out = str(tmp_path / "recs")
    n = prep.prepare_coco(str(anno), str(img_dir), out, "val", num_shards=1,
                          num_workers=1)
    assert n == 1
    s = load_detection_records(out, "val")[0]
    assert s["classes"].tolist() == [1, 0]  # 17→1, 1→0
    np.testing.assert_allclose(s["boxes"][0], [0.1, 0.1, 0.4, 0.5], atol=1e-6)


def test_prepare_mpii(tmp_path):
    img_dir = tmp_path / "images"
    img_dir.mkdir()
    _save_jpg(img_dir / "pose1.jpg", 80, 80)
    annos = [{
        "image": "pose1.jpg",
        "joints": [[10, 20], [-1, -1]] + [[5, 5]] * 14,
        "joints_visibility": [1, 0] + [1] * 14,
        "center": [40, 40], "scale": 0.8,
    }, {"image": "missing.jpg", "joints": [[0, 0]] * 16,
        "joints_visibility": [0] * 16}]
    anno = tmp_path / "mpii.json"
    anno.write_text(json.dumps(annos))
    out = str(tmp_path / "recs")
    n = prep.prepare_mpii(str(anno), str(img_dir), out, "train",
                          num_shards=1, num_workers=1)
    assert n == 1  # missing image skipped
    s = load_pose_records(out, "train")[0]
    assert s["keypoints"].shape == (16, 3)
    assert s["keypoints"][0].tolist() == [10.0, 20.0, 2.0]  # vis 1→2
    assert s["keypoints"][1][2] == 0.0
    assert s["scale"] == pytest.approx(0.8)


def test_prepare_imagenet_shards(tmp_path):
    src = tmp_path / "flat"
    src.mkdir()
    for syn, k in (("n01440764", 2), ("n01443537", 3)):
        for j in range(k):
            _save_jpg(src / f"{syn}_{j}.JPEG", 32, 32)
    labels = tmp_path / "meta.txt"
    labels.write_text("n01440764 tench\nn01443537 goldfish\n")
    out = str(tmp_path / "recs")
    n = prep.prepare_imagenet(str(src), str(labels), out, "train",
                              num_shards=2, num_workers=1)
    assert n == 5
    shards = list_shards(out, "train")
    assert len(shards) == 2
    labels_seen = [h["label"] for sh in shards for h, _ in read_records(sh)]
    assert sorted(labels_seen) == [0, 0, 1, 1, 1]


def test_prepare_imagenet_dirty_dir(tmp_path):
    """VERDICT r1 item 7: a dirty source dir (PNG-as-.JPEG, CMYK JPEG,
    truncated JPEG, undecodable junk) must yield 100% READABLE shards —
    the reference handled only 23 hard-coded blacklist files
    (build_imagenet_tfrecord.py:272-309); we detect by content."""
    import io

    src = tmp_path / "flat"
    src.mkdir()
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 255, (32, 32, 3), dtype=np.uint8)
    # 1 clean JPEG
    Image.fromarray(arr).save(src / "n01440764_0.JPEG", format="JPEG")
    # 1 PNG masquerading as .JPEG (the _is_png case)
    Image.fromarray(arr).save(src / "n01440764_1.JPEG", format="PNG")
    # 1 CMYK JPEG (the _is_cmyk case)
    Image.fromarray(arr).convert("CMYK").save(src / "n01443537_0.JPEG",
                                              format="JPEG")
    # 1 mildly truncated JPEG (tail of the scan cut — salvageable)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG")
    (src / "n01443537_1.JPEG").write_bytes(
        buf.getvalue()[:int(len(buf.getvalue()) * 0.9)])
    # 1 undecodable junk file (severe corruption — dropped)
    (src / "n01443537_2.JPEG").write_bytes(b"not an image at all")
    labels = tmp_path / "meta.txt"
    labels.write_text("n01440764\ttench, Tinca tinca\n"
                      "n01443537\tgoldfish, Carassius auratus\n")
    out = str(tmp_path / "recs")
    prep.prepare_imagenet(str(src), str(labels), out, "train",
                          num_shards=1, num_workers=1)
    recs = [(h, p) for sh in list_shards(out, "train")
            for h, p in read_records(sh)]
    assert len(recs) == 4  # junk dropped, everything else kept
    for h, payload in recs:
        img = Image.open(io.BytesIO(payload))
        img.load()  # every stored payload decodes fully
        assert img.mode == "RGB" and img.format == "JPEG"
        # synset → human-label metadata in every header (:472-689 role)
        assert h["synset"] in ("n01440764", "n01443537")
        assert "tench" in h["human"] or "goldfish" in h["human"]
    reencoded = [h for h, _ in recs if h.get("reencoded")]
    assert len(reencoded) == 3  # png + cmyk + truncated


def test_process_imagenet_bboxes(tmp_path):
    """The process_bounding_boxes.py:16-264 port: XML tree → relative CSV
    with clamping, min/max swap, degenerate-box and synset filtering."""
    xml_dir = tmp_path / "bbox"
    (xml_dir / "n01440764").mkdir(parents=True)
    (xml_dir / "n09999999").mkdir(parents=True)

    def write_xml(path, objs, w=200, h=100):
        body = "".join(
            f"<object><name>{n}</name><bndbox><xmin>{x1}</xmin>"
            f"<ymin>{y1}</ymin><xmax>{x2}</xmax><ymax>{y2}</ymax>"
            f"</bndbox></object>" for n, x1, y1, x2, y2 in objs)
        path.write_text(f"<annotation><filename>%s</filename>"
                        f"<size><width>{w}</width><height>{h}</height>"
                        f"</size>{body}</annotation>")

    # normal box + inverted min/max + out-of-bounds (clamps) + degenerate
    write_xml(xml_dir / "n01440764" / "n01440764_1.xml",
              [("n01440764", 20, 10, 120, 80),
               ("n01440764", 160, 90, 40, 20),     # inverted → swapped
               ("n01440764", -50, -10, 400, 150),  # clamps to [0,1]
               ("n01440764", 20, 10, 20, 80)])     # zero width → skipped
    # human-label box (kept: 'Scottish_deerhound' is not a synset id)
    # + off-synset challenge box (skipped)
    write_xml(xml_dir / "n01440764" / "n01440764_2.xml",
              [("Scottish_deerhound", 10, 10, 50, 50),
               ("n01443537", 10, 10, 50, 50)])
    # off-challenge synset dir (skipped entirely under synsets filter)
    write_xml(xml_dir / "n09999999" / "n09999999_1.xml",
              [("n09999999", 10, 10, 50, 50)])
    synsets = tmp_path / "synsets.txt"
    synsets.write_text("n01440764\nn01443537\n")
    out_csv = tmp_path / "boxes.csv"
    stats = prep.process_imagenet_bboxes(str(xml_dir), str(out_csv),
                                         str(synsets))
    assert stats["files"] == 2 and stats["skipped_files"] == 1
    assert stats["boxes"] == 4 and stats["skipped_boxes"] == 2
    rows = prep.load_bbox_csv(str(out_csv))
    np.testing.assert_allclose(rows["n01440764_1.JPEG"][0],
                               [0.1, 0.1, 0.6, 0.8], atol=1e-4)
    np.testing.assert_allclose(rows["n01440764_1.JPEG"][1],
                               [0.2, 0.2, 0.8, 0.9], atol=1e-4)
    np.testing.assert_allclose(rows["n01440764_1.JPEG"][2],
                               [0.0, 0.0, 1.0, 1.0], atol=1e-4)
    assert len(rows["n01440764_2.JPEG"]) == 1

    # bbox plumbing into record headers (build_imagenet_tfrecord.py:472-689)
    src = tmp_path / "flat"
    src.mkdir()
    _save_jpg(src / "n01440764_1.JPEG", 32, 32)
    labels = tmp_path / "meta.txt"
    labels.write_text("n01440764 tench\n")
    out = str(tmp_path / "recs")
    prep.prepare_imagenet(str(src), str(labels), out, "train", num_shards=1,
                          num_workers=1, bbox_csv=str(out_csv))
    (h, _), = [(h, p) for sh in list_shards(out, "train")
               for h, p in read_records(sh)]
    assert len(h["bboxes"]) == 3


def test_flatten_imagenet_train_and_val(tmp_path):
    """Raw-layout bootstrap (the untar/flatten-script.sh role): per-synset
    tars/dirs → flat synset-prefixed train dir; flat official val + ground
    truth → synset-prefixed val dir."""
    import tarfile

    # train: one synset as a tar, one as a directory
    raw = tmp_path / "raw_train"
    raw.mkdir()
    syn_dir = raw / "n01443537"
    syn_dir.mkdir()
    _save_jpg(syn_dir / "n01443537_0.JPEG", 16, 16)
    tar_src = tmp_path / "tarsrc"
    tar_src.mkdir()
    _save_jpg(tar_src / "n01440764_0.JPEG", 16, 16)
    _save_jpg(tar_src / "n01440764_1.JPEG", 16, 16)
    with tarfile.open(raw / "n01440764.tar", "w") as tf:
        for f in sorted(tar_src.iterdir()):
            tf.add(f, arcname=f.name)
    flat = tmp_path / "train_flat"
    n = prep.flatten_imagenet_train(str(raw), str(flat))
    assert n == 3
    assert sorted(os.listdir(flat)) == [
        "n01440764_0.JPEG", "n01440764_1.JPEG", "n01443537_0.JPEG"]

    # val: flat official naming + 1-based ground truth
    raw_val = tmp_path / "raw_val"
    raw_val.mkdir()
    _save_jpg(raw_val / "ILSVRC2012_val_00000001.JPEG", 16, 16)
    _save_jpg(raw_val / "ILSVRC2012_val_00000002.JPEG", 16, 16)
    (tmp_path / "synsets.txt").write_text("n01440764\nn01443537\n")
    (tmp_path / "gt.txt").write_text("2\n1\n")
    flat_val = tmp_path / "val_flat"
    n = prep.flatten_imagenet_val(str(raw_val), str(flat_val),
                                  str(tmp_path / "gt.txt"),
                                  str(tmp_path / "synsets.txt"))
    assert n == 2
    assert sorted(os.listdir(flat_val)) == [
        "n01440764_ILSVRC2012_val_00000002.JPEG",
        "n01443537_ILSVRC2012_val_00000001.JPEG"]

    # val: per-synset-dir layout needs no ground truth
    raw_val2 = tmp_path / "raw_val2"
    (raw_val2 / "n01440764").mkdir(parents=True)
    _save_jpg(raw_val2 / "n01440764" / "x.JPEG", 16, 16)
    flat_val2 = tmp_path / "val_flat2"
    assert prep.flatten_imagenet_val(str(raw_val2), str(flat_val2)) == 1
    assert os.listdir(flat_val2) == ["n01440764_x.JPEG"]


def test_prepare_unpaired_and_celeba(tmp_path):
    da, db = tmp_path / "a", tmp_path / "b"
    da.mkdir(), db.mkdir()
    for i in range(3):
        _save_jpg(da / f"a{i}.jpg")
    for i in range(2):
        _save_jpg(db / f"b{i}.jpg")
    out = str(tmp_path / "recs")
    na, nb = prep.prepare_unpaired(str(da), str(db), out, "train",
                                   num_shards=1, num_workers=1)
    assert (na, nb) == (3, 2)
    assert list_shards(out, "train_a") and list_shards(out, "train_b")

    # celeba split
    imgs = tmp_path / "celeba"
    imgs.mkdir()
    for f in ("1.jpg", "2.jpg", "3.jpg"):
        _save_jpg(imgs / f)
    attr = tmp_path / "attrs.txt"
    attr.write_text("3\nSmiling Male\n1.jpg 1 1\n2.jpg 1 -1\n3.jpg -1 1\n")
    oa, ob = str(tmp_path / "m"), str(tmp_path / "f")
    na, nb = prep.split_celeba_by_attribute(str(attr), str(imgs), oa, ob,
                                            "Male")
    assert (na, nb) == (2, 1)
    assert len(os.listdir(oa)) == 2 and len(os.listdir(ob)) == 1


def test_prepare_voc_honors_split_lists(voc_layout, tmp_path):
    """Regression: train/val shards must be disjoint when ImageSets exist."""
    import pathlib

    base = pathlib.Path(voc_layout) / "VOC2007"
    main = base / "ImageSets" / "Main"
    main.mkdir(parents=True)
    (main / "train.txt").write_text("img000\nimg001\n")
    (main / "val.txt").write_text("img002\n")
    out = str(tmp_path / "recs")
    n_train = prep.prepare_voc(voc_layout, out, "train", num_shards=1,
                               num_workers=1)
    n_val = prep.prepare_voc(voc_layout, out, "val", num_shards=1,
                             num_workers=1)
    assert (n_train, n_val) == (2, 1)
