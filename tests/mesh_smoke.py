"""`make mesh-smoke` (runs inside `make serve-smoke`): boot the real
cli.serve wiring with a FORCED 2×2 ``data × model`` mesh over 4 virtual
host devices, fault-injected, and assert the whole mesh surface end to
end: every request answers 200 through bisect-retry, /v1/healthz
advertises the mesh shape + per-chip shard bytes + HBM headroom,
/v1/stats prices the per-chip footprint strictly below the replicated
one, and every /metrics line parses — including the new
``dvt_serve_mesh_shape`` (one sample per axis) and
``dvt_serve_param_shard_bytes`` gauges, which must agree with the
stats document.  Run directly, not under pytest."""

import argparse
import json
import os
import re
import sys
import tempfile
import urllib.request

# 4 virtual host devices for the 2×2 mesh, BEFORE any jax import
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if not f.startswith("--xla_force_host_platform_device_count")]
_flags.append("--xla_force_host_platform_device_count=4")
os.environ["XLA_FLAGS"] = " ".join(_flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize pins the TPU

import numpy as np  # noqa: E402

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/mesh_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SAMPLE_RE = re.compile(r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)")


def parse_metrics(text: str) -> dict:
    """Validate every exposition line; return {name: {labels_str: value}}."""
    samples: dict = {}
    for line in text.splitlines():
        assert line.strip() == line and line, f"bad line {line!r}"
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert not line.startswith("#"), f"unknown comment {line!r}"
        m = _SAMPLE_RE.fullmatch(line)
        assert m, f"unparseable sample {line!r}"
        name, labels, value = m.groups()
        v = float("inf") if value == "+Inf" else float(value)
        samples.setdefault(name, {})[labels or ""] = v
    return samples


def main():
    from deep_vision_tpu.cli.serve import build_server

    with tempfile.TemporaryDirectory() as workdir:
        args = argparse.Namespace(
            model="lenet5", workdir=workdir, stablehlo=None,
            host="127.0.0.1", port=0, max_batch=4, max_wait_ms=2.0,
            buckets=None, max_queue=64, warmup=False, verbose=False,
            pipeline_depth=2, faults="compute:exception:times=1",
            fault_seed=0, serve_devices=1, shard_batches=False,
            mesh="2,2", partition_rules=None, partition_strict=False,
            partition_min_dim=64,
            wire_dtype="float32", infer_dtype="float32")
        engine, server = build_server(args)
        server.start_background()
        base = f"http://{server.host}:{server.port}"
        try:
            body = json.dumps(
                {"pixels": np.zeros((32, 32, 1)).tolist()}).encode()
            for _ in range(4):
                req = urllib.request.Request(
                    base + "/v1/classify", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as r:
                    assert r.status == 200, r.status
                    assert len(json.loads(r.read())["top"]) == 5

            with urllib.request.urlopen(base + "/v1/healthz",
                                        timeout=60) as r:
                health = json.loads(r.read())
            rep = health["engines"]["lenet5"]
            assert rep["mesh_shape"] == {"data": 2, "model": 2}, rep
            assert rep["param_shard_bytes"] > 0, rep
            assert "hbm_headroom_bytes" in rep, rep

            with urllib.request.urlopen(base + "/v1/stats",
                                        timeout=60) as r:
                stats = json.loads(r.read())["lenet5"]
            assert stats["mesh_shape"] == {"data": 2, "model": 2}, stats
            shard, glob = (stats["param_shard_bytes"],
                           stats["param_global_bytes"])
            assert 0 < shard < glob, (shard, glob)
            h = stats["health"]
            # the injected failure fired AND was recovered from
            assert h["batch_failures"] >= 1, h
            assert h["retry_executions"] >= 1, h
            assert h["state"] == "ok", h

            with urllib.request.urlopen(base + "/metrics",
                                        timeout=60) as r:
                samples = parse_metrics(r.read().decode())
            mesh_g = samples["dvt_serve_mesh_shape"]
            assert mesh_g['{axis="data",model="lenet5"}'] == 2, mesh_g
            assert mesh_g['{axis="model",model="lenet5"}'] == 2, mesh_g
            shard_g = samples["dvt_serve_param_shard_bytes"]
            assert shard_g['{model="lenet5"}'] == shard, shard_g
            assert samples["dvt_serve_weight_hbm_bytes"][
                '{model="lenet5"}'] == shard, "cache unit must be per-chip"
            print(f"mesh smoke OK (2x2, faults recovered): per-chip "
                  f"{shard} B of {glob} B logical, "
                  f"{len(samples)} metric families parsed")
        finally:
            server.shutdown()
            engine.stop(drain_deadline=5.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
