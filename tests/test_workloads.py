"""Workload subsystem (CPU, tier-1 fast): the serve/workloads.py
adapters route verbs, decode latents, fuse the pose/generate epilogues
into bucket programs, shrink the generate D2H exactly 4× vs a float32
output wire (the output-side mirror of the PR 5 H2D assertion), cache
generate payloads, and score shadow agreement per workload.

Heavyweight pieces (hourglass/DCGAN compiles) live in module-scoped
fixtures so each compiles once for the whole file; the on-device
decode parity test is pure numpy-vs-traced math, no model."""

import json
import tempfile
import urllib.error
import urllib.request

import numpy as np
import pytest

from deep_vision_tpu.serve.engine import BatchingEngine
from deep_vision_tpu.serve.registry import ModelRegistry
from deep_vision_tpu.serve.workloads import (
    LIFECYCLE_VERBS,
    WORKLOADS,
    SLO,
    infer_paths,
    infer_verbs,
    workload_for_task,
)

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def dcgan_serving(tmp_path_factory):
    reg = ModelRegistry()
    # empty workdir fixture → deterministic PRNGKey(0) random init;
    # wire requested uint8 ON PURPOSE: the generate workload must
    # override it to float32 for the latent input
    sm = reg.load_checkpoint(
        "dcgan", str(tmp_path_factory.mktemp("dcgan_workdir")),
        wire_dtype="uint8")
    return reg, sm


@pytest.fixture(scope="module")
def hourglass_serving(tmp_path_factory):
    reg = ModelRegistry()
    sm = reg.load_checkpoint(
        "hourglass_toy",
        str(tmp_path_factory.mktemp("hourglass_workdir")),
        wire_dtype="uint8")
    return reg, sm


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req) as r:
        return r.status, dict(r.headers), json.loads(r.read())


# -- registry / routing ----------------------------------------------------


def test_workload_registry_tables():
    assert set(infer_verbs()) == {"classify", "detect", "pose",
                                  "generate"}
    assert infer_paths() == tuple(
        f"/v1/{v}" for v in sorted(WORKLOADS))
    assert workload_for_task("classification").verb == "classify"
    assert workload_for_task("detection").verb == "detect"
    assert workload_for_task("centernet").verb == "detect"
    assert workload_for_task("pose").verb == "pose"
    assert workload_for_task("gan_dcgan").verb == "generate"
    assert workload_for_task("gan_cyclegan").verb == "generate"
    # unknown tasks degrade to the logits-style default, not a crash
    assert workload_for_task("some_future_task").verb == "classify"
    assert not set(LIFECYCLE_VERBS) & set(infer_verbs())


def test_slo_bound_queue():
    slo = SLO("batchy", deadline_ms=60_000.0, max_queue=64)
    assert slo.bound_queue(256) == 64   # workload class caps
    assert slo.bound_queue(16) == 16    # operator's tighter bound wins
    assert WORKLOADS["generate"].slo.max_queue < \
        WORKLOADS["classify"].slo.max_queue


# -- pose: traced decode parity + fused epilogue ---------------------------


def test_decode_heatmaps_parity_with_host_argmax():
    """refine=False integer peaks == host heatmap_argmax to 1e-6;
    refine=True moves each coordinate at most a quarter pixel."""
    import jax.numpy as jnp

    from deep_vision_tpu.tasks.pose import decode_heatmaps, heatmap_argmax

    hm = np.random.RandomState(0).randn(3, 16, 16, 8).astype(np.float32)
    dec = decode_heatmaps(jnp.asarray(hm), refine=False)
    kp = np.asarray(dec["keypoints"])
    sc = np.asarray(dec["scores"])
    assert kp.shape == (3, 8, 2) and sc.shape == (3, 8)
    for i in range(3):
        np.testing.assert_allclose(kp[i], heatmap_argmax(hm[i]),
                                   atol=1e-6)
        np.testing.assert_allclose(sc[i], hm[i].max(axis=(0, 1)),
                                   atol=1e-6)
    refined = np.asarray(
        decode_heatmaps(jnp.asarray(hm), refine=True)["keypoints"])
    assert np.abs(refined - kp).max() <= 0.25 + 1e-6


def test_decode_heatmaps_border_peaks_not_refined():
    """A peak on the heatmap border skips refinement on that axis —
    the clipped neighbor gather would compare the peak to itself."""
    import jax.numpy as jnp

    from deep_vision_tpu.tasks.pose import decode_heatmaps

    hm = np.zeros((1, 8, 8, 2), np.float32)
    hm[0, 0, 0, 0] = 5.0   # corner: both axes on the border
    hm[0, 3, 7, 1] = 5.0   # right edge: x on the border, y interior
    hm[0, 2, 7, 1] = 1.0   # y-neighbor above, to pull the offset
    kp = np.asarray(decode_heatmaps(jnp.asarray(hm))["keypoints"])[0]
    assert tuple(kp[0]) == (0.0, 0.0)
    assert kp[1, 0] == 7.0           # no x refinement on the edge
    assert kp[1, 1] == pytest.approx(3.0 - 0.25)


def test_pose_epilogue_fused_into_bucket_program(hourglass_serving):
    """The compiled bucket program returns decoded keypoints, not
    heatmaps — D2H per image is K coordinate pairs + K scores."""
    _, sm = hourglass_serving
    assert sm.workload.verb == "pose"
    with BatchingEngine(sm, buckets=[2], max_wait_ms=2) as eng:
        img = np.random.RandomState(0).randint(
            0, 256, (64, 64, 3), np.uint8)
        row = eng.infer(img, timeout=300)
        assert set(row) == {"keypoints", "scores"}
        assert np.asarray(row["keypoints"]).shape == (8, 2)
        assert np.asarray(row["scores"]).shape == (8,)
        pipe = eng.stats()["pipeline"]
        # 8 kp × (2 coords + 1 score) × 4 B × bucket 2 = 192 B/batch —
        # the 16×16×8 heatmap stack would have been 8192 B/image
        assert pipe["d2h_bytes"] == 2 * 8 * 3 * 4
        assert pipe["d2h_bytes_by_bucket"] == {2: 2 * 8 * 3 * 4}


# -- generate: latent codec + uint8 output wire ----------------------------


def test_dcgan_latent_input_and_wire_override(dcgan_serving):
    """Latent-in generative serving: input is the (latent_dim,) float
    vector (the trainer's init shape — image-shaped init would build
    unrestorable Dense params), and the requested uint8 wire is
    overridden to float32."""
    _, sm = dcgan_serving
    assert sm.workload.verb == "generate"
    assert sm.input_shape == (100,)
    assert str(sm.wire_dtype) == "float32"
    assert sm.output_wire == "uint8"
    assert sm.describe()["workload"] == "generate"
    assert sm.describe()["output_wire"] == "uint8"


def test_generate_decode_latent_and_seed(dcgan_serving):
    _, sm = dcgan_serving
    wl = WORKLOADS["generate"]
    z = wl.decode({"seed": 7}, sm)
    assert z.shape == (100,) and z.dtype == np.float32
    np.testing.assert_array_equal(z, wl.decode({"seed": 7}, sm))
    explicit = wl.decode({"latent": z.tolist()}, sm)
    np.testing.assert_allclose(explicit, z, atol=1e-6)
    with pytest.raises(ValueError, match="latent shape"):
        wl.decode({"latent": [0.0] * 3}, sm)
    with pytest.raises(ValueError, match="non-finite"):
        wl.decode({"latent": [float("nan")] * 100}, sm)


def test_generate_d2h_bytes_exactly_4x_smaller(dcgan_serving):
    """The output-side mirror of the PR 5 H2D assertion: with the
    fused uint8 epilogue the bulk device_get moves EXACTLY 4× fewer
    bytes than the float32 output wire, per batch and in total."""
    import copy

    _, sm = dcgan_serving
    z = [np.random.RandomState(i).randn(100).astype(np.float32)
         for i in range(4)]
    with BatchingEngine(sm, buckets=[4], max_wait_ms=50) as eng:
        for f in [eng.submit(x) for x in z]:
            img = np.asarray(f.result(300))
            assert img.dtype == np.uint8 and img.shape == (28, 28, 1)
        u8 = eng.stats()["pipeline"]
    sm_f32 = copy.copy(sm)
    sm_f32.output_wire = "float32"  # pin the A/B baseline epilogue off
    with BatchingEngine(sm_f32, buckets=[4], max_wait_ms=50) as eng:
        for f in [eng.submit(x) for x in z]:
            assert np.asarray(f.result(300)).dtype == np.float32
        f32 = eng.stats()["pipeline"]
    assert u8["d2h_bytes"] == 4 * 28 * 28 * 1          # one uint8 batch
    assert f32["d2h_bytes"] == 4 * u8["d2h_bytes"]     # exactly 4.0×
    assert f32["d2h_bytes_by_bucket"][4] == \
        4 * u8["d2h_bytes_by_bucket"][4]


# -- HTTP: routes, response cache, agreement -------------------------------


def test_generate_http_roundtrip_and_response_cache(dcgan_serving):
    """POST /v1/generate over real HTTP: wire-ready uint8 bytes come
    back base64'd; an identical payload replays from the response
    cache (X-DVT-Cache: hit) without touching the engine."""
    from deep_vision_tpu.serve.cache import ResponseCache
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = dcgan_serving
    eng = BatchingEngine(sm, buckets=[1], max_wait_ms=2).start()
    cache = ResponseCache(max_bytes=8 * 2**20)
    srv = ServeServer(reg, {sm.name: eng}, port=0,
                      response_cache=cache).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        status, headers, out = _post(base + "/v1/generate", {"seed": 3})
        assert status == 200
        img = out["image"]
        assert img["shape"] == [28, 28, 1] and img["dtype"] == "uint8"
        import base64

        raw = base64.b64decode(img["b64"])
        assert len(raw) == 28 * 28 * 1  # 1 byte/pixel on the wire
        served = eng.served
        status, headers, out2 = _post(base + "/v1/generate", {"seed": 3})
        assert status == 200
        assert headers.get("X-DVT-Cache") == "hit"
        assert out2 == out
        assert eng.served == served  # hit consumed no engine capacity
        assert cache.stats()["hits"] == 1
        # different seed → different payload digest → miss
        status, headers, out3 = _post(base + "/v1/generate", {"seed": 4})
        assert headers.get("X-DVT-Cache") != "hit"
        assert out3["image"]["b64"] != img["b64"]
    finally:
        srv.shutdown()
        eng.stop()


def test_unknown_verb_404_lists_supported(dcgan_serving):
    """Satellite: unknown verbs 404 with the registry-derived verb
    list in the body — both the flat and the per-model route."""
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = dcgan_serving
    eng = BatchingEngine(sm, buckets=[1], max_wait_ms=2).start()
    srv = ServeServer(reg, {sm.name: eng}, port=0).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        for path in ("/v1/frobnicate", "/v1/models/dcgan/frobnicate"):
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(base + path, {"seed": 0})
            assert exc.value.code == 404
            body = json.loads(exc.value.read())
            assert body["supported_verbs"] == sorted(
                infer_verbs() + LIFECYCLE_VERBS)
        # wrong verb for the model's workload: 400 names the right one
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base + "/v1/pose", {"model": "dcgan", "seed": 0})
        assert exc.value.code == 400
        assert "/v1/generate" in json.loads(exc.value.read())["error"]
    finally:
        srv.shutdown()
        eng.stop()


def test_shadow_agreement_per_workload():
    """models.py delegates shadow comparison to the workload: top-1
    for classify, PCK proximity for pose, digest equality for
    generate, greedy IoU-matched pairing for detect's device-decoded
    rows (dense host pyramids and Shed-ish rows stay not-comparable).
    Detect verdict details live in tests/test_detect_epilogue.py."""
    from deep_vision_tpu.serve.admission import Shed

    cls = WORKLOADS["classify"]
    a = np.asarray([0.1, 0.9, 0.3], np.float32)
    b = np.asarray([0.2, 0.8, 0.1], np.float32)
    c = np.asarray([0.9, 0.1, 0.1], np.float32)
    assert cls.agree(a, b) is True
    assert cls.agree(a, c) is False
    assert cls.agree(a, Shed("x", "y")) is None
    # dense pyramid rows (host decode path) are not comparable...
    assert WORKLOADS["detect"].agree(a, a) is None
    # ...device-decoded dict rows are
    det = {"boxes": np.asarray([[0.1, 0.1, 0.4, 0.4]], np.float32),
           "scores": np.asarray([0.9], np.float32),
           "classes": np.asarray([1], np.int32),
           "valid": np.asarray([1.0], np.float32)}
    miss = dict(det, boxes=np.asarray([[0.6, 0.6, 0.9, 0.9]],
                                      np.float32))
    assert WORKLOADS["detect"].agree(det, det) is True
    assert WORKLOADS["detect"].agree(det, miss) is False
    assert WORKLOADS["detect"].agree(det, Shed("x", "y")) is None

    pose = WORKLOADS["pose"]
    kp = {"keypoints": np.zeros((8, 2), np.float32),
          "scores": np.zeros(8, np.float32)}
    near = {"keypoints": kp["keypoints"] + 1.0, "scores": kp["scores"]}
    far = {"keypoints": kp["keypoints"] + 10.0, "scores": kp["scores"]}
    assert pose.agree(kp, near) is True     # within pck_px
    assert pose.agree(kp, far) is False
    assert pose.agree(kp, Shed("x", "y")) is None

    gen = WORKLOADS["generate"]
    img = np.random.RandomState(0).randint(0, 256, (28, 28, 1),
                                           np.uint8)
    assert gen.agree(img, img.copy()) is True
    other = img.copy()
    other[0, 0, 0] ^= 1
    assert gen.agree(img, other) is False
    assert gen.agree(img, Shed("x", "y")) is None


def test_generate_cacheable_guard():
    gen, cls = WORKLOADS["generate"], WORKLOADS["classify"]
    big = 512 * 1024
    assert gen.cacheable(big)        # generated images are large
    assert not cls.cacheable(big)    # logits responses never are
    assert not gen.cacheable(gen.cacheable_bytes + 1)


def test_gan_serve_preprocess_kind_matches_trainer():
    """The image-in GAN wire ("gan" kind) scales exactly like the
    trainer's make_gan_preprocess: (x - 127.5)/127.5."""
    import jax.numpy as jnp

    from deep_vision_tpu.ops.preprocess import (
        make_serve_preprocess,
        serve_normalize,
        serve_preprocess_kind,
    )

    assert serve_preprocess_kind("gan_cyclegan", 3) == "gan"
    assert serve_preprocess_kind("gan_dcgan", 1) == "gan"
    u8 = np.asarray([[0, 127, 128, 255]], np.uint8)
    out = np.asarray(serve_normalize(jnp.asarray(u8), "gan"))
    np.testing.assert_allclose(
        out, u8.astype(np.float32) / 127.5 - 1.0, atol=1e-6)
    assert out.min() >= -1.0 and out.max() <= 1.0
    # a float wire passes through untouched (client shipped [-1,1])
    pre = make_serve_preprocess("gan", np.float32)
    x = np.linspace(-1, 1, 8).astype(np.float32)
    np.testing.assert_allclose(np.asarray(pre(jnp.asarray(x))), x,
                               atol=1e-6)


def test_restore_serving_input_shape():
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.restore import serving_input_shape

    assert serving_input_shape(get_config("lenet5")) == (32, 32, 1)
    assert serving_input_shape(get_config("hourglass_toy")) == \
        (64, 64, 3)
    assert serving_input_shape(get_config("dcgan")) == (100,)


def test_dcgan_load_state_roundtrips_trainer_params(tmp_path):
    """load_state's latent-shaped init builds the SAME param tree the
    trainer does (DCGANTask.init_states inits G with a (1, latent_dim)
    z) — an image-shaped init would build Dense kernels a trainer
    checkpoint could never restore into."""
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.restore import load_state

    cfg = get_config("dcgan")
    model, state = load_state(cfg, str(tmp_path), log=lambda *a: None)
    z = jnp.zeros((1, model.latent_dim))
    g_vars = model.init({"params": jax.random.PRNGKey(0)}, z,
                        train=False)
    serve_shapes = jax.tree_util.tree_map(jnp.shape, state.params)
    train_shapes = jax.tree_util.tree_map(jnp.shape, g_vars["params"])
    assert serve_shapes == train_shapes
