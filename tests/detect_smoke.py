"""`make detect-smoke`: boot the plane the way
`python -m deep_vision_tpu.cli.serve --models yolov3_toy` does
(cli.serve.build_server's plane path) with an injected transient
compute fault, then prove device-side detect decode end to end over
real HTTP:

  * POST /v1/detect answers trimmed detections (decode → score floor →
    top-k → class-wise NMS compiled INTO the bucket program — the
    dense anchor pyramid never crosses D2H): ``num_detections`` always
    equals the row count, no padded/invalid rows ever reach a client,
    and per-request ``score_threshold`` trims server-side — zero
    client errors through the fault (bisect-retry absorbs it);
  * the engine's own counters prove the wire: bulk D2H is EXACTLY
    (served + padded) × K·28 B — boxes, not pyramids;
  * the wrong verb for a detect model 400s naming /v1/detect;
  * hot-reload yolov3_toy under live detect traffic through the FULL
    ladder — reload → SHADOW (the new greedy-IoU agreement metric
    gates the candidate: ≥10 live comparisons, perfect agreement for
    identical weights) → canary → explicit operator /promote
    (min_requests pinned high so auto-promote can't race) — v2
    active, ZERO hammer errors;
  * /v1/stats is plane-shaped with the shadow verdict banked on the
    v2 row, and every /metrics line parses as Prometheus text —
    including dvt_serve_d2h_bytes_total carrying workload="detect".

Run directly, not under pytest."""

import argparse
import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/detect_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# a metric line: name{labels} value  (labels optional; the value is
# validated separately with float(), which accepts nan/inf spellings)
_PROM_LINE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? (\S+)$")

#: fixed-size device row: K × (boxes f32×4 + score + class + valid)
_ROW_BYTES = 16 + 4 + 4 + 4


def _post(base, path, payload, timeout=120):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(base, path, timeout=60):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _check_detect_body(out, min_score):
    assert out["model"] == "yolov3_toy", out
    dets = out["detections"]
    assert out["num_detections"] == len(dets), out
    for d in dets:
        assert {"box", "score", "class"} <= set(d), d
        assert len(d["box"]) == 4, d
        assert d["score"] >= min_score, (d, min_score)
        assert 0 <= d["class"] < 3, d
    return dets


def smoke():
    from deep_vision_tpu.cli.serve import build_server

    with tempfile.TemporaryDirectory() as workdir:
        os.makedirs(os.path.join(workdir, "yolov3_toy"), exist_ok=True)
        args = argparse.Namespace(
            model=None, models="yolov3_toy", workdir=workdir,
            stablehlo=None, host="127.0.0.1", port=0, max_batch=2,
            max_wait_ms=2.0, buckets=None, max_queue=64, warmup=False,
            verbose=False, pipeline_depth=2,
            # one transient compute failure somewhere in the mix: every
            # request below must still answer 200 through bisect-retry
            faults="compute:exception:times=1", fault_seed=0,
            serve_devices=1, shard_batches=False,
            wire_dtype="uint8", infer_dtype="float32",
            hbm_budget_mb=0.0, canary_frac=0.5,
            # pinned far above any traffic this test sends, so the
            # explicit operator /promote below is the ONLY way v2 goes
            # active (exercises the override path, not the auto-gate)
            canary_min_requests=10**6, canary_max_error_rate=0.0,
            canary_max_p99_ratio=50.0,
            # every 2nd live request duplicated onto the candidate:
            # the reload below must clear the detect agreement gate
            # (greedy IoU≥0.5 class-matched pairing) on REAL traffic
            shadow_frac=0.5,
            phase_timeout_s=120.0)
        plane, server = build_server(args)
        server.start_background()
        base = f"http://{server.host}:{server.port}"
        try:
            health = _get(base, "/v1/healthz")
            assert health["status"] == "ok", health
            assert sorted(health["engines"]) == ["yolov3_toy"], health

            # detect: raw uint8 pixels in, trimmed box list out — both
            # the flat verb route and the per-model path route
            px = np.random.default_rng(0).integers(
                0, 256, (64, 64, 3)).tolist()
            for path, body in (
                    ("/v1/detect", {"model": "yolov3_toy",
                                    "pixels": px}),
                    ("/v1/models/yolov3_toy/detect", {"pixels": px})):
                status, out = _post(base, path, body)
                assert status == 200, (path, out)
                # default request threshold is 0.3 — every surfaced
                # row clears it; padded device rows never appear
                _check_detect_body(out, 0.3)

            # per-request score_threshold trims server-side: a looser
            # floor returns a superset, a hopeless one returns empty
            _, loose = _post(base, "/v1/detect",
                             {"model": "yolov3_toy", "pixels": px,
                              "score_threshold": 0.05})
            _, tight = _post(base, "/v1/detect",
                             {"model": "yolov3_toy", "pixels": px,
                              "score_threshold": 0.999999})
            assert loose["num_detections"] >= out["num_detections"]
            assert tight["num_detections"] == 0, tight
            assert tight["detections"] == [], tight

            # the wrong verb for a detect model 400s naming the route
            try:
                _post(base, "/v1/classify",
                      {"model": "yolov3_toy", "pixels": px})
                raise AssertionError("wrong verb should 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400, e.code
                assert "/v1/detect" in json.loads(e.read())["error"]

            # the injected fault fired and bisect-retry absorbed it
            # (every request above was a 200) — asserted BEFORE the
            # rollout, because promote retires the engine that took it
            pre = _get(base, "/v1/stats")
            pre_health = pre["models"]["yolov3_toy"]["engine"]["health"]
            assert pre_health["batch_failures"] >= 1, pre_health
            assert pre_health["retry_executions"] >= 1, pre_health
            failures = pre_health["batch_failures"]
            retries = pre_health["retry_executions"]

            # hot-reload under live detect traffic: reload → shadow
            # (agreement-gated) → canary → explicit operator promote,
            # zero client errors end to end
            errors, served = [], [0]
            stop = threading.Event()

            def hammer():
                while not stop.is_set():
                    try:
                        status, out = _post(
                            base, "/v1/detect",
                            {"model": "yolov3_toy", "pixels": px},
                            timeout=60)
                        assert status == 200, out
                        _check_detect_body(out, 0.3)
                        served[0] += 1
                    except Exception as e:  # noqa: BLE001 — any failure is a lost request
                        errors.append(repr(e))

            t = threading.Thread(target=hammer, daemon=True)
            t.start()
            status, out = _post(base, "/v1/models/yolov3_toy/reload",
                                {"force": True})
            assert status == 200 and out["status"] == "reloading", out
            deadline = time.monotonic() + 180
            canary_seen = None
            while time.monotonic() < deadline:
                table = _get(base, "/v1/models")["models"]
                versions = table["yolov3_toy"]["versions"]
                canary_seen = [v for v in versions
                               if v["state"] == "canary"]
                if canary_seen and canary_seen[0].get(
                        "canary", {}).get("requests", 0) >= 2:
                    break
                time.sleep(0.05)
            assert canary_seen, versions
            # reaching canary means the shadow gate PASSED on live
            # traffic: ≥ min_compared comparisons, and identical
            # weights give perfect greedy-IoU agreement
            shadow = canary_seen[0].get("shadow")
            assert shadow, canary_seen[0]
            assert shadow["compared"] >= 10, shadow
            assert shadow["agreed"] == shadow["compared"], shadow
            status, out = _post(base,
                                "/v1/models/yolov3_toy/promote", {})
            assert status == 200 and out["status"] == "promoted", out
            assert out["version"] == 2, out
            while time.monotonic() < deadline:
                if _get(base, "/v1/models")["models"]["yolov3_toy"][
                        "active_version"] == 2:
                    break
                time.sleep(0.05)
            # v2 serves through the same fused epilogue
            status, out = _post(base, "/v1/detect",
                                {"model": "yolov3_toy", "pixels": px})
            assert status == 200, out
            _check_detect_body(out, 0.3)
            stop.set()
            t.join(60)
            assert not errors, \
                f"rollout lost {len(errors)}: {errors[:3]}"

            # boxes, not pyramids: the drainer's bulk D2H is EXACTLY
            # (served + padded) × K·28 B fixed rows — the dense 64²
            # pyramid would be 8,064 B/image, the 416² one 340,704
            stats = _get(base, "/v1/stats")
            assert set(stats) >= {"models", "plane"}, set(stats)
            assert stats["plane"]["promotions"] == 1, stats["plane"]
            eng = stats["models"]["yolov3_toy"]["engine"]
            assert eng["workload"] == "detect", eng
            pipe = eng["pipeline"]
            detect = stats["models"]["yolov3_toy"].get(
                "describe", {}).get("detect") or _get(
                base, "/v1/models")["models"]["yolov3_toy"].get(
                "detect", {"top_k": 100})
            top_k = detect.get("top_k", 100)
            rows = eng["served"] + eng["padded_images"]
            assert pipe["d2h_bytes"] == rows * top_k * _ROW_BYTES, \
                (pipe["d2h_bytes"], rows, top_k)
            assert pipe["d2h_bytes_by_bucket"], pipe

            # /metrics: every line parses; the per-workload D2H series
            # carries the detect label
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=60) as r:
                text = r.read().decode()
            for line in text.splitlines():
                if not line or line.startswith("#"):
                    continue
                m = _PROM_LINE.match(line)
                assert m, f"bad metric line: {line}"
                float(m.group(2))  # ValueError = unparseable sample
            d2h_lines = [ln for ln in text.splitlines()
                         if ln.startswith("dvt_serve_d2h_bytes_total")]
            assert any('workload="detect"' in ln for ln in d2h_lines), \
                d2h_lines
            print(f"detect-smoke PASS: device decode from port "
                  f"{server.port}; reload under load cleared the "
                  f"shadow agreement gate ({shadow['agreed']}/"
                  f"{shadow['compared']} matched) and promoted "
                  f"yolov3_toy v2 with {served[0]} client requests "
                  f"and 0 errors; fault fired ({failures} batch "
                  f"failure(s), {retries} retried); detect D2H "
                  f"{pipe['d2h_bytes']}B for {rows} bucket rows — "
                  f"{top_k * _ROW_BYTES}B/image, not 8,064; "
                  f"{len(text.splitlines())} metric lines parsed")
        finally:
            server.shutdown()
            plane.stop(drain_deadline=5.0)
    return 0


def main():
    # pin the platform before jax initializes (site config can override
    # the env var alone, so set it at the config level too)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    return smoke()


if __name__ == "__main__":
    sys.exit(main())
