"""StableHLO export roundtrip (the reference's TFLite path,
CycleGAN/tensorflow/convert.py:7-16, done JAX-native)."""

import jax
import jax.numpy as jnp
import numpy as np

from deep_vision_tpu.core.export import export_forward, load_exported
from deep_vision_tpu.models.common import ConvBN
from deep_vision_tpu.models.lenet import LeNet5


def test_export_roundtrip(tmp_path):
    model = LeNet5()
    x = jnp.zeros((2, 32, 32, 1))
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    path = str(tmp_path / "lenet.stablehlo")
    n = export_forward(model, variables, (2, 32, 32, 1), path)
    assert n > 1000
    fn = load_exported(path)
    xin = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 1))
    out = fn(variables, xin)
    ref = model.apply(variables, xin, train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_export_batch_stats_roundtrip(tmp_path):
    """A model with a second variables collection (batch_stats) must
    survive serialize→deserialize with the pytree structure — collection
    and key ordering — intact, and numerics matching: the loader passes
    ``(variables, x)`` positionally, so any silent reordering of the
    flattened inputs would bind running means to conv kernels."""
    model = ConvBN(features=4)
    x = jnp.zeros((2, 8, 8, 3))
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    assert set(variables) == {"params", "batch_stats"}
    path = str(tmp_path / "convbn.stablehlo")
    export_forward(model, variables, (2, 8, 8, 3), path)
    fn = load_exported(path)
    # the exported input treedef is ((variables, x), {}) — exactly the
    # call signature, so the variables pytree round-tripped
    expected = jax.tree_util.tree_structure(
        ((variables, jnp.zeros((2, 8, 8, 3))), {}))
    assert fn.in_tree == expected
    xin = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 3))
    out = fn(variables, xin)
    ref = model.apply(variables, xin, train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
