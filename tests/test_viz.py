"""Result drawing (VERDICT r2 missing #3): the demo-notebook role —
`infer detect/pose --out annotated.jpg` turns an image into an annotated
image (YOLO/tensorflow/demo_mscoco.ipynb,
Hourglass/tensorflow/demo_hourglass_pose.ipynb)."""

import numpy as np
import pytest

from deep_vision_tpu.viz import draw_detections, draw_keypoints

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402


def test_draw_detections_marks_pixels():
    img = np.zeros((200, 300, 3), np.uint8)
    boxes = np.array([[0.1, 0.2, 0.5, 0.8], [0.6, 0.1, 0.9, 0.4]])
    out = draw_detections(img, boxes, np.array([0.9, 0.4]),
                          np.array([3, 7]),
                          class_names=[f"c{i}" for i in range(20)])
    assert out.shape == img.shape and out.dtype == np.uint8
    assert (out != img).any(), "nothing drawn"
    # box outline lands where the normalized corners say: column x1=0.1*300
    x1 = int(0.1 * 300)
    assert (out[int(0.2 * 200):int(0.8 * 200), x1] != 0).any()
    # input not mutated
    assert (img == 0).all()


def test_draw_detections_respects_min_score():
    img = np.zeros((64, 64, 3), np.uint8)
    out = draw_detections(img, np.array([[0.2, 0.2, 0.8, 0.8]]),
                          np.array([0.1]), np.array([0]), min_score=0.5)
    assert (out == img).all(), "sub-threshold box drawn"


def test_draw_keypoints_skeleton_and_visibility():
    img = np.zeros((128, 128, 3), np.uint8)
    kp = np.stack([np.linspace(10, 110, 16), np.linspace(10, 110, 16)], 1)
    vis = np.ones(16)
    out = draw_keypoints(img, kp, visible=vis)
    assert (out != img).any()
    # hidden joint draws nothing: isolate it (no skeleton) far from others
    img2 = np.zeros((128, 128, 3), np.uint8)
    kp2 = np.array([[20.0, 20.0], [100.0, 100.0]])
    out2 = draw_keypoints(img2, kp2, visible=np.array([1.0, 0.0]),
                          skeleton=())
    assert (out2[95:106, 95:106] == 0).all(), "hidden joint drawn"
    assert (out2[15:26, 15:26] != 0).any(), "visible joint missing"


@pytest.mark.slow
def test_infer_detect_writes_annotated_image(tmp_path):
    """End-to-end CLI: random-init toy YOLO, threshold 0 → some boxes →
    --out writes an annotated file (the one-command demo path)."""
    from deep_vision_tpu.cli import infer

    src = tmp_path / "scene.jpg"
    rng = np.random.default_rng(0)
    Image.fromarray(rng.integers(0, 255, (96, 128, 3), dtype=np.uint8)
                    ).save(src)
    out = tmp_path / "annotated.jpg"
    infer.main(["detect", "-m", "yolov3_toy",
                "--workdir", str(tmp_path / "w"),
                "--images", str(src), "--score-threshold", "0.0",
                "--out", str(out)])
    assert out.exists()
    assert Image.open(out).size == (128, 96)  # original resolution kept


@pytest.mark.slow
def test_infer_pose_writes_annotated_image(tmp_path):
    from deep_vision_tpu.cli import infer
    from deep_vision_tpu.core.config import TrainConfig, register_config
    from deep_vision_tpu.core.optim import OptimizerConfig
    from deep_vision_tpu.models.hourglass import StackedHourglass

    import jax.numpy as jnp

    register_config("hg_viz_toy")(lambda: TrainConfig(
        name="hg_viz_toy",
        model=lambda: StackedHourglass(num_stack=1, num_heatmap=16,
                                       filters=16, dtype=jnp.float32),
        task="pose", batch_size=2, total_epochs=1,
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
        image_size=64, num_classes=16, half_precision=False))
    src = tmp_path / "person.jpg"
    rng = np.random.default_rng(1)
    Image.fromarray(rng.integers(0, 255, (80, 60, 3), dtype=np.uint8)
                    ).save(src)
    out = tmp_path / "pose.jpg"
    infer.main(["pose", "-m", "hg_viz_toy",
                "--workdir", str(tmp_path / "w"),
                "--images", str(src), "--out", str(out)])
    assert out.exists()
    assert Image.open(out).size == (60, 80)
