"""Artifact upload (VERDICT r1 item 9): checkpoint sync to a destination
URI — the Hourglass GCS-upload role (main.py:21-65) with a local/file://
backend that works air-gapped."""

import os

import numpy as np
import pytest

from deep_vision_tpu.core.config import get_config
from deep_vision_tpu.core.trainer import Trainer
from deep_vision_tpu.core.upload import sync_dir
from deep_vision_tpu.data.loader import ArrayLoader
from deep_vision_tpu.data.mnist import synthetic_mnist
from deep_vision_tpu.tasks.classification import ClassificationTask


def test_sync_dir_incremental(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.txt").write_text("one")
    (src / "sub" / "b.txt").write_text("two")
    dest = tmp_path / "dest"
    assert sync_dir(str(src), f"file://{dest}") == 2
    assert (dest / "sub" / "b.txt").read_text() == "two"
    # unchanged files are skipped on re-sync; modified ones re-copy
    assert sync_dir(str(src), str(dest)) == 0
    (src / "a.txt").write_text("one-changed")
    assert sync_dir(str(src), str(dest)) == 1
    assert (dest / "a.txt").read_text() == "one-changed"


def test_sync_fresh_run_never_wipes_mirror(tmp_path):
    """An empty local dir (fresh run, nothing written yet) must not delete
    a populated mirror — the mirror may be the only surviving copy after a
    preemption killed the local disk (ADVICE r2, medium)."""
    src = tmp_path / "src"
    src.mkdir()
    dest = tmp_path / "dest"
    (dest / "ckpt-5").mkdir(parents=True)
    (dest / "ckpt-5" / "data").write_text("precious")
    assert sync_dir(str(src), str(dest)) == 0
    assert (dest / "ckpt-5" / "data").read_text() == "precious"


def test_restore_dir_roundtrip(tmp_path):
    from deep_vision_tpu.core.upload import restore_dir

    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "sub" / "b.txt").write_text("two")
    dest = tmp_path / "dest"
    sync_dir(str(src), str(dest))
    back = tmp_path / "back"
    assert restore_dir(f"file://{dest}", str(back)) == 1
    assert (back / "sub" / "b.txt").read_text() == "two"
    # absent mirror → 0, no error (genuinely fresh run)
    assert restore_dir(str(tmp_path / "nope"), str(back / "x")) == 0


@pytest.mark.slow
def test_trainer_restores_from_mirror_on_fresh_host(tmp_path, mesh1):
    """Preemption recovery: train + upload, wipe the workdir (the VM died),
    re-create the Trainer with the same upload URI → checkpoints come back
    from the mirror and the run resumes instead of starting over."""
    import shutil

    cfg = get_config("lenet5")
    cfg.total_epochs = 1
    cfg.batch_size = 32
    dest = tmp_path / "mirror"
    workdir = tmp_path / "run"
    trainer = Trainer(cfg, cfg.model(), ClassificationTask(10), mesh=mesh1,
                      workdir=str(workdir), upload=str(dest))
    data = synthetic_mnist(64)
    train = ArrayLoader(data, cfg.batch_size, seed=1)
    val = ArrayLoader(data, cfg.batch_size, shuffle=False)
    trainer.fit(train, val)
    trainer.checkpointer.close()
    trainer.best_checkpointer.close()
    shutil.rmtree(workdir)

    trainer2 = Trainer(cfg, cfg.model(), ClassificationTask(10), mesh=mesh1,
                       workdir=str(workdir), upload=str(dest))
    assert trainer2.checkpointer.latest_step() is not None, \
        "mirror checkpoints not restored onto the fresh host"
    state = trainer2.init_state(next(iter(train)))
    state = trainer2.maybe_resume(state)
    assert trainer2.start_epoch == 2  # continues after epoch 1, not from 0
    # and the mirror survived the fresh host's first sync
    assert os.listdir(dest / "checkpoints")


@pytest.mark.slow
def test_trainer_uploads_checkpoints(tmp_path, mesh1):
    """A run with upload=<uri> must land its rolling AND best checkpoints
    at the destination."""
    cfg = get_config("lenet5")
    cfg.total_epochs = 1
    cfg.batch_size = 32
    dest = tmp_path / "mirror"
    trainer = Trainer(cfg, cfg.model(), ClassificationTask(10), mesh=mesh1,
                      workdir=str(tmp_path / "run"), upload=str(dest))
    data = synthetic_mnist(64)
    train = ArrayLoader(data, cfg.batch_size, seed=1)
    val = ArrayLoader(data, cfg.batch_size, shuffle=False)
    trainer.fit(train, val)
    ckpts = os.listdir(dest / "checkpoints")
    assert ckpts, "rolling checkpoint not uploaded"
    best = os.listdir(dest / "checkpoints_best")
    assert best, "best-val checkpoint not uploaded"
    # uploaded payload mirrors the local checkpoint byte-for-byte
    local = tmp_path / "run" / "checkpoints"
    for root, _, files in os.walk(local):
        for f in files:
            full = os.path.join(root, f)
            rel = os.path.relpath(full, local)
            mirrored = dest / "checkpoints" / rel
            assert mirrored.exists(), rel
            assert np.fromfile(full, np.uint8).tobytes() == \
                np.fromfile(mirrored, np.uint8).tobytes()
