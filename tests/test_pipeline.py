"""Pipeline parallelism (parallel/pipeline.py): the GPipe microbatch
pipeline must be EXACTLY the sequential network — forward and gradients —
and must train the hourglass stack family it was built for."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.parallel import make_mesh, pipeline_apply, stack_stages
from deep_vision_tpu.parallel.pipeline import PIPE_AXIS, unstack_stages


def _conv_stage(p, x, state):
    """BN-free toy stage: SAME conv + bias + tanh (same-shape map)."""
    y = jax.lax.conv_general_dilated(
        x, p["w"], (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    y = jnp.tanh(y + p["b"])
    return y, y, state


def _stage_params(s, c=4, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), s)
    return stack_stages([
        {"w": jax.random.normal(k, (3, 3, c, c)) * 0.3,
         "b": jax.random.normal(k, (c,)) * 0.1} for k in ks])


def _sequential(params, x):
    outs = []
    c = x
    for p in unstack_stages(params):
        c, out, _ = _conv_stage(p, c, {})
        outs.append(out)
    return jnp.stack(outs)


@pytest.mark.parametrize("n_pipe,n_stages,n_micro", [
    pytest.param(4, 4, 4, marks=pytest.mark.slow),  # covered by the rest
    (4, 4, 8), (4, 8, 2), (2, 2, 4),
])
def test_pipeline_matches_sequential(n_pipe, n_stages, n_micro):
    """Forward outputs of every stage are bit-identical to the plain
    sequential loop — including S/n > 1 (multiple stages per device) and
    M != n (more microbatches than stages)."""
    mesh = make_mesh({PIPE_AXIS: n_pipe}, devices=jax.devices()[:n_pipe])
    params = _stage_params(n_stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 6, 6, 4))

    outs, _ = pipeline_apply(_conv_stage, params, x, mesh=mesh,
                             num_microbatches=n_micro)
    want = _sequential(params, x)
    assert outs.shape == want.shape == (n_stages, 8, 6, 6, 4)
    np.testing.assert_array_equal(np.asarray(outs), np.asarray(want))


def test_pipeline_gradients_match_sequential():
    """grad of a loss over ALL stage outputs (intermediate supervision
    shape) agrees with the sequential network's grad — the backward
    pipeline from plain autodiff through scan + ppermute."""
    mesh = make_mesh({PIPE_AXIS: 4}, devices=jax.devices()[:4])
    params = _stage_params(4)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 5, 5, 4))

    def loss_pipe(p):
        outs, _ = pipeline_apply(_conv_stage, p, x, mesh=mesh,
                                 num_microbatches=2)
        return jnp.mean(outs ** 2)

    def loss_seq(p):
        return jnp.mean(_sequential(p, x) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_seq = jax.jit(jax.grad(loss_seq))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        g_pipe, g_seq)


def test_pipeline_composes_with_data_parallel():
    """{"data": 2, "pipe": 4} mesh: batch sharded over data, stages over
    pipe — same numbers as the sequential network."""
    mesh = make_mesh({"data": 2, PIPE_AXIS: 4})
    params = _stage_params(4)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 6, 6, 4))

    outs, _ = pipeline_apply(_conv_stage, params, x, mesh=mesh,
                             num_microbatches=2)
    want = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(outs), np.asarray(want),
                               rtol=1e-6, atol=1e-7)


def test_pipeline_state_composes_with_data_parallel():
    """Regression: stage_state on a {data, pipe} mesh (BN-stats under
    data parallelism — the advertised composition).  A per-stage
    microbatch counter must come back = num_microbatches for every
    stage: bubbles don't count, data shards agree after the pmean."""
    mesh = make_mesh({"data": 2, PIPE_AXIS: 4})
    params = _stage_params(4)
    x = jax.random.normal(jax.random.PRNGKey(7), (8, 6, 6, 4))

    def counting_stage(p, c, s):
        y, out, _ = _conv_stage(p, c, {})
        return y, out, {"count": s["count"] + 1.0}

    state = {"count": jnp.zeros((4, 1))}
    outs, new_state = pipeline_apply(counting_stage, params, x, mesh=mesh,
                                     num_microbatches=4, stage_state=state)
    np.testing.assert_allclose(np.asarray(outs),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(np.asarray(new_state["count"]),
                                  np.full((4, 1), 4.0))


def test_pipeline_validates_shapes():
    mesh = make_mesh({PIPE_AXIS: 4}, devices=jax.devices()[:4])
    x = jnp.zeros((8, 6, 6, 4))
    with pytest.raises(ValueError, match="not divisible by pipe"):
        pipeline_apply(_conv_stage, _stage_params(6), x, mesh=mesh,
                       num_microbatches=2)
    with pytest.raises(ValueError, match="extra axes"):
        pipeline_apply(_conv_stage, _stage_params(4), x,
                       mesh=make_mesh({"model": 2, PIPE_AXIS: 4}),
                       num_microbatches=2)


@pytest.mark.slow
def test_hourglass_stacks_train_pipelined():
    """The real workload: 4 HourglassStack stages (BN running stats as
    device-local pipeline state) on a pipe=4 mesh — intermediate-
    supervision MSE loss falls under plain SGD, stats update."""
    from deep_vision_tpu.models.hourglass import HourglassStack

    mesh = make_mesh({PIPE_AXIS: 4}, devices=jax.devices()[:4])
    module = HourglassStack(num_heatmap=3, filters=8, order=2)
    x = jax.random.normal(jax.random.PRNGKey(4), (4, 16, 16, 8))
    target = jax.random.uniform(jax.random.PRNGKey(5), (4, 16, 16, 3))

    inits = [module.init({"params": k}, x[:1], train=False)
             for k in jax.random.split(jax.random.PRNGKey(6), 4)]
    params = stack_stages([v["params"] for v in inits])
    stats = stack_stages([v["batch_stats"] for v in inits])

    def stage_fn(p, c, s):
        (c2, heat), upd = module.apply(
            {"params": p, "batch_stats": s}, c, train=True,
            mutable=["batch_stats"])
        return c2, heat, upd["batch_stats"]

    @jax.jit
    def step(params, stats):
        def loss_fn(p):
            outs, new_stats = pipeline_apply(
                stage_fn, p, x, mesh=mesh, num_microbatches=2,
                stage_state=stats)
            # intermediate supervision: every stack vs the same target
            return jnp.mean((outs - target[None]) ** 2), new_stats

        (loss, new_stats), g = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        params = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, params, g)
        return params, new_stats, loss

    losses = []
    for _ in range(4):
        params, stats, loss = step(params, stats)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses
    # running stats moved off their init (mean 0 / var 1)
    means = np.asarray(jax.device_get(
        jax.tree_util.tree_leaves(stats)[0]), np.float64)
    assert np.abs(means).max() > 0
