"""Offline batch-inference tier contract (CPU, tier-1 fast): the job
store checkpoints progress at shard granularity and replays JSONL
ledgers (torn tails skipped) so a restarted server resumes mid-job with
zero duplicated and zero lost results; the trough-filling scheduler is
a strict priority band below every interactive tenant (starvation-free
both ways); shed shards retry whole — all-or-nothing results keep
replay exactly-once; the results endpoint streams the completed prefix
as chunked ndjson over both HTTP front-ends; and the autoscaler's
batchy-SLO engines scale on rolling compute occupancy, not queue
pressure.

Uses LeNet at random init for the real-engine paths (batch correctness
is about scheduling and durability, not learned weights) and stub
engines for the pure state-machine tests.  Runs with the lock-order
sanitizer enabled (conftest fixture keyed on the ``batch`` marker).
"""

import json
import queue
import threading
import time
import types
import urllib.error
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

from deep_vision_tpu.serve.admission import Shed
from deep_vision_tpu.serve.batch_sched import BatchScheduler
from deep_vision_tpu.serve.engine import BatchingEngine
from deep_vision_tpu.serve.jobs import JobStore
from deep_vision_tpu.serve.registry import ModelRegistry

pytestmark = pytest.mark.batch


@pytest.fixture(scope="module")
def lenet_serving(tmp_path_factory):
    reg = ModelRegistry()
    sm = reg.load_checkpoint(
        "lenet5", str(tmp_path_factory.mktemp("lenet_workdir")))
    return reg, sm


def _manifest(n, shape=(32, 32, 1)):
    return [{"pixels":
             np.random.RandomState(i).randn(*shape).tolist()}
            for i in range(n)]


def _wait(pred, what, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = pred()
        if out:
            return out
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


# -- job store: shard accounting + exactly-once guard ----------------------


def test_jobstore_shard_accounting_memory_only():
    store = JobStore()  # no root: same API, no durability
    view = store.submit("m", "classify", [{"x": i} for i in range(10)],
                        shard_size=4)
    jid = view["job_id"]
    assert view["state"] == "pending" and view["n_shards"] == 3
    job, idx = store.next_shard()
    assert job.job_id == jid and idx == 0
    assert job.shard_range(0) == (0, 4)
    assert job.shard_range(2) == (8, 10)  # ragged tail shard
    assert store.record_shard(jid, 0, [{"y": i} for i in range(4)], 4)
    # the exactly-once guard: a double-record is refused, not merged
    assert not store.record_shard(jid, 0, [{"y": 0}] * 4, 4)
    assert store.status(jid)["images_done"] == 4
    assert store.next_shard()[1] == 1  # lowest missing shard
    # results stream only the CONTIGUOUS completed prefix: with shard 2
    # done but shard 1 missing, only shard 0 is visible
    assert store.record_shard(jid, 2, [{"y": 8}, {"y": 9}], 2)
    assert [i for i, _ in store.results_items(jid)] == [0, 1, 2, 3]
    assert store.record_shard(jid, 1, [{"y": i} for i in range(4, 8)], 4)
    st = store.status(jid)
    assert st["state"] == "done" and st["images_done"] == 10
    items = list(store.results_items(jid))
    assert [i for i, _ in items] == list(range(10))
    assert store.next_shard() is None
    assert store.stats()["states"]["done"] == 1
    assert not store.stats()["durable"]
    with pytest.raises(ValueError):
        store.submit("m", "classify", [])


def test_jobstore_restart_replay_and_torn_tail(tmp_path):
    root = str(tmp_path / "jobs")
    store = JobStore(root, shard_size=2)
    jid = store.submit("m", "classify",
                       [{"x": i} for i in range(6)])["job_id"]
    store.record_shard(jid, 0, [{"y": 0}, {"y": 1}], 2)
    store.record_shard(jid, 1, [{"y": 2}, {"y": 3}], 2)

    # restart #1: both durable shards replay, job resumes at shard 2
    s2 = JobStore(root)
    assert s2.resumed == 1 and s2.replayed_shards == 2
    assert s2.status(jid)["images_done"] == 4
    assert s2.next_shard()[1] == 2

    # a crash mid-append leaves a torn tail: the half-written shard is
    # dropped (it re-runs), every complete line before it survives
    path = [p for p in (tmp_path / "jobs").iterdir()
            if p.suffix == ".jsonl"][0]
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"kind": "shard", "job": "%s", "index": 2, "res' % jid)
    s3 = JobStore(root)
    assert s3.torn_lines == 1
    assert s3.replayed_shards == 2  # the torn shard did NOT apply
    assert s3.next_shard()[1] == 2
    s3.record_shard(jid, 2, [{"y": 4}, {"y": 5}], 2)
    assert s3.status(jid)["state"] == "done"

    # restart #2: terminal state replays; nothing resumes, nothing
    # re-emits — indices come back exactly once, in manifest order.
    # Replay leaves the payload cache COLD (the rows are already in the
    # ledger), so this also exercises the disk-streaming read path
    s4 = JobStore(root)
    assert s4.resumed == 0 and s4.next_shard() is None
    assert s4.stats()["cached_shards"] == 0
    assert [i for i, _ in s4.results_items(jid)] == list(range(6))


def test_jobstore_results_spill_to_ledger(tmp_path):
    """Bounded payload cache: completed shards past ``max_cached_shards``
    evict (LRU) and the results endpoint streams them back from the
    JSONL ledger — rows identical and in order, memory O(cap)."""
    store = JobStore(str(tmp_path / "jobs"), shard_size=2,
                     max_cached_shards=1)
    jid = store.submit("m", "classify",
                       [{"x": i} for i in range(8)])["job_id"]
    for s in range(4):
        store.record_shard(jid, s, [{"y": 2 * s}, {"y": 2 * s + 1}], 2)
    st = store.stats()
    assert st["spilled_shards"] == 3 and st["cached_shards"] == 1
    assert store.status(jid)["state"] == "done"  # spilling ≠ progress loss
    rows = list(store.results_items(jid))
    assert [i for i, _ in rows] == list(range(8))
    assert [r["y"] for _, r in rows] == list(range(8))
    # second read: identical (the ledger is the authority, the cache is
    # only an optimization)
    assert list(store.results_items(jid)) == rows

    # memory-only stores never evict — memory is the only copy
    mem = JobStore(shard_size=2, max_cached_shards=1)
    jid2 = mem.submit("m", "classify",
                      [{"x": i} for i in range(8)])["job_id"]
    for s in range(4):
        mem.record_shard(jid2, s, [{"y": 2 * s}, {"y": 2 * s + 1}], 2)
    assert mem.stats()["spilled_shards"] == 0
    assert mem.stats()["cached_shards"] == 4
    assert [r["y"] for _, r in mem.results_items(jid2)] == list(range(8))


# -- scheduler: priority band, retries, terminal failures ------------------


class _StubWorkload:
    verb = "classify"

    def decode_manifest_item(self, item, model):
        if "x" not in item:
            raise ValueError("manifest entry needs 'x'")
        return item["x"]

    def respond(self, model, item, row):
        return {"y": row}


class _StubEngine:
    """Just the scheduler's surface: a queue-depth signal, an EWMA, and
    an instantly-resolving submit."""

    def __init__(self):
        self.queue_depth = 0
        self.admission = types.SimpleNamespace(
            bucket_ewma_s=lambda bucket=None: 0.005)
        self.served = 0
        self.shed_next = 0  # shed this many submits (shard retry test)

    def submit(self, x):
        fut: Future = Future()
        if self.shed_next > 0:
            self.shed_next -= 1
            fut.set_result(Shed("queue_full"))
        else:
            self.served += 1
            fut.set_result(x * 2)
        return fut


def _stub_rig(store=None):
    store = store or JobStore()
    eng = _StubEngine()
    model = types.SimpleNamespace(name="stub", workload=_StubWorkload())

    def resolve(name):
        if name != "stub":
            raise KeyError(f"unknown model '{name}'")
        return model, eng

    sched = BatchScheduler(store, resolve, interval_s=0.002)
    return store, eng, sched


def test_scheduler_priority_band_defers_then_drains():
    """The band in action: any waiting interactive request parks the
    batch tier outright; the moment the queue drains, shards flow —
    starvation-freedom in both directions."""
    store, eng, sched = _stub_rig()
    jid = store.submit("stub", "classify",
                       [{"x": i} for i in range(8)],
                       shard_size=4)["job_id"]
    eng.queue_depth = 3  # interactive backlog: trough check must fail
    sched.start()
    try:
        _wait(lambda: sched.stats()["deferred"] >= 3, "deferrals")
        assert sched.stats()["shards_done"] == 0
        assert store.status(jid)["state"] == "pending"
        assert eng.served == 0  # parked, not trickling
        eng.queue_depth = 0  # trough opens
        sched.kick()
        _wait(lambda: store.status(jid)["state"] == "done", "job drain")
    finally:
        sched.stop()
    items = list(store.results_items(jid))
    assert [i for i, _ in items] == list(range(8))
    assert [r["y"] for _, r in items] == [2 * i for i in range(8)]
    st = sched.stats()
    assert st["shards_done"] == 2 and st["images_total"] == 8


def test_scheduler_shed_retries_whole_shard_exactly_once():
    """A shed anywhere in a shard voids the WHOLE attempt: nothing is
    recorded, the shard re-runs, and the final results hold each index
    exactly once — the all-or-nothing rule the JSONL replay leans on."""
    store, eng, sched = _stub_rig()
    jid = store.submit("stub", "classify",
                       [{"x": i} for i in range(4)],
                       shard_size=4)["job_id"]
    eng.shed_next = 2  # first attempt: 2 of 4 rows shed
    sched.start()
    try:
        _wait(lambda: store.status(jid)["state"] == "done", "retry drain")
    finally:
        sched.stop()
    assert sched.stats()["shards_shed"] >= 1
    items = list(store.results_items(jid))
    assert [i for i, _ in items] == list(range(4))
    assert store.status(jid)["images_done"] == 4


def test_scheduler_per_item_error_never_wedges_job():
    """A malformed manifest entry records as that ITEM's error result;
    the rest of the shard serves — one poison entry can't wedge a job
    into eternal retry."""
    store, eng, sched = _stub_rig()
    manifest = [{"x": 0}, {"bad": 1}, {"x": 2}]
    jid = store.submit("stub", "classify", manifest,
                       shard_size=3)["job_id"]
    sched.start()
    try:
        _wait(lambda: store.status(jid)["state"] == "done", "drain")
    finally:
        sched.stop()
    rows = [r for _, r in store.results_items(jid)]
    assert rows[0] == {"y": 0} and rows[2] == {"y": 4}
    assert "bad manifest entry" in rows[1]["error"]
    assert store.status(jid)["images_done"] == 2  # goodput, not rows
    assert sched.stats()["decode_errors"] == 1


def test_scheduler_unknown_model_fails_job_terminally():
    store, eng, sched = _stub_rig()
    jid = store.submit("ghost", "classify", [{"x": 1}])["job_id"]
    sched.start()
    try:
        _wait(lambda: store.status(jid)["state"] == "failed",
              "terminal failure")
    finally:
        sched.stop()
    assert "not servable" in store.status(jid)["error"]
    assert sched.stats()["jobs_failed"] == 1
    assert store.next_shard() is None  # never rescheduled


# -- restart resume on a real engine: exactly-once end to end --------------


class _StopAfterStore(JobStore):
    """Durable store that halts its scheduler after N recorded shards —
    the deterministic 'kill -9 mid-job' stand-in (the scheduler's loop
    checks its stop flag between shards, so at most the in-flight shard
    also lands)."""

    def __init__(self, root, *, stop_after, **kw):
        super().__init__(root, **kw)
        self.sched: BatchScheduler | None = None
        self._stop_after = stop_after
        self._recorded = 0

    def record_shard(self, *a, **kw):
        ok = super().record_shard(*a, **kw)
        if ok:
            self._recorded += 1
            if self._recorded >= self._stop_after \
                    and self.sched is not None:
                self.sched._stop.set()
        return ok


def test_restart_resumes_from_checkpoint_exactly_once(tmp_path,
                                                      lenet_serving):
    """Kill mid-job, restart, drain: every manifest index appears in
    the durable results exactly once, and the engine executed each
    image exactly once — durable shards are never re-run."""
    reg, sm = lenet_serving
    root = str(tmp_path / "jobs")
    manifest = _manifest(12)

    def resolve(name):
        return reg.get(name), eng

    with BatchingEngine(sm, buckets=[4], max_wait_ms=2) as eng:
        store1 = _StopAfterStore(root, stop_after=1, shard_size=4)
        jid = store1.submit(sm.name, "classify", manifest)["job_id"]
        sched1 = BatchScheduler(store1, resolve, interval_s=0.002)
        store1.sched = sched1
        sched1.start()
        _wait(lambda: not sched1._thread.is_alive(), "mid-job halt")
        sched1.stop()
        done1 = store1.status(jid)["shards_done"]
        served1 = eng.served
        assert 1 <= done1 < 3  # genuinely mid-job

        # "restart": a fresh store replays the JSONL ledger
        store2 = JobStore(root)
        assert store2.resumed == 1
        assert store2.replayed_shards == done1
        assert store2.next_shard()[1] == done1  # first missing shard
        sched2 = BatchScheduler(store2, resolve, interval_s=0.002)
        sched2.start()
        try:
            _wait(lambda: store2.status(jid)["state"] == "done",
                  "post-restart drain")
        finally:
            sched2.stop()
        # zero duplicates: the engine never re-executed a durable shard
        assert served1 + (eng.served - served1) == eng.served == 12
        items = list(store2.results_items(jid))
        assert [i for i, _ in items] == list(range(12))
        assert all("top" in r for _, r in items)
        assert store2.status(jid)["images_done"] == 12


# -- interference: interactive p99 unharmed by a draining bulk job ---------


def _p99(lat):
    return sorted(lat)[max(0, int(len(lat) * 0.99) - 1)]


def test_interactive_p99_unharmed_while_bulk_job_drains(lenet_serving):
    """The acceptance gate: a bulk job drains to completion while a
    foreground client's p99 stays in its no-batch envelope — the
    priority band admits shards only into troughs, so the worst case
    an interactive request sees is one batch-sized cohort."""
    reg, sm = lenet_serving
    img = np.random.RandomState(0).randn(32, 32, 1).astype(np.float32)

    def resolve(name):
        return reg.get(name), eng

    with BatchingEngine(sm, buckets=[4], max_wait_ms=2) as eng:
        # baseline: interactive latencies with no batch tier at all
        base_lat = []
        for _ in range(30):
            t0 = time.monotonic()
            assert eng.infer(img) is not None
            base_lat.append(time.monotonic() - t0)

        store = JobStore(shard_size=4)
        jid = store.submit(sm.name, "classify",
                           _manifest(32))["job_id"]
        sched = BatchScheduler(store, resolve, interval_s=0.002)
        sched.start()
        try:
            during_lat = []
            for _ in range(30):
                t0 = time.monotonic()
                assert eng.infer(img) is not None
                during_lat.append(time.monotonic() - t0)
            # starvation-freedom under interleaved interactive load:
            # the job still finishes
            _wait(lambda: store.status(jid)["state"] == "done",
                  "bulk drain under interactive load")
        finally:
            sched.stop()
        assert store.status(jid)["images_done"] == 32
        assert _p99(during_lat) <= _p99(base_lat) * 5 + 0.25, (
            f"interactive p99 regressed under batch drain: "
            f"{_p99(base_lat):.4f}s -> {_p99(during_lat):.4f}s")


# -- engine + scheduler occupancy signals ----------------------------------


def test_engine_occupancy_rolling_signal(lenet_serving):
    reg, sm = lenet_serving
    with BatchingEngine(sm, buckets=[4], max_wait_ms=2) as eng:
        assert eng.occupancy() == 0.0  # no compute yet
        img = np.random.RandomState(0).randn(32, 32, 1)
        for _ in range(8):
            assert eng.infer(img.astype(np.float32)) is not None
        occ = eng.occupancy()
        assert 0.0 < occ <= 1.0
        pipe = eng.stats()["pipeline"]
        assert 0.0 < pipe["occupancy"] <= 1.0


def test_scheduler_occupancy_after_drain():
    store, eng, sched = _stub_rig()
    assert sched.occupancy() == 0.0
    store.submit("stub", "classify", [{"x": i} for i in range(4)])
    sched.start()
    try:
        _wait(lambda: sched.stats()["shards_done"] >= 1, "drain")
    finally:
        sched.stop()
    assert 0.0 <= sched.stats()["occupancy"] <= 1.0


# -- occupancy-based autoscaling for the batchy SLO class ------------------


class _OccEngine:
    """The scaler's engine surface plus the occupancy signal and a
    workload SLO class name."""

    def __init__(self, occ=0.0, slo="batchy", live=1):
        self._queue: queue.Queue = queue.Queue()
        self.admission = types.SimpleNamespace(
            bucket_ewma_s=lambda: 0.01)
        self.model = types.SimpleNamespace(
            name="fake",
            workload=types.SimpleNamespace(
                slo=types.SimpleNamespace(name=slo)))
        self.occ = occ
        self.live = live

    def occupancy(self):
        return self.occ

    def total_inflight(self):
        return 0

    def live_replicas(self):
        return self.live

    def add_replica(self):
        self.live += 1
        return self.live - 1

    def remove_replica(self, drain_deadline=5.0):
        self.live -= 1
        return self.live


def test_autoscaler_batchy_scales_up_on_occupancy_not_queue():
    """The signal switch: a saturated batchy engine runs flat out with
    an EMPTY queue (whole cohorts go straight in-flight), so queue
    pressure reads 0 — occupancy is what must drive the scale-up."""
    from deep_vision_tpu.deploy import ReplicaAutoscaler

    eng = _OccEngine(occ=0.9)
    s = ReplicaAutoscaler(eng, min_replicas=1, max_replicas=3,
                          up_window=3, down_window=3, cooldown_s=0.0,
                          occupancy_high=0.75, occupancy_low=0.2)
    sig = s.signals()
    assert sig["batchy"] and sig["occupancy"] == 0.9
    assert sig["pressure_ms"] == 0.0  # the signal queue pressure misses
    assert s.tick() is None and s.tick() is None  # hysteresis holds
    act = s.tick()
    assert act["action"] == "scale_up" and eng.live == 2
    # an interactive engine with the same occupancy does NOT scale:
    # the switch is keyed on the SLO class, not on the signal existing
    inter = _OccEngine(occ=0.9, slo="interactive")
    s2 = ReplicaAutoscaler(inter, min_replicas=1, max_replicas=3,
                           up_window=1, cooldown_s=0.0)
    assert not s2.signals()["batchy"]
    for _ in range(5):
        assert s2.tick() is None
    assert inter.live == 1


def test_autoscaler_batchy_scale_down_needs_low_occupancy():
    """The inter-shard gap samples as queue 0 / inflight 0; the rolling
    occupancy window is what keeps that from reading as idle."""
    from deep_vision_tpu.deploy import ReplicaAutoscaler

    eng = _OccEngine(occ=0.5, live=3)  # between the two thresholds
    s = ReplicaAutoscaler(eng, min_replicas=1, max_replicas=3,
                          up_window=3, down_window=2, cooldown_s=0.0,
                          occupancy_high=0.75, occupancy_low=0.2)
    for _ in range(6):
        assert s.tick() is None  # neither hot nor idle: holds steady
    assert eng.live == 3
    eng.occ = 0.05  # genuinely drained
    assert s.tick() is None
    act = s.tick()
    assert act["action"] == "scale_down" and eng.live == 2


# -- HTTP: job API, chunked results stream, metrics ------------------------


def _get_json(url, timeout=60):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _post_json(url, payload, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_jobs_http_end_to_end_with_metrics(lenet_serving):
    """POST a manifest, poll the handle, stream the chunked ndjson
    results, and find the batch tier's goodput series in /metrics —
    the full wire contract of docs/BATCH.md."""
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    eng = BatchingEngine(sm, buckets=[4], max_wait_ms=2).start()

    def resolve(name):
        return reg.get(name), eng

    store = JobStore(shard_size=4)
    sched = BatchScheduler(store, resolve, interval_s=0.002).start()
    srv = ServeServer(reg, {sm.name: eng}, port=0, jobs=store,
                      batch_sched=sched).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        status, view = _post_json(base + "/v1/jobs",
                                  {"model": "lenet5",
                                   "items": _manifest(6),
                                   "shard_size": 2})
        assert status == 202 and view["n_shards"] == 3
        jid = view["job_id"]
        _wait(lambda: _get_json(base + f"/v1/jobs/{jid}")[1]["state"]
              == "done", "job drain over HTTP")
        _, listing = _get_json(base + "/v1/jobs")
        assert [j["job_id"] for j in listing["jobs"]] == [jid]

        # the results stream: chunked ndjson, one line per item in
        # manifest order, then the terminal status line
        req = urllib.request.urlopen(base + f"/v1/jobs/{jid}/results",
                                     timeout=60)
        assert req.headers.get("Transfer-Encoding") == "chunked"
        lines = [json.loads(ln) for ln in req.read().splitlines()]
        assert [ln["index"] for ln in lines[:-1]] == list(range(6))
        assert all("top" in ln for ln in lines[:-1])
        assert lines[-1]["status"]["state"] == "done"

        _, stats = _get_json(base + "/v1/stats")
        batch = stats["batch"]
        assert batch["jobs"]["images_done"] == 6
        assert batch["scheduler"]["shards_done"] == 3
        assert "mfu_occupancy_weighted" in batch
        with urllib.request.urlopen(base + "/metrics", timeout=60) as r:
            text = r.read().decode()
        assert "dvt_batch_images_total 6" in text
        assert "dvt_batch_occupancy" in text
        assert "dvt_serve_occupancy" in text

        with pytest.raises(urllib.error.HTTPError) as exc:
            _get_json(base + "/v1/jobs/nope")
        assert exc.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post_json(base + "/v1/jobs",
                       {"model": "lenet5", "items": []})
        assert exc.value.code == 400
    finally:
        srv.shutdown()
        sched.stop()
        eng.stop()


def test_jobs_http_503_when_tier_not_enabled(lenet_serving):
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    eng = BatchingEngine(sm, buckets=[4], max_wait_ms=2).start()
    srv = ServeServer(reg, {sm.name: eng}, port=0).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        for do in (lambda: _get_json(base + "/v1/jobs"),
                   lambda: _post_json(base + "/v1/jobs",
                                      {"items": [{}]})):
            with pytest.raises(urllib.error.HTTPError) as exc:
                do()
            assert exc.value.code == 503
            assert "--jobs-dir" in json.loads(exc.value.read())["error"]
    finally:
        srv.shutdown()
        eng.stop()


@pytest.mark.parametrize("edge", [True, False],
                         ids=["edge-loop", "thread-server"])
def test_results_stream_partial_prefix_both_frontends(lenet_serving,
                                                      edge):
    """Both HTTP front-ends speak the same chunked stream: a partially
    drained job streams its contiguous completed prefix plus a
    ``running`` status line — a stable, never-repeated view a client
    can poll."""
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    store = JobStore(shard_size=2)
    jid = store.submit(sm.name, "classify",
                       [{"k": i} for i in range(6)])["job_id"]
    store.record_shard(jid, 0, [{"y": 0}, {"y": 1}], 2)
    store.record_shard(jid, 2, [{"y": 4}, {"y": 5}], 2)  # gap at 1
    srv = ServeServer(reg, {}, port=0, jobs=store,
                      edge=edge).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with urllib.request.urlopen(base + f"/v1/jobs/{jid}/results",
                                    timeout=60) as r:
            assert r.headers.get("Transfer-Encoding") == "chunked"
            lines = [json.loads(ln) for ln in r.read().splitlines()]
        # shard 2 is done but NOT streamed: the prefix stops at the gap
        assert [ln["index"] for ln in lines[:-1]] == [0, 1]
        assert lines[-1]["status"]["state"] == "running"
    finally:
        srv.shutdown()


# -- CycleGAN 256² image-in serving on real restored weights ---------------


@pytest.mark.slow
def test_cyclegan_256_image_in_serving_real_weights(tmp_path):
    """End-to-end generative image translation at full 256² resolution
    on a real restored checkpoint (not the random-init fallback):
    uint8 pixels in over the wire, fused uint8 epilogue out, and the
    same manifest entry drains through the batch job path."""
    import os

    from deep_vision_tpu.core.checkpoint import Checkpointer
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.restore import load_state

    cfg = get_config("cyclegan")
    seed_dir = str(tmp_path / "seed")
    _, state = load_state(cfg, seed_dir, log=lambda *a, **k: None)
    workdir = str(tmp_path / "cyclegan")
    ckpt = Checkpointer(os.path.join(workdir, "checkpoints"))
    ckpt.save(1, state)
    ckpt.wait_until_finished()

    reg = ModelRegistry()
    sm = reg.load_checkpoint("cyclegan", workdir, wire_dtype="uint8")
    assert sm.restored_step == 1  # real weights, not the fallback init
    assert sm.workload.verb == "generate"
    assert sm.input_shape == (256, 256, 3)
    assert str(sm.wire_dtype) == "uint8"  # image-in wire is honored
    assert sm.output_wire == "uint8"

    img = np.random.RandomState(0).randint(
        0, 256, size=(256, 256, 3), dtype=np.uint8)
    with BatchingEngine(sm, buckets=[1], max_wait_ms=2) as eng:
        out = np.asarray(eng.submit(img).result(600))
        assert out.dtype == np.uint8 and out.shape == (256, 256, 3)
        resp = sm.workload.respond(sm, {}, out)
        assert resp["image"]["shape"] == [256, 256, 3]
        assert resp["image"]["dtype"] == "uint8"

        # the same image as a batch manifest entry: decode → engine →
        # respond, through the real scheduler
        store = JobStore()
        jid = store.submit("cyclegan", "generate",
                           [{"pixels": img.tolist()}])["job_id"]
        sched = BatchScheduler(store, lambda n: (sm, eng),
                               interval_s=0.002).start()
        try:
            _wait(lambda: store.status(jid)["state"] == "done",
                  "cyclegan job drain", timeout=600)
        finally:
            sched.stop()
        rows = [r for _, r in store.results_items(jid)]
        assert rows[0]["image"]["shape"] == [256, 256, 3]
