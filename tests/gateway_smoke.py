"""`make gateway-smoke`: the cross-host failover contract, end to end
with REAL process boundaries.  Spawns two `python -m
deep_vision_tpu.cli.serve` backend subprocesses (LeNet workdir fixture,
fault injection active on backend 0 so the smoke also crosses the
bisect-retry path), boots the gateway in-process on a random port via
the production wiring (cli.gateway.build_gateway), then:

  1. runs a closed-loop client burst through the gateway — all 200s;
  2. SIGKILLs backend 1 (a real `kill -9`: sockets die mid-flight) while
     the client loop keeps running — still all 200s, zero lost
     requests, and the gateway's breaker must stop routing to the
     corpse within a few probe intervals;
  3. POSTs /v1/drain to the surviving backend and asserts its healthz
     flips to 503 draining and the gateway's healthz goes 503 (no
     routable backend) — the zero-downtime-restart signal chain.

Run directly, not under pytest (subprocesses + real signals)."""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np

# plain script (not pytest): make the repo root importable when invoked
# as `python tests/gateway_smoke.py` from the checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _wait_healthy(port: int, proc, timeout_s: float = 120.0) -> None:
    deadline = time.monotonic() + timeout_s
    url = f"http://127.0.0.1:{port}/v1/healthz"
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"backend on port {port} exited rc={proc.returncode} "
                f"before becoming healthy")
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                if r.status == 200:
                    return
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(0.1)
    raise AssertionError(f"backend on port {port} never became healthy")


def main():
    argparse.ArgumentParser().parse_args()  # no options; --help works
    from deep_vision_tpu.cli.gateway import build_gateway

    pixels = np.random.default_rng(0).integers(
        0, 256, (32, 32, 1)).tolist()
    body = json.dumps({"pixels": pixels}).encode()
    procs = []
    with tempfile.TemporaryDirectory() as workdir:
        # two real backend PROCESSES on OS-assigned-free ports: ports are
        # picked by binding port 0 briefly — a race is theoretically
        # possible but these are loopback smoke runs
        import socket

        ports = []
        holds = []
        for _ in range(2):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            holds.append(s)
        for s in holds:
            s.close()
        for i, port in enumerate(ports):
            cmd = [sys.executable, "-m", "deep_vision_tpu.cli.serve",
                   "-m", "lenet5", "--workdir", workdir,
                   "--port", str(port), "--max-batch", "4",
                   "--max-wait-ms", "2"]
            if i == 0:
                # transient compute fault on the survivor: the smoke
                # crosses gateway failover AND bisect-retry recovery
                cmd += ["--faults", "compute:exception:times=1"]
            procs.append(subprocess.Popen(
                cmd, env=dict(os.environ, JAX_PLATFORMS="cpu"),
                stdout=subprocess.DEVNULL))
        try:
            for port, proc in zip(ports, procs):
                _wait_healthy(port, proc)
            gw, server = build_gateway(argparse.Namespace(
                backend=[f"127.0.0.1:{p}" for p in ports],
                host="127.0.0.1", port=0, probe_interval_ms=50.0,
                retry_budget=3, breaker_threshold=2,
                breaker_cooldown_s=30.0))
            base = f"http://127.0.0.1:{server.port}"
            server.start_background()
            try:
                ok = [0]
                errors = []
                lock = threading.Lock()
                stop = threading.Event()

                def client():
                    while not stop.is_set():
                        req = urllib.request.Request(
                            base + "/v1/classify", data=body,
                            headers={"Content-Type": "application/json"})
                        try:
                            with urllib.request.urlopen(
                                    req, timeout=60) as r:
                                assert r.status == 200
                                assert len(json.loads(
                                    r.read())["top"]) == 5
                            with lock:
                                ok[0] += 1
                        except Exception as e:  # noqa: BLE001
                            with lock:
                                errors.append(repr(e))

                threads = [threading.Thread(target=client)
                           for _ in range(3)]
                for t in threads:
                    t.start()
                time.sleep(1.0)        # warm load over both backends
                procs[1].send_signal(signal.SIGKILL)  # the chaos moment
                procs[1].wait(30)
                time.sleep(2.0)        # load keeps running over the kill
                stop.set()
                for t in threads:
                    t.join(60)
                assert errors == [], \
                    f"client-visible errors after SIGKILL: {errors[:5]}"
                assert ok[0] > 20, f"only {ok[0]} requests completed"
                deadline = time.monotonic() + 5
                while gw.backends[1].routable() \
                        and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert not gw.backends[1].routable(), \
                    "gateway still routing to the SIGKILL'd backend"
                dead = gw.backends[1].report()
                assert dead["breaker"] == "open", dead
                c = gw.counters()
                assert c["breaker_opens"] >= 1, c
                print(f"gateway-smoke PASS (kill): {ok[0]} requests, 0 "
                      f"errors across SIGKILL of backend :{ports[1]}; "
                      f"gateway retries={c['retries']} "
                      f"failovers={c['failovers']} "
                      f"breaker_opens={c['breaker_opens']}")

                # zero-downtime drain on the survivor: healthz flips to
                # 503 draining, and with no routable backend left the
                # GATEWAY healthz goes 503 too
                req = urllib.request.Request(
                    f"http://127.0.0.1:{ports[0]}/v1/drain", data=b"")
                with urllib.request.urlopen(req, timeout=60) as r:
                    assert json.loads(r.read())["status"] == "draining"
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{ports[0]}/v1/healthz",
                        timeout=5)
                    raise AssertionError("drained backend healthz != 503")
                except urllib.error.HTTPError as e:
                    assert e.code == 503, e.code
                    assert json.loads(e.read())["status"] == "draining"
                deadline = time.monotonic() + 5
                while gw.backends[0].routable() \
                        and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert gw.backends[0].report()["unavailable"] \
                    == "draining"
                try:
                    urllib.request.urlopen(base + "/v1/healthz",
                                           timeout=5)
                    raise AssertionError("gateway healthz != 503 with "
                                         "no routable backend")
                except urllib.error.HTTPError as e:
                    assert e.code == 503, e.code
                print(f"gateway-smoke PASS (drain): backend :{ports[0]} "
                      f"draining -> gateway healthz 503, breaker still "
                      f"closed (drain is not failure)")
            finally:
                server.shutdown()
                gw.stop()
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.terminate()
            for proc in procs:
                try:
                    proc.wait(30)
                except subprocess.TimeoutExpired:
                    proc.kill()
    return 0


if __name__ == "__main__":
    sys.exit(main())
