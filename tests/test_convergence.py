"""Tiny-overfit convergence tests — each task stack must provably LEARN,
not just produce finite losses (SURVEY §4 implication (e)).

Each test trains a scaled-down model on a handful of synthetic scenes and
asserts an outcome a silently-broken loss/codec wiring would fail:
- YOLO: loss falls ≥5× AND train-set mAP ≥0.8 through the wired
  decode→NMS→VOC-AP evaluator (the eval the reference lists as "WIP").
- CenterNet: decode recovers the planted objects (mAP ≥0.8) — the stack
  the reference left unfinished (ObjectsAsPoints/tensorflow/train.py:35).
- Hourglass: predicted heatmap argmax hits planted keypoints (PCK ≥0.85).
- DCGAN: 50-step adversarial loss trajectories stay in sane ranges.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.core.config import TrainConfig, get_config
from deep_vision_tpu.core.optim import OptimizerConfig
from deep_vision_tpu.core.trainer import Trainer

# convergence = real multi-epoch CPU training; excluded from the default
# `make test` lane (VERDICT r2 weak #4) — run via `make test-all`
pytestmark = pytest.mark.slow


@pytest.mark.parametrize("augment", [False, True],
                         ids=["no-aug", "augmented"])
def test_yolo_overfit_reaches_map(tmp_path, mesh1, augment):
    """Overfit a toy YOLO; with ``augment=True`` the bbox-preserving
    crop/flip pipeline (data/detection.py) is trained THROUGH, not just
    unit-tested (VERDICT r2 weak #6) — broken box remapping would sink
    train-set mAP."""
    from deep_vision_tpu.data.detection import (
        DetectionLoader,
        synthetic_detection_dataset,
    )
    from deep_vision_tpu.tasks.detection import YoloTask

    cfg = get_config("yolov3_toy")
    cfg.total_epochs = 150
    cfg.checkpoint_every_epochs = 1000
    samples = synthetic_detection_dataset(8, 64, 3, seed=3)
    train = DetectionLoader(samples, 8, 3, 64, train=True, augment=augment,
                            seed=0)
    val = DetectionLoader(samples, 8, 3, 64, train=False)
    task = YoloTask(3)
    trainer = Trainer(cfg, cfg.model(), task, mesh=mesh1,
                      workdir=str(tmp_path))
    state = trainer.init_state(next(iter(train)))
    m0 = trainer.evaluate(state, val)
    state = trainer.fit(train, None, state=state)
    m1 = trainer.evaluate(state, val)
    assert m1["loss"] * 5 < m0["loss"], (m0, m1)   # loss falls ≥5×
    # augmentation jitters every epoch's crops, so the un-augmented eval
    # bar is slightly lower there; both prove box codec + loss learn
    assert m1["mAP"] >= (0.7 if augment else 0.8), m1
    # COCO-standard average: high-IoU slices demand tight box regression,
    # so the bar sits below mAP@0.5 but far above a broken codec's ~0
    # (measured: 0.24 augmented — every epoch's crops jitter the boxes —
    # 0.5+ un-augmented)
    assert m1["mAP50_95"] >= (0.2 if augment else 0.35), m1


def test_centernet_overfit_recovers_planted_objects(tmp_path, mesh1):
    from deep_vision_tpu.data.detection import (
        CenterNetLoader,
        synthetic_detection_dataset,
    )
    from deep_vision_tpu.models.centernet import CenterNet
    from deep_vision_tpu.tasks.centernet import CenterNetTask

    cfg = TrainConfig(
        name="centernet_toy",
        model=lambda: CenterNet(num_classes=3, num_stack=1, order=3,
                                filters=(32, 32, 48, 64),
                                dtype=jnp.float32),
        task="centernet", batch_size=8, total_epochs=150,
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
        image_size=64, num_classes=3, half_precision=False,
        checkpoint_every_epochs=1000)
    samples = synthetic_detection_dataset(8, 64, 3, seed=4)
    train = CenterNetLoader(samples, 8, 3, 64, train=True, augment=False,
                            seed=0)
    val = CenterNetLoader(samples, 8, 3, 64, train=False)
    task = CenterNetTask(3)
    trainer = Trainer(cfg, cfg.model(), task, mesh=mesh1,
                      workdir=str(tmp_path))
    state = trainer.init_state(next(iter(train)))
    state = trainer.fit(train, None, state=state)
    m = trainer.evaluate(state, val)
    assert m["mAP"] >= 0.8, m
    # CenterNet decodes boxes at output-grid quantization (G=16 on 64px
    # images), so the highest IoU slices saturate lower than YOLO's
    assert m["mAP50_95"] >= 0.25, m


def test_hourglass_overfit_localizes_keypoints(tmp_path, mesh1):
    from deep_vision_tpu.data.pose import PoseLoader, synthetic_pose_dataset
    from deep_vision_tpu.models.hourglass import StackedHourglass
    from deep_vision_tpu.tasks.pose import PoseTask

    K = 4
    cfg = TrainConfig(
        name="hg_toy",
        model=lambda: StackedHourglass(num_stack=1, num_heatmap=K,
                                       filters=32, dtype=jnp.float32),
        task="pose", batch_size=8, total_epochs=120,
        optimizer=OptimizerConfig(name="adam", learning_rate=2e-3),
        image_size=64, num_classes=K, half_precision=False,
        checkpoint_every_epochs=1000)
    samples = synthetic_pose_dataset(8, 64, K, seed=5)
    train = PoseLoader(samples, 8, 64, 16, K, train=True, seed=0)
    val = PoseLoader(samples, 8, 64, 16, K, train=False)
    trainer = Trainer(cfg, cfg.model(), PoseTask(), mesh=mesh1,
                      workdir=str(tmp_path))
    state = trainer.init_state(next(iter(train)))
    state = trainer.fit(train, None, state=state)

    # PCK: argmax of each predicted heatmap within 2 cells of the planted
    # keypoint (the demo_hourglass_pose.ipynb eyeball check, quantified)
    batch = next(iter(val))
    variables = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    heat = np.asarray(trainer.model.apply(
        variables, jnp.asarray(batch["image"]), train=False)[-1])
    pck = _pck(heat, batch["keypoints"])
    assert pck >= 0.85, f"PCK {pck}"


def _pck(heat, kp, radius=2):
    """Fraction of visible keypoints whose predicted-heatmap argmax lands
    within ``radius`` cells of the planted location."""
    hits = total = 0
    for b in range(heat.shape[0]):
        for k in range(heat.shape[-1]):
            if kp[b, k, 2] <= 0:
                continue
            total += 1
            yy, xx = np.unravel_index(np.argmax(heat[b, :, :, k]),
                                      heat.shape[1:3])
            if abs(xx - kp[b, k, 0]) <= radius and \
                    abs(yy - kp[b, k, 1]) <= radius:
                hits += 1
    assert total > 0
    return hits / total


def test_pipelined_hourglass_converges_with_microbatch_bn(tmp_path):
    """The pipelined training mode through its REAL recipe (VERDICT r4
    weak #1): {data:2, pipe:4} with microbatches=2 — i.e. BN normalizing
    over 2-sample microbatches per data shard, the semantics production
    pipelining actually runs — must still CONVERGE to the monolithic
    PCK bar (0.85), not merely agree with a pipe=1 run of itself.
    Eval goes through export_monolithic_variables + the monolithic
    network, so the layout converter is validated on trained weights."""
    from deep_vision_tpu.data.pose import PoseLoader, synthetic_pose_dataset
    from deep_vision_tpu.models.hourglass import StackedHourglass
    from deep_vision_tpu.parallel import make_mesh
    from deep_vision_tpu.parallel.pipelined import PipelinedModel
    from deep_vision_tpu.tasks.pose import PoseTask

    K = 4

    def model_fn():
        return StackedHourglass(num_stack=4, num_heatmap=K, filters=16,
                                order=2, dtype=jnp.float32)

    cfg = TrainConfig(
        name="hg_pipe_conv", model=model_fn, task="pose",
        batch_size=8, total_epochs=120,
        optimizer=OptimizerConfig(name="adam", learning_rate=2e-3),
        image_size=64, num_classes=K, half_precision=False,
        checkpoint_every_epochs=1000)
    mesh = make_mesh({"data": 2, "pipe": 4})
    pm = PipelinedModel.for_model(model_fn(), mesh, num_microbatches=2)
    samples = synthetic_pose_dataset(8, 64, K, seed=5)
    train = PoseLoader(samples, 8, 64, 16, K, train=True, seed=0)
    val = PoseLoader(samples, 8, 64, 16, K, train=False)
    trainer = Trainer(cfg, pm, PoseTask(), mesh=mesh, workdir=str(tmp_path))
    state = trainer.init_state(next(iter(train)))
    state = trainer.fit(train, None, state=state)

    merged = pm.export_monolithic_variables(state.params, state.batch_stats)
    batch = next(iter(val))
    heat = np.asarray(model_fn().apply(
        merged, jnp.asarray(batch["image"]), train=False)[-1])
    pck = _pck(heat, batch["keypoints"])
    assert pck >= 0.85, f"PCK {pck}"


def test_pipelined_centernet_converges_with_microbatch_bn(tmp_path):
    """CenterNet through the same real pipelined recipe ({data:2, pipe:2},
    microbatches=2, per-microbatch BN) reaches the monolithic mAP bar."""
    from deep_vision_tpu.data.detection import (
        CenterNetLoader,
        synthetic_detection_dataset,
    )
    from deep_vision_tpu.models.centernet import CenterNet
    from deep_vision_tpu.parallel import make_mesh
    from deep_vision_tpu.parallel.pipelined import PipelinedModel
    from deep_vision_tpu.tasks.centernet import CenterNetTask

    def model_fn():
        return CenterNet(num_classes=3, num_stack=2, order=3,
                         filters=(32, 32, 48, 64), dtype=jnp.float32)

    cfg = TrainConfig(
        name="cn_pipe_conv", model=model_fn, task="centernet",
        batch_size=8, total_epochs=150,
        optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
        image_size=64, num_classes=3, half_precision=False,
        checkpoint_every_epochs=1000)
    mesh = make_mesh({"data": 2, "pipe": 2}, devices=jax.devices()[:4])
    pm = PipelinedModel.for_model(model_fn(), mesh, num_microbatches=2)
    samples = synthetic_detection_dataset(8, 64, 3, seed=4)
    train = CenterNetLoader(samples, 8, 3, 64, train=True, augment=False,
                            seed=0)
    val = CenterNetLoader(samples, 8, 3, 64, train=False)
    trainer = Trainer(cfg, pm, CenterNetTask(3), mesh=mesh,
                      workdir=str(tmp_path))
    state = trainer.init_state(next(iter(train)))
    state = trainer.fit(train, None, state=state)
    m = trainer.evaluate(state, val)
    assert m["mAP"] >= 0.8, m


@pytest.mark.slow
def test_cyclegan_learns_deterministic_translation(tmp_path, mesh1):
    """CycleGAN convergence (VERDICT r3 weak #5): the synthetic unpaired
    domains differ by a DETERMINISTIC affine shift (opposite pattern +
    color casts, data/gan.synthetic_unpaired), so a trained a→b generator
    must (1) move images toward that target far better than at init,
    (2) land in B's color cast, and (3) leave B images alone (identity) —
    a broken cycle/identity weighting fails all three.  Runs the full
    AdversarialTrainer loop including the host ImagePool exchange."""
    from deep_vision_tpu.core.adversarial import AdversarialTrainer
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.data.gan import UnpairedLoader, synthetic_unpaired
    from deep_vision_tpu.models.gan import (
        CycleGANGenerator,
        PatchGANDiscriminator,
    )
    from deep_vision_tpu.tasks.gan import CycleGANTask

    size, n = 16, 16
    rng = np.random.default_rng(3)
    # SMOOTH per-image base fields (4×4 grid ×4 upsample): the generator
    # downsamples 4×, so iid per-pixel noise (synthetic_unpaired's base)
    # would put an irreducible ~0.2 floor under the identity/cycle errors
    grid = rng.uniform(-0.2, 0.2, (2 * n, 4, 4, 3))
    base = np.repeat(np.repeat(grid, 4, 1), 4, 2)
    ys = np.mgrid[0:size, 0:size][0] / size
    pattern = np.sin(6.28 * ys)[..., None] * np.array([1.0, -1.0, 0.5])
    # amplitudes sum to 0.2+0.5+0.25 < 1, so no pixel saturates and the
    # analytic a→b oracle (flip pattern + cast) is EXACT — a clipped
    # construction would make the target wrong at saturated pixels
    a = (base[:n] + pattern * 0.5 + [0.25, -0.25, 0.0]).astype(np.float32)
    b = (base[n:] - pattern * 0.5 + [-0.25, 0.25, 0.0]).astype(np.float32)
    shift = (2 * 0.5 * pattern + 2 * np.array([0.25, -0.25, 0.0]))[None]
    target = (a - shift).astype(np.float32)

    cfg = get_config("cyclegan")
    cfg.batch_size = 4
    cfg.image_size = size
    cfg.log_every_steps = 100
    cfg.optimizer.learning_rate = 1e-3  # toy scale: 400 steps total
    task = CycleGANTask(lambda: CycleGANGenerator(n_blocks=2),
                        lambda: PatchGANDiscriminator())
    trainer = AdversarialTrainer(cfg, task, mesh=mesh1,
                                 workdir=str(tmp_path))
    loader = UnpairedLoader(a, b, cfg.batch_size, seed=0)

    states0 = trainer.init_states(next(iter(loader)))
    err_init = float(np.abs(task.translate(states0, a) - target).mean())
    ident_init = float(np.abs(task.translate(states0, b) - b).mean())

    states = trainer.fit(loader, epochs=100)
    trans = task.translate(states, a)
    # measured at this recipe (in the 8-virtual-device test env):
    # ratio 0.38, casts ±0.23, ident 0.41x its init; GAN trajectories
    # are chaotic in f32, so thresholds carry ~30% margin
    err = float(np.abs(trans - target).mean())
    assert err < 0.55 * err_init, (err, err_init)
    # lands in B's color cast (R negative, G positive — A had +/-0.25)
    assert trans[..., 0].mean() < -0.12, trans[..., 0].mean()
    assert trans[..., 1].mean() > 0.12, trans[..., 1].mean()
    # identity: already-B images pass through far closer than at init —
    # a broken LAMBDA_ID leaves this flat
    ident_err = float(np.abs(task.translate(states, b) - b).mean())
    assert ident_err < 0.6 * ident_init, (ident_err, ident_init)


def test_dcgan_loss_trajectories_sane():
    from deep_vision_tpu.models.gan import DCGANDiscriminator, DCGANGenerator
    from deep_vision_tpu.tasks.gan import DCGANTask

    task = DCGANTask(DCGANGenerator(), DCGANDiscriminator(), latent_dim=16,
                     opt=OptimizerConfig(name="adam", learning_rate=2e-4,
                                         b1=0.5))
    rng = jax.random.PRNGKey(0)
    data = np.random.default_rng(0).uniform(
        -1, 1, (8, 28, 28, 1)).astype(np.float32)
    batch = {"image": jnp.asarray(data)}
    states = task.init_states(rng, batch)
    step = jax.jit(task.train_step)
    g_losses, d_losses = [], []
    for i in range(50):
        states, _, metrics = step(states, batch, jax.random.fold_in(rng, i))
        g_losses.append(float(metrics["g_loss"]))
        d_losses.append(float(metrics["d_loss"]))
    g, d = np.asarray(g_losses), np.asarray(d_losses)
    assert np.isfinite(g).all() and np.isfinite(d).all()
    # discriminator improves on the fixed real batch: d_loss trends down
    assert d[-10:].mean() < d[:5].mean(), (d[:5], d[-10:])
    # neither side collapses: G still gets gradient signal (finite, nonzero)
    assert 0.0 < g[-1] < 20.0 and 0.0 < d[-1] < 10.0
    # stronger than loss-shape checks (VERDICT r2 weak #5): after training,
    # D must actually SEPARATE real from generated — real logits above fake
    # by a margin, i.e. real/fake accuracy ≥ 75% at threshold 0 — and G's
    # samples must not have collapsed to a constant image
    fake = task.sample(states, 8, jax.random.fold_in(rng, 999))
    d_state = states["discriminator"]
    d_vars = {"params": d_state.params}
    if d_state.batch_stats:
        d_vars["batch_stats"] = d_state.batch_stats
    real_logit = np.asarray(task.discriminator.apply(
        d_vars, batch["image"], train=False)).reshape(-1)
    fake_logit = np.asarray(task.discriminator.apply(
        d_vars, jnp.asarray(fake), train=False)).reshape(-1)
    real_acc = (real_logit > 0).mean()
    fake_acc = (fake_logit < 0).mean()
    assert (real_acc + fake_acc) / 2 >= 0.75, (real_acc, fake_acc)
    assert real_logit.mean() > fake_logit.mean() + 0.5, \
        (real_logit.mean(), fake_logit.mean())
    per_sample_std = np.asarray(fake).std(axis=0).mean()
    assert per_sample_std > 1e-3, "generator collapsed to a constant"
