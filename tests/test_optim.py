"""Scheduler semantics tests (epoch_begin fixes the LR used DURING the
epoch — warmup must cover epoch 1; tables must survive JSON resume)."""

import json

from deep_vision_tpu.core.optim import (
    EpochTableSchedule,
    LinearDecay,
    ReduceLROnPlateau,
    WarmupCosine,
    build_scheduler,
)


def test_warmup_covers_first_epoch():
    s = WarmupCosine(0.4, total_epochs=90, warmup_epochs=5)
    ramp = [round(s.epoch_begin(e), 4) for e in range(1, 6)]
    assert ramp == [0.08, 0.16, 0.24, 0.32, 0.4]
    # first post-warmup epoch starts at peak, then decays
    assert s.epoch_begin(6) == 0.4
    assert s.epoch_begin(7) < 0.4
    assert s.epoch_begin(90) < 0.01


def test_epoch_table_survives_json_roundtrip():
    s = EpochTableSchedule({1: 1e-3, 40: 1e-4, 60: 1e-5})
    assert s.epoch_begin(1) == 1e-3
    assert s.epoch_begin(45) == 1e-4
    state = json.loads(json.dumps(s.state_dict()))  # stringifies int keys
    s2 = EpochTableSchedule({1: 0.0})
    s2.load_state_dict(state)
    assert s2.epoch_begin(41) == 1e-4
    assert s2.epoch_begin(61) == 1e-5


def test_linear_decay_reaches_zero():
    s = LinearDecay(2e-4, total_epochs=200, decay_start=100)
    assert s.epoch_begin(1) == 2e-4
    assert s.epoch_begin(100) == 2e-4
    assert s.epoch_begin(101) == 2e-4  # first decayed epoch is still ~base
    assert s.epoch_begin(151) == 1e-4
    assert s.epoch_begin(201) == 0.0


def test_plateau_decays_after_patience():
    s = ReduceLROnPlateau(0.1, mode="max", factor=0.1, patience=2)
    s.step(1, 0.5)
    for e in range(2, 6):
        s.step(e, 0.4)  # no improvement ×4 > patience 2
    assert abs(s.lr - 0.01) < 1e-9


def test_build_scheduler_registry():
    s = build_scheduler("epoch_table", 0.0, table={1: 1e-3})
    assert isinstance(s, EpochTableSchedule)
    s = build_scheduler("warmup_cosine", 0.1, total_epochs=10)
    assert isinstance(s, WarmupCosine)


def test_momentum_dtype_bf16_accumulator():
    """momentum_dtype='bfloat16' stores the SGD trace in bf16 (the
    optimizer-state bandwidth experiment, docs/PERF.md) and is rejected
    for anything but sgd / any other dtype string."""
    import jax
    import jax.numpy as jnp
    import pytest

    from deep_vision_tpu.core.optim import OptimizerConfig, build_optimizer

    params = {"w": jnp.ones((4, 4), jnp.float32)}
    tx = build_optimizer(OptimizerConfig(name="sgd", learning_rate=0.1,
                                         momentum=0.9,
                                         momentum_dtype="bfloat16"))
    st = tx.init(params)
    accs = [l for l in jax.tree_util.tree_leaves(st)
            if getattr(l, "shape", None) == (4, 4)]
    assert accs and all(l.dtype == jnp.bfloat16 for l in accs)
    upd, _ = tx.update({"w": jnp.full((4, 4), 0.5)}, st, params)
    assert jnp.isfinite(upd["w"]).all()

    with pytest.raises(ValueError, match="momentum_dtype"):
        build_optimizer(OptimizerConfig(name="sgd", momentum_dtype="bf16"))
    with pytest.raises(ValueError, match="sgd"):
        build_optimizer(OptimizerConfig(name="adam",
                                        momentum_dtype="bfloat16"))
