"""Continuous-deploy pipeline contract (CPU, tier-1 fast): the
checkpoint watcher debounces in-progress saves and acts on a stable
fingerprint exactly once, the accuracy gate blocks NaN/regressed
candidates while the active version keeps serving, revert restores the
previous promoted weights under live load with zero lost requests, and
the replica autoscaler scales up on queue pressure / down on sustained
idle with hysteresis + cooldown — draining, never dropping, in-flight
cohorts.

Uses LeNet at random init (deterministic under PRNGKey(0)): deploy
correctness is about state machines and routing, not learned weights.
Runs with the lock-order sanitizer enabled (conftest fixture keyed on
the ``deploy`` marker).
"""

import os
import queue
import threading
import time
import types

import numpy as np
import pytest

from deep_vision_tpu.serve.admission import AdmissionController, Shed
from deep_vision_tpu.serve.engine import BatchingEngine
from deep_vision_tpu.serve.models import (ACTIVE, RETIRED, CanaryPolicy,
                                          ModelControlPlane, WeightCache)
from deep_vision_tpu.serve.registry import (CheckpointServingModel,
                                            ModelRegistry)

pytestmark = pytest.mark.deploy


def _engine_factory(model):
    return BatchingEngine(model, buckets=[4], max_wait_ms=2)


def _clone_sm(sm, transform=None):
    """A new ServingModel over the same (or ``transform``-ed) weights —
    the watcher loader seam's 'new checkpoint' stand-in."""
    import jax

    params = sm._variables["params"]
    if transform is not None:
        params = jax.tree_util.tree_map(transform, params)
    state = types.SimpleNamespace(
        params=params,
        batch_stats=sm._variables.get("batch_stats"))
    new = CheckpointServingModel(sm.name, sm.cfg, sm._model, state)
    new.restored_step = (sm.restored_step or 0) + 1
    return new


@pytest.fixture()
def lenet_plane(tmp_path):
    reg = ModelRegistry()
    workdir = str(tmp_path / "lenet5")
    sm = reg.load_checkpoint("lenet5", workdir)
    plane = ModelControlPlane(
        reg, _engine_factory, cache=WeightCache(budget_bytes=0),
        policy=CanaryPolicy(canary_frac=0.5, min_requests=3,
                            max_p99_ratio=None, phase_timeout_s=15.0),
        admission_factory=lambda name: AdmissionController(name=name))
    plane.deploy(sm, workdir=workdir)
    yield reg, sm, plane, workdir
    plane.stop()


def _img(shape=(32, 32, 1), seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


class _LoadThread(threading.Thread):
    """Closed-loop client collecting every failure, so deploy/revert
    tests can assert the zero-lost-requests contract."""

    def __init__(self, plane, name, img):
        super().__init__(daemon=True)
        self.plane, self.name, self.img = plane, name, img
        self.stop_flag = threading.Event()
        self.served = 0
        self.errors: list = []

    def run(self):
        while not self.stop_flag.is_set():
            try:
                r = self.plane.infer(self.name, self.img, timeout=30)
            except Exception as e:  # noqa: BLE001 — every failure is a lost request
                self.errors.append(repr(e))
                continue
            if isinstance(r, Shed):
                self.errors.append(repr(r))
                continue
            self.served += 1

    def finish(self):
        self.stop_flag.set()
        self.join(30)


def _fake_ckpt(workdir: str, step: int, mtime: float | None = None,
               kind: str = "checkpoints") -> str:
    """A complete-looking Orbax step dir: fingerprinting reads only
    filesystem metadata, so a numeric dir with one file inside is a
    checkpoint as far as the watcher is concerned."""
    d = os.path.join(workdir, kind, str(step))
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "params"), "w") as f:
        f.write("x")
    if mtime is not None:
        os.utime(d, (mtime, mtime))
    return d


# -- checkpoint fingerprint (satellite: tmp/incomplete artifacts) ----------


def test_fingerprint_skips_tmp_and_incomplete(tmp_path):
    """An async save's ``*.orbax-checkpoint-tmp-*`` staging dir and an
    empty (still-materializing) step dir must not move the fingerprint;
    the completed step must."""
    from deep_vision_tpu.core.restore import checkpoint_fingerprint

    workdir = str(tmp_path / "w")
    assert checkpoint_fingerprint(workdir)["step"] is None
    _fake_ckpt(workdir, 100)
    before = checkpoint_fingerprint(workdir)
    assert before["step"] == 100

    # in-progress async save: staging dir + empty final dir
    staging = os.path.join(workdir, "checkpoints",
                           "101.orbax-checkpoint-tmp-1234")
    os.makedirs(staging)
    with open(os.path.join(staging, "params"), "w") as f:
        f.write("x")
    os.makedirs(os.path.join(workdir, "checkpoints", "101"))
    assert checkpoint_fingerprint(workdir) == before

    # non-numeric clutter is ignored too
    os.makedirs(os.path.join(workdir, "checkpoints", "tmpdir"))
    assert checkpoint_fingerprint(workdir) == before

    # the save completes: fingerprint moves to the new step
    _fake_ckpt(workdir, 101)
    assert checkpoint_fingerprint(workdir)["step"] == 101
    # checkpoints_best outranks checkpoints (load_state's preference)
    _fake_ckpt(workdir, 102, kind="checkpoints_best")
    assert checkpoint_fingerprint(workdir)["step"] == 102


# -- deployment history ----------------------------------------------------


def test_history_ledger_survives_restart_and_torn_tail(tmp_path):
    from deep_vision_tpu.deploy import DeploymentHistory

    root = str(tmp_path / "_deploy")
    h = DeploymentHistory(root, retain=4)
    for i in range(6):
        h.record("lenet5", "candidate", step=i)
    h.record("other", "promoted", version=2)
    # in-memory view trims to retain; the file keeps everything
    assert [e["step"] for e in h.entries("lenet5")] == [2, 3, 4, 5]
    assert h.entries("lenet5", n=2)[-1]["step"] == 5
    assert h.last_outcome("other") == "promoted"

    # crash mid-append: a torn tail line is skipped on reload
    with open(os.path.join(root, "lenet5.jsonl"), "a") as f:
        f.write('{"ts": 1, "model": "lenet5", "outco')
    h2 = DeploymentHistory(root, retain=4)
    assert [e["step"] for e in h2.entries("lenet5")] == [2, 3, 4, 5]
    assert sorted(h2.names()) == ["lenet5", "other"]
    st = h2.stats()
    assert st["models"]["lenet5"]["last_outcome"] == "candidate"


# -- accuracy gate ---------------------------------------------------------


def test_gate_identical_weights_pass(lenet_plane):
    from deep_vision_tpu.deploy import AccuracyGate

    _, sm, _, _ = lenet_plane
    out = AccuracyGate().evaluate(_clone_sm(sm), sm)
    assert out["passed"]
    assert out["agreement"] == 1.0
    assert out["gate_dir"] == "synthetic"


def test_gate_fails_nan_candidate(lenet_plane):
    from deep_vision_tpu.deploy import AccuracyGate

    _, sm, _, _ = lenet_plane
    bad = _clone_sm(sm, transform=lambda a: a * np.nan)
    out = AccuracyGate().evaluate(bad, sm)
    assert not out["passed"]
    assert "NaN" in out["reason"]


def test_gate_labeled_accuracy(lenet_plane, tmp_path):
    """labels.txt beside the *.npy images upgrades the gate from
    agreement to real accuracy: identical weights pass at delta 0, a
    candidate collapsed to one class fails on the accuracy drop."""
    from deep_vision_tpu.deploy import AccuracyGate

    _, sm, _, _ = lenet_plane
    gate_dir = str(tmp_path / "holdout")
    os.makedirs(gate_dir)
    rng = np.random.RandomState(0)
    for i in range(16):
        np.save(os.path.join(gate_dir, f"img_{i:02d}.npy"),
                rng.randint(0, 256, (32, 32, 1), dtype=np.uint8))
    gate = AccuracyGate(gate_dir=gate_dir)
    # labels := the active model's own predictions → active_acc == 1.0
    preds, nan = gate._predict(sm, gate._batches(sm))
    assert preds is not None and not nan
    np.savetxt(os.path.join(gate_dir, "labels.txt"),
               np.asarray(preds, np.int64), fmt="%d")

    out = gate.evaluate(_clone_sm(sm), sm)
    assert out["passed"]
    assert out["candidate_acc"] == 1.0
    assert out["active_acc"] == 1.0
    assert out["delta"] == 0.0

    # zeroed params → uniform logits → argmax collapses to class 0
    flat = gate.evaluate(_clone_sm(sm, transform=np.zeros_like), sm)
    assert flat["candidate_acc"] < 1.0
    assert not flat["passed"]
    assert "dropped" in flat["reason"]


# -- checkpoint watcher ----------------------------------------------------


def _watcher(plane, history=None, gate=None, loader=None):
    from deep_vision_tpu.deploy import CheckpointWatcher, DeploymentHistory

    history = history or DeploymentHistory()
    w = CheckpointWatcher(plane, history, interval_s=0.05, gate=gate,
                          loader=loader).watch("lenet5")
    return w, history


def test_watcher_debounce_never_acts_on_moving_fingerprint(lenet_plane):
    _, sm, plane, workdir = lenet_plane
    w, _ = _watcher(plane, loader=lambda p, n: _clone_sm(sm))
    assert w.poll_once("lenet5")["status"] == "no_checkpoint"
    # a fingerprint that changes between every pair of polls (an async
    # save still materializing) never graduates past debounce
    for i in range(4):
        _fake_ckpt(workdir, 5, mtime=1000.0 + i)
        assert w.poll_once("lenet5")["status"] == "debounce"
    assert w.stats()["deploys"] == 0
    assert w.stats()["debounces"] == 4


def test_watcher_deploys_stable_fingerprint_exactly_once(lenet_plane):
    _, sm, plane, workdir = lenet_plane
    w, history = _watcher(plane, loader=lambda p, n: _clone_sm(sm))
    _fake_ckpt(workdir, 5, mtime=1000.0)
    assert w.poll_once("lenet5")["status"] == "debounce"
    load = _LoadThread(plane, "lenet5", _img())
    load.start()
    try:
        out = w.poll_once("lenet5")  # stable across two polls → deploy
    finally:
        load.finish()
    assert out["status"] == "promoted"
    assert load.errors == []
    assert plane.active_version("lenet5").model.restored_step \
        == (sm.restored_step or 0) + 1
    # the same fingerprint is decided at most once
    assert w.poll_once("lenet5")["status"] == "acted"
    assert w.stats()["deploys"] == 1
    outcomes = [e["outcome"] for e in history.entries("lenet5")]
    assert outcomes == ["candidate", "promoted"]


def test_watcher_gate_failure_keeps_active_serving(lenet_plane):
    from deep_vision_tpu.deploy import AccuracyGate

    _, sm, plane, workdir = lenet_plane
    active_before = plane.active_version("lenet5")
    w, history = _watcher(
        plane, gate=AccuracyGate(),
        loader=lambda p, n: _clone_sm(sm, transform=lambda a: a * np.nan))
    _fake_ckpt(workdir, 7, mtime=2000.0)
    assert w.poll_once("lenet5")["status"] == "debounce"
    out = w.poll_once("lenet5")
    assert out["status"] == "gate_failed"
    assert "NaN" in out["gate"]["reason"]
    # FAILED deployment recorded with the eval verdict; active untouched
    outcomes = [e["outcome"] for e in history.entries("lenet5")]
    assert outcomes == ["candidate", "gate_failed"]
    assert plane.active_version("lenet5") is active_before
    assert w.stats()["gate_failures"] == 1
    assert w.stats()["deploys"] == 0
    assert w.poll_once("lenet5")["status"] == "acted"


# -- revert ----------------------------------------------------------------


def test_revert_under_load_restores_previous_version(lenet_plane):
    from deep_vision_tpu.deploy import DeployPipeline

    _, sm, plane, _ = lenet_plane
    pipeline = DeployPipeline(plane)
    v1_digest = plane.active_version("lenet5").model.params_digest
    load = _LoadThread(plane, "lenet5", _img())
    load.start()
    try:
        out = plane.reload("lenet5", wait=True,
                           _loader=lambda: _clone_sm(sm))
        assert out["version"]["state"] == ACTIVE
        assert plane.active_version("lenet5").version == 2
        rv = pipeline.revert("lenet5")
    finally:
        load.finish()
    assert rv["status"] == "reverted"
    assert rv["from_version"] == 2
    active = plane.active_version("lenet5")
    assert active.version == 3
    assert active.model.params_digest == v1_digest
    # zero admitted-request loss across reload AND revert
    assert load.errors == []
    assert load.served > 0
    assert pipeline.history.last_outcome("lenet5") == "reverted"
    # the displaced v2 drained out of service
    assert plane.models()["lenet5"]["versions"][1]["state"] == RETIRED


def test_revert_refused_without_prior_promoted_version(lenet_plane):
    from deep_vision_tpu.deploy import DeployPipeline

    _, _, plane, _ = lenet_plane
    out = DeployPipeline(plane).revert("lenet5")
    assert out["status"] == "refused"  # → HTTP 409
    with pytest.raises(KeyError):
        DeployPipeline(plane).revert("nope")


def test_revert_refuses_while_reload_in_flight(lenet_plane):
    _, sm, plane, _ = lenet_plane
    gate = threading.Event()

    def slow_loader():
        gate.wait(10)
        return _clone_sm(sm)

    load = _LoadThread(plane, "lenet5", _img())
    load.start()
    try:
        assert plane.reload("lenet5", wait=False,
                            _loader=slow_loader)["status"] == "reloading"
        out = plane.revert("lenet5")
        assert out["status"] == "in_progress"  # → HTTP 409
    finally:
        gate.set()
        worker = plane._reloading.get("lenet5")
        if worker is not None:
            worker.join(20)
        load.finish()


# -- replica autoscaler ----------------------------------------------------


class _FakeEngine:
    """The four signals + two actions the scaler touches, no devices."""

    def __init__(self, live=1, ewma_s=0.01):
        self._queue: queue.Queue = queue.Queue()
        self.admission = types.SimpleNamespace(
            bucket_ewma_s=lambda: ewma_s)
        self.model = types.SimpleNamespace(name="fake")
        self.live = live
        self.inflight = 0

    def total_inflight(self):
        return self.inflight

    def live_replicas(self):
        return self.live

    def add_replica(self):
        self.live += 1
        return self.live - 1

    def remove_replica(self, drain_deadline=5.0):
        self.live -= 1
        return self.live


def _pressurize(eng, n):
    while eng._queue.qsize() < n:
        eng._queue.put(object())
    while eng._queue.qsize() > n:
        eng._queue.get_nowait()


def test_autoscaler_hysteresis_and_cooldown():
    from deep_vision_tpu.deploy import ReplicaAutoscaler

    eng = _FakeEngine()
    s = ReplicaAutoscaler(eng, min_replicas=1, max_replicas=3,
                          high_water_ms=50.0, up_window=3,
                          down_window=3, cooldown_s=60.0)
    # pressure_ms = depth × 10ms: 10 deep = 100ms > high water
    _pressurize(eng, 10)
    assert s.tick() is None and s.tick() is None  # hysteresis: 2 < 3
    assert eng.live == 1  # monotone within the window
    act = s.tick()
    assert act["action"] == "scale_up" and eng.live == 2
    # cooldown: sustained pressure cannot act again immediately
    for _ in range(5):
        assert s.tick() is None
    assert eng.live == 2
    assert s.scale_ups == 1

    # a contrary tick resets the idle streak
    s2 = ReplicaAutoscaler(_FakeEngine(live=3), min_replicas=1,
                           max_replicas=3, high_water_ms=50.0,
                           up_window=3, down_window=3, cooldown_s=0.0)
    assert s2.tick() is None and s2.tick() is None  # idle ×2
    _pressurize(s2.engine, 1)  # brief blip: not idle, not high water
    assert s2.tick() is None
    _pressurize(s2.engine, 0)
    assert s2.tick() is None and s2.tick() is None  # restart the streak
    assert s2.engine.live == 3
    act = s2.tick()
    assert act["action"] == "scale_down" and s2.engine.live == 2

    # bounds: at min_replicas, idleness never counts
    s3 = ReplicaAutoscaler(_FakeEngine(live=1), min_replicas=1,
                           max_replicas=3, down_window=1, cooldown_s=0.0)
    for _ in range(5):
        assert s3.tick() is None
    assert s3.engine.live == 1


def test_autoscaler_failed_action_consumes_cooldown():
    from deep_vision_tpu.deploy import ReplicaAutoscaler

    class _Broken(_FakeEngine):
        def add_replica(self):
            raise ValueError("no free local device")

    eng = _Broken()
    s = ReplicaAutoscaler(eng, min_replicas=1, max_replicas=3,
                          up_window=1, cooldown_s=60.0)
    _pressurize(eng, 10)
    assert s.tick() is None
    assert s.scale_errors == 1
    assert s.tick() is None  # cooling down, not retrying hot
    assert s.scale_errors == 1


# -- elastic ReplicatedEngine on forced host devices -----------------------


@pytest.fixture()
def elastic_engine(tmp_path, host_devices):
    from deep_vision_tpu.serve.replicas import ReplicatedEngine

    reg = ModelRegistry()
    sm = reg.load_checkpoint("lenet5", str(tmp_path / "l"))
    eng = ReplicatedEngine(sm, devices=host_devices[:1], buckets=[4],
                           max_wait_ms=2)
    eng.start()
    yield eng
    eng.stop()


def test_add_remove_replica_live_accounting(elastic_engine):
    eng = elastic_engine
    assert eng.live_replicas() == 1
    i = eng.add_replica()
    assert i == 1
    assert eng.live_replicas() == 2
    # satellite (b): admission accounting follows elasticity
    assert eng.admission.stats()["live_replicas"] == 2
    assert eng.stats()["routing"]["live_replicas"] == 2
    for seed in range(8):
        r = eng.infer(_img(seed=seed), timeout=30)
        assert not isinstance(r, Shed)

    removed = eng.remove_replica(drain_deadline=10.0)
    assert eng.live_replicas() == 1
    assert eng.admission.stats()["live_replicas"] == 1
    per = eng.stats()["replicas"]
    assert per[removed]["retired"] is True
    # retired slots are masked, never popped: indices stay stable
    assert [p["replica"] for p in per] == [0, 1]
    r = eng.infer(_img(), timeout=30)
    assert not isinstance(r, Shed)
    with pytest.raises(ValueError):
        eng.remove_replica()  # never below one live replica


def test_scale_down_drains_inflight_cohorts(elastic_engine):
    """remove_replica under load: every future admitted before the
    drain resolves to a real output — scale-down drops nothing."""
    eng = elastic_engine
    eng.add_replica()
    futs = [eng.submit(_img(seed=s)) for s in range(24)]
    removed = eng.remove_replica(drain_deadline=10.0)
    for f in futs:
        r = f.result(timeout=30)
        assert not isinstance(r, Shed)
        assert np.isfinite(np.asarray(r)).all()
    assert eng.stats()["replicas"][removed]["retired"] is True


def test_autoscaler_drives_real_engine(elastic_engine):
    """Forced pressure scales the real engine up; real idleness scales
    it back down; the count stays inside [min, max] throughout."""
    from deep_vision_tpu.deploy import ReplicaAutoscaler

    eng = elastic_engine

    class _Forced(ReplicaAutoscaler):
        forced: dict | None = None

        def signals(self):
            sig = super().signals()
            if self.forced is not None:
                sig.update(self.forced)
            return sig

    s = _Forced(eng, min_replicas=1, max_replicas=2, up_window=2,
                down_window=2, cooldown_s=0.0, high_water_ms=50.0)
    s.forced = {"pressure_ms": 500.0, "queue_depth": 5}
    acts = [s.tick() for _ in range(3)]
    assert [a["action"] for a in acts if a] == ["scale_up"]
    assert eng.live_replicas() == 2
    # at max_replicas, pressure no longer counts toward scaling up
    assert s.tick() is None and s.tick() is None
    assert eng.live_replicas() == 2

    s.forced = None  # real signals: queue empty, nothing in flight
    acts = [s.tick() for _ in range(3)]
    assert [a["action"] for a in acts if a] == ["scale_down"]
    assert eng.live_replicas() == 1
    assert 1 <= s.stats()["live"] <= 2


# -- pipeline stats / HTTP glue -------------------------------------------


def test_pipeline_entries_unknown_model_raises(lenet_plane):
    from deep_vision_tpu.deploy import DeployPipeline

    _, _, plane, _ = lenet_plane
    pipeline = DeployPipeline(plane)
    pipeline.history.record("lenet5", "candidate", step=1)
    assert pipeline.entries("lenet5")[-1]["outcome"] == "candidate"
    with pytest.raises(KeyError):
        pipeline.entries("nope")
    st = pipeline.stats()
    assert st["history"]["records"] == 1
