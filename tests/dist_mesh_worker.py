"""Worker for test_mesh_serving_two_processes: one rank of a 2-process
CPU 'pod' (2 virtual devices per rank) serving LeNet over a 2×2
``data × model`` pod mesh.  Each rank shards the (deterministic,
identical) restore across all 4 global devices via the partition
fallback, compiles the bucket program, runs one global batch, and
checks every ADDRESSABLE output shard against a locally-computed
single-device reference — the GSPMD collectives cross process
boundaries, the numerics must not.  RESULT payloads are identical
across ranks by construction (same weights, same batch).

Run: python dist_mesh_worker.py <coordinator> <process_id> <n> <workdir>.
"""

import os
import sys

# 2 virtual CPU devices per process, BEFORE any jax import
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if not f.startswith("--xla_force_host_platform_device_count")]
flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize pins the TPU

import numpy as np  # noqa: E402

from deep_vision_tpu.parallel.distributed import (  # noqa: E402
    initialize,
    make_pod_mesh,
)


def main():
    coordinator, pid, nprocs, workdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    initialize(coordinator_address=coordinator, num_processes=nprocs,
               process_id=pid)
    # 2 procs × 2 local devices → data=2 (across processes, DCN-ish),
    # model=2 (inside each process)
    mesh = make_pod_mesh({"data": 2, "model": -1})

    from deep_vision_tpu.serve.registry import ModelRegistry

    reg = ModelRegistry()
    # empty shared workdir → deterministic PRNGKey(0) init on BOTH
    # ranks (the multi-process analogue of the smoke fixture)
    sm = reg.load_checkpoint("lenet5", workdir)
    view = sm.for_mesh(mesh, min_shard_dim=64)
    shard_bytes = view.param_bytes()
    global_bytes = view.param_global_bytes()
    assert shard_bytes < global_bytes, (shard_bytes, global_bytes)

    batch = 2
    try:
        prog = view.compile_bucket(batch)
    except Exception as e:  # noqa: BLE001 — backend capability probe
        if "Multiprocess computations aren't implemented" in str(e):
            # this jaxlib's CPU backend can't execute cross-process
            # SPMD programs at all (same limitation test_distributed
            # hits); the launcher turns this sentinel into a skip
            print(f"SKIPBACKEND pid={pid} cpu-multiprocess-unsupported",
                  flush=True)
            return
        raise
    x = np.random.RandomState(0).randn(
        batch, *sm.input_shape).astype(np.float32)
    # every rank holds the full batch; the global array slices each
    # addressable shard locally (no cross-host transfer)
    xg = jax.make_array_from_callback(
        x.shape, view.placement, lambda idx: x[idx])
    out = prog(xg)

    # local single-device reference: eager apply on this rank's own
    # host restore (float32 wire passes through the serve preprocess)
    ref = np.asarray(sm._model.apply(
        sm._variables, x, train=False)).astype(np.float32)
    for shard in out.addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data),
                                   ref[shard.index],
                                   rtol=1e-5, atol=1e-5)
    top1 = [int(c) for c in np.argmax(ref, axis=-1)]
    print(f"RESULT pid={pid} top1={top1} "
          f"logit_sum={float(np.sum(ref)):.6f} "
          f"shard_bytes={shard_bytes} global_bytes={global_bytes}",
          flush=True)


if __name__ == "__main__":
    main()
