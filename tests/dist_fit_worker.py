"""Worker for test_distributed_trainer_fit: one rank of an N-process CPU
'pod' running a REAL Trainer.fit — per-process data shards feeding a
process-spanning mesh, Orbax checkpointing coordinated across ranks
(process 0 writes), then a resume from the shared checkpoint directory.

Run: python dist_fit_worker.py <coordinator> <process_id> <n> <workdir>.
"""

import os
import sys

# 2 virtual CPU devices per process, BEFORE any jax import
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if not f.startswith("--xla_force_host_platform_device_count")]
flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize pins the TPU

import numpy as np  # noqa: E402

from deep_vision_tpu.parallel.distributed import (  # noqa: E402
    initialize,
    make_pod_mesh,
)


def main():
    coordinator, pid, nprocs, workdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    initialize(coordinator_address=coordinator, num_processes=nprocs,
               process_id=pid)
    mesh = make_pod_mesh({"data": -1})

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.loader import ArrayLoader
    from deep_vision_tpu.data.mnist import synthetic_mnist
    from deep_vision_tpu.tasks.classification import ClassificationTask

    cfg = get_config("lenet5")
    cfg.total_epochs = 2
    cfg.log_every_steps = 2

    # identical seeded dataset on every rank; each rank FEEDS its own
    # interleaved shard (the per-host file sharding semantics) — global
    # batch 32 = 16 local × 2 processes
    data = synthetic_mnist(128)
    shard = {k: v[pid::nprocs] for k, v in data.items()}

    def loaders():
        return (ArrayLoader(shard, 16, seed=1),
                ArrayLoader(shard, 16, shuffle=False))

    train_loader, val_loader = loaders()
    trainer = Trainer(cfg, cfg.model(), ClassificationTask(10), mesh=mesh,
                      workdir=workdir)
    state = trainer.fit(train_loader, val_loader)
    step1 = int(jax.device_get(state.step))
    m1 = trainer.evaluate(state, val_loader)
    assert np.isfinite(m1["loss"]), m1
    assert trainer.checkpointer.latest_step() == step1
    # process 0 wrote the checkpoint files; every rank sees them (shared FS)
    print(f"FIT pid={pid} step={step1} loss={m1['loss']:.6f}", flush=True)

    # resume on a FRESH trainer from the shared checkpoint dir, train one
    # more epoch — the v4-32 recovery path
    cfg2 = get_config("lenet5")
    cfg2.total_epochs = 3
    cfg2.log_every_steps = 2
    train2, val2 = loaders()
    trainer2 = Trainer(cfg2, cfg2.model(), ClassificationTask(10), mesh=mesh,
                       workdir=workdir)
    state2 = trainer2.fit(train2, val2, resume=True)
    step2 = int(jax.device_get(state2.step))
    assert trainer2.start_epoch == 3, trainer2.start_epoch
    assert step2 > step1, (step1, step2)
    m2 = trainer2.evaluate(state2, val2)
    print(f"RESULT pid={pid} step={step2} loss={m2['loss']:.6f}", flush=True)


if __name__ == "__main__":
    main()
