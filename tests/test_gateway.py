"""Gateway chaos contract: killing one of two backends mid-load loses
ZERO admitted requests, the breaker stops routing to a dead backend
within one probe interval, half-open recovers a returned backend, a
hedged request's first answer wins, and 429 Retry-After survives the
extra hop.

Most tests run against scriptable STUB backends (a ThreadingHTTPServer
whose healthz status, answer mode, and delay are test-controlled) so
routing/breaker/retry behavior is deterministic and fast; one
integration test drives two REAL serve stacks (LeNet engines) and
SIGKILLs one mid-load."""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from deep_vision_tpu.serve.gateway import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    Backend,
    Gateway,
    GatewayServer,
)

pytestmark = pytest.mark.gateway


class StubBackend:
    """A scriptable backend: mode/healthz/delay flipped mid-test."""

    def __init__(self, tag: str):
        self.tag = tag
        self.mode = "ok"            # ok | fail | shed | busy
        self.delay_s = 0.0
        self.healthz_status = 200
        self.retry_after = 2
        self.requests = 0
        self.killed = False
        self._lock = threading.Lock()
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, status, payload, headers=None):
                blob = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def do_GET(self):
                if stub.killed:
                    self.close_connection = True
                    return
                if self.path == "/v1/healthz":
                    s = stub.healthz_status
                    self._reply(s, {"status": "ok" if s == 200
                                    else "draining"})
                else:
                    self._reply(200, {"stub": stub.tag,
                                      "served": stub.requests})

            def do_POST(self):
                if stub.killed:
                    self.close_connection = True
                    return
                with stub._lock:
                    stub.requests += 1
                if stub.delay_s:
                    time.sleep(stub.delay_s)
                self.rfile.read(
                    int(self.headers.get("Content-Length") or 0))
                if stub.mode == "fail":
                    self._reply(500, {"error": "injected"})
                elif stub.mode == "shed":
                    self._reply(429, {"error": "shed: queue_full"},
                                {"Retry-After": stub.retry_after})
                elif stub.mode == "busy":
                    # a lifecycle verb the backend refuses: reload
                    # already running / no candidate to promote
                    self._reply(409, {"status": "in_progress"})
                else:
                    self._reply(200, {"stub": stub.tag})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        self.url = f"127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    def kill(self):
        """SIGKILL-alike: stop answering, free the port.  A killed
        process takes its ESTABLISHED sockets with it, so in-flight
        keep-alive connections must die too, not just the listener —
        the flag makes handler threads hang up without replying."""
        self.killed = True
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(5)


def _post(base, payload=None, timeout=10):
    req = urllib.request.Request(
        base + "/v1/classify",
        data=json.dumps(payload or {"x": 1}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), json.loads(r.read())


def test_backend_url_parsing():
    b = Backend("http://127.0.0.1:8001/")
    assert (b.host, b.port) == ("127.0.0.1", 8001)
    assert Backend("localhost:9000").name == "localhost:9000"
    with pytest.raises(ValueError):
        Backend("no-port")
    with pytest.raises(ValueError):
        Gateway(["127.0.0.1:1", "127.0.0.1:1"])
    with pytest.raises(ValueError):
        Gateway([])


def test_routing_spreads_and_stats_aggregate():
    """An idle fleet round-robins; /v1/stats carries gateway counters
    plus every backend's own stats blob."""
    stubs = [StubBackend("a"), StubBackend("b")]
    gw = Gateway([s.url for s in stubs], probe_interval_s=60).start()
    srv = GatewayServer(gw, port=0).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        for _ in range(8):
            status, _, payload = _post(base)
            assert status == 200 and payload["stub"] in ("a", "b")
        assert stubs[0].requests >= 2 and stubs[1].requests >= 2
        with urllib.request.urlopen(base + "/v1/stats") as r:
            stats = json.loads(r.read())
        assert stats["gateway"]["proxied"] == 8
        assert stats["gateway"]["retries"] == 0
        for s in stubs:
            assert stats["gateway"]["backends"][s.url]["state"] == "ok"
            assert stats["backends"][s.url]["stub"] == s.tag
        with urllib.request.urlopen(base + "/v1/healthz") as r:
            assert r.status == 200
            assert set(json.loads(r.read())["routable"]) == \
                {s.url for s in stubs}
    finally:
        srv.shutdown()
        gw.stop()
        for s in stubs:
            s.kill()


def test_kill_one_backend_loses_zero_requests():
    """THE acceptance chaos test (stub edition): under concurrent load,
    killing one of two backends produces zero client-visible errors —
    every request fails over — and the breaker opens on the dead one."""
    stubs = [StubBackend("a"), StubBackend("b")]
    # probes effectively off: failure detection must work passively too
    gw = Gateway([s.url for s in stubs], probe_interval_s=60,
                 request_timeout_s=5, retry_budget=3,
                 breaker_threshold=2, breaker_cooldown_s=30).start()
    srv = GatewayServer(gw, port=0).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    errors, oks = [], []
    lock = threading.Lock()
    stop = threading.Event()

    def client():
        while not stop.is_set():
            try:
                status, _, payload = _post(base)
                with lock:
                    oks.append(payload["stub"])
            except Exception as e:  # noqa: BLE001 — any client error fails
                with lock:
                    errors.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.3)
        stubs[0].kill()  # mid-load
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(10)
        assert errors == []          # zero lost requests, no 5xx seen
        assert len(oks) > 20
        assert "b" in oks[-5:]       # traffic converged on the survivor
        dead = gw.backends[0]
        assert dead.breaker == OPEN
        assert dead.state in ("degraded", "dead")
        c = gw.counters()
        assert c["failovers"] >= 1 and c["retries"] >= 1
        assert c["breaker_opens"] >= 1
    finally:
        stop.set()
        srv.shutdown()
        gw.stop()
        stubs[1].kill()


def test_lifecycle_fanout_distinguishes_busy_fleet_from_failed():
    """A fleet that uniformly answers 409 to a lifecycle verb (reload
    already in progress everywhere) comes back as 409 — busy, not the
    502 a genuinely failed fan-out earns; one accepting backend flips
    the verdict to 200."""
    stubs = [StubBackend("a"), StubBackend("b")]
    for s in stubs:
        s.mode = "busy"
    gw = Gateway([s.url for s in stubs], probe_interval_s=60).start()
    srv = GatewayServer(gw, port=0).start_background()
    url = (f"http://127.0.0.1:{srv.port}"
           f"/v1/models/lenet5/reload")
    try:
        req = urllib.request.Request(
            url, data=b"{}",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 409
        body = json.loads(exc.value.read())
        assert all(v["http_status"] == 409
                   for v in body["backends"].values())
        assert all(v["status"] == "in_progress"
                   for v in body["backends"].values())
        stubs[0].mode = "ok"
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
            body = json.loads(r.read())
        assert body["backends"][stubs[0].url]["http_status"] == 200
        assert body["backends"][stubs[1].url]["http_status"] == 409
        # a fleet that actually fails the call still reads as 502
        for s in stubs:
            s.mode = "fail"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 502
    finally:
        srv.shutdown()
        gw.stop()
        for s in stubs:
            s.kill()


def test_probe_opens_breaker_without_traffic():
    """Active probing alone takes a dead backend out of routing within
    one probe interval — no request needs to eat the failure."""
    stubs = [StubBackend("a"), StubBackend("b")]
    gw = Gateway([s.url for s in stubs], probe_interval_s=0.05,
                 probe_timeout_s=0.5, breaker_threshold=2,
                 breaker_cooldown_s=30).start()
    try:
        stubs[0].kill()
        deadline = time.monotonic() + 5
        while gw.backends[0].routable() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not gw.backends[0].routable()
        assert gw.backends[0].breaker == OPEN
        assert gw.routable_backends() == [stubs[1].url]
        # and a request now goes straight to the survivor, no retry
        out = gw.forward("/v1/classify", b'{"x":1}')
        assert out[0] == 200 and json.loads(out[2])["stub"] == "b"
        assert gw.counters()["retries"] == 0
    finally:
        gw.stop()
        stubs[1].kill()


def test_breaker_half_open_recovers():
    """CLOSED → OPEN on consecutive failures → HALF_OPEN after the
    cooldown admits ONE trial → success closes the breaker."""
    stub = StubBackend("a")
    stub.mode = "fail"
    gw = Gateway([stub.url], probe_interval_s=60, retry_budget=1,
                 breaker_threshold=2, breaker_cooldown_s=0.2,
                 backoff_ms=1).start()
    try:
        status, _, _ = gw.forward("/v1/classify", b'{"x":1}')
        assert status == 502          # both attempts failed
        b = gw.backends[0]
        assert b.breaker == OPEN and b.breaker_opens == 1
        # while OPEN and inside the cooldown: no routable backend → 503
        status, headers, _ = gw.forward("/v1/classify", b'{"x":1}')
        assert status == 503 and "Retry-After" in headers
        assert stub.requests == 2     # the dead window sent it nothing
        # cooldown elapses; backend is healthy again: trial closes it
        stub.mode = "ok"
        time.sleep(0.25)
        assert b.routable() and b.breaker == HALF_OPEN
        status, _, _ = gw.forward("/v1/classify", b'{"x":1}')
        assert status == 200
        assert b.breaker == CLOSED and b.breaker_closes == 1
        assert b.half_open_trials == 1 and b.state == "ok"
    finally:
        gw.stop()
        stub.kill()


def test_breaker_reopens_on_failed_trial():
    stub = StubBackend("a")
    stub.mode = "fail"
    gw = Gateway([stub.url], probe_interval_s=60, retry_budget=0,
                 breaker_threshold=1, breaker_cooldown_s=0.1).start()
    try:
        assert gw.forward("/v1/classify", b'{"x":1}')[0] == 502
        b = gw.backends[0]
        assert b.breaker == OPEN
        time.sleep(0.15)              # cooldown → trial admitted
        assert gw.forward("/v1/classify", b'{"x":1}')[0] == 502
        assert b.breaker == OPEN      # failed trial re-opened
        assert b.breaker_opens == 2
    finally:
        gw.stop()
        stub.kill()


def test_429_propagates_with_retry_after():
    """When EVERY backend sheds, the 429 (and its Retry-After) reaches
    the client; with one shedding and one healthy, traffic fails over."""
    stubs = [StubBackend("a"), StubBackend("b")]
    for s in stubs:
        s.mode = "shed"
    gw = Gateway([s.url for s in stubs], probe_interval_s=60,
                 retry_budget=3).start()
    srv = GatewayServer(gw, port=0).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base)
        assert exc.value.code == 429
        assert exc.value.headers["Retry-After"] == "2"
        # a shed isn't a failure: breakers stay closed, state stays ok
        assert all(b.breaker == CLOSED and b.state == "ok"
                   for b in gw.backends)
        # one backend recovers: the shed fails over and succeeds
        stubs[1].mode = "ok"
        status, _, payload = _post(base)
        assert status == 200 and payload["stub"] == "b"
        assert gw.counters()["failovers"] >= 1
    finally:
        srv.shutdown()
        gw.stop()
        for s in stubs:
            s.kill()


def test_retry_budget_token_bucket():
    """Backend-level bucket arithmetic: a retry spends 1.0, a REAL
    success refills +ratio capped at burst, and the bucket starts full
    so the first failover after boot is never blocked."""
    b = Backend("127.0.0.1:1", retry_ratio=0.5, retry_burst=2.0)
    assert b.retry_tokens_left() == 2.0
    assert b.try_retry() and b.try_retry()
    assert not b.try_retry()             # dry: the storm dies here
    assert b.retries_granted == 2 and b.retries_denied == 1
    b.begin()
    b.done_success(0.01)
    assert b.retry_tokens_left() == pytest.approx(0.5)
    for _ in range(10):                  # refill is capped at burst
        b.begin()
        b.done_success(0.01)
    assert b.retry_tokens_left() == pytest.approx(2.0)


def test_retry_storm_is_bounded_by_budget():
    """N aggressive closed-loop clients against a 100%-shedding fleet
    must not amplify load: with zero successes nothing refills the
    buckets, so granted retries stop at the boot burst per backend and
    total upstream attempts stay at offered + burst x backends.  On
    recovery the buckets refill +ratio per success — gradual re-arming,
    not a thundering herd of banked retries on the first good answer."""
    stubs = [StubBackend("a"), StubBackend("b")]
    for s in stubs:
        s.mode = "shed"
    burst = 4.0
    gw = Gateway([s.url for s in stubs], probe_interval_s=60,
                 retry_budget=3, retry_budget_ratio=0.1,
                 retry_budget_burst=burst, backoff_ms=1.0,
                 backoff_max_ms=2.0).start()
    srv = GatewayServer(gw, port=0).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    offered = 25 * 4
    codes = []
    budgets = []
    lock = threading.Lock()

    def client():
        for _ in range(25):
            try:
                _post(base)
                with lock:
                    codes.append(200)
            except urllib.error.HTTPError as exc:
                exc.read()
                with lock:
                    codes.append(exc.code)
                    budgets.append(
                        exc.headers.get("X-DVT-Retry-Budget"))

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    try:
        assert len(codes) == offered and set(codes) == {429}
        # the budget state rode every shed back to the client, and by
        # the end it reported dry — cooperating clients stop retrying
        assert all(b is not None for b in budgets)
        assert float(budgets[-1]) < 1.0
        c = gw.counters()
        # granted retries never exceed the boot burst across the fleet
        assert c["retries"] <= burst * len(stubs)
        assert c["retry_budget_denied"] > 0
        assert sum(s.requests for s in stubs) <= \
            offered + burst * len(stubs)
        # recovery: successes refill +ratio each, so the post-outage
        # allowance grows from ~0 — it does NOT snap back to burst
        for s in stubs:
            s.mode = "ok"
        for _ in range(10):
            status, _, _ = _post(base)
            assert status == 200
        for b in gw.backends:
            assert b.retry_tokens_left() < 2.0
    finally:
        srv.shutdown()
        gw.stop()
        for s in stubs:
            s.kill()


def test_unavailable_healthz_leaves_routing_without_penalty():
    """A 503 healthz (draining) removes the backend from routing with
    NO breaker damage, and a 200 probe restores it."""
    stubs = [StubBackend("a"), StubBackend("b")]
    gw = Gateway([s.url for s in stubs], probe_interval_s=0.05).start()
    try:
        stubs[0].healthz_status = 503
        deadline = time.monotonic() + 5
        while gw.backends[0].routable() and time.monotonic() < deadline:
            time.sleep(0.02)
        b = gw.backends[0]
        assert not b.routable()
        assert b.unavailable == "draining"
        assert b.breaker == CLOSED and b.consecutive_failures == 0
        stubs[0].healthz_status = 200
        deadline = time.monotonic() + 5
        while not b.routable() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert b.routable()
    finally:
        gw.stop()
        for s in stubs:
            s.kill()


def test_hedged_request_first_answer_wins():
    """Primary stalls past the hedge delay → the duplicate on the other
    backend answers first and wins; the loser is discarded, not failed."""
    slow, fast = StubBackend("slow"), StubBackend("fast")
    slow.delay_s = 1.0
    gw = Gateway([slow.url, fast.url], probe_interval_s=60,
                 hedge=True, hedge_after_ms=50).start()
    try:
        # pin the primary pick to the slow backend (rr offset 0)
        gw._rr = 0
        t0 = time.monotonic()
        status, _, payload = gw.forward("/v1/classify", b'{"x":1}')
        elapsed = time.monotonic() - t0
        assert status == 200 and json.loads(payload)["stub"] == "fast"
        assert elapsed < 0.9          # did not wait out the slow one
        c = gw.counters()
        assert c["hedges"] == 1 and c["hedge_wins"] == 1
        assert c["retries"] == 0      # hedging is not a retry
    finally:
        gw.stop()
        slow.kill()
        fast.kill()


def test_hedge_waits_for_p99_history():
    """Without an explicit delay, hedging stays off until the gateway
    has enough of its own latency history to know its p99."""
    stub = StubBackend("a")
    other = StubBackend("b")
    gw = Gateway([stub.url, other.url], probe_interval_s=60,
                 hedge=True, hedge_min_history=4).start()
    try:
        assert gw._hedge_delay_s() is None
        for _ in range(4):
            assert gw.forward("/v1/classify", b'{"x":1}')[0] == 200
        assert gw._hedge_delay_s() is not None
    finally:
        gw.stop()
        stub.kill()
        other.kill()


def test_no_routable_backend_is_503_not_hang():
    stub = StubBackend("a")
    gw = Gateway([stub.url], probe_interval_s=0.05,
                 breaker_threshold=1, breaker_cooldown_s=30).start()
    srv = GatewayServer(gw, port=0).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        stub.kill()
        deadline = time.monotonic() + 5
        while gw.backends[0].routable() and time.monotonic() < deadline:
            time.sleep(0.02)
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base)
        assert exc.value.code == 503
        assert "Retry-After" in exc.value.headers
        with urllib.request.urlopen(base + "/v1/healthz") as r:
            pytest.fail(f"healthz should be 503, got {r.status}")
    except urllib.error.HTTPError as e:
        assert e.code == 503
    finally:
        srv.shutdown()
        gw.stop()


def test_gateway_rejects_bad_requests_locally():
    """Malformed client input never consumes a backend attempt."""
    stub = StubBackend("a")
    gw = Gateway([stub.url], probe_interval_s=60).start()
    srv = GatewayServer(gw, port=0).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        req = urllib.request.Request(base + "/v1/nope", data=b"{}")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 404
        req = urllib.request.Request(base + "/v1/classify", data=b"")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 400
        assert stub.requests == 0
    finally:
        srv.shutdown()
        gw.stop()
        stub.kill()


# -- integration: real serve stacks behind the gateway ---------------------


@pytest.fixture(scope="module")
def lenet_serving(tmp_path_factory):
    from deep_vision_tpu.serve.registry import ModelRegistry

    reg = ModelRegistry()
    sm = reg.load_checkpoint(
        "lenet5", str(tmp_path_factory.mktemp("lenet_gw_workdir")))
    return reg, sm


def test_real_backends_survive_kill(lenet_serving):
    """Two REAL LeNet serve stacks behind the gateway; hard-killing one
    mid-load loses zero admitted requests from the client's view."""
    from deep_vision_tpu.serve.engine import BatchingEngine
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    engines = [BatchingEngine(sm, buckets=[1, 4], max_wait_ms=2).start()
               for _ in range(2)]
    servers = [ServeServer(reg, {sm.name: eng}, port=0).start_background()
               for eng in engines]
    gw = Gateway([f"127.0.0.1:{s.port}" for s in servers],
                 probe_interval_s=0.05, request_timeout_s=30,
                 retry_budget=3, breaker_threshold=2,
                 breaker_cooldown_s=30).start()
    gsrv = GatewayServer(gw, port=0).start_background()
    base = f"http://127.0.0.1:{gsrv.port}"
    body = {"pixels": np.zeros((32, 32, 1)).tolist()}
    errors, oks = [], []
    lock = threading.Lock()
    stop = threading.Event()

    def client():
        while not stop.is_set():
            try:
                status, _, payload = _post(base, body, timeout=30)
                with lock:
                    oks.append(status)
                assert len(payload["top"]) == 5
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.5)
        # hard-kill backend 0 mid-load: sockets die like a SIGKILL
        servers[0].httpd.shutdown()
        servers[0].httpd.server_close()
        engines[0].stop(timeout=1)
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(30)
        assert errors == []
        assert len(oks) > 10 and all(s == 200 for s in oks)
        assert not gw.backends[0].routable()
        assert gw.backends[1].routable()
    finally:
        stop.set()
        gsrv.shutdown()
        gw.stop()
        for srv in servers[1:]:
            srv.shutdown()
        for eng in engines[1:]:
            eng.stop()


def test_drain_under_load_fails_no_admitted_request(lenet_serving):
    """POST /v1/drain mid-load: healthz flips to 503 immediately, the
    gateway routes away, and every admitted request still answers."""
    from deep_vision_tpu.serve.engine import BatchingEngine
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    engines = [BatchingEngine(sm, buckets=[1, 4], max_wait_ms=2).start()
               for _ in range(2)]
    servers = [ServeServer(reg, {sm.name: eng}, port=0).start_background()
               for eng in engines]
    gw = Gateway([f"127.0.0.1:{s.port}" for s in servers],
                 probe_interval_s=0.05, retry_budget=3).start()
    gsrv = GatewayServer(gw, port=0).start_background()
    base = f"http://127.0.0.1:{gsrv.port}"
    body = {"pixels": np.zeros((32, 32, 1)).tolist()}
    errors, oks = [], []
    lock = threading.Lock()
    stop = threading.Event()

    def client():
        while not stop.is_set():
            try:
                status, _, _ = _post(base, body, timeout=30)
                with lock:
                    oks.append(status)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.3)
        # drain backend 0 while the load is running
        req = urllib.request.Request(
            f"http://127.0.0.1:{servers[0].port}/v1/drain",
            data=json.dumps({"drain_deadline_s": 5}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert json.loads(r.read())["status"] == "draining"
        # its healthz answers 503 draining from now on
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{servers[0].port}/v1/healthz",
                timeout=5)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["status"] == "draining"
        # gateway sees it unavailable within a probe interval or two
        deadline = time.monotonic() + 5
        while gw.backends[0].routable() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert gw.backends[0].unavailable == "draining"
        assert gw.backends[0].breaker == CLOSED  # drain is not failure
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(30)
        assert errors == []           # zero admitted requests failed
        assert len(oks) > 10 and all(s == 200 for s in oks)
        # draining again is an idempotent no-op
        with urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{servers[0].port}/v1/drain",
                data=b""), timeout=30) as r:
            assert json.loads(r.read())["already_draining"] is True
    finally:
        stop.set()
        gsrv.shutdown()
        gw.stop()
        for srv in servers:
            srv.shutdown()
        for eng in engines:
            eng.stop()
