"""Multi-device serving (serve/replicas.py) under 8 forced host devices
(conftest pins XLA_FLAGS=--xla_force_host_platform_device_count=8):
routing spreads work over every replica, results stay bit-identical to
the single-engine path, a replica killed mid-load loses zero admitted
requests, and the sharded mega-batch path matches the unsharded
reference.  CPU-only and deterministic — the 8 "devices" share one
host, so these tests verify CORRECTNESS of placement/routing/failover,
not speedup (bench.py --serve --serve-devices measures that)."""

from concurrent.futures import wait

import numpy as np
import pytest

from deep_vision_tpu.serve.admission import AdmissionController, Shed
from deep_vision_tpu.serve.engine import BatchingEngine, sharded_buckets
from deep_vision_tpu.serve.faults import Quarantined
from deep_vision_tpu.serve.registry import ModelRegistry
from deep_vision_tpu.serve.replicas import ReplicatedEngine, local_devices

pytestmark = [pytest.mark.serve, pytest.mark.replicas]


@pytest.fixture(scope="module")
def lenet_serving(tmp_path_factory):
    reg = ModelRegistry()
    # empty workdir fixture → deterministic PRNGKey(0) random init
    sm = reg.load_checkpoint(
        "lenet5", str(tmp_path_factory.mktemp("replica_workdir")))
    return reg, sm


def _images(n, shape=(32, 32, 1)):
    return [np.random.RandomState(i).randn(*shape).astype(np.float32)
            for i in range(n)]


def _serve_all(engine, images, timeout=120):
    futs = [engine.submit(x) for x in images]
    wait(futs, timeout)
    return [f.result(0) for f in futs]


def test_local_devices_validation(host_devices):
    assert len(local_devices()) == len(host_devices)
    assert local_devices(3) == host_devices[:3]
    with pytest.raises(ValueError, match="only"):
        local_devices(len(host_devices) + 1)
    with pytest.raises(ValueError, match="at least 1"):
        local_devices(0)


def test_sharded_buckets_ladder():
    # every bucket a multiple of the device count, topping at max_batch
    assert sharded_buckets(32, 8) == [8, 16, 32]
    assert sharded_buckets(32, 4) == [4, 8, 16, 32]
    assert sharded_buckets(8, 8) == [8]
    assert sharded_buckets(32, 1) == [1, 2, 4, 8, 16, 32]


def test_routing_spreads_across_replicas(lenet_serving, host_devices):
    """8 replicas, mixed sequential + concurrent workload: every replica
    executes at least one batch (the round-robin tie-break keeps an
    idle fleet from piling onto replica 0), and the full response set
    is served."""
    _, sm = lenet_serving
    imgs = _images(48)
    with ReplicatedEngine(sm, devices=host_devices, max_batch=4,
                          max_wait_ms=1.0) as eng:
        # sequential singles — each forms its own batch, ties rotate
        for x in imgs[:16]:
            r = eng.infer(x, timeout=60)
            assert isinstance(r, np.ndarray)
        # then a concurrent burst
        results = _serve_all(eng, imgs[16:])
        assert all(isinstance(r, np.ndarray) for r in results)
        st = eng.stats()
    assert len(st["replicas"]) == 8
    per_replica = [r["batches"] for r in st["replicas"]]
    assert all(n >= 1 for n in per_replica), per_replica
    assert st["served"] == len(imgs)
    assert sum(r["routed_batches"] for r in st["replicas"]) \
        == st["batches"]
    # each replica is pinned to its own device
    assert len({r["device"] for r in st["replicas"]}) == 8


def test_replicated_bit_identical_to_single(lenet_serving, host_devices):
    _, sm = lenet_serving
    imgs = _images(32)
    with BatchingEngine(sm, max_batch=8, max_wait_ms=2.0,
                        watchdog_interval_s=0) as eng:
        ref = _serve_all(eng, imgs)
    with ReplicatedEngine(sm, devices=host_devices[:4], max_batch=8,
                          max_wait_ms=2.0) as eng:
        got = _serve_all(eng, imgs)
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dead_replica_reroute_serves_all_inflight(lenet_serving,
                                                  host_devices):
    """Kill a replica mid-load: its in-flight cohorts are evacuated and
    bisect-retried on a healthy replica — zero admitted requests are
    lost, routing masks the corpse, healthz stays serveable."""
    _, sm = lenet_serving
    imgs = _images(96)
    eng = ReplicatedEngine(sm, devices=host_devices[:3], max_batch=4,
                          max_wait_ms=5.0, watchdog_interval_s=0.02)
    with eng:
        eng.warmup([4])
        futs = [eng.submit(x) for x in imgs]
        eng.replicas[0].health.force_dead("test kill")
        wait(futs, 120)
        results = [f.result(0) for f in futs]
        st = eng.stats()
        health = eng.health_report()
    lost = [r for r in results
            if not isinstance(r, np.ndarray)
            and not isinstance(r, Quarantined)]
    assert not lost, f"{len(lost)} admitted requests lost: {lost[:3]}"
    assert st["served"] == len(imgs)
    assert st["replicas"][0]["state"] == "dead"
    assert st["routing"]["free_replicas"] == 2
    assert st["admission"]["free_replicas"] == 2
    # one dead replica degrades the fleet but does NOT take it down
    assert health["state"] == "degraded"
    assert health["can_serve"] is True
    assert health["replicas"]["0"]["state"] == "dead"


def test_all_replicas_dead_cannot_serve(lenet_serving, host_devices):
    _, sm = lenet_serving
    with ReplicatedEngine(sm, devices=host_devices[:2], max_batch=4,
                          max_wait_ms=1.0,
                          watchdog_interval_s=0.02) as eng:
        assert eng.infer(_images(1)[0], timeout=60) is not None
        for rep in eng.replicas:
            rep.health.force_dead("test kill")
        health = eng.health_report()
        assert health["state"] == "dead"
        assert health["can_serve"] is False
        # a batch formed with nobody routable sheds, it doesn't hang
        r = eng.infer(_images(1)[0], timeout=60)
        assert isinstance(r, Shed), r


def test_sharded_megabatch_equals_unsharded(lenet_serving, mesh8):
    """--shard-batches: one padded mega-batch laid across the 8-device
    data axis produces the same answers as the default single-device
    engine (allclose — SPMD partitioning may reorder reductions)."""
    _, sm = lenet_serving
    imgs = _images(24)
    smesh = sm.for_mesh(mesh8)
    buckets = sharded_buckets(32, 8)
    with BatchingEngine(smesh, max_batch=32, buckets=buckets,
                        max_wait_ms=20.0, watchdog_interval_s=0) as eng:
        got = _serve_all(eng, imgs)
        st = eng.stats()
    with BatchingEngine(sm, max_batch=32, max_wait_ms=20.0,
                        watchdog_interval_s=0) as eng:
        ref = _serve_all(eng, imgs)
    for a, b in zip(ref, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)
    assert st["buckets"] == buckets
    assert "sharded over 8 devices" in smesh.placement_desc()


def test_sharded_bucket_must_divide_mesh(lenet_serving, mesh8):
    _, sm = lenet_serving
    smesh = sm.for_mesh(mesh8)
    with pytest.raises(ValueError, match="not divisible"):
        smesh.compile_bucket(4)  # 4 % 8 != 0


def test_admission_divides_by_free_replicas():
    """The shed estimate's exec term divides by routable replicas (the
    drain window does not), and stats expose the divisor + per-bucket
    EWMAs (satellite: surfaced through /v1/stats)."""
    adm = AdmissionController(max_wait_ms=0.0)
    adm.observe_exec(0.100, bucket=8)
    base = adm.estimated_service_s(bucket=8, inflight=3)
    assert base == pytest.approx(0.4)
    adm.set_free_replicas(4)
    assert adm.estimated_service_s(bucket=8, inflight=3) \
        == pytest.approx(base / 4)
    # a callable divisor follows live replica state, floored at 1
    n = {"free": 0}
    adm.set_free_replicas(lambda: n["free"])
    assert adm.estimated_service_s(bucket=8, inflight=3) \
        == pytest.approx(base)
    n["free"] = 2
    assert adm.estimated_service_s(bucket=8, inflight=3) \
        == pytest.approx(base / 2)
    st = adm.stats()
    assert st["free_replicas"] == 2
    assert st["exec_ewma_ms_by_bucket"] == {"8": 100.0}


def test_replica_views_pin_devices(lenet_serving, host_devices):
    """for_device views: variables live on the view's device, outputs
    land there, and the base model's default placement is untouched."""
    import jax

    _, sm = lenet_serving
    view = sm.for_device(host_devices[3])
    leaf = jax.tree_util.tree_leaves(view._variables)[0]
    assert leaf.devices() == {host_devices[3]}
    fn = view.compile_bucket(2)
    out = fn(np.zeros((2, 32, 32, 1), np.float32))
    assert out.devices() == {host_devices[3]}
    assert sm.placement is None  # base model untouched
    assert str(host_devices[3]) in view.placement_desc()
