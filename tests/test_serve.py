"""Serving-engine contract (CPU, tier-1 fast): dynamic batching is
numerically invisible, bucket padding compiles once per bucket, doomed
requests are shed — never executed — and the pipelined executor (bounded
in-flight window, reused staging buffers, one bulk D2H per batch) is
bit-identical to the synchronous depth-1 path.

Uses LeNet at random init (the restore path's no-checkpoint fallback):
serving correctness is about request plumbing, not learned weights."""

import json
import socket
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from deep_vision_tpu.core.metrics import LatencyHistogram
from deep_vision_tpu.serve.admission import AdmissionController, Shed
from deep_vision_tpu.serve.engine import BatchingEngine, power_of_two_buckets
from deep_vision_tpu.serve.registry import ModelRegistry

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def lenet_serving(tmp_path_factory):
    reg = ModelRegistry()
    # empty workdir fixture → deterministic PRNGKey(0) random init
    sm = reg.load_checkpoint(
        "lenet5", str(tmp_path_factory.mktemp("lenet_workdir")))
    return reg, sm


def _images(n, shape=(32, 32, 1)):
    return [np.random.RandomState(i).randn(*shape).astype(np.float32)
            for i in range(n)]


def test_batching_invariance(lenet_serving):
    """N concurrent single requests == one N-batch call, bit-identical."""
    _, sm = lenet_serving
    imgs = _images(8)
    with BatchingEngine(sm, buckets=[8], max_wait_ms=250) as eng:
        futures = [eng.submit(im) for im in imgs]
        rows = [np.asarray(f.result(60)) for f in futures]
        assert eng.batches == 1  # all 8 coalesced into one execution
    ref = np.asarray(sm.compile_bucket(8)(np.stack(imgs)))
    for i in range(8):
        assert np.array_equal(rows[i], ref[i])


def test_bucket_padding_compiles_once(lenet_serving):
    """Waves of 3 and 5 both pad to the 8-bucket: one compile total."""
    _, sm = lenet_serving
    imgs = _images(8)
    with BatchingEngine(sm, buckets=[8], max_wait_ms=100) as eng:
        for f in [eng.submit(im) for im in imgs[:3]]:
            assert f.result(60) is not None
        assert eng.compiles == 1
        for f in [eng.submit(im) for im in imgs[:5]]:
            assert f.result(60) is not None
        assert eng.compiles == 1  # second wave hit the compiled bucket
        assert eng.batches == 2
        assert eng.served == 8
        assert eng.padded_images == (8 - 3) + (8 - 5)


def test_expired_deadline_is_shed_not_executed(lenet_serving):
    _, sm = lenet_serving
    img = _images(1)[0]
    with BatchingEngine(sm, buckets=[4], max_wait_ms=5) as eng:
        assert eng.infer(img) is not None  # prime EWMA + compile
        served = eng.served
        result = eng.infer(img, deadline_ms=0.0)
        assert isinstance(result, Shed)
        assert result.reason == "deadline"
        assert not result  # Shed is falsy: `if result:` reads as served
        assert eng.served == served  # never executed
        assert eng.admission.stats()["shed_deadline"] == 1


def test_queue_full_is_shed(lenet_serving):
    import time

    from deep_vision_tpu.serve.faults import FaultPlane

    _, sm = lenet_serving
    img = _images(1)[0]
    # kill the batcher on its first iteration (watchdog disabled, so it
    # stays dead): the queue backs up while submits stay accepted
    eng = BatchingEngine(sm, buckets=[1],
                         admission=AdmissionController(max_queue=1),
                         faults=FaultPlane("batcher:die:times=1"),
                         watchdog_interval_s=0).start()
    deadline = time.monotonic() + 10
    while eng._thread.is_alive() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert not eng._thread.is_alive()
    # nothing drains: the first request parks in the queue, the second
    # exceeds max_queue and must shed immediately — with a Retry-After
    # hint so HTTP clients can back off against another replica
    first = eng.submit(img)
    second = eng.submit(img).result(1)
    assert isinstance(second, Shed) and second.reason == "queue_full"
    assert second.retry_after_s is None or second.retry_after_s >= 0
    eng.stop()  # drains the queue: parked request sheds as shutdown
    assert first.result(1).reason == "shutdown"
    # and once stopped, submits fail fast instead of parking forever
    assert eng.submit(img).result(1).reason == "shutdown"


def test_power_of_two_buckets():
    assert power_of_two_buckets(8) == [1, 2, 4, 8]
    assert power_of_two_buckets(24) == [1, 2, 4, 8, 16, 24]
    assert power_of_two_buckets(1) == [1]


def test_latency_histogram_quantiles_and_merge():
    h = LatencyHistogram()
    for ms in range(1, 101):  # 1..100 ms, uniform
        h.record(ms / 1e3)
    p = h.percentiles()
    assert p["count"] == 100
    # log-spaced bins: quantiles are bin midpoints, ~12% relative error
    assert 40 <= p["p50_ms"] <= 62
    assert 83 <= p["p95_ms"] <= 110
    assert 86 <= p["p99_ms"] <= 115
    # mergeable: two half-histograms sum to the full one
    a, b = LatencyHistogram(), LatencyHistogram()
    for ms in range(1, 51):
        a.record(ms / 1e3)
    for ms in range(51, 101):
        b.record(ms / 1e3)
    a.merge(b.state_dict())
    assert a.total == 100
    merged = a.percentiles()
    assert merged == pytest.approx(p)  # mean differs only by fp sum order
    with pytest.raises(ValueError):
        a.merge(LatencyHistogram(bins_per_decade=5).state_dict())


def test_http_roundtrip(lenet_serving):
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    eng = BatchingEngine(sm, buckets=[4], max_wait_ms=2).start()
    srv = ServeServer(reg, {sm.name: eng}, port=0).start_background()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with urllib.request.urlopen(base + "/v1/healthz") as r:
            assert r.status == 200
            assert json.loads(r.read())["models"] == ["lenet5"]
        body = json.dumps(
            {"pixels": np.zeros((32, 32, 1)).tolist()}).encode()
        req = urllib.request.Request(
            base + "/v1/classify", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
            top = json.loads(r.read())["top"]
            assert len(top) == 5
        # expired deadline surfaces as 429, not a late answer
        body = json.dumps({"pixels": np.zeros((32, 32, 1)).tolist(),
                           "deadline_ms": 0}).encode()
        req = urllib.request.Request(
            base + "/v1/classify", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 429
        with urllib.request.urlopen(base + "/v1/stats") as r:
            stats = json.loads(r.read())["lenet5"]
            assert stats["served"] >= 1
            assert stats["latency"]["count"] >= 1
            assert stats["admission"]["shed_deadline"] >= 1
    finally:
        srv.shutdown()
        eng.stop()


def test_slow_loris_cannot_pin_handler(lenet_serving):
    """A client that opens a socket and never sends a request line is
    disconnected after the per-connection timeout instead of holding a
    handler thread forever; a healthy client still gets served after."""
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    eng = BatchingEngine(sm, buckets=[4], max_wait_ms=2).start()
    srv = ServeServer(reg, {sm.name: eng}, port=0,
                      socket_timeout_s=0.3).start_background()
    try:
        loris = socket.create_connection(("127.0.0.1", srv.port))
        loris.settimeout(5)
        # send NOTHING: the server must close the connection on its own
        assert loris.recv(1) == b""  # EOF = server hung up
        loris.close()
        # the handler thread is free again: normal traffic unaffected
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/healthz", timeout=5) as r:
            assert r.status == 200
    finally:
        srv.shutdown()
        eng.stop()


def test_stalled_body_answers_408(lenet_serving):
    """Headers arrive but the body stalls: the server answers 408 and
    closes, instead of blocking in rfile.read until the client gives up."""
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    eng = BatchingEngine(sm, buckets=[4], max_wait_ms=2).start()
    srv = ServeServer(reg, {sm.name: eng}, port=0,
                      socket_timeout_s=0.3).start_background()
    try:
        conn = socket.create_connection(("127.0.0.1", srv.port))
        conn.settimeout(5)
        conn.sendall(b"POST /v1/classify HTTP/1.1\r\n"
                     b"Host: x\r\nContent-Type: application/json\r\n"
                     b"Content-Length: 1000\r\n\r\n{\"pix")  # ...stall
        reply = b""
        while b"\r\n\r\n" not in reply:
            chunk = conn.recv(4096)
            if not chunk:
                break
            reply += chunk
        assert b"408" in reply.split(b"\r\n", 1)[0]
        conn.close()
    finally:
        srv.shutdown()
        eng.stop()


def test_exported_blob_serving(lenet_serving, tmp_path):
    """StableHLO path: registry loads a cli.infer-export artifact and the
    engine serves it at the blob's fixed batch, matching direct apply."""
    import jax

    from deep_vision_tpu.core.export import export_forward

    reg, sm = lenet_serving
    variables = sm._variables
    path = str(tmp_path / "lenet.stablehlo")
    export_forward(sm._model, variables, (4, 32, 32, 1), path)
    sm2 = reg.load_exported("lenet5", path, str(tmp_path / "no_ckpt"),
                            name="lenet5_hlo")
    assert sm2.fixed_batch == 4
    imgs = _images(4)
    with BatchingEngine(sm2, max_wait_ms=100) as eng:
        assert eng.buckets == [4]
        rows = [np.asarray(f.result(60))
                for f in [eng.submit(im) for im in imgs]]
    ref = np.asarray(sm._model.apply(variables, jax.numpy.asarray(
        np.stack(imgs)), train=False))
    np.testing.assert_allclose(np.stack(rows), ref, atol=1e-5)


def test_exported_blob_unavailable_bucket_names_sizes(lenet_serving,
                                                      tmp_path):
    """Asking a StableHLO blob for a bucket it wasn't exported with
    raises an error naming the exported sizes and the fix — not XLA
    shape-mismatch noise."""
    from deep_vision_tpu.core.export import export_forward

    reg, sm = lenet_serving
    path = str(tmp_path / "lenet_b4.stablehlo")
    export_forward(sm._model, sm._variables, (4, 32, 32, 1), path)
    sm2 = reg.load_exported("lenet5", path, str(tmp_path / "no_ckpt"),
                            name="lenet5_hlo_b4")
    assert sm2.bucket_sizes == [4]
    with pytest.raises(ValueError) as ei:
        sm2.compile_bucket(8)
    msg = str(ei.value)
    assert "exported with bucket sizes [4]" in msg
    assert "batch 8" in msg and "re-export" in msg
    # the compiled callable guards runtime shapes with the same message
    run = sm2.compile_bucket(4)
    with pytest.raises(ValueError, match=r"bucket sizes \[4\]"):
        run(np.zeros((2, 32, 32, 1), np.float32))
    # an engine configured with conflicting buckets refuses at build
    with pytest.raises(ValueError, match=r"bucket sizes \[4\]"):
        BatchingEngine(sm2, buckets=[2, 4])


def test_pipelined_bit_identical_to_sync(lenet_serving):
    """The same request stream through pipeline_depth=2 and the
    synchronous depth=1 path yields bit-identical rows."""
    _, sm = lenet_serving
    imgs = _images(16)

    def run(depth):
        with BatchingEngine(sm, buckets=[1, 2, 4], max_wait_ms=2,
                            pipeline_depth=depth) as eng:
            rows = [np.asarray(f.result(60))
                    for f in [eng.submit(im) for im in imgs]]
            stats = eng.stats()
        return rows, stats

    sync_rows, sync_stats = run(1)
    pipe_rows, pipe_stats = run(2)
    for a, b in zip(sync_rows, pipe_rows):
        assert np.array_equal(a, b)
    assert sync_stats["pipeline"]["depth"] == 1
    assert pipe_stats["pipeline"]["depth"] == 2


def test_one_bulk_transfer_per_batch(lenet_serving):
    """The acceptance contract: the result scatter performs EXACTLY one
    device→host transfer per executed batch — counted, not eyeballed —
    and moves the whole padded output (bucket rows × 10 logits f32)."""
    _, sm = lenet_serving
    imgs = _images(8)
    with BatchingEngine(sm, buckets=[8], max_wait_ms=250,
                        pipeline_depth=2) as eng:
        for f in [eng.submit(im) for im in imgs]:
            assert f.result(60) is not None
        stats = eng.stats()
    pipe = stats["pipeline"]
    assert stats["batches"] == 1
    assert pipe["bulk_transfers"] == stats["batches"]
    assert pipe["bulk_transfer_bytes"] == 8 * 10 * 4


def test_inflight_window_bounded(lenet_serving):
    """Under a flood of tiny batches the dispatched-but-undrained window
    never exceeds pipeline_depth."""
    _, sm = lenet_serving
    imgs = _images(2)
    with BatchingEngine(sm, buckets=[1, 2], max_wait_ms=0.5,
                        pipeline_depth=2) as eng:
        futures = [eng.submit(imgs[k % 2]) for k in range(40)]
        for f in futures:
            assert f.result(60) is not None
        stats = eng.stats()
    assert stats["served"] == 40
    assert 1 <= stats["pipeline"]["max_inflight"] <= 2
    assert stats["pipeline"]["inflight"] == 0  # all drained at stop


def test_staged_buffers_reused(lenet_serving):
    """Many batches into one bucket allocate at most depth+1 staging
    buffers — the rest are reuses, never per-batch np.zeros."""
    _, sm = lenet_serving
    imgs = _images(4)
    with BatchingEngine(sm, buckets=[4], max_wait_ms=250,
                        pipeline_depth=2) as eng:
        for _ in range(6):  # 6 sequential full batches, same bucket
            for f in [eng.submit(im) for im in imgs]:
                assert f.result(60) is not None
        stats = eng.stats()
    staging = stats["pipeline"]["staging"]
    assert stats["batches"] == 6
    assert staging["allocated"] <= 3  # depth + 1
    assert staging["reused"] == stats["batches"] - staging["allocated"]


def test_per_bucket_ewma(lenet_serving):
    """Mixed bucket sizes train SEPARATE exec-time EWMAs, and each
    converges to its own bucket's service time."""
    from deep_vision_tpu.serve.admission import AdmissionController

    adm = AdmissionController(max_wait_ms=1.0)
    for _ in range(50):
        adm.observe_exec(0.002, bucket=1)
        adm.observe_exec(0.020, bucket=8)
    by_bucket = adm.stats()["exec_ewma_ms_by_bucket"]
    assert by_bucket["1"] == pytest.approx(2.0, rel=0.05)
    assert by_bucket["8"] == pytest.approx(20.0, rel=0.05)
    # feasibility uses the bucket that will actually run: a 12 ms
    # deadline is feasible for the 1-bucket, doomed for the 8-bucket
    now = 1000.0
    assert adm.admit(0, now + 0.012, now, bucket=1) is None
    shed = adm.admit(0, now + 0.012, now, bucket=8)
    assert isinstance(shed, Shed) and shed.reason == "deadline"
    # each in-flight batch ahead adds one more execution to the estimate
    assert adm.estimated_service_s(bucket=8, inflight=2) == pytest.approx(
        0.001 + 3 * 0.020, rel=0.06)
    # engine end-to-end: serving mixed sizes populates both EWMAs
    _, sm = lenet_serving
    with BatchingEngine(sm, buckets=[1, 8], max_wait_ms=1,
                        pipeline_depth=2) as eng:
        assert eng.infer(_images(1)[0], timeout=60) is not None
        for f in [eng.submit(im) for im in _images(8)]:
            assert f.result(60) is not None
        by_bucket = eng.stats()["admission"]["exec_ewma_ms_by_bucket"]
    assert "1" in by_bucket and "8" in by_bucket


def test_concurrent_submitters_all_answered(lenet_serving):
    """Many client threads, small buckets: every request gets exactly one
    result and none are lost across batch boundaries."""
    _, sm = lenet_serving
    imgs = _images(4)
    results = []
    lock = threading.Lock()
    with BatchingEngine(sm, buckets=[1, 2, 4], max_wait_ms=5) as eng:
        def client(k):
            row = eng.infer(imgs[k % 4], timeout=60)
            with lock:
                results.append(row)

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        stats = eng.stats()
    assert len(results) == 12
    assert all(r is not None and not isinstance(r, Shed) for r in results)
    assert stats["served"] == 12
    assert stats["latency"]["count"] == 12
