"""Golden-run regression fixture (VERDICT r3 missing #2): a committed
seeded loss trace plays the regression role of the reference's committed
training logs (ResNet/pytorch/logs/resnet34-yanjiali-010319.log) until
real-data artifacts exist — a numerics change anywhere in the trainer
stack (loss scaling, BN update, optimizer wiring, LR plumbing, data
pipeline determinism) shifts the replayed losses outside tolerance.

Regenerate intentionally with:
    GOLDEN_UPDATE=1 python -m pytest tests/test_golden_run.py -m slow -q
"""

import json
import os

import jax
import numpy as np
import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
FIXTURE = os.path.join(FIXTURES, "golden_resnet50_cpu.json")
STEPS = 20


def _check_or_update(losses, path, meta):
    """Shared replay/regenerate mechanics for all golden traces."""
    assert np.isfinite(losses).all()
    if os.environ.get("GOLDEN_UPDATE"):
        os.makedirs(FIXTURES, exist_ok=True)
        with open(path, "w") as f:
            json.dump({**meta, "platform": "cpu-1dev", "steps": STEPS,
                       "dtype": "float32", "losses": losses}, f, indent=1)
        pytest.skip(f"fixture regenerated at {path}")
    with open(path) as f:
        golden = json.load(f)
    # tolerance covers XLA-version fusion drift, not semantic changes:
    # any real numerics regression moves the late-step losses by far more
    np.testing.assert_allclose(losses, golden["losses"],
                               rtol=2e-3, atol=2e-3)


def _golden_run(tmp_path):
    """Seeded 20-step ResNet-50 run on synthetic data, single CPU device,
    f32 (bf16 CPU emulation would add platform noise)."""
    import jax.numpy as jnp

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.loader import ArrayLoader
    from deep_vision_tpu.data.synthetic import synthetic_classification
    from deep_vision_tpu.models.resnet import ResNet50
    from deep_vision_tpu.parallel import make_mesh
    from deep_vision_tpu.tasks.classification import ClassificationTask

    cfg = get_config("resnet50")
    cfg.batch_size = 8
    cfg.image_size = 64
    cfg.half_precision = False
    cfg.model = lambda: ResNet50(dtype=jnp.float32)
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    trainer = Trainer(cfg, cfg.model(), ClassificationTask(cfg.num_classes),
                      mesh=mesh, workdir=str(tmp_path))
    data = synthetic_classification(8 * STEPS, cfg.image_size, 3,
                                    cfg.num_classes, seed=11)
    loader = ArrayLoader(data, cfg.batch_size, seed=13, shuffle=False)
    state = trainer.init_state(next(iter(loader)))
    losses = []
    for i, batch in enumerate(loader):
        if i >= STEPS:
            break
        state, metrics = trainer.train_step(state, dict(batch))
        losses.append(float(jax.device_get(metrics["loss"])))
    return losses


def _run_steps(trainer, loader):
    """Seeded STEPS-step trace: cycles the loader across passes (toy
    datasets are one batch per pass) — the pass-level reshuffle comes from
    the loader's own seeded rng, so the trace is run-to-run deterministic."""
    state = trainer.init_state(next(iter(loader)))
    losses = []
    while len(losses) < STEPS:
        for batch in loader:
            state, metrics = trainer.train_step(state, dict(batch))
            losses.append(float(jax.device_get(metrics["loss"])))
            if len(losses) >= STEPS:
                break
    return losses


@pytest.mark.slow
def test_golden_resnet50_trace_replays(tmp_path):
    losses = _golden_run(tmp_path)
    _check_or_update(losses, FIXTURE,
                     {"model": "resnet50", "image_size": 64,
                      "batch_size": 8})


@pytest.mark.slow
def test_golden_yolo_trace_replays(tmp_path):
    """Detection golden trace (VERDICT r4 weak #6): protects the label
    scatter (anchor assignment + 3-scale grid encode) and the 4-term YOLO
    loss — a codec regression fails here in seconds instead of only via
    the 150-epoch convergence test."""
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.detection import (
        DetectionLoader,
        synthetic_detection_dataset,
    )
    from deep_vision_tpu.parallel import make_mesh
    from deep_vision_tpu.tasks.detection import YoloTask

    cfg = get_config("yolov3_toy")
    samples = synthetic_detection_dataset(8, 64, 3, seed=3)
    loader = DetectionLoader(samples, 8, 3, 64, train=True, augment=False,
                             seed=0)
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    trainer = Trainer(cfg, cfg.model(), YoloTask(3), mesh=mesh,
                      workdir=str(tmp_path))
    losses = _run_steps(trainer, loader)
    _check_or_update(losses,
                     os.path.join(FIXTURES, "golden_yolo_toy_cpu.json"),
                     {"model": "yolov3_toy", "image_size": 64,
                      "batch_size": 8})


@pytest.mark.slow
def test_golden_dcgan_trace_replays():
    """Adversarial golden trace: protects the twin G/D step numerics
    (simultaneous updates, BCE-from-logits, latent sampling) — the last
    task family whose step had no committed trace (VERDICT r4 weak #6)."""
    import jax.numpy as jnp

    from deep_vision_tpu.core.optim import OptimizerConfig
    from deep_vision_tpu.models.gan import DCGANDiscriminator, DCGANGenerator
    from deep_vision_tpu.tasks.gan import DCGANTask

    task = DCGANTask(DCGANGenerator(), DCGANDiscriminator(), latent_dim=16,
                     opt=OptimizerConfig(name="adam", learning_rate=2e-4,
                                         b1=0.5))
    rng = jax.random.PRNGKey(7)
    data = np.random.default_rng(7).uniform(
        -1, 1, (8, 28, 28, 1)).astype(np.float32)
    batch = {"image": jnp.asarray(data)}
    states = task.init_states(rng, batch)
    step = jax.jit(task.train_step)
    losses = []
    for i in range(STEPS):
        states, _, metrics = step(states, batch, jax.random.fold_in(rng, i))
        losses.append([float(jax.device_get(metrics["g_loss"])),
                       float(jax.device_get(metrics["d_loss"]))])
    _check_or_update(losses,
                     os.path.join(FIXTURES, "golden_dcgan_cpu.json"),
                     {"model": "dcgan", "image_size": 28, "batch_size": 8})


@pytest.mark.slow
def test_golden_hourglass_trace_replays(tmp_path):
    """Pose golden trace: protects the Gaussian heatmap target generation
    and weighted-MSE intermediate supervision numerics."""
    import jax.numpy as jnp

    from deep_vision_tpu.core.config import TrainConfig
    from deep_vision_tpu.core.optim import OptimizerConfig
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.pose import PoseLoader, synthetic_pose_dataset
    from deep_vision_tpu.models.hourglass import StackedHourglass
    from deep_vision_tpu.parallel import make_mesh
    from deep_vision_tpu.tasks.pose import PoseTask

    K = 4
    cfg = TrainConfig(
        name="hg_toy",
        model=lambda: StackedHourglass(num_stack=1, num_heatmap=K,
                                       filters=32, dtype=jnp.float32),
        task="pose", batch_size=8, total_epochs=1,
        optimizer=OptimizerConfig(name="adam", learning_rate=2e-3),
        image_size=64, num_classes=K, half_precision=False,
        checkpoint_every_epochs=1000)
    samples = synthetic_pose_dataset(8, 64, K, seed=5)
    loader = PoseLoader(samples, 8, 64, 16, K, train=True, seed=0)
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    trainer = Trainer(cfg, cfg.model(), PoseTask(), mesh=mesh,
                      workdir=str(tmp_path))
    losses = _run_steps(trainer, loader)
    _check_or_update(losses,
                     os.path.join(FIXTURES, "golden_hourglass_toy_cpu.json"),
                     {"model": "hourglass_toy", "image_size": 64,
                      "batch_size": 8})
