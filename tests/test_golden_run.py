"""Golden-run regression fixture (VERDICT r3 missing #2): a committed
seeded loss trace plays the regression role of the reference's committed
training logs (ResNet/pytorch/logs/resnet34-yanjiali-010319.log) until
real-data artifacts exist — a numerics change anywhere in the trainer
stack (loss scaling, BN update, optimizer wiring, LR plumbing, data
pipeline determinism) shifts the replayed losses outside tolerance.

Regenerate intentionally with:
    GOLDEN_UPDATE=1 python -m pytest tests/test_golden_run.py -m slow -q
"""

import json
import os

import jax
import numpy as np
import pytest

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "golden_resnet50_cpu.json")
STEPS = 20


def _golden_run(tmp_path):
    """Seeded 20-step ResNet-50 run on synthetic data, single CPU device,
    f32 (bf16 CPU emulation would add platform noise)."""
    import jax.numpy as jnp

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.loader import ArrayLoader
    from deep_vision_tpu.data.synthetic import synthetic_classification
    from deep_vision_tpu.models.resnet import ResNet50
    from deep_vision_tpu.parallel import make_mesh
    from deep_vision_tpu.tasks.classification import ClassificationTask

    cfg = get_config("resnet50")
    cfg.batch_size = 8
    cfg.image_size = 64
    cfg.half_precision = False
    cfg.model = lambda: ResNet50(dtype=jnp.float32)
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    trainer = Trainer(cfg, cfg.model(), ClassificationTask(cfg.num_classes),
                      mesh=mesh, workdir=str(tmp_path))
    data = synthetic_classification(8 * STEPS, cfg.image_size, 3,
                                    cfg.num_classes, seed=11)
    loader = ArrayLoader(data, cfg.batch_size, seed=13, shuffle=False)
    state = trainer.init_state(next(iter(loader)))
    losses = []
    for i, batch in enumerate(loader):
        if i >= STEPS:
            break
        state, metrics = trainer.train_step(state, dict(batch))
        losses.append(float(jax.device_get(metrics["loss"])))
    return losses


@pytest.mark.slow
def test_golden_resnet50_trace_replays(tmp_path):
    losses = _golden_run(tmp_path)
    assert np.isfinite(losses).all()
    if os.environ.get("GOLDEN_UPDATE"):
        os.makedirs(os.path.dirname(FIXTURE), exist_ok=True)
        with open(FIXTURE, "w") as f:
            json.dump({"model": "resnet50", "image_size": 64,
                       "batch_size": 8, "dtype": "float32",
                       "platform": "cpu-1dev", "steps": STEPS,
                       "losses": losses}, f, indent=1)
        pytest.skip(f"fixture regenerated at {FIXTURE}")
    with open(FIXTURE) as f:
        golden = json.load(f)
    # tolerance covers XLA-version fusion drift, not semantic changes:
    # any real trainer-numerics regression moves step-20 loss by far more
    np.testing.assert_allclose(losses, golden["losses"],
                               rtol=2e-3, atol=2e-3)
