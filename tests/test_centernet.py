"""CenterNet tests: label splat, focal loss fixtures, decode roundtrip,
model shapes — the subsystem the reference left unfinished."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.models.centernet import CenterNet
from deep_vision_tpu.tasks import centernet as C


def test_gaussian_radius_monotone():
    r_small = C.gaussian_radius(np.array([4.0]), np.array([4.0]))[0]
    r_big = C.gaussian_radius(np.array([32.0]), np.array([32.0]))[0]
    assert 0 < r_small < r_big


def test_encode_labels_peak_and_targets():
    boxes = np.array([[0.5, 0.5, 0.25, 0.25]], np.float32)  # center cell 32
    enc = C.encode_centernet_labels(boxes, np.array([2]), num_classes=4,
                                    grid=64)
    assert enc["heatmap"][32, 32, 2] == 1.0
    assert enc["heatmap"][:, :, 0].sum() == 0.0
    assert enc["obj_mask"].sum() == 1.0
    np.testing.assert_allclose(enc["wh"][0], [16.0, 16.0])
    assert enc["indices"][0] == 32 * 64 + 32
    assert 0 <= enc["offset"][0][0] < 1 and 0 <= enc["offset"][0][1] < 1


def test_focal_loss_perfect_vs_wrong():
    gt = np.zeros((1, 8, 8, 1), np.float32)
    gt[0, 3, 3, 0] = 1.0
    gt_j = jnp.asarray(gt)
    perfect = jnp.where(gt_j >= 1.0, 15.0, -15.0)
    wrong = -perfect
    l_perfect = float(C.focal_loss(perfect, gt_j)[0])
    l_wrong = float(C.focal_loss(wrong, gt_j)[0])
    assert l_perfect < 1e-4
    assert l_wrong > 5.0


def test_decode_recovers_encoded_object():
    boxes = np.array([[0.5, 0.5, 0.25, 0.25]], np.float32)
    enc = C.encode_centernet_labels(boxes, np.array([1]), num_classes=3,
                                    grid=32)
    heat_logits = jnp.asarray(
        np.where(enc["heatmap"] >= 1.0, 10.0, -10.0))[None]
    wh = jnp.zeros((1, 32, 32, 2)).at[0, 16, 16].set(jnp.asarray([8.0, 8.0]))
    offset = jnp.zeros((1, 32, 32, 2))
    dboxes, scores, cls = C.decode_detections(heat_logits, wh, offset, k=5)
    assert int(cls[0, 0]) == 1
    assert float(scores[0, 0]) > 0.99
    np.testing.assert_allclose(
        np.asarray(dboxes[0, 0]), [12.0, 12.0, 20.0, 20.0], atol=1e-4)


def test_centernet_model_shapes():
    # order-5 module needs ≥32² after the /4 stem → 128² input minimum
    model = CenterNet(num_classes=5, num_stack=2)
    x = jnp.zeros((1, 128, 128, 3))
    variables = jax.eval_shape(
        lambda a: model.init({"params": jax.random.PRNGKey(0)}, a,
                             train=False), x)
    outs = jax.eval_shape(
        lambda v, a: model.apply(v, a, train=False), variables, x)
    assert len(outs) == 2
    heat, wh, offset = outs[0]
    assert heat.shape == (1, 32, 32, 5)   # /4 resolution
    assert wh.shape == (1, 32, 32, 2)
    assert offset.shape == (1, 32, 32, 2)


def test_task_loss_finite_and_decreasing_signal():
    task = C.CenterNetTask(num_classes=3)
    boxes = np.array([[0.4, 0.6, 0.2, 0.3]], np.float32)
    enc = C.encode_centernet_labels(boxes, np.array([0]), num_classes=3,
                                    grid=16)
    batch = {k: jnp.asarray(v)[None] for k, v in enc.items()}
    G = 16
    zero_out = [(jnp.zeros((1, G, G, 3)), jnp.zeros((1, G, G, 2)),
                 jnp.zeros((1, G, G, 2)))]
    perfect_heat = jnp.where(batch["heatmap"] >= 1.0, 15.0, -15.0)
    # wh/offset exact at the object cell
    wh_map = jnp.zeros((1, G, G, 2))
    off_map = jnp.zeros((1, G, G, 2))
    idx = int(enc["indices"][0])
    wh_map = wh_map.at[0, idx // G, idx % G].set(jnp.asarray(enc["wh"][0]))
    off_map = off_map.at[0, idx // G, idx % G].set(
        jnp.asarray(enc["offset"][0]))
    perfect_out = [(perfect_heat, wh_map, off_map)]
    l_zero, _ = task.loss(zero_out, batch)
    l_perfect, _ = task.loss(perfect_out, batch)
    assert float(l_perfect) < 0.05
    assert float(l_zero) > float(l_perfect) + 0.5
