"""Observability contract (CPU, tier-1 fast): per-request spans whose
breakdown sums exactly to the measured total, request-id propagation
across a REAL gateway→backend hop, Prometheus text that parses line by
line, fleet histogram merging that matches a recomputation, serving-MFU
sanity under load, and structured JSON-line logging.

Uses LeNet at random init like test_serve.py: observability is about
plumbing, not learned weights."""

import json
import logging
import re
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from deep_vision_tpu.core.metrics import LatencyHistogram, PromText
from deep_vision_tpu.obs.log import configure_logging, event, get_logger
from deep_vision_tpu.obs.mfu import MfuMeter
from deep_vision_tpu.obs.trace import REQUEST_ID_HEADER, Span, Tracer
from deep_vision_tpu.serve.engine import BatchingEngine
from deep_vision_tpu.serve.registry import ModelRegistry

pytestmark = pytest.mark.obs


@pytest.fixture(scope="module")
def lenet_serving(tmp_path_factory):
    reg = ModelRegistry()
    # empty workdir fixture → deterministic PRNGKey(0) random init
    sm = reg.load_checkpoint(
        "lenet5", str(tmp_path_factory.mktemp("lenet_workdir")))
    return reg, sm


def _images(n, shape=(32, 32, 1)):
    return [np.random.RandomState(i).randn(*shape).astype(np.float32)
            for i in range(n)]


# -- Prometheus text format -------------------------------------------------

_SAMPLE_RE = re.compile(
    r"([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_prom(text: str) -> dict:
    """Validate EVERY line of a text exposition; return
    ``{name: {frozenset(labels): value}}``."""
    assert text.endswith("\n"), "exposition must end with a newline"
    samples: dict = {}
    typed: set = set()
    for line in text.splitlines():
        assert line == line.strip() and line, f"blank/padded line {line!r}"
        if line.startswith("# TYPE "):
            _, _, name, typ = line.split(" ", 3)
            assert typ in ("counter", "gauge", "histogram"), line
            assert name not in typed, f"duplicate TYPE for {name}"
            typed.add(name)
            continue
        if line.startswith("# HELP "):
            assert len(line.split(" ", 3)) == 4, line
            continue
        assert not line.startswith("#"), f"unknown comment {line!r}"
        m = _SAMPLE_RE.fullmatch(line)
        assert m, f"unparseable sample line {line!r}"
        name, rawlabels, value = m.groups()
        labels = {}
        if rawlabels:
            inner = rawlabels[1:-1]
            labels = dict(_LABEL_RE.findall(inner))
            # nothing between the matched pairs but commas
            assert _LABEL_RE.sub("", inner).strip(",") == "", line
        v = float("inf") if value == "+Inf" else float(value)
        samples.setdefault(name, {})[
            frozenset(labels.items())] = v
        # every sample's base name must have a TYPE declaration
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert name in typed or base in typed, f"untyped sample {name}"
    return samples


def test_prom_text_rendering():
    p = PromText()
    p.counter("t_total", 3, {"model": "m"}, help="a counter")
    p.counter("t_total", 4, {"model": 'q"uote\n'})  # HELP/TYPE once
    p.gauge("t_gauge", 0.25, help="a gauge")
    p.gauge("t_skipped", None)  # None samples are absent, never 0
    text = p.render()
    samples = _parse_prom(text)
    assert samples["t_total"][frozenset({("model", "m")})] == 3
    assert samples["t_gauge"][frozenset()] == 0.25
    assert "t_skipped" not in samples
    assert text.count("# TYPE t_total counter") == 1


def test_prom_histogram_cumulative_buckets():
    h = LatencyHistogram()
    obs = [1e-5, 1e-3, 1e-2, 1e-2, 5e3]  # underflow + overflow included
    for s in obs:
        h.record(s)
    p = PromText()
    p.histogram("lat_seconds", h.state_dict(), {"model": "m"},
                help="latency")
    samples = _parse_prom(p.render())
    buckets = [(dict(k).get("le"), v)
               for k, v in samples["lat_seconds_bucket"].items()]
    # every edge emitted, cumulative counts non-decreasing, +Inf = total
    assert len(buckets) == len(h.edges) + 1
    ordered = sorted(buckets, key=lambda kv: float(kv[0]))
    values = [v for _, v in ordered]
    assert values == sorted(values)
    assert values[0] >= 1  # the underfow observation folds into edge 0
    assert values[-1] == len(obs)  # +Inf parses as inf → sorts last
    assert samples["lat_seconds_count"][
        frozenset({("model", "m")})] == len(obs)
    assert samples["lat_seconds_sum"][
        frozenset({("model", "m")})] == pytest.approx(sum(obs))


def test_histogram_merge_matches_recompute():
    """The gateway's fleet-p99 contract: merging per-backend histogram
    states must give the SAME quantiles as one histogram that saw every
    observation directly."""
    rng = np.random.RandomState(0)
    a, b, ref = (LatencyHistogram(), LatencyHistogram(),
                 LatencyHistogram())
    for s in rng.lognormal(-4, 1, 500):
        a.record(s)
        ref.record(s)
    for s in rng.lognormal(-2, 0.5, 300):
        b.record(s)
        ref.record(s)
    merged = LatencyHistogram()
    merged.load_state_dict(a.state_dict())
    merged.merge(b.state_dict())
    assert merged.total == ref.total == 800
    mp, rp = merged.percentiles(), ref.percentiles()
    for k in ("p50_ms", "p95_ms", "p99_ms", "count"):
        assert mp[k] == rp[k]  # quantiles read from counts: exact
    assert mp["mean_ms"] == pytest.approx(rp["mean_ms"])


# -- spans & tracer ---------------------------------------------------------

def test_span_breakdown_sums_to_total():
    span = Span("rid0", origin="recv")
    for stage in ("decode", "admit", "staging", "compute_d2h",
                  "staging", "respond"):  # a repeated stage accumulates
        time.sleep(0.001)
        span.mark(stage)
    span.note("attempt", "b0")
    d = span.to_dict()
    assert d["request_id"] == "rid0" and d["origin"] == "recv"
    assert set(d["stages"]) == {"decode", "admit", "staging",
                                "compute_d2h", "respond"}
    # the ≥95% accounting criterion holds with equality by construction
    assert sum(d["stages"].values()) == pytest.approx(
        d["total_ms"], abs=0.005)
    assert d["notes"][0]["event"] == "attempt"


def test_tracer_ring_disable_and_env(monkeypatch):
    tr = Tracer(ring=4)
    for i in range(10):
        tr.finish(tr.start(f"r{i}"))
    s = tr.summary()
    assert s["started"] == s["finished"] == 10
    assert s["ring"] == 4 and len(tr.recent(100)) == 4
    tr.finish(None)  # no-op by contract: tracing-off call sites pass None
    assert Tracer(enabled=False).start() is None
    monkeypatch.setenv("DVT_SERVE_TRACE", "0")
    assert not Tracer().enabled
    monkeypatch.delenv("DVT_SERVE_TRACE")
    assert Tracer().enabled


def test_slow_sampler_threshold():
    tr = Tracer(slow_ms=1.0)
    fast = tr.start("fast")
    tr.finish(fast)
    slow = tr.start("slow")
    time.sleep(0.005)
    slow.mark("work")
    tr.finish(slow)
    assert tr.summary()["slow_sampled"] == 1


# -- MFU meter --------------------------------------------------------------

def test_mfu_meter_arithmetic():
    m = MfuMeter(peak=100.0)
    m.set_bucket_flops(8, 50.0, "xla_cost_analysis")
    m.observe(8, images=8, compute_s=1.0)
    m.observe(8, images=4, compute_s=1.0)
    assert m.mfu() == pytest.approx(100.0 / 2.0 / 100.0)
    r = m.report()
    assert r["serving_mfu"] == pytest.approx(0.5)
    assert r["flops_source"] == "xla_cost_analysis"
    assert r["batches"] == 2 and r["images"] == 12
    m.observe(16, images=16, compute_s=0.5)  # bucket with unknown flops
    assert m.report()["unknown_flops_batches"] == 1
    assert MfuMeter(peak=1.0).mfu() is None  # no traffic → no gauge
    merged = MfuMeter.merged_report([m, m])
    assert merged["flops_total"] == 2 * m.report()["flops_total"]
    assert merged["serving_mfu"] == pytest.approx(
        m.report()["serving_mfu"])


# -- engine span plumbing ---------------------------------------------------

def test_engine_trace_normal_request_stages(lenet_serving):
    _, sm = lenet_serving
    tracer = Tracer(ring=64)
    with BatchingEngine(sm, buckets=[8], max_wait_ms=250,
                        tracer=tracer) as eng:
        for f in [eng.submit(im) for im in _images(8)]:
            assert f.result(60) is not None
    s = tracer.summary()
    assert s["started"] == s["finished"] == 8
    for trace in tracer.recent(8):
        assert set(trace["stages"]) >= {
            "admit", "queue_wait", "batch_form", "staging",
            "h2d_dispatch", "compute_d2h"}
        assert sum(trace["stages"].values()) == pytest.approx(
            trace["total_ms"], abs=0.005)


def test_engine_trace_shed_is_noted(lenet_serving):
    from deep_vision_tpu.serve.admission import Shed

    _, sm = lenet_serving
    tracer = Tracer(ring=16)
    with BatchingEngine(sm, buckets=[4], max_wait_ms=5,
                        tracer=tracer) as eng:
        img = _images(1)[0]
        assert eng.infer(img) is not None  # prime EWMA + compile
        assert isinstance(eng.infer(img, deadline_ms=0.0), Shed)
    shed_traces = [t for t in tracer.recent(16)
                   if any(n["event"] == "shed" for n in t["notes"])]
    assert len(shed_traces) == 1
    assert shed_traces[0]["notes"][0]["detail"].startswith("deadline")


def test_engine_trace_bisect_retry_and_quarantine(lenet_serving):
    from deep_vision_tpu.serve.faults import FaultPlane, Quarantined

    _, sm = lenet_serving
    tracer = Tracer(ring=16)
    with BatchingEngine(sm, buckets=[8],
                        faults=FaultPlane("compute:poison:nth=3"),
                        tracer=tracer) as eng:
        results = [f.result(60) for f in
                   [eng.submit(im) for im in _images(8)]]
    assert isinstance(results[3], Quarantined)
    traces = tracer.recent(16)
    assert len(traces) == 8
    retried = [t for t in traces
               if any(n["event"] == "bisect_retry" for n in t["notes"])]
    assert retried, "no bisect_retry notes on a poisoned cohort"
    quarantined = [t for t in traces
                   if any(n["event"] == "quarantined"
                          for n in t["notes"])]
    assert len(quarantined) == 1
    # innocents that re-executed carry the retry_exec stage AND still
    # account their full timeline
    rescued = [t for t in retried if "retry_exec" in t["stages"]]
    assert rescued
    for t in rescued:
        assert sum(t["stages"].values()) == pytest.approx(
            t["total_ms"], abs=0.005)


def test_engine_serving_mfu_sane_under_load(lenet_serving):
    _, sm = lenet_serving
    with BatchingEngine(sm, buckets=[8], max_wait_ms=2) as eng:
        for wave in range(4):
            for f in [eng.submit(im) for im in _images(8)]:
                assert f.result(60) is not None
        stats = eng.stats()
    mfu = stats["mfu"]
    assert mfu["serving_mfu"] is not None
    assert 0 < mfu["serving_mfu"] < 1
    assert mfu["compute_s"] > 0
    assert mfu["flops_source"] in ("xla_cost_analysis",
                                   "params_lower_bound")
    assert mfu["batches"] == stats["batches"]
    assert mfu["flops_by_bucket"].get("8")


# -- HTTP front-end ---------------------------------------------------------

@pytest.fixture()
def serve_stack(lenet_serving):
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    eng = BatchingEngine(sm, buckets=[4], max_wait_ms=2).start()
    srv = ServeServer(reg, {sm.name: eng}, port=0).start_background()
    yield eng, srv, f"http://127.0.0.1:{srv.port}"
    srv.shutdown()
    eng.stop()


def _classify(base, rid=None, debug=False, timeout=60):
    body = json.dumps(
        {"pixels": np.zeros((32, 32, 1)).tolist()}).encode()
    headers = {"Content-Type": "application/json"}
    if rid:
        headers[REQUEST_ID_HEADER] = rid
    url = base + "/v1/classify" + ("?debug=1" if debug else "")
    req = urllib.request.Request(url, data=body, headers=headers)
    t0 = time.monotonic()
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return (r.status, dict(r.headers), json.loads(r.read()),
                (time.monotonic() - t0) * 1e3)


def test_http_debug_trace_and_request_id(serve_stack):
    _, _, base = serve_stack
    status, headers, payload, wall_ms = _classify(
        base, rid="cafe0123deadbeef", debug=True)
    assert status == 200
    assert headers[REQUEST_ID_HEADER] == "cafe0123deadbeef"
    trace = payload["trace"]
    assert trace["request_id"] == "cafe0123deadbeef"
    assert trace["origin"] == "recv"
    assert set(trace["stages"]) >= {"decode", "admit", "queue_wait",
                                    "compute_d2h", "respond"}
    # acceptance: the breakdown accounts ≥95% of the span total (exact
    # by construction) and the span total is within the client's wall
    assert sum(trace["stages"].values()) >= 0.95 * trace["total_ms"]
    assert trace["total_ms"] <= wall_ms
    # a request WITHOUT the header gets a minted id echoed back
    status, headers, payload, _ = _classify(base)
    assert status == 200 and len(headers[REQUEST_ID_HEADER]) == 16
    assert "trace" not in payload  # debug off → clean payload


def test_http_traces_endpoint(serve_stack):
    _, _, base = serve_stack
    _classify(base, rid="feedface00000001")
    with urllib.request.urlopen(base + "/v1/traces?n=8",
                                timeout=60) as r:
        doc = json.loads(r.read())
    assert doc["summary"]["finished"] >= 1
    assert any(t["request_id"] == "feedface00000001"
               for t in doc["traces"])


def test_http_metrics_parse_and_monotonic(serve_stack):
    _, _, base = serve_stack

    def scrape():
        with urllib.request.urlopen(base + "/metrics", timeout=60) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            return _parse_prom(r.read().decode())

    _classify(base)
    first = scrape()
    lab = frozenset({("model", "lenet5")})
    for name in ("dvt_serve_requests_submitted_total",
                 "dvt_serve_requests_served_total",
                 "dvt_serve_batches_total", "dvt_serve_up",
                 "dvt_serve_mfu", "dvt_serve_compute_seconds_total",
                 "dvt_serve_traces_finished_total"):
        assert lab in first[name], f"{name} missing model label"
    assert first["dvt_serve_up"][lab] == 1
    assert 0 < first["dvt_serve_mfu"][lab] < 1
    assert frozenset({("model", "lenet5"), ("le", "+Inf")}) in \
        first["dvt_serve_request_latency_seconds_bucket"]
    _classify(base)
    # the handler seals its span AFTER replying — poll briefly so the
    # trace counters have landed before comparing scrapes
    monotone = ("dvt_serve_requests_served_total",
                "dvt_serve_batches_total",
                "dvt_serve_traces_finished_total",
                "dvt_serve_compute_seconds_total")
    deadline = time.monotonic() + 5.0
    while True:
        second = scrape()
        if all(second[n][lab] > first[n][lab] for n in monotone) \
                or time.monotonic() > deadline:
            break
        time.sleep(0.01)
    for name in monotone:
        assert second[name][lab] > first[name][lab], \
            f"{name} did not advance"
    assert second["dvt_serve_request_latency_seconds_count"][lab] > \
        first["dvt_serve_request_latency_seconds_count"][lab]


# -- gateway ----------------------------------------------------------------

def test_gateway_request_id_propagates_to_backend(lenet_serving):
    """One id names the whole client→gateway→backend→engine path: sent
    as a header to the gateway, it must come back on the response AND
    appear in the BACKEND's trace ring (a real HTTP hop away)."""
    from deep_vision_tpu.serve.gateway import Gateway, GatewayServer
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    engines = [BatchingEngine(sm, buckets=[4], max_wait_ms=2).start()
               for _ in range(2)]
    servers = [ServeServer(reg, {sm.name: eng}, port=0).start_background()
               for eng in engines]
    gw = Gateway([f"127.0.0.1:{s.port}" for s in servers],
                 probe_interval_s=0.05).start()
    gsrv = GatewayServer(gw, port=0).start_background()
    base = f"http://127.0.0.1:{gsrv.port}"
    try:
        rid = "0123456789abcdef"
        status, headers, payload, _ = _classify(base, rid=rid,
                                                debug=True)
        assert status == 200
        assert headers[REQUEST_ID_HEADER] == rid
        # the backend's own span rode back in the body (?debug=1) …
        assert payload["trace"]["request_id"] == rid
        # … and the gateway attached its proxy-side breakdown
        gtrace = payload["gateway_trace"]
        assert gtrace["request_id"] == rid
        assert "backend_hop" in gtrace["stages"]
        assert any(n["event"] == "attempt" for n in gtrace["notes"])
        # the id crossed the wire: some backend ring holds it
        ring_ids = []
        for eng in engines:
            ring_ids += [t["request_id"] for t in eng.tracer.recent(32)]
        assert rid in ring_ids
        # gateway ring holds it too
        assert rid in [t["request_id"]
                       for t in gw.tracer.recent(32)]
    finally:
        gsrv.shutdown()
        gw.stop()
        for srv in servers:
            srv.shutdown()
        for eng in engines:
            eng.stop()


def test_gateway_stats_merge_and_metrics(lenet_serving):
    """The fleet latency distribution in gateway /v1/stats must equal a
    local recomputation from the per-backend histogram states, and the
    gateway /metrics exposition must parse whole."""
    from deep_vision_tpu.serve.gateway import (Gateway, GatewayServer,
                                               render_gateway_metrics)
    from deep_vision_tpu.serve.http import ServeServer

    reg, sm = lenet_serving
    engines = [BatchingEngine(sm, buckets=[4], max_wait_ms=2).start()
               for _ in range(2)]
    servers = [ServeServer(reg, {sm.name: eng}, port=0).start_background()
               for eng in engines]
    gw = Gateway([f"127.0.0.1:{s.port}" for s in servers],
                 probe_interval_s=0.05).start()
    gsrv = GatewayServer(gw, port=0).start_background()
    base = f"http://127.0.0.1:{gsrv.port}"
    try:
        for _ in range(10):
            status, _, _, _ = _classify(base)
            assert status == 200
        # recompute the fleet histogram from each backend directly …
        expect = None
        for srv in servers:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/v1/stats",
                    timeout=60) as r:
                hist = json.loads(r.read())["lenet5"]["latency_hist"]
            if expect is None:
                expect = LatencyHistogram()
                expect.load_state_dict(hist)
            else:
                expect.merge(hist)
        # … and it must match what the gateway aggregated
        with urllib.request.urlopen(base + "/v1/stats",
                                    timeout=60) as r:
            stats = json.loads(r.read())
        g = stats["gateway"]
        assert g["backend_latency_hist"]["total"] == expect.total >= 10
        assert g["backend_latency"] == expect.percentiles()
        assert g["mfu"]["serving_mfu"] is not None
        assert 0 < g["mfu"]["serving_mfu"] < 1
        assert g["latency"]["count"] >= 10  # gateway-side histogram
        # both backends saw probes; at least one served traffic
        assert set(stats["backends"]) == {b.name for b in gw.backends}
        # the full exposition parses, fleet gauges included
        samples = _parse_prom(render_gateway_metrics(gw))
        assert samples["dvt_gateway_proxied_total"][frozenset()] >= 10
        assert samples["dvt_gateway_routable_backends"][
            frozenset()] == 2
        assert 0 < samples["dvt_gateway_serving_mfu"][frozenset()] < 1
        assert frozenset({("le", "+Inf")}) in \
            samples["dvt_gateway_request_latency_seconds_bucket"]
        for b in gw.backends:
            assert samples["dvt_gateway_backend_up"][
                frozenset({("backend", b.name)})] == 1
    finally:
        gsrv.shutdown()
        gw.stop()
        for srv in servers:
            srv.shutdown()
        for eng in engines:
            eng.stop()


def _stub_backend(delay_s: float):
    """Minimal scriptable backend for the hedge-span test."""
    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _reply(self, status, payload):
            blob = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def do_GET(self):
            self._reply(200, {"status": "ok"})

        def do_POST(self):
            self.rfile.read(
                int(self.headers.get("Content-Length") or 0))
            if delay_s:
                time.sleep(delay_s)
            self._reply(200, {"ok": True})

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def test_gateway_hedged_request_span(lenet_serving):
    """A hedged request's span records the hedge and the winner — noted
    from the forwarding thread only, so the trace is complete without
    the pool workers ever touching the span."""
    from deep_vision_tpu.serve.gateway import Gateway

    slow = _stub_backend(delay_s=0.4)
    fast = _stub_backend(delay_s=0.0)
    gw = Gateway([f"127.0.0.1:{slow.server_address[1]}",
                  f"127.0.0.1:{fast.server_address[1]}"],
                 probe_interval_s=0.05, hedge=True,
                 hedge_after_ms=20.0).start()
    try:
        # the round-robin scan starts at backend 0 (the slow one) on an
        # idle fleet, so the first request hedges to the fast one
        status, headers, payload = gw.forward(
            "/v1/classify", b'{"x": 1}', request_id="feedbead00000002")
        assert status == 200
        assert headers[REQUEST_ID_HEADER] == "feedbead00000002"
        assert gw.hedges == 1 and gw.hedge_wins == 1
        trace = gw.tracer.recent(4)[-1]
        assert trace["request_id"] == "feedbead00000002"
        events = [n["event"] for n in trace["notes"]]
        assert events.count("attempt") == 1
        assert "hedge" in events and "hedge_win" in events
        assert {"backend_hop", "respond"} <= set(trace["stages"])
        assert sum(trace["stages"].values()) == pytest.approx(
            trace["total_ms"], abs=0.005)
    finally:
        gw.stop()
        for httpd in (slow, fast):
            httpd.shutdown()
            httpd.server_close()


# -- structured logging -----------------------------------------------------

def test_event_emits_one_json_line(caplog):
    log = get_logger("dvt.serve.testsink")
    with caplog.at_level(logging.INFO, logger="dvt.serve.testsink"):
        event(log, "breaker_open", backend="127.0.0.1:1",
              consecutive_failures=3)
    assert len(caplog.records) == 1
    doc = json.loads(caplog.records[0].getMessage())
    assert doc["event"] == "breaker_open"
    assert doc["logger"] == "dvt.serve.testsink"
    assert doc["backend"] == "127.0.0.1:1"
    assert doc["consecutive_failures"] == 3
    assert isinstance(doc["ts"], float)
    # below-threshold events are guarded out before any JSON encoding
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="dvt.serve.testsink"):
        event(log, "suppressed", level=logging.INFO)
    assert not caplog.records


def test_configure_logging_idempotent():
    root = logging.getLogger("dvt")
    before = list(root.handlers)
    try:
        configure_logging("warning")
        configure_logging("info")  # re-configure: still ONE handler
        ours = [h for h in root.handlers if h not in before]
        assert len(ours) == 1
        assert root.level == logging.INFO
        assert root.propagate is False
    finally:
        for h in list(root.handlers):
            if h not in before:
                root.removeHandler(h)
        root.propagate = True
        root.setLevel(logging.NOTSET)


def test_overload_logging_is_edge_triggered(caplog):
    """A saturated engine must not saturate its own log: one line when
    queue_full shedding starts, one when it clears — not one per shed."""
    from deep_vision_tpu.serve.admission import AdmissionController

    adm = AdmissionController(max_queue=1)
    with caplog.at_level(logging.INFO, logger="dvt.serve.admission"):
        for _ in range(5):
            assert adm.admit(queue_depth=3, deadline=None) is not None
        assert adm.admit(queue_depth=0, deadline=None) is None
    events = [json.loads(r.getMessage())["event"]
              for r in caplog.records]
    assert events == ["overload_shed_start", "overload_cleared"]
    assert adm.stats()["shed_queue_full"] == 5
