"""Confidence-routed cascade contract (CPU, tier-1 fast): calibration
is deterministic for a seeded sample and fails CLOSED on thin data, a
confident front tier answers while low confidence escalates to a
bit-identical big-only answer, an escalated request carries its
REMAINING deadline (never a fresh budget), a version swap of either
tier drops the calibration, and always-big QoS tenants bypass the
front tier entirely.

Most tests drive ``CascadeRouter`` over a fake plane (synchronous
futures, recorded deadlines) — routing correctness is about the
decision logic, not real engines.  One real-plane test runs LeNet-5
(front, confidence epilogue fused) against LeNet5Big (big, dense
logits) at random init to pin the end-to-end row shapes.
"""

import time
from concurrent.futures import Future

import numpy as np
import pytest

from deep_vision_tpu.serve.admission import Shed, TenantQoS
from deep_vision_tpu.serve.cascade import CascadeRouter, CascadeSpec
from deep_vision_tpu.serve.models import AgreementHistogram
from deep_vision_tpu.serve.workloads import ClassifyWorkload

pytestmark = pytest.mark.models


def _front_row(cls=3, prob=0.9):
    """A confidence-epilogue row as the front engine scatters it."""
    return {"topk_class": np.array([cls, 1, 2], np.int32),
            "topk_prob": np.array([prob, 0.05, 0.02], np.float32),
            "topk_logit": np.array([5.0, 1.0, 0.5], np.float32)}


def _big_row(cls=3, n=10, seed=0):
    """Dense logits with argmax ``cls`` — what the big tier serves."""
    logits = np.random.RandomState(seed).randn(n).astype(np.float32)
    logits[cls] = logits.max() + 3.0
    return logits


class FakePlane:
    """Synchronous stand-in for ModelControlPlane.submit: resolves each
    future inline from a per-model row (value, callable, or exception)
    and records every ``(name, deadline_ms)`` for deadline assertions."""

    def __init__(self, rows, delay_s=0.0):
        self.rows = rows
        self.delay_s = delay_s
        self.calls = []
        self.listeners = []

    def add_version_listener(self, fn):
        self.listeners.append(fn)

    def submit(self, name, image, deadline_ms=None, span=None):
        self.calls.append((name, deadline_ms))
        if self.delay_s:
            time.sleep(self.delay_s)
        fut = Future()
        row = self.rows[name]
        if callable(row):
            row = row(image)
        if isinstance(row, Exception):
            fut.set_exception(row)
        else:
            fut.set_result(row)
        return fut

    def resolve(self, name):
        raise KeyError(name)

    def canary_active(self, name):
        return False


def _router(rows, *, delay_s=0.0, threshold=None, **spec_kw):
    spec_kw.setdefault("sample_period", 1000)  # no sampling by default
    spec = CascadeSpec("small", "large", **spec_kw)
    plane = FakePlane(dict(rows), delay_s=delay_s)
    router = CascadeRouter(plane, spec)
    if threshold is not None:
        # seed a calibration directly: every sample at the threshold's
        # bin agreed, enough of them to clear min_sample
        for _ in range(max(spec.min_sample, 1)):
            router.hist.record(threshold, True)
        router._recalibrate()
        assert router.threshold is not None
    return router, plane


# -- calibration math ------------------------------------------------------


def test_histogram_threshold_deterministic_seeded_sample():
    """Seeded synthetic sample: agreement rises with confidence, and
    the threshold lands exactly on the smallest bin edge whose suffix
    clears the floor — same sample, same answer, every run."""
    hist = AgreementHistogram(bins=10)
    rng = np.random.RandomState(42)
    for conf in rng.uniform(0.0, 1.0, size=2000):
        # agreement probability grows with confidence: sure-above-0.7,
        # coin-flip-below — the shape a real cascade sample has
        agreed = bool(conf >= 0.7 or rng.uniform() < 0.5)
        hist.record(float(conf), agreed)
    thr = hist.threshold(min_agreement=0.95, min_sample=100)
    assert thr == pytest.approx(0.7)
    # a laxer floor admits more of the distribution (smaller threshold);
    # a stricter one admits less or nothing — monotone in the floor
    lax = hist.threshold(min_agreement=0.60, min_sample=100)
    assert lax is not None and lax <= thr
    assert hist.threshold(min_agreement=1.01, min_sample=100) is None


def test_histogram_fails_closed_on_thin_sample():
    hist = AgreementHistogram(bins=10)
    for _ in range(50):
        hist.record(0.95, True)   # bin 9: perfect
    for _ in range(49):
        hist.record(0.55, False)  # bin 5: hopeless
    # 99 samples < min_sample: fail closed regardless of agreement
    assert hist.threshold(min_agreement=0.9, min_sample=100) is None
    hist.record(0.55, False)
    # thick enough: bin 9 qualifies, the empty bins 6-8 never extend
    # the threshold into unobserved territory, and the disagreeing
    # bin 5 can't qualify
    assert hist.threshold(min_agreement=0.9, min_sample=100) == \
        pytest.approx(0.9)
    hist.reset()
    assert hist.threshold(min_agreement=0.9, min_sample=1) is None
    assert hist.stats()["samples"] == 0


# -- routing ---------------------------------------------------------------


def test_uncalibrated_routes_everything_big():
    """Fail closed: before min_sample dual-runs, no request may stop at
    the front tier."""
    router, plane = _router({"small": _front_row(), "large": _big_row()})
    for _ in range(20):
        tier, row = router.infer(np.zeros((4, 4, 1), np.float32))
        assert tier == "big"
        np.testing.assert_array_equal(row, plane.rows["large"])
    assert all(name == "large" for name, _ in plane.calls)
    st = router.stats()
    assert st["calibrated"] is False and st["threshold"] is None
    assert st["served"] == {"front": 0, "big": 20}
    assert st["escalation_rate"] is None  # front judged nothing yet


def test_confident_front_serves_lowconf_escalates_bit_identical():
    router, plane = _router(
        {"small": _front_row(prob=0.9), "large": _big_row()},
        threshold=0.5)
    x = np.zeros((4, 4, 1), np.float32)
    tier, row = router.infer(x)
    assert tier == "front" and isinstance(row, dict)
    assert ClassifyWorkload.top1(row) == (3, pytest.approx(0.9))

    # drop the front's confidence below threshold: the answer must be
    # the big tier's row, bit-identical to a big-only submission
    plane.rows["small"] = _front_row(prob=0.2)
    tier, row = router.infer(x)
    assert tier == "big"
    assert row.tobytes() == plane.rows["large"].tobytes()
    st = router.stats()
    assert st["served"] == {"front": 1, "big": 1}
    assert st["escalations"] == 1 and st["escalated_lowconf"] == 1
    assert st["escalation_rate"] == pytest.approx(0.5)


def test_front_error_escalates():
    """A front-tier Shed (or raise) never reaches the client — the big
    tier owns the contract."""
    router, plane = _router(
        {"small": Shed("queue_full", "front full"), "large": _big_row()},
        threshold=0.5)
    tier, row = router.infer(np.zeros((4, 4, 1), np.float32))
    assert tier == "big" and not isinstance(row, Shed)
    assert router.stats()["escalated_error"] == 1

    plane.rows["small"] = RuntimeError("front died")
    tier, row = router.infer(np.zeros((4, 4, 1), np.float32))
    assert tier == "big" and not isinstance(row, Shed)
    assert router.stats()["escalated_error"] == 2


def test_escalation_preserves_original_deadline():
    """The escalated submit carries deadline − front-elapsed, never a
    fresh budget; a front attempt that ate the whole budget sheds
    instead of escalating."""
    router, plane = _router(
        {"small": _front_row(prob=0.2), "large": _big_row()},
        threshold=0.5, delay_s=0.02)
    tier, _ = router.infer(np.zeros((4, 4, 1), np.float32),
                           deadline_ms=500.0)
    assert tier == "big"
    (fname, fdl), (bname, bdl) = plane.calls
    assert (fname, fdl) == ("small", 500.0)
    assert bname == "large" and 0.0 < bdl <= 500.0 - 20.0

    # budget thinner than the front attempt: no big submit at all
    plane.calls.clear()
    tier, row = router.infer(np.zeros((4, 4, 1), np.float32),
                             deadline_ms=5.0)
    assert tier == "big" and isinstance(row, Shed)
    assert row.reason == "deadline"
    assert [name for name, _ in plane.calls] == ["small"]
    assert router.stats()["escalated_shed"] == 1


def test_sampling_calibrates_then_version_swap_resets():
    """Every sample_period-th request dual-runs both tiers; once the
    sample is thick enough the threshold appears, and a version swap of
    either tier drops it (fail closed again)."""
    router, plane = _router(
        {"small": _front_row(cls=3, prob=0.97), "large": _big_row(cls=3)},
        sample_period=1, min_sample=10, min_agreement=0.9)
    x = np.zeros((4, 4, 1), np.float32)
    for _ in range(10):
        tier, _ = router.infer(x)
        assert tier == "big"  # sampled requests answer from big
    st = router.stats()
    assert st["samples"] == 10 and st["calibrated"] is True
    assert st["threshold"] == pytest.approx(0.95)
    assert st["agreement"] == pytest.approx(1.0)

    assert len(plane.listeners) == 1
    plane.listeners[0]("unrelated-model")
    assert router.threshold is not None  # foreign swap: no reset
    plane.listeners[0]("small")
    st = router.stats()
    assert st["calibrated"] is False and st["resets"] == 1
    assert st["agreement_bins"]["samples"] == 0


def test_disagreeing_sample_never_calibrates():
    """Front and big that never agree: no confidence level clears the
    floor, so the cascade stays all-big forever."""
    router, _ = _router(
        {"small": _front_row(cls=1, prob=0.99), "large": _big_row(cls=3)},
        sample_period=1, min_sample=5, min_agreement=0.9)
    x = np.zeros((4, 4, 1), np.float32)
    for _ in range(20):
        tier, _ = router.infer(x)
        assert tier == "big"
    st = router.stats()
    assert st["calibrated"] is False and st["samples"] == 20


def test_force_big_bypasses_front():
    """Always-big QoS tenants: force_big never touches the front tier,
    calibrated or not."""
    router, plane = _router(
        {"small": _front_row(prob=0.99), "large": _big_row()},
        threshold=0.1)
    tier, _ = router.infer(np.zeros((4, 4, 1), np.float32),
                           force_big=True)
    assert tier == "big"
    assert [name for name, _ in plane.calls] == ["large"]
    assert router.stats()["forced_big"] == 1


def test_qos_always_big_spec_parses():
    qos = TenantQoS.parse("premium:rate=0,always_big=1,tenants=acme;"
                          "standard:rate=100;default=standard")
    assert qos.class_of("acme").always_big is True
    assert qos.class_of("someone").always_big is False
    st = qos.stats()
    assert st["premium"]["always_big"] is True
    assert st["standard"]["always_big"] is False


def test_serves_only_big_name():
    router, _ = _router({"small": _front_row(), "large": _big_row()})
    assert router.serves("large") and not router.serves("small")
    with pytest.raises(ValueError):
        CascadeSpec("same", "same")
    with pytest.raises(ValueError):
        CascadeSpec.parse("no-colon-here")


def test_respond_identical_for_escalated_and_big_only():
    """The full client-visible JSON of an escalated answer matches a
    big-only answer byte for byte — the quality contract the big name
    promises."""
    import json

    big = _big_row()
    router, _ = _router({"small": _front_row(prob=0.1), "large": big},
                        threshold=0.5)
    _, escalated = router.infer(np.zeros((4, 4, 1), np.float32))

    class _M:
        name = "large"

    w = ClassifyWorkload()
    a = json.dumps(w.respond(_M(), {}, escalated), sort_keys=True)
    b = json.dumps(w.respond(_M(), {}, big), sort_keys=True)
    assert a == b


# -- N-tier chains ---------------------------------------------------------


def _mid_row(cls=3, prob=0.9):
    return _front_row(cls=cls, prob=prob)


def _router3(rows, *, delay_s=0.0, thresholds=(None, None), **spec_kw):
    """3-tier small:mid:large router over a FakePlane; ``thresholds``
    seeds hop 0 / hop 1 calibrations directly."""
    spec_kw.setdefault("sample_period", 1000)
    spec = CascadeSpec("small", "mid", "large", **spec_kw)
    plane = FakePlane(dict(rows), delay_s=delay_s)
    router = CascadeRouter(plane, spec)
    for hop, thr in zip(router.hops, thresholds):
        if thr is not None:
            for _ in range(max(spec.min_sample, 1)):
                hop.hist.record(thr, True)
            router._recalibrate(hop)
            assert hop.threshold is not None
    return router, plane


def test_three_tier_tokens_and_mid_serving():
    """A calibrated middle hop answers with token "t1"; hop 0 low
    confidence escalates one hop, not straight to big."""
    router, plane = _router3(
        {"small": _front_row(prob=0.2), "mid": _mid_row(prob=0.9),
         "large": _big_row()},
        thresholds=(0.5, 0.5))
    tier, row = router.infer(np.zeros((4, 4, 1), np.float32))
    assert tier == "t1" and isinstance(row, dict)
    assert [name for name, _ in plane.calls] == ["small", "mid"]
    st = router.stats()
    assert st["served"] == {"front": 0, "t1": 1, "big": 0}
    assert st["tiers"] == ["small", "mid", "large"]
    assert [h["token"] for h in st["hops"]] == ["front", "t1"]


def test_uncalibrated_hop_escalates_through_without_running_tier():
    """Fail closed per hop: an uncalibrated middle hop is SKIPPED — its
    tier never runs, the request proceeds down the chain."""
    router, plane = _router3(
        {"small": _front_row(prob=0.2), "mid": _mid_row(prob=0.99),
         "large": _big_row()},
        thresholds=(0.5, None))
    tier, row = router.infer(np.zeros((4, 4, 1), np.float32))
    assert tier == "big"
    assert [name for name, _ in plane.calls] == ["small", "large"]
    assert row.tobytes() == plane.rows["large"].tobytes()

    # fully uncalibrated chain: only big runs
    router2, plane2 = _router3(
        {"small": _front_row(), "mid": _mid_row(),
         "large": _big_row()})
    tier, _ = router2.infer(np.zeros((4, 4, 1), np.float32))
    assert tier == "big"
    assert [name for name, _ in plane2.calls] == ["large"]


def test_twice_escalated_request_never_exceeds_original_budget():
    """Satellite: a request escalated through BOTH cheap tiers submits
    to each next tier with strictly shrinking remainders of its ONE
    original deadline — and sheds when the chain eats the budget."""
    router, plane = _router3(
        {"small": _front_row(prob=0.1), "mid": _mid_row(prob=0.1),
         "large": _big_row()},
        thresholds=(0.5, 0.5), delay_s=0.02)
    tier, _ = router.infer(np.zeros((4, 4, 1), np.float32),
                           deadline_ms=500.0)
    assert tier == "big"
    (n0, d0), (n1, d1), (n2, d2) = plane.calls
    assert (n0, d0) == ("small", 500.0)  # hop 0 sees the EXACT budget
    assert n1 == "mid" and n2 == "large"
    # each hop burned >= 20ms of the same 500ms budget
    assert 0.0 < d2 < d1 <= 500.0 - 20.0
    assert d2 <= 500.0 - 40.0
    assert router.stats()["escalations"] == 2

    # budget dies mid-chain: big is never submitted, the client gets a
    # deadline Shed
    plane.calls.clear()
    tier, row = router.infer(np.zeros((4, 4, 1), np.float32),
                             deadline_ms=30.0)
    assert tier == "big" and isinstance(row, Shed)
    assert row.reason == "deadline"
    assert [name for name, _ in plane.calls] == ["small", "mid"]
    assert router.stats()["escalated_shed"] == 1


def test_version_swap_resets_only_its_hop_big_resets_all():
    """A mid-tier swap drops hop 1's calibration only; a big swap drops
    every hop (big is every hop's comparison target)."""
    router, plane = _router3(
        {"small": _front_row(), "mid": _mid_row(), "large": _big_row()},
        thresholds=(0.5, 0.7))
    plane.listeners[0]("mid")
    assert router.hops[0].threshold is not None
    assert router.hops[1].threshold is None
    # re-seed hop 1, then swap big: both hops drop
    for _ in range(200):
        router.hops[1].hist.record(0.7, True)
    router._recalibrate(router.hops[1])
    plane.listeners[0]("large")
    assert router.hops[0].threshold is None
    assert router.hops[1].threshold is None


def test_ledger_roundtrip_and_any_tier_digest_rejection(tmp_path):
    """Satellite: the ledger key covers ALL tier digests — a restore
    adopts a hop's calibration only when EVERY live tier matches, so a
    mid-tier reload while down rejects the record."""

    class DigestPlane(FakePlane):
        def __init__(self, rows, digests):
            super().__init__(rows)
            self.digests = digests

        def resolve(self, name):
            m = type("M", (), {})()
            m.params_digest = self.digests[name]
            return m

    rows = {"small": _front_row(), "mid": _mid_row(),
            "large": _big_row()}
    digests = {"small": "d0", "mid": "d1", "large": "d2"}
    spec = CascadeSpec("small", "mid", "large", sample_period=1000,
                       min_sample=10)
    plane = DigestPlane(rows, dict(digests))
    router = CascadeRouter(plane, spec, root=str(tmp_path))
    assert router.params_digest() == "d0+d1+d2"
    for _ in range(10):
        router.hops[0].hist.record(0.8, True)
    router._recalibrate(router.hops[0])
    for _ in range(10):
        router.hops[1].hist.record(0.6, True)
    router._recalibrate(router.hops[1])

    # same digests: both hops restore, thresholds re-derived
    r2 = CascadeRouter(DigestPlane(rows, dict(digests)), spec,
                       root=str(tmp_path))
    assert r2.restored is True
    assert r2.hops[0].threshold == pytest.approx(0.8)
    assert r2.hops[1].threshold == pytest.approx(0.6)

    # ONE tier (the middle one) reloaded while down: every hop's
    # record is stale — nothing restores
    changed = dict(digests, mid="d1-reloaded")
    r3 = CascadeRouter(DigestPlane(rows, changed), spec,
                       root=str(tmp_path))
    assert r3.restored is False
    assert r3.hops[0].threshold is None
    assert r3.hops[1].threshold is None

    # a persisted reset for one hop wins over its older calibration
    router._on_version_swap("mid")
    r4 = CascadeRouter(DigestPlane(rows, dict(digests)), spec,
                       root=str(tmp_path))
    assert r4.hops[0].threshold == pytest.approx(0.8)
    assert r4.hops[1].threshold is None


def test_per_class_thresholds_and_fail_closed_class():
    """Per-class axis: a class with its own qualifying sample uses its
    own threshold; a measured-bad class fails CLOSED (escalates at any
    confidence) instead of riding the pooled threshold."""
    router, plane = _router(
        {"small": _front_row(cls=3, prob=0.9), "large": _big_row()},
        per_class=True, class_min_sample=20, min_sample=20,
        min_agreement=0.9)
    hop = router.hops[0]
    # class 3 agrees from 0.62 up; class 1 NEVER agrees; class 7 thin
    for _ in range(30):
        hop.hist.record(0.62, True, cls=3)
    for _ in range(30):
        hop.hist.record(0.9, False, cls=1)
    for _ in range(5):
        hop.hist.record(0.9, True, cls=7)
    router._recalibrate()
    assert hop.class_thresholds[3] == pytest.approx(0.60)
    assert hop.class_thresholds[1] is None  # fail-closed class
    assert 7 not in hop.class_thresholds    # thin → pooled fallback

    # class 3 at 0.9: served by the front tier
    tier, _ = router.infer(np.zeros((4, 4, 1), np.float32))
    assert tier == "front"
    # class 1 at 0.9 (above any pooled threshold): still escalates
    plane.rows["small"] = _front_row(cls=1, prob=0.97)
    tier, _ = router.infer(np.zeros((4, 4, 1), np.float32))
    assert tier == "big"
    st = router.stats()
    assert st["hops"][0]["class_thresholds"]["3"] == pytest.approx(0.6)


def test_detect_cascade_rule_signal_and_agreement():
    """The detect rule: confidence = best valid device-decoded score,
    class = its label; agreement = the greedy-IoU verdict; decoded-row
    shape errors are (None, None) → escalate."""
    from deep_vision_tpu.serve.workloads import DetectWorkload

    rule = DetectWorkload().cascade_rule()

    def det_row(scores, classes, boxes=None):
        k = len(scores)
        b = boxes if boxes is not None else \
            np.tile(np.array([0.1, 0.1, 0.3, 0.3], np.float32), (k, 1))
        return {"boxes": np.asarray(b, np.float32),
                "scores": np.asarray(scores, np.float32),
                "classes": np.asarray(classes, np.int64),
                "valid": (np.asarray(scores) > 0).astype(np.float32)}

    cls, conf = rule.signal(det_row([0.9, 0.4, 0.0], [2, 5, 0]))
    assert cls == 2 and conf == pytest.approx(0.9)
    # empty detection is a SIGNAL (confidently nothing), not an error
    cls, conf = rule.signal(det_row([0.0, 0.0], [0, 0]))
    assert cls is None and conf == 0.0
    # a dense (non-decoded) row has no signal: escalate
    assert rule.signal(np.zeros((13, 13, 18))) == (None, None)

    a = det_row([0.9], [2])
    assert rule.agree(a, a) is True
    far = det_row([0.9], [2],
                  boxes=[[0.7, 0.7, 0.9, 0.9]])
    assert rule.agree(a, far) is False


def test_inner_hop_calibrates_against_final_tier():
    """Each hop dual-runs its OWN tier against the final tier on the
    traffic that reaches it: a front tier the big model keeps
    contradicting never calibrates (fail-closed), while the middle
    tier calibrates on the escalated-through stream and starts
    serving."""
    router, plane = _router3(
        {"small": _front_row(cls=2, prob=0.97),   # big says 3: disagree
         "mid": _mid_row(cls=3, prob=0.97),       # agrees with big
         "large": _big_row(cls=3)},
        sample_period=2, min_sample=3, min_agreement=0.9)
    x = np.zeros((4, 4, 1), np.float32)
    tiers = [router.infer(x)[0] for _ in range(20)]
    st = router.stats()
    # hop 0 ticks every request, sampling half of it — and every
    # sample disagrees, so it stays uncalibrated
    assert st["hops"][0]["samples"] == 10
    assert not st["hops"][0]["calibrated"]
    assert st["hops"][0]["agreement"] == pytest.approx(0.0)
    # the other half escalates THROUGH to hop 1, which samples ITS
    # even ticks against big, calibrates, and begins serving "t1"
    assert st["hops"][1]["samples"] == 5
    assert st["hops"][1]["calibrated"]
    assert st["served"]["t1"] >= 1 and "t1" in tiers
    # nothing was ever answered by the measured-bad front tier
    assert st["served"]["front"] == 0


# -- real plane ------------------------------------------------------------


def test_real_plane_front_epilogue_and_escalation(tmp_path):
    """LeNet-5 (front, cascade_topk=3 → fused confidence epilogue)
    against LeNet5Big (big, dense logits) on a real control plane:
    front rows are top-K dicts, big rows are dense logits bit-identical
    to big-only serving, and both shapes flow through respond()."""
    from deep_vision_tpu.serve.admission import AdmissionController
    from deep_vision_tpu.serve.engine import BatchingEngine
    from deep_vision_tpu.serve.models import ModelControlPlane
    from deep_vision_tpu.serve.registry import ModelRegistry

    reg = ModelRegistry()
    front = reg.load_checkpoint("lenet5", str(tmp_path / "f"),
                                cascade_topk=3)
    big = reg.load_checkpoint("lenet5_big", str(tmp_path / "b"))
    plane = ModelControlPlane(
        reg, lambda m: BatchingEngine(m, buckets=[4], max_wait_ms=2),
        admission_factory=lambda name: AdmissionController(name=name))
    plane.deploy(front)
    plane.deploy(big)
    try:
        spec = CascadeSpec("lenet5", "lenet5_big", sample_period=1000,
                           min_sample=4, topk=3)
        router = CascadeRouter(plane, spec)
        x = np.random.RandomState(0).randint(
            0, 255, (32, 32, 1)).astype(np.float32)

        # uncalibrated: big answers, bit-identical to big-only serving
        tier, row = router.infer(x, timeout=120)
        assert tier == "big"
        direct = plane.infer("lenet5_big", x, timeout=120)
        np.testing.assert_array_equal(np.asarray(row),
                                      np.asarray(direct))

        # calibrate at 0.0: everything stops at the front tier, whose
        # engine scatters the fused top-K dict
        for _ in range(4):
            router.hist.record(0.0, True)
        router._recalibrate()
        assert router.threshold == 0.0
        tier, row = router.infer(x, timeout=120)
        assert tier == "front" and isinstance(row, dict)
        assert np.asarray(row["topk_class"]).shape == (3,)
        resp = ClassifyWorkload().respond(big, {"top_k": 3}, row)
        assert len(resp["top"]) == 3
        # front top-1 equals the front model served standalone
        fdirect = plane.infer("lenet5", x, timeout=120)
        assert ClassifyWorkload.top1(row)[0] == \
            ClassifyWorkload.top1(fdirect)[0]
    finally:
        plane.stop()
