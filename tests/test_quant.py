"""Int8 post-training quantization + fused Pallas ingest (CPU, tier-1).

The int8 serving contract (docs/SERVING.md "Wire format & inference
dtype"): ``--infer-dtype int8`` quantizes conv/dense kernels to
symmetric per-channel int8 AT LOAD (serve/quant.py), keeps them
int8-resident in HBM (~0.26× the f32 footprint — the WeightCache
then admits ~4× more versions per budget), and runs bucket programs
that dequantize in-trace with float32 accumulation and float32
outputs.  On the uint8 wire the serve prologue is a single fused
Pallas pass (ops/pallas_ops.serve_ingest: decode + normalize +
activation-quantize in one VMEM trip), interpret-mode here on CPU,
with the XLA prologue as the always-available fallback — the two
must agree to ≤ 1 quantization step.

Uses LeNet at random init (restore's no-checkpoint fallback), same as
the wire-format suite: the gates are about dtype plumbing and
agreement with the f32 path, not learned accuracy."""

import numpy as np
import pytest

from deep_vision_tpu.serve.engine import BatchingEngine
from deep_vision_tpu.serve.quant import (
    Calibration,
    calibrate,
    dequantize_params,
    load_calibration_dir,
    quantize_params,
    synthetic_calibration_batches,
)
from deep_vision_tpu.serve.registry import ModelRegistry

pytestmark = pytest.mark.serve

MNIST_MEAN, MNIST_STD = 0.1307, 0.3081


@pytest.fixture(scope="module")
def quant_serving(tmp_path_factory):
    """One restore, f32 reference + int8 via both ingest paths."""
    reg = ModelRegistry()
    td = str(tmp_path_factory.mktemp("quant_workdir"))
    sm_f32 = reg.load_checkpoint("lenet5", td, name="lenet_f32q")
    sm_i8 = reg.load_checkpoint("lenet5", td, name="lenet_i8",
                                wire_dtype="uint8", infer_dtype="int8")
    sm_i8_xla = reg.load_checkpoint("lenet5", td, name="lenet_i8_xla",
                                    wire_dtype="uint8",
                                    infer_dtype="int8", ingest="xla")
    return sm_f32, sm_i8, sm_i8_xla


def _raw_images(n, shape=(32, 32, 1)):
    return [np.random.RandomState(i).randint(0, 256, shape, dtype=np.uint8)
            for i in range(n)]


def _host_normalized(raw):
    return [((r.astype(np.float32) / 255.0) - MNIST_MEAN) / MNIST_STD
            for r in raw]


def _serve_all(engine, images, timeout=120):
    from concurrent.futures import wait

    futs = [engine.submit(x) for x in images]
    wait(futs, timeout)
    return [np.asarray(f.result(0)) for f in futs]


# -- weight quantization ---------------------------------------------------


def test_quantize_params_roundtrip():
    """Kernels → int8 + per-channel (cout,) scales with ≤ half-step
    dequant error; 1-D leaves pass through with identity scales."""
    rng = np.random.RandomState(0)
    params = {"conv": {"kernel": rng.randn(3, 3, 4, 8).astype(np.float32),
                       "bias": rng.randn(8).astype(np.float32)},
              "dense": {"kernel": rng.randn(16, 10).astype(np.float32)}}
    q, s = quantize_params(params)
    assert q["conv"]["kernel"].dtype == np.int8
    assert s["conv"]["kernel"].shape == (8,)
    assert q["dense"]["kernel"].dtype == np.int8
    assert s["dense"]["kernel"].shape == (10,)
    # bias untouched, scalar identity scale keeps the trees congruent
    np.testing.assert_array_equal(q["conv"]["bias"],
                                  params["conv"]["bias"])
    assert s["conv"]["bias"].shape == ()
    assert float(s["conv"]["bias"]) == 1.0
    # symmetric round-to-nearest: |deq - w| ≤ scale/2 everywhere
    for key in ("conv", "dense"):
        w = params[key]["kernel"]
        deq = (q[key]["kernel"].astype(np.float32)
               * s[key]["kernel"].astype(np.float32))
        assert np.max(np.abs(deq - w)) <= np.max(s[key]["kernel"]) / 2 + 1e-7
        # absmax channels hit ±127 exactly (symmetric, no zero-point)
        assert np.max(np.abs(q[key]["kernel"])) == 127


def test_quantize_zero_channel_guard():
    """An all-zero output channel gets scale 1.0 and exact-zero int8
    codes instead of a 0/0."""
    w = np.random.RandomState(1).randn(5, 4).astype(np.float32)
    w[:, 2] = 0.0
    q, s = quantize_params({"k": w})
    assert float(s["k"][2]) == 1.0
    np.testing.assert_array_equal(q["k"][:, 2], np.zeros(5, np.int8))
    assert np.isfinite(s["k"]).all()


def test_dequantize_params_traced():
    import jax.numpy as jnp

    w = np.random.RandomState(2).randn(6, 3).astype(np.float32)
    q, s = quantize_params({"k": w, "b": np.ones(3, np.float32)})
    deq = dequantize_params(
        {"k": jnp.asarray(q["k"]), "b": jnp.asarray(q["b"])},
        {"k": jnp.asarray(s["k"]), "b": jnp.asarray(s["b"])})
    assert deq["k"].dtype == jnp.float32
    assert deq["b"].dtype == jnp.float32  # passthrough keeps its dtype
    np.testing.assert_allclose(np.asarray(deq["k"]),
                               q["k"].astype(np.float32) * s["k"],
                               atol=0)


# -- calibration -----------------------------------------------------------


def test_synthetic_calibration_deterministic():
    a = synthetic_calibration_batches((8, 8, 1), n_batches=2, batch_size=4)
    b = synthetic_calibration_batches((8, 8, 1), n_batches=2, batch_size=4)
    assert len(a) == len(b) == 2
    for x, y in zip(a, b):
        assert x.dtype == np.uint8 and x.shape == (4, 8, 8, 1)
        np.testing.assert_array_equal(x, y)


def test_calibrate_is_pure(quant_serving):
    """Same model + same batches → bit-identical scales and ranges (the
    determinism gate: a hot reload recalibrates and must agree)."""
    sm_f32, sm_i8, _ = quant_serving
    batches = synthetic_calibration_batches(sm_f32.input_shape)
    c1 = calibrate(sm_f32._model, sm_f32._variables, batches, "mnist")
    c2 = calibrate(sm_f32._model, sm_f32._variables, batches, "mnist")
    assert isinstance(c1, Calibration)
    assert c1.act_scale == c2.act_scale > 0
    assert c1.act_absmax == c2.act_absmax
    assert c1.ranges and c1.ranges == c2.ranges
    # the registry load calibrated the SAME weights on the SAME
    # synthetic batches — its recorded scale must match too
    assert sm_i8.quant.act_scale == c1.act_scale
    with pytest.raises(ValueError, match="at least one batch"):
        calibrate(sm_f32._model, sm_f32._variables, [], "mnist")


def test_load_calibration_dir(tmp_path):
    rng = np.random.RandomState(3)
    np.save(tmp_path / "a.npy",
            rng.randint(0, 256, (6, 8, 8, 1), dtype=np.uint8))
    np.save(tmp_path / "b.npy",
            rng.randint(0, 256, (8, 8, 1), dtype=np.uint8))  # single HWC
    batches = load_calibration_dir(str(tmp_path), (8, 8, 1),
                                   n_batches=2, batch_size=3)
    assert len(batches) == 2
    assert all(b.shape == (3, 8, 8, 1) and b.dtype == np.uint8
               for b in batches)
    with pytest.raises(FileNotFoundError, match="calibration"):
        load_calibration_dir(str(tmp_path / "empty"), (8, 8, 1))
    bad = tmp_path / "bad"
    bad.mkdir()
    np.save(bad / "x.npy", np.zeros((2, 4, 4, 3), np.uint8))
    with pytest.raises(ValueError, match="expected uint8 images"):
        load_calibration_dir(str(bad), (8, 8, 1))


# -- fused Pallas ingest (interpret mode on CPU) ---------------------------


def test_ingest_decode_normalize_parity():
    """quantize=False mode is serve_normalize's math: decode /255 then
    (x-mean)/std, per family, to the same tolerance the XLA prologue is
    held to against the host path."""
    import jax.numpy as jnp

    from deep_vision_tpu.ops.pallas_ops import serve_ingest
    from deep_vision_tpu.ops.preprocess import serve_normalize

    gray = np.random.RandomState(0).randint(0, 256, (3, 32, 32, 1),
                                            dtype=np.uint8)
    got = np.asarray(serve_ingest(jnp.asarray(gray), "mnist",
                                  quantize=False, interpret=True))
    want = np.asarray(serve_normalize(jnp.asarray(gray), "mnist"))
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, want, atol=1e-6)

    rgb = np.random.RandomState(1).randint(0, 256, (2, 8, 8, 3),
                                           dtype=np.uint8)
    got = np.asarray(serve_ingest(jnp.asarray(rgb), "imagenet",
                                  quantize=False, interpret=True))
    want = np.asarray(serve_normalize(jnp.asarray(rgb), "imagenet"))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_ingest_quantize_matches_xla_prologue():
    """The fused kernel's int8 activations agree with the two-op XLA
    path (serve_normalize → quantize_activations) to ≤ 1 step — the
    same bar ingest_parity_ok holds the compiled kernel to on TPU."""
    import jax.numpy as jnp

    from deep_vision_tpu.ops.pallas_ops import serve_ingest
    from deep_vision_tpu.ops.preprocess import (
        quantize_activations,
        serve_normalize,
    )

    act_scale = 2.8 / 127.0
    raw = np.random.RandomState(2).randint(0, 256, (4, 32, 32, 1),
                                           dtype=np.uint8)
    got = np.asarray(serve_ingest(jnp.asarray(raw), "mnist",
                                  act_scale=act_scale, interpret=True))
    assert got.dtype == np.int8
    ref = np.asarray(quantize_activations(
        serve_normalize(jnp.asarray(raw), "mnist"), act_scale))
    assert np.max(np.abs(got.astype(np.int32)
                         - ref.astype(np.int32))) <= 1


def test_ingest_parity_gate():
    from deep_vision_tpu.ops.pallas_ops import ingest_parity_ok

    assert ingest_parity_ok((8, 32, 32, 1), "mnist", 2.8 / 127.0,
                            interpret=True)
    assert ingest_parity_ok((2, 8, 8, 3), "imagenet", 3.1 / 127.0,
                            interpret=True)


# -- the int8 serving path end to end --------------------------------------


def test_int8_top1_agreement(quant_serving):
    """Acceptance gate: int8 engines return FLOAT32 outputs within
    loose tolerance of the f32 path with top-1 intact (the bf16 bar),
    and the Pallas-ingest and XLA-ingest engines agree with each other
    to the tight tolerance (same quantized weights, ≤1-step ingest
    difference)."""
    sm_f32, sm_i8, sm_i8_xla = quant_serving
    raw = _raw_images(12)
    kw = dict(buckets=[4, 8], max_wait_ms=150, watchdog_interval_s=0)
    with BatchingEngine(sm_f32, **kw) as eng:
        ref = _serve_all(eng, _host_normalized(raw[:8]))
        ref += _serve_all(eng, _host_normalized(raw[8:]))
    with BatchingEngine(sm_i8, **kw) as eng:
        got = _serve_all(eng, raw[:8])
        got += _serve_all(eng, raw[8:])
        stats = eng.stats()
    assert stats["infer_dtype"] == "int8"
    assert stats["weight_hbm_bytes"] == sm_i8.param_bytes()
    for a, b in zip(ref, got):
        assert b.dtype == np.float32
        np.testing.assert_allclose(a, b, atol=5e-2, rtol=5e-2)
        assert int(np.argmax(a)) == int(np.argmax(b))
    with BatchingEngine(sm_i8_xla, **kw) as eng:
        got_x = _serve_all(eng, raw[:8])
        got_x += _serve_all(eng, raw[8:])
    for a, b in zip(got, got_x):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)
        assert int(np.argmax(a)) == int(np.argmax(b))
    assert sm_i8.ingest_path == "pallas"  # uint8 wire, no TPU veto here
    assert sm_i8_xla.ingest_path == "xla"


def test_int8_weight_footprint_and_describe(quant_serving):
    """Acceptance gate: int8 weight HBM ≤ 0.27× f32 (int8 kernels +
    f32 scales/biases), priced by param_bytes() and surfaced in
    describe()'s quant block."""
    sm_f32, sm_i8, _ = quant_serving
    ratio = sm_i8.param_bytes() / sm_f32.param_bytes()
    assert ratio <= 0.27, f"int8/f32 weight bytes {ratio:.4f} > 0.27"
    d = sm_i8.describe()
    assert d["infer_dtype"] == "int8"
    q = d["quant"]
    assert q["act_scale"] > 0 and q["act_absmax"] > 0
    assert q["calib_source"] == "synthetic"
    assert q["calib_batches"] == 2
    assert q["activation_ranges"] > 0
    assert q["param_bytes"] == sm_i8.param_bytes()
    assert q["ingest"] == "pallas"
    assert "quant" not in sm_f32.describe()


def test_int8_validation_and_stablehlo_rejection():
    reg = ModelRegistry()
    # int8 is an INFER dtype, never a wire format
    with pytest.raises(ValueError, match="wire_dtype"):
        reg.load_checkpoint("lenet5", "/nonexistent", wire_dtype="int8")
    with pytest.raises(ValueError, match="ingest"):
        reg.load_checkpoint("lenet5", "/nonexistent",
                            infer_dtype="int8", ingest="mosaic")
    # exported blobs serve exactly their traced f32 signature — every
    # non-f32 knob names the checkpoint path, checked before any I/O
    for kw in ({"infer_dtype": "int8"}, {"infer_dtype": "bfloat16"},
               {"wire_dtype": "uint8"}):
        with pytest.raises(ValueError,
                           match="f32-wire/f32-compute only"):
            reg.load_exported("lenet5", "/nonexistent.bin",
                              "/nonexistent", **kw)


def test_int8_does_not_recompile_f32_programs(quant_serving):
    """Compiling an int8 bucket must not invalidate a retained f32
    program: the f32 callable compiled BEFORE still serves identical
    outputs AFTER (the no-global-recompile acceptance)."""
    sm_f32, sm_i8, _ = quant_serving
    x = np.stack(_host_normalized(_raw_images(4)))
    call_f32 = sm_f32.compile_bucket(4)
    before = np.asarray(call_f32(x.copy()))
    call_i8 = sm_i8.compile_bucket(4)
    raw4 = np.stack(_raw_images(4))
    out_i8 = np.asarray(call_i8(raw4))
    assert out_i8.dtype == np.float32
    after = np.asarray(call_f32(x.copy()))
    np.testing.assert_array_equal(before, after)


# -- WeightCache density + spill/re-admit ----------------------------------


def test_weight_cache_admits_more_int8_versions(quant_serving,
                                                tmp_path_factory):
    """A budget sized for ONE f32 version holds ≥ 3 int8 versions
    resident simultaneously (the ~4× density win the control plane's
    version retention buys from quantization)."""
    from deep_vision_tpu.serve.models import WeightCache

    sm_f32, _, _ = quant_serving
    reg = ModelRegistry()
    td = str(tmp_path_factory.mktemp("cache_workdir"))
    versions = [reg.load_checkpoint("lenet5", td, name=f"lenet_i8_v{k}",
                                    wire_dtype="uint8",
                                    infer_dtype="int8")
                for k in range(3)]
    cache = WeightCache(budget_bytes=sm_f32.param_bytes())
    for sm in versions:
        cache.register(sm)
    st = cache.stats()
    assert st["evictions"] == 0 and st["over_budget"] == 0
    assert st["resident_bytes"] <= st["budget_bytes"]
    assert sorted(cache.resident_models()) == \
        [f"lenet_i8_v{k}" for k in range(3)]
    # the density claim itself: three int8 trees fit where one f32 did
    assert 3 * versions[0].param_bytes() <= sm_f32.param_bytes()


def test_int8_spill_readmit_bit_identity(tmp_path_factory):
    """Evict→re-admit round-trips the quantized tree leaf-wise: int8
    codes, f32 scales, and batch_stats all come back bit-identical (the
    opaque-pytree contract in serve/quant.py)."""
    import jax

    reg = ModelRegistry()
    td = str(tmp_path_factory.mktemp("spill_workdir"))
    m1 = reg.load_checkpoint("lenet5", td, name="spill_a",
                             wire_dtype="uint8", infer_dtype="int8")
    m2 = reg.load_checkpoint("lenet5", td, name="spill_b",
                             wire_dtype="uint8", infer_dtype="int8")
    pristine = jax.tree_util.tree_map(
        np.array, jax.device_get(m1._variables))
    from deep_vision_tpu.serve.models import WeightCache

    # budget fits exactly one int8 version: registering m2 evicts m1
    cache = WeightCache(budget_bytes=m1.param_bytes())
    cache.register(m1)
    cache.register(m2)
    assert cache.resident_models() == ["spill_b"]
    # hot path re-admits m1 (evicting m2) via one device_put
    live = m1._live_variables()
    assert cache.resident_models() == ["spill_a"]
    assert cache.stats()["misses"] == 1
    flat_p = jax.tree_util.tree_leaves_with_path(pristine)
    flat_l = jax.tree_util.tree_leaves_with_path(
        jax.device_get(live))
    assert len(flat_p) == len(flat_l)
    for (pa, a), (pb, b) in zip(flat_p, flat_l):
        assert pa == pb
        assert np.asarray(a).dtype == np.asarray(b).dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # int8 leaves really are int8 through the round trip
    dtypes = {np.asarray(a).dtype for a in
              jax.tree_util.tree_leaves(jax.device_get(live))}
    assert np.dtype(np.int8) in dtypes
