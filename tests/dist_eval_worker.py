"""Worker for test_distributed_eval_rank0_broadcast: one rank of a
2-process CPU 'pod' running Trainer.evaluate on YOLO-toy at random init.
The detection extras are still allgathered collectively (every rank's
shard reaches the global val set), but the host-side mAP accumulator
feeds on process 0 ONLY — the scalar metrics are broadcast so every
rank reports identical numbers without redoing the sweep per rank.

Run: python dist_eval_worker.py <coordinator> <process_id> <n> <workdir>.
"""

import os
import sys

# 2 virtual CPU devices per process, BEFORE any jax import
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if not f.startswith("--xla_force_host_platform_device_count")]
flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize pins the TPU

import numpy as np  # noqa: E402

from deep_vision_tpu.parallel.distributed import (  # noqa: E402
    initialize,
    make_pod_mesh,
)


def main():
    coordinator, pid, nprocs, workdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    initialize(coordinator_address=coordinator, num_processes=nprocs,
               process_id=pid)
    mesh = make_pod_mesh({"data": -1})

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.trainer import Trainer
    from deep_vision_tpu.data.detection import (
        DetectionLoader,
        synthetic_detection_dataset,
    )
    from deep_vision_tpu.tasks.detection import YoloTask

    cfg = get_config("yolov3_toy")
    samples = synthetic_detection_dataset(16, 64, 3, seed=5)
    shard = [samples[i] for i in range(pid, len(samples), nprocs)]
    val = DetectionLoader(shard, 4, 3, 64, train=False)

    task = YoloTask(3)
    # count host-evaluator feeds on THIS rank: the whole point of the
    # rank-0 gate is that only process 0's accumulator sees batches
    real_make = task.make_host_evaluator
    feeds = {"n": 0}

    def counting_make():
        ev = real_make()
        orig = ev.add_batch

        def add_batch(batch):
            feeds["n"] += 1
            return orig(batch)

        ev.add_batch = add_batch
        return ev

    task.make_host_evaluator = counting_make

    trainer = Trainer(cfg, cfg.model(), task, mesh=mesh, workdir=workdir)
    state = trainer.init_state(next(iter(val)))
    m = trainer.evaluate(state, val)
    assert np.isfinite(m["loss"]), m
    assert "mAP" in m and "mAP50_95" in m, m
    if pid == 0:
        assert feeds["n"] > 0, "rank 0 must feed the accumulator"
    else:
        assert feeds["n"] == 0, \
            f"rank {pid} fed the accumulator {feeds['n']}x — the mAP " \
            f"sweep should run on process 0 only"
    # RESULT lines must be identical across ranks (broadcast metrics)
    print(f"RESULT pid={pid} loss={m['loss']:.6f} mAP={m['mAP']:.4f} "
          f"mAP50_95={m['mAP50_95']:.4f}", flush=True)
    print(f"EVALFEEDS pid={pid} n={feeds['n']}", flush=True)


if __name__ == "__main__":
    main()
